"""Interprocedural dtype-flow analysis over lowered HLO (ISSUE 10).

THE one dtype analyzer in the tree: ``dtype_summary()`` is the
dtype-policy family hlocheck's ``summarize()`` delegates to, and the
rest of the module is mxprec's substrate — every convert is tracked to
its producing op and source site (``cast_flows``), and precision
hazards are classified per instruction (``hazard_findings``):

* ``bf16-accum-reduction`` — a reduce whose accumulator is a sub-f32
  float (direct, pre-optimization form) or whose region round-trips
  the accumulator through a narrowing float convert (the shape CPU
  FloatNormalization leaves behind), i.e. softmax/logsumexp/norm sums
  without fp32 accumulation;
* ``matmul-preferred-type`` — a dot/convolution whose operands AND
  result are sub-f32 floats: the ``preferred_element_type=f32`` the
  MXU recipe requires was dropped;
* ``f64-creep`` — any instruction carrying f64, named per site (the
  coarse count lives in ``dtype_summary``; this is the ledger's
  per-site form);
* ``int8-accum-matmul`` — a dot/convolution on int8 operands whose
  result is narrower than i32: the ``preferred_element_type=int32``
  the quantized-GEMM recipe requires was dropped, so partial sums
  wrap at ±127 (mxtpu.quant's accumulation rule, ISSUE 18);
* ``quant-missing-scale`` — an int8 dot/convolution whose metadata
  carries no ``q8_<key>`` scale tag: ``mxtpu.quant.wrap_op`` tags
  every contraction it quantizes with the dispatch key of its
  recorded activation threshold, so an untagged int8 contraction is
  a quantized op with no calibrated scale behind it;
* ``master-weight`` — not an HLO rule: ``master_weight_findings``
  eval_shapes the optimizer's functional rule per parameter and flags
  any sub-f32 param whose update chain carries no f32 master copy.

Source sites come from HLO ``metadata={... source_file= source_line=}``
(present in the pre-optimization dump ``analysis.lowered_text``
produces); paths are normalized repo-relative so committed ledgers
under ``contracts/prec/`` are byte-deterministic across machines.

Pure stdlib except ``master_weight_findings`` (imports jax lazily) —
parsing saved dumps must not pay a framework import.
"""
from __future__ import annotations

import re
from pathlib import Path
from typing import Dict, List, Optional, Tuple, Union

from .hlo import (_FLOAT_WIDTH, Computation, HloProgram, Instruction,
                  parse_hlo)

REPO_ROOT = Path(__file__).resolve().parents[2]

# ledger site lists are capped (sorted, then "+N more") so a fusion
# explosion can't turn a lockfile into a megabyte diff
MAX_SITES = 3

_F32_WIDTH = _FLOAT_WIDTH["f32"]

_MD_OP_RE = re.compile(r'op_name="([^"]*)"')
_MD_FILE_RE = re.compile(r'source_file="([^"]*)"')
_MD_LINE_RE = re.compile(r"source_line=(\d+)")

# reduce regions whose root is one of these accumulate (sum / product);
# min/max/and/or regions are order-insensitive and dtype-safe
_ACCUM_ROOTS = ("add", "multiply")

_MATMUL_OPS = ("dot", "convolution")
_REDUCE_OPS = ("reduce", "reduce-window")

# the int8 quantization tier (mxtpu.quant): s8/u8 contractions must
# accumulate in >= 32-bit integers, and each must carry the q8_<key>
# tag wrap_op stamps (named_scope) when it holds a calibrated scale
_INT8_DTS = ("s8", "u8")
_INT_WIDTH = {"s8": 1, "u8": 1, "s16": 2, "u16": 2,
              "s32": 4, "u32": 4, "s64": 8, "u64": 8}
_Q8_TAG_RE = re.compile(r"\bq8_")


def _norm_path(path: str) -> str:
    """Deterministic source path: repo-relative when inside the repo,
    trimmed after site/dist-packages for library frames, basename
    otherwise — ledgers must not embed a machine's directory layout."""
    for marker in ("site-packages/", "dist-packages/"):
        if marker in path:
            return path.split(marker)[-1]
    root = str(REPO_ROOT)
    if path.startswith(root):
        return path[len(root):].lstrip("/")
    return path.rsplit("/", 1)[-1]


def instr_site(instr: Instruction) -> Tuple[str, str]:
    """(jax op_name, "file:line") from the instruction's metadata;
    empty strings when the dump carries none (post-optimization text
    usually doesn't)."""
    attrs = instr.attrs
    om = _MD_OP_RE.search(attrs)
    fm = _MD_FILE_RE.search(attrs)
    lm = _MD_LINE_RE.search(attrs)
    op_name = om.group(1) if om else ""
    site = f"{_norm_path(fm.group(1))}:{lm.group(1)}" \
        if fm and lm else ""
    return op_name, site


def _short_op_name(op_name: str) -> str:
    return op_name.rsplit("/", 1)[-1] if op_name else ""


def _is_sub_f32(dt: str) -> bool:
    return dt in _FLOAT_WIDTH and _FLOAT_WIDTH[dt] < _F32_WIDTH


def _result_dtype(instr: Instruction) -> str:
    return instr.shapes[0][0] if instr.shapes else "?"


# ----------------------------------------------------------------------
# the dtype-policy family (hlocheck's summarize() delegates here)
# ----------------------------------------------------------------------
def is_upcast(pair: str) -> bool:
    """True for a widening float->float convert pair like
    ``bf16->f32``."""
    src, _, dst = pair.partition("->")
    return (src in _FLOAT_WIDTH and dst in _FLOAT_WIDTH and
            _FLOAT_WIDTH[dst] > _FLOAT_WIDTH[src])


def _convert_pair(comp: Computation, instr: Instruction) -> str:
    src = comp.by_name.get(instr.operands[0])
    src_dt = src.shapes[0][0] if src and src.shapes else "?"
    return f"{src_dt}->{_result_dtype(instr)}"


def dtype_summary(program: Union[str, HloProgram]) -> Dict:
    """The ``dtype`` block of a contract summary — f64 op count plus
    every convert pair (upcasts broken out).  Byte-compatible with the
    sections committed in ``contracts/*.json``."""
    if isinstance(program, str):
        program = parse_hlo(program)
    converts: Dict[str, int] = {}
    f64_ops = 0
    for comp in program.computations.values():
        for instr in comp.instructions:
            if any(dt == "f64" for dt in instr.dtypes()):
                f64_ops += 1
            if instr.opcode == "convert" and instr.operands:
                pair = _convert_pair(comp, instr)
                converts[pair] = converts.get(pair, 0) + 1
    upcasts = {p: n for p, n in converts.items() if is_upcast(p)}
    return {"f64_ops": f64_ops,
            "upcasts": {k: upcasts[k] for k in sorted(upcasts)},
            "converts": {k: converts[k] for k in sorted(converts)}}


# ----------------------------------------------------------------------
# cast provenance (the ledger's `flows` section)
# ----------------------------------------------------------------------
def _cap_sites(sites) -> List[str]:
    ordered = sorted(sites)
    if len(ordered) > MAX_SITES:
        extra = len(ordered) - MAX_SITES
        ordered = ordered[:MAX_SITES] + [f"+{extra} more"]
    return ordered


def cast_flows(program: Union[str, HloProgram]) -> Dict[str, Dict]:
    """Every convert tracked to its producing op and source site:
    ``{"src->dst": {"count": n, "sites": [...]}}``.  A site reads
    ``<producer-opcode> @ <file>:<line>`` (the convert's own metadata;
    bare producer opcode when the dump has none)."""
    if isinstance(program, str):
        program = parse_hlo(program)
    flows: Dict[str, Dict] = {}
    for comp in program.computations.values():
        for instr in comp.instructions:
            if instr.opcode != "convert" or not instr.operands:
                continue
            pair = _convert_pair(comp, instr)
            src = comp.by_name.get(instr.operands[0])
            producer = src.opcode if src else "?"
            _, site = instr_site(instr)
            desc = f"{producer} @ {site}" if site else producer
            slot = flows.setdefault(pair, {"count": 0, "sites": set()})
            slot["count"] += 1
            slot["sites"].add(desc)
    return {pair: {"count": flows[pair]["count"],
                   "sites": _cap_sites(flows[pair]["sites"])}
            for pair in sorted(flows)}


def float_opcode_counts(program: Union[str, HloProgram]
                        ) -> Dict[str, int]:
    """Float-carrying instructions per opcode — the observation base
    mxprec's ``contracts/amp_policy.json`` classifies (every opcode in
    the policy was actually seen in a lowered target program)."""
    if isinstance(program, str):
        program = parse_hlo(program)
    out: Dict[str, int] = {}
    for instr in program.all_instructions():
        if any(dt in _FLOAT_WIDTH for dt in instr.dtypes()):
            out[instr.opcode] = out.get(instr.opcode, 0) + 1
    return {k: out[k] for k in sorted(out)}


def float_op_counts(program: Union[str, HloProgram]) -> Dict[str, int]:
    """Instructions carrying each float dtype (an instruction counts
    once per distinct float dtype in its result shapes)."""
    if isinstance(program, str):
        program = parse_hlo(program)
    out: Dict[str, int] = {}
    for instr in program.all_instructions():
        for dt in sorted(set(instr.dtypes())):
            if dt in _FLOAT_WIDTH:
                out[dt] = out.get(dt, 0) + 1
    return {k: out[k] for k in sorted(out)}


# ----------------------------------------------------------------------
# hazard rules
# ----------------------------------------------------------------------
def _hazard(rule: str, instr: Instruction, detail: str) -> Dict:
    op_name, site = instr_site(instr)
    short = _short_op_name(op_name)
    return {"rule": rule, "op": instr.opcode,
            "site": site or short or "?",
            "detail": detail + (f" [{short}]" if short else "")}


def _region_comps(program: HloProgram,
                  instr: Instruction) -> List[Computation]:
    return [program.computations[c] for c in instr.calls
            if c in program.computations]


def _region_root_opcode(comp: Computation) -> str:
    for instr in comp.instructions:
        if instr.root:
            return instr.opcode
    return comp.instructions[-1].opcode if comp.instructions else "?"


def _region_narrowing_convert(comp: Computation) -> Optional[str]:
    """The ``f32->bf16``-style pair of a narrowing float convert
    inside a reduce region — the accumulator round-trip shape CPU
    FloatNormalization rewrites a sub-f32 reduce into."""
    for instr in comp.instructions:
        if instr.opcode != "convert" or not instr.operands:
            continue
        dst = _result_dtype(instr)
        src_i = comp.by_name.get(instr.operands[0])
        src = src_i.shapes[0][0] if src_i and src_i.shapes else "?"
        if (src in _FLOAT_WIDTH and dst in _FLOAT_WIDTH and
                _FLOAT_WIDTH[dst] < _FLOAT_WIDTH[src]):
            return f"{src}->{dst}"
    return None


def _reduction_hazards(program: HloProgram) -> List[Dict]:
    out = []
    for comp in program.computations.values():
        for instr in comp.instructions:
            if instr.opcode not in _REDUCE_OPS:
                continue
            regions = _region_comps(program, instr)
            accum = [r for r in regions
                     if _region_root_opcode(r) in _ACCUM_ROOTS]
            if not accum:
                continue
            res = next((dt for dt in instr.dtypes()
                        if dt in _FLOAT_WIDTH), None)
            if res is not None and _is_sub_f32(res):
                out.append(_hazard(
                    "bf16-accum-reduction", instr,
                    f"accumulating {instr.opcode} carries a {res} "
                    f"accumulator — sum in f32 and downcast once"))
                continue
            for r in accum:
                pair = _region_narrowing_convert(r)
                if pair:
                    out.append(_hazard(
                        "bf16-accum-reduction", instr,
                        f"accumulating {instr.opcode} round-trips "
                        f"its accumulator through {pair} every step "
                        f"— sum in f32 and downcast once"))
                    break
    return out


def _matmul_hazards(program: HloProgram) -> List[Dict]:
    out = []
    for comp in program.computations.values():
        for instr in comp.instructions:
            if instr.opcode not in _MATMUL_OPS:
                continue
            res = _result_dtype(instr)
            if not _is_sub_f32(res):
                continue
            op_dts = []
            for name in instr.operands:
                src = comp.by_name.get(name)
                if src and src.shapes:
                    op_dts.append(src.shapes[0][0])
            floats = [dt for dt in op_dts if dt in _FLOAT_WIDTH]
            if floats and all(_is_sub_f32(dt) for dt in floats):
                out.append(_hazard(
                    "matmul-preferred-type", instr,
                    f"{instr.opcode} accumulates "
                    f"{'x'.join(floats)} into {res} — pass "
                    f"preferred_element_type=float32"))
    return out


def _int8_operand_dts(comp: Computation,
                      instr: Instruction) -> List[str]:
    """Operand dtypes of a contraction when EVERY operand is int8
    (s8/u8), else [] — the gate both quantization hazard rules and
    the census share."""
    op_dts = []
    for name in instr.operands:
        src = comp.by_name.get(name)
        if src and src.shapes:
            op_dts.append(src.shapes[0][0])
    if len(op_dts) < 2 or any(dt not in _INT8_DTS for dt in op_dts):
        return []
    return op_dts


def _int8_matmul_hazards(program: HloProgram) -> List[Dict]:
    out = []
    for comp in program.computations.values():
        for instr in comp.instructions:
            if instr.opcode not in _MATMUL_OPS:
                continue
            op_dts = _int8_operand_dts(comp, instr)
            if not op_dts:
                continue
            res = _result_dtype(instr)
            if res in _INT_WIDTH and _INT_WIDTH[res] < 4:
                out.append(_hazard(
                    "int8-accum-matmul", instr,
                    f"{instr.opcode} accumulates "
                    f"{'x'.join(op_dts)} into {res} — pass "
                    f"preferred_element_type=int32"))
    return out


def _quant_scale_hazards(program: HloProgram) -> List[Dict]:
    out = []
    for comp in program.computations.values():
        for instr in comp.instructions:
            if instr.opcode not in _MATMUL_OPS:
                continue
            op_dts = _int8_operand_dts(comp, instr)
            if not op_dts:
                continue
            op_name, _ = instr_site(instr)
            if not _Q8_TAG_RE.search(op_name):
                out.append(_hazard(
                    "quant-missing-scale", instr,
                    f"int8 {instr.opcode} carries no q8_<key> scale "
                    f"tag — quantize through mxtpu.quant so every "
                    f"int8 contraction has a recorded activation "
                    f"threshold"))
    return out


def int8_contraction_census(program: Union[str, HloProgram]
                            ) -> Dict[str, int]:
    """Signature counts of int8 contractions —
    ``{"s8xs8->s32": n, ...}`` — the i32-accumulation evidence the
    quantized serving contracts pin."""
    if isinstance(program, str):
        program = parse_hlo(program)
    counts: Dict[str, int] = {}
    for comp in program.computations.values():
        for instr in comp.instructions:
            if instr.opcode not in _MATMUL_OPS:
                continue
            op_dts = _int8_operand_dts(comp, instr)
            if not op_dts:
                continue
            sig = f"{'x'.join(op_dts)}->{_result_dtype(instr)}"
            counts[sig] = counts.get(sig, 0) + 1
    return {k: counts[k] for k in sorted(counts)}


def _f64_hazards(program: HloProgram) -> List[Dict]:
    out = []
    for instr in program.all_instructions():
        if any(dt == "f64" for dt in instr.dtypes()):
            out.append(_hazard(
                "f64-creep", instr,
                f"{instr.opcode} carries f64 — silent f32->f64 "
                f"promotion (np scalar leak or jax_enable_x64)"))
    return out


def hazard_findings(program: Union[str, HloProgram]) -> List[Dict]:
    """All HLO-level precision hazards of one program, sorted for
    byte-deterministic ledgers."""
    if isinstance(program, str):
        program = parse_hlo(program)
    out = (_reduction_hazards(program) + _matmul_hazards(program)
           + _int8_matmul_hazards(program)
           + _quant_scale_hazards(program) + _f64_hazards(program))
    return sorted(out, key=lambda h: (h["rule"], h["op"], h["site"],
                                      h["detail"]))


def format_hazard(h: Dict) -> str:
    return f"[{h['rule']}] {h['op']} at {h['site']}: {h['detail']}"


# ----------------------------------------------------------------------
# the per-program ledger entry
# ----------------------------------------------------------------------
def program_ledger(program: Union[str, HloProgram]) -> Dict:
    """One program's ``contracts/prec/`` entry: cast provenance,
    float-op census, hazards.  Deterministic across lowerings of the
    same program."""
    if isinstance(program, str):
        program = parse_hlo(program)
    return {"flows": cast_flows(program),
            "float_ops": float_op_counts(program),
            "hazards": hazard_findings(program)}


# ----------------------------------------------------------------------
# master weights (the optimizer's multi-precision contract)
# ----------------------------------------------------------------------
def master_weight_findings(optimizer, param_sigs) -> List[Dict]:
    """Flag every sub-f32 float parameter whose optimizer update chain
    carries no f32 master copy of the weight.  ``param_sigs`` is
    ``[(name, shape, dtype_str), ...]``; the check eval_shapes the
    functional rule (the one the compiled TrainStep uses), so it sees
    exactly the state the batched/ZeRO buckets will carry — no device
    work."""
    import jax
    import jax.numpy as jnp
    from ..optimizer.functional import opt_rule
    init, _ = opt_rule(optimizer)
    out = []
    for name, shape, dtype in param_sigs:
        # NOT dt.kind — numpy classes bfloat16 (ml_dtypes) as 'V';
        # jnp.issubdtype knows the extension float types
        dt = jnp.dtype(dtype)
        if not jnp.issubdtype(dt, jnp.floating) or dt.itemsize >= 4:
            continue
        leaves = jax.tree_util.tree_leaves(jax.eval_shape(
            lambda s=tuple(shape), d=dt: init(jnp.zeros(s, d))))
        has_master = any(
            tuple(leaf.shape) == tuple(shape) and
            jnp.issubdtype(jnp.dtype(leaf.dtype), jnp.floating) and
            jnp.dtype(leaf.dtype).itemsize >= 4
            for leaf in leaves)
        if not has_master:
            out.append({
                "rule": "master-weight",
                "op": type(optimizer).__name__.lower(),
                "site": name,
                "detail": f"{dtype} param updates with no f32 master "
                          f"weight in the optimizer state "
                          f"(multi_precision="
                          f"{optimizer.multi_precision!r})"})
    return sorted(out, key=lambda h: (h["op"], h["site"]))
