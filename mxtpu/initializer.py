"""Weight initializers (reference ``python/mxnet/initializer.py``†).

Registry + JSON-string serialization kept because the reference serializes
initializers into kvstore init and symbol attrs.  Sampling uses the global
counter-based RNG streams (mxtpu.ndarray.random)."""
from __future__ import annotations

import json
import math
import re
from typing import Optional

import numpy as np

from .base import MXNetError, Registry
from .ndarray import ndarray as _nda
from .ndarray import random as _rnd
from .ndarray.ndarray import NDArray

__all__ = ["Initializer", "Uniform", "Normal", "Zero", "One", "Constant",
           "Xavier", "MSRAPrelu", "Orthogonal", "Bilinear", "LSTMBias",
           "Mixed", "register", "create", "InitDesc"]

_REGISTRY: Registry = Registry("initializer")


def register(klass=None, *, aliases=()):
    """Register an initializer class under its name, lowercase name, and
    any aliases (the reference registers ``Zero`` as ``'zeros'`` etc. —
    ``python/mxnet/initializer.py``† ``@register`` + ``alias``)."""
    def _do(k):
        _REGISTRY.register(k.__name__, aliases=tuple(aliases))(k)
        return k
    if klass is not None:
        return _do(klass)
    return _do


def create(init, **kwargs) -> "Initializer":
    if isinstance(init, Initializer):
        return init
    if init is None:
        return Uniform(0.07)
    if isinstance(init, str):
        # accept plain names and the reference's JSON form '["xavier", {}]'
        if init.startswith("["):
            name, kw = json.loads(init)
            return _REGISTRY.get(name)(**kw)
        return _REGISTRY.get(init)(**kwargs)
    raise MXNetError(f"cannot create initializer from {init!r}")


class InitDesc(str):
    """Parameter name + attrs hint passed to initializers (reference
    ``initializer.InitDesc``†)."""
    def __new__(cls, name, attrs=None, global_init=None):
        obj = super().__new__(cls, name)
        obj.attrs = attrs or {}
        obj.global_init = global_init
        return obj


class Initializer:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self) -> str:
        return json.dumps([type(self).__name__.lower(), self._kwargs])

    def __call__(self, desc, arr: NDArray) -> None:
        # A parameter-specific initializer rides in attrs['__init__'] and
        # bypasses the name-suffix dispatch (reference gluon passes
        # Parameter.init this way so e.g. bias_initializer='ones' wins
        # over the default bias→zero rule).
        if isinstance(desc, InitDesc):
            specific = desc.attrs.get("__init__", "")
            if specific:
                create(specific)._init_weight(desc, arr)
                return
        self.init_weight(desc, arr)

    def init_weight(self, name: str, arr: NDArray) -> None:
        # name-based dispatch like the reference's default flow
        if name.endswith("gamma"):
            arr[:] = 1.0
        elif name.endswith("beta") or name.endswith("bias") or \
                name.endswith("running_mean") or name.endswith("moving_mean"):
            arr[:] = 0.0
        elif name.endswith("running_var") or name.endswith("moving_var"):
            arr[:] = 1.0
        else:
            self._init_weight(name, arr)

    def _init_weight(self, name: str, arr: NDArray) -> None:
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__}({self._kwargs})"


@register
class Uniform(Initializer):
    def __init__(self, scale: float = 0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, name, arr):
        arr._data = _rnd.uniform(-self.scale, self.scale,
                                 shape=arr.shape,
                                 dtype=str(arr.data.dtype))._data


@register
class Normal(Initializer):
    def __init__(self, sigma: float = 0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, name, arr):
        arr._data = _rnd.normal(0.0, self.sigma, shape=arr.shape,
                                dtype=str(arr.data.dtype))._data


@register(aliases=("zeros",))
class Zero(Initializer):
    def _init_weight(self, name, arr):
        arr[:] = 0.0


@register(aliases=("ones",))
class One(Initializer):
    def _init_weight(self, name, arr):
        arr[:] = 1.0


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, name, arr):
        arr[:] = self.value


@register
class Xavier(Initializer):
    """Xavier/Glorot (reference default for conv/dense in examples)."""

    def __init__(self, rnd_type: str = "uniform", factor_type: str = "avg",
                 magnitude: float = 3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = magnitude

    def _init_weight(self, name, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            raise MXNetError(
                f"Xavier requires ndim>=2, got shape {shape} for {name}")
        if len(shape) > 2:
            hw_scale = float(np.prod(shape[2:]))
        fan_in = shape[1] * hw_scale
        fan_out = shape[0] * hw_scale
        factor = {"avg": (fan_in + fan_out) / 2.0, "in": fan_in,
                  "out": fan_out}[self.factor_type]
        scale = math.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            arr._data = _rnd.uniform(-scale, scale, shape=shape,
                                     dtype=str(arr.data.dtype))._data
        else:
            arr._data = _rnd.normal(0, scale, shape=shape,
                                    dtype=str(arr.data.dtype))._data


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type: str = "avg", slope: float = 0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Orthogonal(Initializer):
    def __init__(self, scale: float = 1.414, rand_type: str = "uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, name, arr):
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = np.random.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = np.random.normal(0.0, 1.0, (nout, nin))
        u, _, v = np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == (nout, nin) else v
        arr._data = _nda.array(
            self.scale * q.reshape(arr.shape).astype(np.float32))._data


@register
class Bilinear(Initializer):
    """For UpSampling deconv weights."""

    def _init_weight(self, name, arr):
        weight = np.zeros(arr.shape, np.float32)
        shape = arr.shape
        f = math.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight.flat[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr._data = _nda.array(weight)._data


@register
class LSTMBias(Initializer):
    """Forget-gate bias = 1 (reference ``initializer.LSTMBias``†)."""

    def __init__(self, forget_bias: float = 1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        arr[:] = 0.0
        num_hidden = arr.shape[0] // 4
        a = arr.asnumpy()
        a[num_hidden:2 * num_hidden] = self.forget_bias
        arr._data = _nda.array(a)._data


class Mixed:
    """Pattern-based initializer mixing (reference ``Mixed``†)."""

    def __init__(self, patterns, initializers):
        if len(patterns) != len(initializers):
            raise MXNetError("patterns and initializers length mismatch")
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for pat, init in self.map:
            if pat.match(name):
                init(name, arr)
                return
        raise MXNetError(f"no initializer pattern matches {name}")
