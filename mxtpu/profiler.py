"""Profiler (reference ``python/mxnet/profiler.py`` +
``src/profiler/profiler.cc``†): op/scope-level tracing with
chrome://tracing JSON output and per-op aggregate tables.

TPU-native notes: host-side dispatch timing comes from hooking the
eager ``_invoke_op`` path (the analogue of the engine instrumenting
every pushed operation); device-side detail can additionally be
captured with ``jax.profiler`` (xplane/tensorboard) via
``start_jax_trace``/``stop_jax_trace`` — the host trace stays in the
reference's chrome-trace format so existing tooling works.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import defaultdict
from typing import Dict, List, Optional

from .base import MXNetError

__all__ = ["set_config", "set_state", "state", "is_active", "dump",
           "dumps", "pause", "resume", "events", "Task", "Frame",
           "Event", "Counter", "Marker", "record_span",
           "start_jax_trace", "stop_jax_trace"]

_ACTIVE = False          # fast-path flag read by the op dispatcher
_PAUSED = False          # guarded-by: _LOCK
_LOCK = threading.Lock()
_EVENTS: List[dict] = []  # guarded-by: _LOCK
_CONFIG = {"filename": "profile.json", "aggregate_stats": False,
           "profile_imperative": True, "profile_api": True,
           "profile_symbolic": True,
           "profile_memory": False, "profile_all": False}
_START_TS: Optional[float] = None  # guarded-by: _LOCK


def _now_us() -> float:
    return time.perf_counter() * 1e6


def set_config(**kwargs):
    """Configure (reference ``set_config``†).  Recognized keys:
    filename, aggregate_stats, profile_all, profile_symbolic,
    profile_imperative, profile_memory, profile_api.  Unknown keys
    raise — silently accepting a typo (``filname=...``) used to leave
    the profiler writing to the default path with no diagnostic."""
    unknown = set(kwargs) - set(_CONFIG)
    if unknown:
        raise MXNetError(
            f"profiler.set_config: unknown key(s) {sorted(unknown)}; "
            f"recognized: {sorted(_CONFIG)}")
    with _LOCK:
        _CONFIG.update(kwargs)


def set_state(state_: str = "stop"):
    """'run' or 'stop' (reference ``set_state``†).  ``stop`` also
    clears any pending pause so a later ``resume()`` cannot silently
    re-activate a stopped profiler."""
    global _ACTIVE, _START_TS, _PAUSED
    if state_ not in ("run", "stop"):
        raise MXNetError("state must be 'run' or 'stop'")
    with _LOCK:
        if state_ == "run":
            if _START_TS is None:
                _START_TS = _now_us()
            _ACTIVE, _PAUSED = True, False
        else:
            _ACTIVE, _PAUSED = False, False


def state() -> str:
    return "run" if _ACTIVE else "stop"


def is_active() -> bool:
    """Cheap hot-path gate: True while the profiler collects.  Callers
    that build span ``args`` dicts should check this first so the
    profiler-off path stays allocation-free."""
    return _ACTIVE


def pause():
    """Temporarily stop collection (reference ``pause``†)."""
    global _ACTIVE, _PAUSED
    with _LOCK:
        if _ACTIVE:
            _ACTIVE, _PAUSED = False, True


def resume():
    global _ACTIVE, _PAUSED
    with _LOCK:
        if _PAUSED:
            _ACTIVE, _PAUSED = True, False


def _record(name: str, cat: str, ts_us: float, dur_us: float,
            args: Optional[dict] = None):
    if not _ACTIVE:
        return
    ev = {"name": name, "cat": cat, "ph": "X",
          "ts": ts_us - (_START_TS or 0.0), "dur": dur_us,
          "pid": os.getpid(), "tid": threading.get_ident()}
    if args:
        ev["args"] = args
    with _LOCK:
        _EVENTS.append(ev)


def record_op(name: str, ts_us: float, dur_us: float,
              shapes=None) -> None:
    """Called by the eager dispatcher per op when profiling."""
    _record(name, "operator", ts_us, dur_us,
            {"shapes": shapes} if shapes else None)


def record_span(name: str, ts_us: float, dur_us: float,
                cat: str = "subsystem", args: Optional[dict] = None
                ) -> None:
    """Public complete-event hook for subsystems that time themselves
    (``mxtpu.serving`` batch execution, io feeds, …): one chrome-trace
    "X" event under category ``cat``.  ``ts_us`` must come from
    ``_now_us()``-compatible time (``time.perf_counter()*1e6``); no-op
    unless the profiler is running."""
    _record(name, cat, ts_us, dur_us, args)


def dumps(reset: bool = False) -> str:
    """Chrome-trace JSON string (reference ``dumps``† returns the
    aggregate table; here the trace itself, plus the aggregate table
    via ``aggregate_stats()``)."""
    with _LOCK:
        out = json.dumps({"traceEvents": list(_EVENTS),
                          "displayTimeUnit": "ms"})
        if reset:
            _EVENTS.clear()
    return out


def events() -> List[dict]:
    """Locked snapshot of the recorded trace events (shallow copies —
    mutating the returned dicts cannot corrupt the trace buffer).
    ``mxtpu.obs.trace_of`` reads this to rebuild per-request
    timelines."""
    with _LOCK:
        return [dict(ev) for ev in _EVENTS]


def dump(finished: bool = True, profile_process: str = "worker"):
    """Write the chrome trace to ``filename`` (reference ``dump``†)."""
    path = _CONFIG["filename"]
    with open(path, "w") as f:
        f.write(dumps())
    return path


def aggregate_stats() -> str:
    """Per-op-name summary table (reference ``aggregate_stats.cc``†)."""
    with _LOCK:
        agg: Dict[str, List[float]] = defaultdict(list)
        for ev in _EVENTS:
            if "dur" in ev:  # complete events only (not markers/counters)
                agg[ev["name"]].append(ev["dur"])
    lines = [f"{'Name':<40}{'Count':>8}{'Total(us)':>14}"
             f"{'Min(us)':>12}{'Max(us)':>12}{'Mean(us)':>12}"]
    for name, durs in sorted(agg.items(),
                             key=lambda kv: -sum(kv[1])):
        lines.append(f"{name:<40}{len(durs):>8}{sum(durs):>14.1f}"
                     f"{min(durs):>12.1f}{max(durs):>12.1f}"
                     f"{sum(durs) / len(durs):>12.1f}")
    return "\n".join(lines)


class _Scope:
    """Base for profiling scopes (Task/Frame/Event; reference
    ``ProfileTask``† etc.)."""

    _cat = "scope"

    def __init__(self, name: str):
        self.name = name
        self._t0: Optional[float] = None

    def start(self):
        self._t0 = _now_us()

    def stop(self):
        if self._t0 is not None:
            _record(self.name, self._cat, self._t0,
                    _now_us() - self._t0)
            self._t0 = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()


class Task(_Scope):
    _cat = "task"


class Frame(_Scope):
    _cat = "frame"


class Event(_Scope):
    _cat = "event"


class Marker:
    """Instant marker (reference ``ProfileMarker``†)."""

    def __init__(self, name: str):
        self.name = name

    def mark(self, scope: str = "process"):
        if _ACTIVE:
            with _LOCK:
                _EVENTS.append({
                    "name": self.name, "cat": "marker", "ph": "i",
                    "ts": _now_us() - (_START_TS or 0.0),
                    "pid": os.getpid(), "tid": threading.get_ident(),
                    "s": "p" if scope == "process" else "t"})


class Counter:
    """Named counter series (reference ``ProfileCounter``†)."""

    def __init__(self, name: str, value: int = 0):
        self.name = name
        self.value = value
        self._emit()

    def _emit(self):
        if _ACTIVE:
            with _LOCK:
                _EVENTS.append({
                    "name": self.name, "cat": "counter", "ph": "C",
                    "ts": _now_us() - (_START_TS or 0.0),
                    "pid": os.getpid(),
                    "args": {"value": self.value}})

    def set_value(self, value: int):
        self.value = value
        self._emit()

    def increment(self, delta: int = 1):
        self.value += delta
        self._emit()

    def decrement(self, delta: int = 1):
        self.value -= delta
        self._emit()

    def __iadd__(self, delta):
        self.increment(delta)
        return self

    def __isub__(self, delta):
        self.decrement(delta)
        return self


def start_jax_trace(logdir: str):
    """Device-side xplane capture (tensorboard format)."""
    import jax
    jax.profiler.start_trace(logdir)


def stop_jax_trace():
    import jax
    jax.profiler.stop_trace()
