"""KVStore — API-parity facade over TPU-native reduction.

Reference: ``src/kvstore/``† (``KVStoreLocal``, ``CommDevice`` P2P
reduce, ``kvstore_nccl.h``†, ``kvstore_dist.h``† parameter server) and
``python/mxnet/kvstore.py``†.

TPU-native mapping (SURVEY.md §2.4, §5.8): the reference's explicit
push/pull reductions become IN-GRAPH collectives — ``mxtpu.parallel``
compiles the gradient all-reduce into the training executable, where
XLA schedules it over ICI.  This facade keeps the reference API for
code that drives KVStore directly:

* ``local``/``device``/``nccl`` → same in-process reducer (device
  arrays summed by XLA; a single fused reduce, not P2P copies).
* ``dist_sync``/``dist_device_sync`` → multi-host SPMD via
  ``jax.distributed`` (process_index = worker rank).  Synchronous by
  construction.
* ``dist_async`` → no TPU analogue (SPMD is synchronous); created as a
  sync store with a warning, per the documented divergence.

``set_optimizer`` reproduces the reference's server-side update: when an
optimizer is attached, ``push`` applies it to the stored weight and
``pull`` returns weights (the ``update_on_kvstore`` path of
``Module``/``Trainer``).
"""
from __future__ import annotations

import warnings
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .base import MXNetError, _as_list
from . import ndarray as nd
from . import optimizer as opt_mod
from .ndarray.ndarray import NDArray

__all__ = ["KVStore", "create"]


# ----------------------------------------------------------------------
# gradient compression (reference ``src/kvstore/gradient_compression.cc``†)
# ----------------------------------------------------------------------
@jax.jit
def _quantize_2bit(g, residual, threshold):
    """2-bit quantization with error feedback: accumulate the residual,
    emit {-threshold, 0, +threshold}, keep the quantization error."""
    acc = g + residual
    comp = jnp.where(acc >= threshold, threshold,
                     jnp.where(acc <= -threshold, -threshold,
                               jnp.zeros_like(acc)))
    return comp, acc - comp


@jax.jit
def _quantize_1bit(g, residual, threshold):
    """1-bit (signSGD-style) quantization with error feedback: emit
    ±threshold by sign of the accumulated gradient."""
    acc = g + residual
    comp = jnp.where(acc >= 0, threshold, -threshold)
    return comp, acc - comp


class KVStore:
    """In-process key-value store with reference semantics."""

    def __init__(self, name: str = "local"):
        self._type = name
        self._store: Dict[Any, NDArray] = {}
        self._updater = None
        self._optimizer = None
        self._compression = {}
        self._residuals: Dict[Any, jax.Array] = {}
        self._slot_counts: Dict[Any, int] = {}

    # ------------------------------------------------------------------
    @property
    def type(self) -> str:
        return self._type

    @property
    def rank(self) -> int:
        return jax.process_index() if self._type.startswith("dist") else 0

    @property
    def num_workers(self) -> int:
        return jax.process_count() if self._type.startswith("dist") else 1

    @property
    def num_devices(self) -> int:
        return jax.device_count()

    # ------------------------------------------------------------------
    def init(self, key, value) -> None:
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            if k in self._store:
                continue
            vv = _as_list(v)[0]
            self._store[k] = vv.copy()
            # fresh key = fresh compression state
            self._slot_counts.pop(k, None)
            for rk in [rk for rk in self._residuals if rk[0] == k]:
                del self._residuals[rk]

    def push(self, key, value, priority: int = 0) -> None:
        """Reduce ``value`` (list = per-device grads) into the store;
        with an attached optimizer, apply the update server-side."""
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            parts = _as_list(v)
            if self._compression:
                nslots = self._slot_counts.setdefault(k, len(parts))
                if nslots != len(parts):
                    raise MXNetError(
                        f"gradient compression: key {k!r} was pushed "
                        f"with {nslots} device parts before, now "
                        f"{len(parts)} — per-slot residuals would be "
                        f"misattributed; call set_gradient_compression "
                        f"again after a device-set change to reset "
                        f"residuals")
                parts = [self._compress(k, i, p)
                         for i, p in enumerate(parts)]
            reduced = parts[0]
            for p in parts[1:]:
                reduced = reduced + p
            reduced = self._cross_process_sum(reduced)
            if self._updater is not None:
                if k not in self._store:
                    raise MXNetError(f"key {k} not init()ed")
                self._updater(self._key_int(k), reduced, self._store[k])
            else:
                self._store[k] = reduced.copy()

    def pull(self, key, out=None, priority: int = 0,
             ignore_sparse: bool = True):
        keys, outs = self._normalize(key, out)
        results = []
        for k, o in zip(keys, outs):
            if k not in self._store:
                raise MXNetError(f"key {k} not init()ed")
            val = self._store[k]
            for dst in _as_list(o):
                if dst is not None:
                    dst._data = val.data
            results.append(val)
        return results if out is None else None

    def pushpull(self, key, value, out=None, priority: int = 0):
        self.push(key, value, priority)
        self.pull(key, out if out is not None else value, priority)

    def row_sparse_pull(self, key, out=None, priority: int = 0,
                        row_ids=None):
        """Sparse pull degenerates to dense pull (TPU has no sparse
        storage; SURVEY.md §7 hard-part 3)."""
        self.pull(key, out=out, priority=priority)

    # ------------------------------------------------------------------
    def set_optimizer(self, optimizer) -> None:
        """Run the optimizer "server-side" on push (reference
        ``kvstore_dist_server.h``† behavior, `update_on_kvstore`).

        The in-graph form of this contract is ``mxtpu.parallel``'s
        ZeRO-1 mode (``TrainStep`` on a dp mesh): the ``dist_sync``
        server that owns a parameter shard and updates it where it
        lives becomes a reduce-scatter to the shard's device, a
        shard-local optimizer update, and an all-gather of the fresh
        params — same placement semantics, compiled into the step."""
        self._optimizer = optimizer
        self._updater = opt_mod.get_updater(optimizer)

    def set_gradient_compression(self, compression_params) -> None:
        """Enable gradient compression on push (reference
        ``GradientCompression``†): ``{'type': '2bit', 'threshold': t}``
        quantizes each pushed gradient to {-t, 0, +t} with an
        error-feedback residual kept per (key, device slot);
        ``'1bit'`` emits ±t by sign.  Numerics match the reference's
        worker-side quantize→aggregate; on a TPU slice the bytes still
        ride ICI uncompressed (no PCIe to save), so the value here is
        algorithmic parity, not transport savings."""
        params = dict(compression_params or {})
        if not params:
            # explicit empty request = no compression (old behaviour)
            self._compression = {}
            self._residuals.clear()
            self._slot_counts.clear()
            return
        unknown = set(params) - {"type", "threshold"}
        if unknown:
            raise MXNetError(
                f"unknown compression params {sorted(unknown)}; "
                f"supported keys: 'type', 'threshold'")
        if "type" not in params:
            raise MXNetError(
                "compression_params requires an explicit 'type' "
                "('2bit' or '1bit')")
        ctype = params["type"]
        if ctype not in ("2bit", "1bit"):
            raise MXNetError(
                f"unsupported compression type {ctype!r}; "
                f"supported: '2bit', '1bit'")
        threshold = float(params.get("threshold", 0.5))
        if threshold <= 0:
            raise MXNetError("compression threshold must be positive")
        self._compression = {"type": ctype, "threshold": threshold}
        self._residuals.clear()
        self._slot_counts.clear()

    def _compress(self, key, slot, grad: NDArray) -> NDArray:
        raw = grad.data if isinstance(grad, NDArray) else jnp.asarray(grad)
        rk = (key, slot)
        res = self._residuals.get(rk)
        if res is not None and res.shape != raw.shape:
            raise MXNetError(
                f"gradient compression: key {key!r} slot {slot} shape "
                f"changed {res.shape} -> {raw.shape}; call "
                f"set_gradient_compression again to reset residuals")
        res_raw = res if res is not None else jnp.zeros_like(raw)
        fn = _quantize_2bit if self._compression["type"] == "2bit" \
            else _quantize_1bit
        comp, new_res = fn(raw, res_raw,
                           jnp.asarray(self._compression["threshold"],
                                       raw.dtype))
        self._residuals[rk] = new_res
        return NDArray(comp, None, _placed=True)

    # ------------------------------------------------------------------
    def save_optimizer_states(self, fname, dump_optimizer=False) -> None:
        if self._updater is None:
            raise MXNetError("no optimizer set on kvstore")
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname) -> None:
        if self._updater is None:
            raise MXNetError("no optimizer set on kvstore")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    def _cross_process_sum(self, reduced: NDArray) -> NDArray:
        """``dist_*`` stores reduce across worker processes too: an
        all-gather over DCN (``jax.distributed`` must be initialised by
        the launcher) followed by a sum.  Single-process runs are a
        no-op, so the same code path works under local testing."""
        if not self._type.startswith("dist") or jax.process_count() == 1:
            return reduced
        from jax.experimental import multihost_utils
        gathered = multihost_utils.process_allgather(reduced.data)
        return NDArray(gathered.sum(axis=0), None, _placed=True)

    # ------------------------------------------------------------------
    def barrier(self) -> None:
        nd.waitall()
        # Only dist_* stores participate in the global sync point —
        # a local store's barrier on one process of a multi-host job
        # must not block on peers that never reach it.
        if self._type.startswith("dist") and jax.process_count() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("mxtpu.kvstore.barrier")

    def _key_int(self, k):
        try:
            return int(k)
        except (TypeError, ValueError):
            return k

    @staticmethod
    def _normalize(key, value):
        if isinstance(key, (list, tuple)):
            if value is None:
                return list(key), [None] * len(key)
            if len(key) != len(value):
                raise MXNetError("key/value length mismatch")
            return list(key), list(value)
        return [key], [value]


def create(name: str = "local") -> KVStore:
    """Reference ``mx.kv.create``†."""
    if not isinstance(name, str):
        raise MXNetError("name must be a string")
    known = ("local", "device", "nccl", "local_allreduce_cpu",
             "local_allreduce_device", "dist_sync", "dist_device_sync",
             "dist_async")
    if name not in known:
        raise MXNetError(f"unknown kvstore type {name!r}")
    if name == "dist_async":
        warnings.warn(
            "dist_async has no TPU analogue (SPMD collectives are "
            "synchronous); creating a synchronous store — see SURVEY.md "
            "§7 hard-part 4")
    return KVStore(name)
