"""Custom operators defined in Python (reference
``python/mxnet/operator.py``† over ``src/operator/custom/custom.cc``†).

TPU-native note: custom python ops are host callbacks by definition —
they execute eagerly on materialized arrays (the reference runs them on
a dedicated callback thread for the same reason).  They compose with
autograd through the same tape as every other op, but are opaque to
``hybridize()``/jit (use ``mxtpu.rtc.PallasKernel`` or a registry
lowering rule for compiled custom ops).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Type

import numpy as np

from .base import MXNetError, Registry
from . import autograd
from .ndarray import NDArray, array

__all__ = ["CustomOp", "CustomOpProp", "register", "get_custom_op",
           "Custom"]

_CUSTOM_REGISTRY: Registry = Registry("custom_op")


class CustomOp:
    """Base custom operator (reference ``mx.operator.CustomOp``†)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst: NDArray, req: str, src) -> None:
        """Write ``src`` into ``dst`` honouring the grad request
        (reference ``assign``†)."""
        if req == "null":
            return
        src_nd = src if isinstance(src, NDArray) else array(src)
        if req == "add":
            dst._data = dst._data + src_nd._data
        else:  # write / inplace
            dst._data = src_nd._data


class CustomOpProp:
    """Operator properties: arity, shapes, op factory
    (reference ``mx.operator.CustomOpProp``†)."""

    def __init__(self, need_top_grad: bool = True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self) -> List[str]:
        return ["data"]

    def list_outputs(self) -> List[str]:
        return ["output"]

    def list_auxiliary_states(self) -> List[str]:
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), []

    def create_operator(self, ctx, in_shapes, in_dtypes) -> CustomOp:
        raise NotImplementedError


def register(reg_name: str):
    """Decorator registering a CustomOpProp subclass
    (reference ``mx.operator.register``†)."""
    def _wrap(prop_cls: Type[CustomOpProp]):
        _CUSTOM_REGISTRY.register(reg_name)(prop_cls)
        return prop_cls
    return _wrap


def get_custom_op(name: str) -> Type[CustomOpProp]:
    return _CUSTOM_REGISTRY.get(name)


def Custom(*inputs, op_type: str, **kwargs):
    """Run a registered custom op eagerly (the ``mx.nd.Custom``
    surface†).  Differentiable via the autograd tape when recording."""
    prop_cls = get_custom_op(op_type)
    prop = prop_cls(**kwargs)
    in_shapes = [tuple(x.shape) for x in inputs]
    _, out_shapes, aux_shapes = prop.infer_shape(in_shapes)
    op = prop.create_operator(None, in_shapes,
                              [x.dtype for x in inputs])
    out_data = [array(np.zeros(s, np.float32)) for s in out_shapes]
    aux = [array(np.zeros(s, np.float32)) for s in aux_shapes]

    recording = autograd.is_recording() and any(
        autograd._needs_grad(x) for x in inputs)

    class _Bridge(autograd.Function):
        def forward(self, *ins):
            op.forward(is_train=recording,
                       req=["write"] * len(out_data),
                       in_data=list(ins), out_data=out_data, aux=aux)
            self._ins = list(ins)
            return tuple(out_data) if len(out_data) > 1 else out_data[0]

        def backward(self, *ograds):
            in_grads = [array(np.zeros(s, np.float32))
                        for s in in_shapes]
            op.backward(req=["write"] * len(in_grads),
                        out_grad=list(ograds), in_data=self._ins,
                        out_data=out_data, in_grad=in_grads, aux=aux)
            return tuple(in_grads) if len(in_grads) > 1 else in_grads[0]

    if recording:
        return _Bridge()(*inputs)
    op.forward(is_train=False, req=["write"] * len(out_data),
               in_data=list(inputs), out_data=out_data, aux=aux)
    return tuple(out_data) if len(out_data) > 1 else out_data[0]
