"""Misc utilities (reference ``python/mxnet/util.py``†)."""
from __future__ import annotations

import functools
import inspect
import os

__all__ = ["makedirs", "use_np_shape", "wrap_ctx_to_device_func"]


def makedirs(d: str) -> None:
    """mkdir -p (reference ``util.makedirs``†)."""
    os.makedirs(os.path.expanduser(d), exist_ok=True)


def use_np_shape(func):
    """Numpy-shape-semantics decorator — this framework already uses
    numpy shape semantics everywhere (zero-dim/zero-size arrays are
    native to jax), so this is the identity (reference gates legacy
    shape behavior)."""
    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        return func(*args, **kwargs)
    return wrapper


def wrap_ctx_to_device_func(func):
    """Compatibility alias decorator (ctx= → device=) used by 2.x-era
    code; accepts both spellings."""
    sig_params = inspect.signature(func).parameters

    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        if "device" in kwargs and "device" not in sig_params \
                and "ctx" in sig_params:
            kwargs["ctx"] = kwargs.pop("device")
        return func(*args, **kwargs)
    return wrapper
