"""``mxtpu.obs`` — the one observability layer (ISSUE 8).

Three surfaces behind one switch (``MXTPU_OBS``, default on):

* **Metrics registry** (:mod:`.metrics`) — typed counters / gauges /
  histograms with label sets, O(1) under leaf locks, exported as
  Prometheus text (:func:`prometheus_text`) and a JSON snapshot
  (:func:`snapshot`) that carry the same values.  ``ServingStats``,
  the fleet counters, ``guards.ChurnDetector``, ``DeviceFeedIter``
  and ``TrainStep`` all publish here.
* **Per-request tracing** (:mod:`.trace`) — trace ids minted at
  submit, phase spans through the chrome-trace profiler,
  :func:`trace_of` to rebuild one request's timeline.
* **Flight recorder** (:mod:`.recorder`) — bounded per-worker ring of
  structured events (health transitions, canary results, compile
  misses, evictions, fault firings), dumped on worker death or
  ``MXTPU_OBS_DUMP_ON_ERROR``.

Zero-overhead-when-off contract (guards-style, asserted by
:func:`self_check` which ``bench.py`` runs at import): with
``MXTPU_OBS=0`` the factories return the SHARED no-op singletons
(:data:`metrics.NULL_COUNTER` …, :data:`recorder.NULL_RECORDER`) — no
registration, no locks, no allocation on the hot path — and results
of any serving/training computation are bit-identical on vs off
(observability never touches what is computed).

Naming convention: ``mxtpu_<subsystem>_<metric>[_total|_seconds|_us|
_bytes]`` — enforced at creation here and statically by the
``obs-registry`` mxlint rule.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional, Sequence

from .. import knobs
from ..base import MXNetError
from . import metrics as metrics
from . import recorder as recorder
from . import trace as trace
from .metrics import (DEFAULT_BUCKETS, MetricsRegistry, NULL_COUNTER,
                      NULL_GAUGE, NULL_HISTOGRAM,
                      parse_prometheus_text, samples_from_snapshot)
from .recorder import NULL_RECORDER, FlightRecorder
from .trace import (SPAN_BACKOFF, SPAN_EXECUTE, SPAN_HEDGE,
                    SPAN_PAD_SCATTER, SPAN_QUEUE_WAIT, SPAN_REDISPATCH,
                    SPAN_REQUEUE, SPAN_RUN, SPAN_SCALE, SPAN_SHED,
                    SPAN_STEAL, SPAN_SUBMIT,
                    new_trace_id, span, trace_of)

__all__ = [
    "enabled", "registry", "counter", "gauge", "histogram",
    "prometheus_text", "snapshot", "summary", "reset",
    "flight", "flight_recorders", "dump_all", "dump_on_error_path",
    "new_trace_id", "span", "trace_of", "self_check",
    "MetricsRegistry", "FlightRecorder",
    "NULL_COUNTER", "NULL_GAUGE", "NULL_HISTOGRAM", "NULL_RECORDER",
    "SPAN_SUBMIT", "SPAN_QUEUE_WAIT", "SPAN_EXECUTE", "SPAN_BACKOFF",
    "SPAN_STEAL", "SPAN_REDISPATCH", "SPAN_HEDGE", "SPAN_PAD_SCATTER",
    "SPAN_RUN", "SPAN_REQUEUE", "SPAN_SHED", "SPAN_SCALE",
]

_REGISTRY = MetricsRegistry()
_FLIGHT_LOCK = threading.Lock()
_FLIGHT: Dict[str, FlightRecorder] = {}  # guarded-by: _FLIGHT_LOCK


def enabled() -> bool:
    """Observability on?  ``MXTPU_OBS`` (default on; ``0`` = off)."""
    return bool(knobs.get("MXTPU_OBS"))


def registry() -> MetricsRegistry:
    """The process-wide metrics registry (always the real one —
    gating happens in the factory functions below)."""
    return _REGISTRY


# -- instrument factories (the only sanctioned way to make metrics) ----
def counter(name: str, help: str = "", labels: Sequence[str] = (),
            enabled_override: Optional[bool] = None):
    """Get-or-create a process-wide counter; the shared no-op when
    obs is off.  Construct once (init time), ``inc()`` on hot paths."""
    on = enabled() if enabled_override is None else enabled_override
    if not on:
        return NULL_COUNTER
    return _REGISTRY.counter(name, help, labels)


def gauge(name: str, help: str = "", labels: Sequence[str] = (),
          enabled_override: Optional[bool] = None):
    on = enabled() if enabled_override is None else enabled_override
    if not on:
        return NULL_GAUGE
    return _REGISTRY.gauge(name, help, labels)


def histogram(name: str, help: str = "", labels: Sequence[str] = (),
              buckets: Sequence[float] = DEFAULT_BUCKETS,
              enabled_override: Optional[bool] = None):
    on = enabled() if enabled_override is None else enabled_override
    if not on:
        return NULL_HISTOGRAM
    return _REGISTRY.histogram(name, help, labels, buckets)


# -- export surfaces ---------------------------------------------------
def prometheus_text() -> str:
    return _REGISTRY.prometheus_text()


def snapshot() -> Dict[str, Any]:
    return _REGISTRY.snapshot()


def summary() -> Dict[str, Any]:
    """Flat ``{name{labels}: value-or-histogram-summary}`` view (the
    shape bench.py embeds in every row's ``details["obs"]``)."""
    return _REGISTRY.summary()


def reset() -> None:
    """Tests only: drop all metric families and flight recorders."""
    _REGISTRY.reset()
    with _FLIGHT_LOCK:
        _FLIGHT.clear()


# -- flight recorders --------------------------------------------------
def flight(name: str, capacity: Optional[int] = None,
           clock: Optional[Callable[[], float]] = None,
           enabled_override: Optional[bool] = None):
    """Get-or-create the named flight recorder; the shared no-op when
    obs is off.  ``clock`` only applies on first creation (fleet
    workers pass their injected clock for deterministic tests)."""
    on = enabled() if enabled_override is None else enabled_override
    if not on:
        return NULL_RECORDER
    with _FLIGHT_LOCK:
        rec = _FLIGHT.get(name)
        if rec is None:
            kw: Dict[str, Any] = {"capacity": capacity}
            if clock is not None:
                kw["clock"] = clock
            rec = _FLIGHT[name] = FlightRecorder(name, **kw)
        return rec


def flight_recorders() -> Dict[str, FlightRecorder]:
    with _FLIGHT_LOCK:
        return dict(_FLIGHT)


def dump_all(reason: str = "", path: Optional[str] = None
             ) -> Dict[str, str]:
    """Dump every live flight recorder (``{name: json}``)."""
    return {name: rec.dump(reason, path=path)
            for name, rec in flight_recorders().items()}


def dump_on_error_path() -> Optional[str]:
    """``MXTPU_OBS_DUMP_ON_ERROR`` decoded: None = off, "" = log
    only, a string = also write JSON under that directory."""
    raw = str(knobs.get("MXTPU_OBS_DUMP_ON_ERROR")).strip()
    if not raw or raw.lower() in ("0", "false", "no", "off"):
        return None
    if raw.lower() in ("1", "true", "yes", "on", "stderr"):
        return ""
    return raw


# -- self check --------------------------------------------------------
def self_check(probe: bool = False) -> Dict[str, Any]:
    """The import-time assertion bench.py runs (mirror of
    ``guards.self_check``):

    * disabled ⇒ every factory returns its SHARED no-op singleton
      (no allocation, no registration — zero overhead);
    * the two export surfaces agree: a parsed Prometheus text dump
      carries exactly the samples a flattened JSON snapshot does
      (exercised on a private throwaway registry);
    * ``probe=True`` additionally dispatches a tiny jitted computation
      with instruments firing around it and asserts bit-identical
      results vs the bare run (obs never touches what is computed).
    """
    if counter("mxtpu_self_check_total",
               enabled_override=False) is not NULL_COUNTER \
            or gauge("mxtpu_self_check",
                     enabled_override=False) is not NULL_GAUGE \
            or histogram("mxtpu_self_check_seconds",
                         enabled_override=False) is not NULL_HISTOGRAM:
        raise MXNetError(
            "obs self_check: disabled metric factory is not the "
            "shared no-op singleton")
    if flight("self_check",
              enabled_override=False) is not NULL_RECORDER:
        raise MXNetError(
            "obs self_check: disabled flight factory is not the "
            "shared no-op recorder")

    # Round-trip on a private registry (never pollutes the process one)
    reg = MetricsRegistry()
    c = reg.counter("mxtpu_selfcheck_events_total", "probe",
                    labels=("kind",))
    c.labels(kind="a").inc(3)
    c.labels(kind='b"\\esc\n').inc()
    reg.gauge("mxtpu_selfcheck_depth", "probe").set(-2.5)
    h = reg.histogram("mxtpu_selfcheck_lat_seconds", "probe",
                      buckets=(0.001, 0.1, 2.0))
    for v in (0.0005, 0.05, 0.05, 5.0):
        h.observe(v)
    # The compile-cache naming shapes (ISSUE 13): a source-labeled
    # compile-seconds histogram (source=cold|disk) and a hit counter
    # beside the churn guard's miss counter — asserted here so the
    # exposition surfaces keep agreeing on multi-label histograms too.
    hc = reg.histogram("mxtpu_selfcheck_compile_seconds", "probe",
                       labels=("entry", "source"),
                       buckets=(0.1, 1.0, 10.0))
    hc.labels(entry="(8, 16)", source="cold").observe(2.0)
    hc.labels(entry="(8, 16)", source="disk").observe(0.01)
    reg.counter("mxtpu_selfcheck_cache_hit_total", "probe",
                labels=("entry",)).labels(entry="(8, 16)").inc()
    text_samples = parse_prometheus_text(reg.prometheus_text())
    snap_samples = samples_from_snapshot(reg.snapshot())
    if text_samples != snap_samples:
        raise MXNetError(
            f"obs self_check: exposition surfaces disagree — "
            f"text={text_samples} snapshot={snap_samples}")

    info: Dict[str, Any] = {
        "enabled": enabled(),
        "flight_capacity": int(knobs.get("MXTPU_OBS_FLIGHT_CAPACITY")),
        "round_trip_samples": len(text_samples),
    }
    if probe:
        import jax
        import jax.numpy as jnp
        import numpy as np
        fn = jax.jit(lambda v: v * 3 - 1)
        x = jnp.arange(8, dtype=jnp.float32)
        bare = np.asarray(fn(x))
        reg2 = MetricsRegistry()
        pc = reg2.counter("mxtpu_selfcheck_probe_total")
        ph = reg2.histogram("mxtpu_selfcheck_probe_seconds")
        pc.inc()
        instrumented = np.asarray(fn(x))
        ph.observe(0.0)
        if not np.array_equal(bare, instrumented):
            raise MXNetError(
                "obs self_check: instrumented dispatch changed "
                "results")
        info["probe"] = True
    return info
