"""``mxtpu.obs`` — the one observability layer (ISSUE 8).

Three surfaces behind one switch (``MXTPU_OBS``, default on):

* **Metrics registry** (:mod:`.metrics`) — typed counters / gauges /
  histograms with label sets, O(1) under leaf locks, exported as
  Prometheus text (:func:`prometheus_text`) and a JSON snapshot
  (:func:`snapshot`) that carry the same values.  ``ServingStats``,
  the fleet counters, ``guards.ChurnDetector``, ``DeviceFeedIter``
  and ``TrainStep`` all publish here.
* **Per-request tracing** (:mod:`.trace`) — trace ids minted at
  submit, phase spans through the chrome-trace profiler,
  :func:`trace_of` to rebuild one request's timeline.
* **Flight recorder** (:mod:`.recorder`) — bounded per-worker ring of
  structured events (health transitions, canary results, compile
  misses, evictions, fault firings), dumped on worker death or
  ``MXTPU_OBS_DUMP_ON_ERROR``.

Zero-overhead-when-off contract (guards-style, asserted by
:func:`self_check` which ``bench.py`` runs at import): with
``MXTPU_OBS=0`` the factories return the SHARED no-op singletons
(:data:`metrics.NULL_COUNTER` …, :data:`recorder.NULL_RECORDER`) — no
registration, no locks, no allocation on the hot path — and results
of any serving/training computation are bit-identical on vs off
(observability never touches what is computed).

Naming convention: ``mxtpu_<subsystem>_<metric>[_total|_seconds|_us|
_bytes]`` — enforced at creation here and statically by the
``obs-registry`` mxlint rule.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional, Sequence

from .. import knobs
from ..base import MXNetError
from . import http as http
from . import metrics as metrics
from . import recorder as recorder
from . import slo as slo
from . import timeseries as timeseries
from . import trace as trace
from .http import NULL_SERVER, DebugServer
from .metrics import (DEFAULT_BUCKETS, MetricsRegistry, NULL_COUNTER,
                      NULL_GAUGE, NULL_HISTOGRAM, bucket_quantile,
                      parse_prometheus_text, percentile,
                      samples_from_snapshot)
from .recorder import NULL_RECORDER, FlightRecorder
from .slo import (DEFAULT_RULES, NULL_SLO_ENGINE, AvailabilitySLO,
                  BurnRateRule, LatencySLO, SLOEngine,
                  parse_slo_classes)
from .timeseries import NULL_SAMPLER, Sampler
from .trace import (SPAN_BACKOFF, SPAN_EXECUTE, SPAN_HEDGE,
                    SPAN_PAD_SCATTER, SPAN_PREFILL, SPAN_QUEUE_WAIT,
                    SPAN_REDISPATCH, SPAN_REPLAY, SPAN_REQUEUE,
                    SPAN_RUN, SPAN_SCALE, SPAN_SHED, SPAN_STEAL,
                    SPAN_SUBMIT, SPAN_TOKEN,
                    new_trace_id, span, trace_of)

__all__ = [
    "enabled", "registry", "counter", "gauge", "histogram",
    "prometheus_text", "snapshot", "summary", "reset",
    "flight", "flight_recorders", "dump_all", "dump_on_error_path",
    "new_trace_id", "span", "trace_of", "self_check",
    "sampler", "slo_engine", "debug_server",
    "MetricsRegistry", "FlightRecorder", "Sampler", "SLOEngine",
    "DebugServer", "AvailabilitySLO", "LatencySLO", "BurnRateRule",
    "DEFAULT_RULES", "parse_slo_classes",
    "percentile", "bucket_quantile",
    "NULL_COUNTER", "NULL_GAUGE", "NULL_HISTOGRAM", "NULL_RECORDER",
    "NULL_SAMPLER", "NULL_SLO_ENGINE", "NULL_SERVER",
    "SPAN_SUBMIT", "SPAN_QUEUE_WAIT", "SPAN_EXECUTE", "SPAN_BACKOFF",
    "SPAN_STEAL", "SPAN_REDISPATCH", "SPAN_HEDGE", "SPAN_PAD_SCATTER",
    "SPAN_RUN", "SPAN_REQUEUE", "SPAN_SHED", "SPAN_SCALE",
    "SPAN_PREFILL", "SPAN_TOKEN", "SPAN_REPLAY",
]

_REGISTRY = MetricsRegistry()
_FLIGHT_LOCK = threading.Lock()
_FLIGHT: Dict[str, FlightRecorder] = {}  # guarded-by: _FLIGHT_LOCK
_SAMPLER: Optional[Sampler] = None       # guarded-by: _FLIGHT_LOCK


def enabled() -> bool:
    """Observability on?  ``MXTPU_OBS`` (default on; ``0`` = off)."""
    return bool(knobs.get("MXTPU_OBS"))


def registry() -> MetricsRegistry:
    """The process-wide metrics registry (always the real one —
    gating happens in the factory functions below)."""
    return _REGISTRY


# -- instrument factories (the only sanctioned way to make metrics) ----
def counter(name: str, help: str = "", labels: Sequence[str] = (),
            enabled_override: Optional[bool] = None):
    """Get-or-create a process-wide counter; the shared no-op when
    obs is off.  Construct once (init time), ``inc()`` on hot paths."""
    on = enabled() if enabled_override is None else enabled_override
    if not on:
        return NULL_COUNTER
    return _REGISTRY.counter(name, help, labels)


def gauge(name: str, help: str = "", labels: Sequence[str] = (),
          enabled_override: Optional[bool] = None):
    on = enabled() if enabled_override is None else enabled_override
    if not on:
        return NULL_GAUGE
    return _REGISTRY.gauge(name, help, labels)


def histogram(name: str, help: str = "", labels: Sequence[str] = (),
              buckets: Sequence[float] = DEFAULT_BUCKETS,
              enabled_override: Optional[bool] = None):
    on = enabled() if enabled_override is None else enabled_override
    if not on:
        return NULL_HISTOGRAM
    return _REGISTRY.histogram(name, help, labels, buckets)


# -- export surfaces ---------------------------------------------------
def prometheus_text() -> str:
    return _REGISTRY.prometheus_text()


def snapshot() -> Dict[str, Any]:
    return _REGISTRY.snapshot()


def summary() -> Dict[str, Any]:
    """Flat ``{name{labels}: value-or-histogram-summary}`` view (the
    shape bench.py embeds in every row's ``details["obs"]``)."""
    return _REGISTRY.summary()


def reset() -> None:
    """Tests only: drop all metric families, flight recorders and the
    process sampler."""
    global _SAMPLER
    _REGISTRY.reset()
    with _FLIGHT_LOCK:
        _FLIGHT.clear()
        _SAMPLER = None


# -- flight recorders --------------------------------------------------
def flight(name: str, capacity: Optional[int] = None,
           clock: Optional[Callable[[], float]] = None,
           enabled_override: Optional[bool] = None):
    """Get-or-create the named flight recorder; the shared no-op when
    obs is off.  ``clock`` only applies on first creation (fleet
    workers pass their injected clock for deterministic tests)."""
    on = enabled() if enabled_override is None else enabled_override
    if not on:
        return NULL_RECORDER
    with _FLIGHT_LOCK:
        rec = _FLIGHT.get(name)
        if rec is None:
            kw: Dict[str, Any] = {"capacity": capacity}
            if clock is not None:
                kw["clock"] = clock
            rec = _FLIGHT[name] = FlightRecorder(name, **kw)
        return rec


def flight_recorders() -> Dict[str, FlightRecorder]:
    with _FLIGHT_LOCK:
        return dict(_FLIGHT)


def dump_all(reason: str = "", path: Optional[str] = None
             ) -> Dict[str, str]:
    """Dump every live flight recorder (``{name: json}``)."""
    return {name: rec.dump(reason, path=path)
            for name, rec in flight_recorders().items()}


# -- time-series sampler / SLO engine / debug server (ISSUE 14) --------
def sampler(period_us: Optional[float] = None,
            capacity: Optional[int] = None,
            clock: Optional[Callable[[], float]] = None,
            enabled_override: Optional[bool] = None):
    """Get-or-create the process-wide :class:`~.timeseries.Sampler`
    over the process registry; the shared no-op when obs is off.
    Like :func:`flight`, ``period_us``/``capacity``/``clock`` only
    apply on first creation (tests pass the fake clock)."""
    on = enabled() if enabled_override is None else enabled_override
    if not on:
        return NULL_SAMPLER
    global _SAMPLER
    with _FLIGHT_LOCK:
        if _SAMPLER is None:
            kw: Dict[str, Any] = {"period_us": period_us,
                                  "clock": clock}
            if capacity is not None:
                kw["capacity"] = capacity
            _SAMPLER = Sampler(_REGISTRY, **kw)
        return _SAMPLER


_sampler_factory = sampler   # slo_engine's param shadows the name


def slo_engine(slos, sampler=None, *,
               rules=DEFAULT_RULES,
               clock: Optional[Callable[[], float]] = None,
               enabled_override: Optional[bool] = None):
    """Build an :class:`~.slo.SLOEngine` over ``slos``; the shared
    no-op when obs is off.  ``sampler`` defaults to the process
    sampler (:func:`sampler`); wire the result into the fleet with
    ``router.attach_slo(engine)``."""
    on = enabled() if enabled_override is None else enabled_override
    if not on:
        return NULL_SLO_ENGINE
    if sampler is None:
        sampler = _sampler_factory(clock=clock)
    return SLOEngine(slos, sampler, rules=rules, clock=clock)


def debug_server(port: Optional[int] = None, *,
                 host: str = "127.0.0.1", router=None, slo=None,
                 sampler=None,
                 enabled_override: Optional[bool] = None):
    """Start a :class:`~.http.DebugServer` (``/metrics`` ``/varz``
    ``/healthz`` ``/statusz`` ``/tracez``) on a daemon thread; the
    shared no-op when obs is off or the port is negative.  ``port``
    defaults to ``MXTPU_OBS_HTTP_PORT`` (-1 = disabled, 0 =
    ephemeral — read the bound port back from ``server.port``).  The
    caller owns ``close()``."""
    on = enabled() if enabled_override is None else enabled_override
    if not on:
        return NULL_SERVER
    if port is None:
        port = int(knobs.get("MXTPU_OBS_HTTP_PORT"))
    if port < 0:
        return NULL_SERVER
    return DebugServer(port=port, host=host, router=router, slo=slo,
                       sampler=sampler)


def dump_on_error_path() -> Optional[str]:
    """``MXTPU_OBS_DUMP_ON_ERROR`` decoded: None = off, "" = log
    only, a string = also write JSON under that directory."""
    raw = str(knobs.get("MXTPU_OBS_DUMP_ON_ERROR")).strip()
    if not raw or raw.lower() in ("0", "false", "no", "off"):
        return None
    if raw.lower() in ("1", "true", "yes", "on", "stderr"):
        return ""
    return raw


# -- self check --------------------------------------------------------
def self_check(probe: bool = False) -> Dict[str, Any]:
    """The import-time assertion bench.py runs (mirror of
    ``guards.self_check``):

    * disabled ⇒ every factory returns its SHARED no-op singleton
      (no allocation, no registration — zero overhead); ISSUE 14
      extends this to the sampler / SLO-engine / debug-server
      factories, and when obs is off in THIS process the
      un-overridden factories are asserted null too;
    * the two export surfaces agree: a parsed Prometheus text dump
      carries exactly the samples a flattened JSON snapshot does
      (exercised on a private throwaway registry);
    * the operator layers work end to end on a private registry and
      a fake clock: sampler windows (counter rate, histogram bucket
      quantile), a burn-rate alert edge on a driven availability
      SLO, and every HTTP renderer producing parseable output — no
      socket bound;
    * ``probe=True`` additionally dispatches a tiny jitted computation
      with instruments firing around it and asserts bit-identical
      results vs the bare run (obs never touches what is computed).
    """
    if counter("mxtpu_self_check_total",
               enabled_override=False) is not NULL_COUNTER \
            or gauge("mxtpu_self_check",
                     enabled_override=False) is not NULL_GAUGE \
            or histogram("mxtpu_self_check_seconds",
                         enabled_override=False) is not NULL_HISTOGRAM:
        raise MXNetError(
            "obs self_check: disabled metric factory is not the "
            "shared no-op singleton")
    if flight("self_check",
              enabled_override=False) is not NULL_RECORDER:
        raise MXNetError(
            "obs self_check: disabled flight factory is not the "
            "shared no-op recorder")
    if sampler(enabled_override=False) is not NULL_SAMPLER \
            or slo_engine([], enabled_override=False) \
            is not NULL_SLO_ENGINE \
            or debug_server(enabled_override=False) is not NULL_SERVER:
        raise MXNetError(
            "obs self_check: disabled sampler/SLO/HTTP factory is "
            "not its shared no-op singleton")
    if not enabled():
        # the env-driven path, not just the override: with MXTPU_OBS=0
        # the live factories must hand out the same null singletons
        if sampler() is not NULL_SAMPLER \
                or slo_engine([]) is not NULL_SLO_ENGINE \
                or debug_server() is not NULL_SERVER:
            raise MXNetError(
                "obs self_check: MXTPU_OBS=0 but a live factory did "
                "not return its shared no-op singleton")

    # Round-trip on a private registry (never pollutes the process one)
    reg = MetricsRegistry()
    c = reg.counter("mxtpu_selfcheck_events_total", "probe",
                    labels=("kind",))
    c.labels(kind="a").inc(3)
    c.labels(kind='b"\\esc\n').inc()
    reg.gauge("mxtpu_selfcheck_depth", "probe").set(-2.5)
    h = reg.histogram("mxtpu_selfcheck_lat_seconds", "probe",
                      buckets=(0.001, 0.1, 2.0))
    for v in (0.0005, 0.05, 0.05, 5.0):
        h.observe(v)
    # The compile-cache naming shapes (ISSUE 13): a source-labeled
    # compile-seconds histogram (source=cold|disk) and a hit counter
    # beside the churn guard's miss counter — asserted here so the
    # exposition surfaces keep agreeing on multi-label histograms too.
    hc = reg.histogram("mxtpu_selfcheck_compile_seconds", "probe",
                       labels=("entry", "source"),
                       buckets=(0.1, 1.0, 10.0))
    hc.labels(entry="(8, 16)", source="cold").observe(2.0)
    hc.labels(entry="(8, 16)", source="disk").observe(0.01)
    reg.counter("mxtpu_selfcheck_cache_hit_total", "probe",
                labels=("entry",)).labels(entry="(8, 16)").inc()
    text_samples = parse_prometheus_text(reg.prometheus_text())
    snap_samples = samples_from_snapshot(reg.snapshot())
    if text_samples != snap_samples:
        raise MXNetError(
            f"obs self_check: exposition surfaces disagree — "
            f"text={text_samples} snapshot={snap_samples}")

    # -- operator layers (ISSUE 14): sampler windows, a burn-rate
    #    alert edge, and the HTTP renderers — private registry, fake
    #    clock, no socket ------------------------------------------------
    import json as _json
    t = [0.0]
    reg3 = MetricsRegistry()
    smp = Sampler(reg3, capacity=8, period_us=1_000_000,
                  clock=lambda: t[0])
    done = reg3.counter("mxtpu_serving_completed_total", "probe",
                        labels=("endpoint",)).labels(endpoint="fleet")
    tout = reg3.counter("mxtpu_serving_timeout_total", "probe",
                        labels=("endpoint",)).labels(endpoint="fleet")
    lat = reg3.histogram("mxtpu_serving_latency_seconds", "probe",
                         labels=("endpoint",),
                         buckets=(0.01, 0.1, 1.0)
                         ).labels(endpoint="fleet")
    smp.sample(0.0)
    done.inc(10)
    for _ in range(10):
        lat.observe(0.05)
    t[0] = 10.0
    smp.sample(10.0)
    r = smp.rate("mxtpu_serving_completed_total",
                 {"endpoint": "fleet"}, window_s=60.0)
    if r is None or abs(r - 1.0) > 1e-9:
        raise MXNetError(
            f"obs self_check: sampler rate wrong (want 1.0, got {r})")
    q50 = smp.quantile("mxtpu_serving_latency_seconds",
                       {"endpoint": "fleet"}, q=50, window_s=60.0)
    if q50 is None or not 0.01 < q50 <= 0.1:
        raise MXNetError(
            f"obs self_check: sampler quantile wrong (10 samples in "
            f"(0.01, 0.1] but p50={q50})")
    eng = SLOEngine(
        [AvailabilitySLO("selfcheck_avail", objective=0.9)], smp,
        rules=(BurnRateRule(fast_s=5.0, slow_s=30.0, factor=2.0),),
        clock=lambda: t[0],
        alerts=reg3.counter("mxtpu_slo_alerts_total", "probe",
                            labels=("slo", "window")),
        recorder=FlightRecorder("selfcheck/slo", clock=lambda: t[0]))
    tout.inc(40)            # error ratio >> budget in both windows
    t[0] = 12.0
    fired = eng.tick(12.0)
    if not fired or not eng.firing():
        raise MXNetError(
            "obs self_check: burn-rate alert did not fire on a "
            "driven availability SLO (fast+slow windows breached)")
    if parse_prometheus_text(http.render_metrics(reg3)) != \
            samples_from_snapshot(reg3.snapshot()):
        raise MXNetError(
            "obs self_check: /metrics rendering disagrees with the "
            "registry snapshot")
    statusz = _json.loads(http.render_statusz(
        slo=eng, sampler=smp, recorders={}))
    if not statusz["slo"]["firing"]:
        raise MXNetError(
            "obs self_check: /statusz lost the firing SLO alert")
    _json.loads(http.render_varz(reg3))
    _json.loads(http.render_healthz())

    info: Dict[str, Any] = {
        "enabled": enabled(),
        "flight_capacity": int(knobs.get("MXTPU_OBS_FLIGHT_CAPACITY")),
        "round_trip_samples": len(text_samples),
        "slo_probe_alerts": len(fired),
        "sampler_probe_series": smp.summary()["series"],
    }
    if probe:
        import jax
        import jax.numpy as jnp
        import numpy as np
        fn = jax.jit(lambda v: v * 3 - 1)
        x = jnp.arange(8, dtype=jnp.float32)
        bare = np.asarray(fn(x))
        reg2 = MetricsRegistry()
        pc = reg2.counter("mxtpu_selfcheck_probe_total")
        ph = reg2.histogram("mxtpu_selfcheck_probe_seconds")
        pc.inc()
        instrumented = np.asarray(fn(x))
        ph.observe(0.0)
        if not np.array_equal(bare, instrumented):
            raise MXNetError(
                "obs self_check: instrumented dispatch changed "
                "results")
        info["probe"] = True
    return info
