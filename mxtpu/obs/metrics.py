"""Typed process-wide metrics registry (ISSUE 8 tentpole a).

One :class:`MetricsRegistry` per process (``mxtpu.obs`` owns the
default) holding three instrument kinds — :class:`Counter` (monotone),
:class:`Gauge` (set/inc/dec), :class:`Histogram` (fixed buckets +
sum/count) — each with an optional label set.  The hot path is O(1)
under one leaf lock per metric family: label resolution is a dict hit,
an increment is a float add.  Nothing here imports jax.

Naming convention (enforced at creation and by the ``obs-registry``
mxlint rule):

* every metric matches ``^mxtpu_[a-z][a-z0-9_]*$``;
* counters end in ``_total``;
* histograms end in a unit suffix: ``_seconds``, ``_us`` or ``_bytes``.

Two export surfaces are kept equivalent by ``obs.self_check()``:
:meth:`MetricsRegistry.prometheus_text` (Prometheus text exposition)
and :meth:`MetricsRegistry.snapshot` (JSON-able dict) — a parsed text
dump and a flattened snapshot must carry the same sample values
(:func:`parse_prometheus_text` / :func:`samples_from_snapshot`).

Disabled path: ``mxtpu.obs`` hands out the shared :data:`NULL_COUNTER`
/ :data:`NULL_GAUGE` / :data:`NULL_HISTOGRAM` singletons instead of
registering anything — the guards-style zero-overhead contract.
"""
from __future__ import annotations

import re
import threading
from bisect import bisect_left
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from ..base import MXNetError

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "NULL_COUNTER", "NULL_GAUGE", "NULL_HISTOGRAM",
           "parse_prometheus_text", "samples_from_snapshot",
           "DEFAULT_BUCKETS", "percentile", "bucket_quantile"]

_NAME_RE = re.compile(r"^mxtpu_[a-z][a-z0-9_]*$")
_LABEL_RE = re.compile(r"^[a-z_][a-z0-9_]*$")

# Latency-shaped default: 100us .. 10s (seconds).
DEFAULT_BUCKETS: Tuple[float, ...] = (
    1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2, 5e-2,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

_HIST_SUFFIXES = ("_seconds", "_us", "_bytes")


def percentile(sorted_vals: Sequence[float], q: float) -> float:
    """Nearest-rank percentile on a pre-sorted sequence, ``q`` in
    [0, 100].  THE percentile implementation (ISSUE 14 satellite):
    ``ServingStats`` (snapshot p50/p95/p99, ``queue_eta_us``) and the
    time-series sampler delegate here, pinned by an equivalence test
    on shared sample sets."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def bucket_quantile(bounds: Sequence[float],
                    cum_counts: Sequence[float],
                    q: float) -> Optional[float]:
    """Quantile from cumulative histogram bucket counts (Prometheus
    ``histogram_quantile`` style), ``q`` in [0, 100].

    ``bounds`` are the finite upper bounds; ``cum_counts`` has one
    cumulative count per bound plus the trailing ``+Inf`` total —
    exactly the shape :meth:`_HistogramChild._snap` exposes and the
    sampler stores.  Linear interpolation inside the landing bucket
    (from the previous bound, 0 below the first); a quantile landing
    in ``+Inf`` clamps to the largest finite bound.  None when the
    (windowed) histogram is empty."""
    total = float(cum_counts[-1]) if cum_counts else 0.0
    if total <= 0:
        return None
    rank = q / 100.0 * total
    prev_bound, prev_cum = 0.0, 0.0
    for bound, cum in zip(bounds, cum_counts):
        if cum >= rank and cum > prev_cum:
            frac = (rank - prev_cum) / (cum - prev_cum)
            return prev_bound + (bound - prev_bound) * max(0.0, frac)
        prev_bound, prev_cum = float(bound), float(cum)
    return float(bounds[-1]) if bounds else None


def _check_name(name: str, kind: str) -> None:
    if not _NAME_RE.match(name):
        raise MXNetError(
            f"obs: metric name {name!r} violates the naming convention "
            f"(^mxtpu_[a-z][a-z0-9_]*$)")
    if kind == "counter" and not name.endswith("_total"):
        raise MXNetError(
            f"obs: counter {name!r} must end in '_total'")
    if kind == "histogram" and not name.endswith(_HIST_SUFFIXES):
        raise MXNetError(
            f"obs: histogram {name!r} must end in a unit suffix "
            f"{_HIST_SUFFIXES}")


def _fmt(v: float) -> str:
    """Float formatting that round-trips through ``float()`` and
    renders integral values bare (Prometheus style)."""
    if v == float("inf"):
        return "+Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace(
        "\n", "\\n")


def _unescape_label(v: str) -> str:
    out, i = [], 0
    while i < len(v):
        c = v[i]
        if c == "\\" and i + 1 < len(v):
            nxt = v[i + 1]
            out.append({"\\": "\\", '"': '"', "n": "\n"}.get(nxt, nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _label_str(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label(str(v))}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class _Family:
    """A named metric + its per-label-set children.  The family lock
    is a LEAF lock: hold it only for the dict hit / float add, never
    while calling out."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = ()):
        _check_name(name, self.kind)
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise MXNetError(
                    f"obs: bad label name {ln!r} on {name!r}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], Any] = {}  # guarded-by: _lock
        # the unlabeled family IS its own child: created once here and
        # never replaced, so _default() reads it lock-free
        self._unlabeled: Any = None
        if not self.labelnames:
            self._unlabeled = self._children[()] = self._new_child()

    def _new_child(self):
        raise NotImplementedError

    def labels(self, **kw) -> Any:
        """Child for one label-value set (created on first use)."""
        if set(kw) != set(self.labelnames):
            raise MXNetError(
                f"obs: {self.name} takes labels {self.labelnames}, "
                f"got {tuple(sorted(kw))}")
        key = tuple(str(kw[ln]) for ln in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._new_child()
            return child

    def _default(self):
        if self.labelnames:
            raise MXNetError(
                f"obs: {self.name} is labeled {self.labelnames}; "
                f"use .labels(...)")
        return self._unlabeled

    def _series(self) -> List[Tuple[Dict[str, str], Any]]:
        with self._lock:
            items = list(self._children.items())
        return [(dict(zip(self.labelnames, key)), child)
                for key, child in items]


class _CounterChild:
    __slots__ = ("_v", "_lock")

    def __init__(self, lock: threading.Lock):
        self._v = 0.0            # guarded-by: _lock
        self._lock = lock

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise MXNetError("obs: counters only go up (inc(n>=0))")
        with self._lock:
            self._v += n

    def value(self) -> float:
        with self._lock:
            return self._v


class Counter(_Family):
    kind = "counter"

    def _new_child(self) -> _CounterChild:
        return _CounterChild(self._lock)

    def inc(self, n: float = 1.0) -> None:
        self._default().inc(n)

    def value(self) -> float:
        return self._default().value()


class _GaugeChild:
    __slots__ = ("_v", "_lock")

    def __init__(self, lock: threading.Lock):
        self._v = 0.0            # guarded-by: _lock
        self._lock = lock

    def set(self, v: float) -> None:
        with self._lock:
            self._v = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._v += n

    def dec(self, n: float = 1.0) -> None:
        with self._lock:
            self._v -= n

    def value(self) -> float:
        with self._lock:
            return self._v


class Gauge(_Family):
    kind = "gauge"

    def _new_child(self) -> _GaugeChild:
        return _GaugeChild(self._lock)

    def set(self, v: float) -> None:
        self._default().set(v)

    def inc(self, n: float = 1.0) -> None:
        self._default().inc(n)

    def dec(self, n: float = 1.0) -> None:
        self._default().dec(n)

    def value(self) -> float:
        return self._default().value()


class _HistogramChild:
    __slots__ = ("_bounds", "_counts", "_sum", "_count", "_lock")

    def __init__(self, bounds: Tuple[float, ...],
                 lock: threading.Lock):
        self._bounds = bounds
        # one slot per finite bound + the +Inf overflow slot
        self._counts = [0] * (len(bounds) + 1)  # guarded-by: _lock
        self._sum = 0.0          # guarded-by: _lock
        self._count = 0          # guarded-by: _lock
        self._lock = lock

    def observe(self, v: float) -> None:
        i = bisect_left(self._bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    def summary(self) -> Dict[str, float]:
        with self._lock:
            n, s = self._count, self._sum
        return {"count": n, "sum": s,
                "mean": (s / n) if n else 0.0}

    def _snap(self) -> Dict[str, Any]:
        with self._lock:
            counts, s, n = list(self._counts), self._sum, self._count
        cum, buckets = 0, {}
        for bound, c in zip(self._bounds, counts):
            cum += c
            buckets[_fmt(bound)] = cum
        buckets["+Inf"] = n
        return {"buckets": buckets, "sum": s, "count": n}


class Histogram(_Family):
    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = (),
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise MXNetError(f"obs: histogram {name!r} needs buckets")
        self._bounds = bounds
        super().__init__(name, help, labelnames)

    def _new_child(self) -> _HistogramChild:
        return _HistogramChild(self._bounds, self._lock)

    def observe(self, v: float) -> None:
        self._default().observe(v)

    def summary(self) -> Dict[str, float]:
        return self._default().summary()


class _NullChild:
    """Shared no-op instrument: every method accepts anything and does
    nothing; ``labels()`` returns itself so call sites never branch."""

    __slots__ = ()

    def labels(self, **kw) -> "_NullChild":
        return self

    def inc(self, n: float = 1.0) -> None:
        pass

    def dec(self, n: float = 1.0) -> None:
        pass

    def set(self, v: float) -> None:
        pass

    def observe(self, v: float) -> None:
        pass

    def value(self) -> float:
        return 0.0

    def summary(self) -> Dict[str, float]:
        return {"count": 0, "sum": 0.0, "mean": 0.0}


NULL_COUNTER = _NullChild()
NULL_GAUGE = _NullChild()
NULL_HISTOGRAM = _NullChild()


class MetricsRegistry:
    """Name → family map; get-or-create semantics so any module can
    declare its instruments idempotently at construction time."""

    _KINDS = {"counter": Counter, "gauge": Gauge,
              "histogram": Histogram}

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Family] = {}  # guarded-by: _lock

    def _get_or_create(self, kind: str, name: str, help: str,
                       labels: Sequence[str],
                       **kw) -> _Family:
        with self._lock:
            fam = self._metrics.get(name)
            if fam is not None:
                if fam.kind != kind:
                    raise MXNetError(
                        f"obs: {name!r} already registered as "
                        f"{fam.kind}, requested {kind}")
                if fam.labelnames != tuple(labels):
                    raise MXNetError(
                        f"obs: {name!r} already registered with labels "
                        f"{fam.labelnames}, requested {tuple(labels)}")
                return fam
            fam = self._KINDS[kind](name, help, labels, **kw)
            self._metrics[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = ()) -> Counter:
        return self._get_or_create("counter", name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = ()) -> Gauge:
        return self._get_or_create("gauge", name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS
                  ) -> Histogram:
        return self._get_or_create("histogram", name, help, labels,
                                   buckets=buckets)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def _families(self) -> List[_Family]:
        with self._lock:
            return [self._metrics[n] for n in sorted(self._metrics)]

    def reset(self) -> None:
        """Drop every registered family (tests only)."""
        with self._lock:
            self._metrics.clear()

    # -- export surfaces -------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """JSON-able dump: ``{name: {type, help, series: [...]}}``.
        Counter/gauge series carry ``value``; histogram series carry
        cumulative ``buckets`` + ``sum`` + ``count`` — the exact
        numbers :meth:`prometheus_text` exposes."""
        out: Dict[str, Any] = {}
        for fam in self._families():
            series = []
            for labels, child in fam._series():
                entry: Dict[str, Any] = {"labels": labels}
                if fam.kind == "histogram":
                    entry.update(child._snap())
                else:
                    entry["value"] = child.value()
                series.append(entry)
            out[fam.name] = {"type": fam.kind, "help": fam.help,
                             "series": series}
        return out

    def prometheus_text(self) -> str:
        """Prometheus text exposition format (v0.0.4)."""
        lines: List[str] = []
        for fam in self._families():
            if fam.help:
                esc = fam.help.replace("\\", "\\\\").replace(
                    "\n", "\\n")
                lines.append(f"# HELP {fam.name} {esc}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            for labels, child in fam._series():
                if fam.kind == "histogram":
                    snap = child._snap()
                    for le, cum in snap["buckets"].items():
                        bl = dict(labels)
                        bl["le"] = le
                        lines.append(f"{fam.name}_bucket"
                                     f"{_label_str(bl)} {_fmt(cum)}")
                    lines.append(f"{fam.name}_sum{_label_str(labels)} "
                                 f"{_fmt(snap['sum'])}")
                    lines.append(f"{fam.name}_count"
                                 f"{_label_str(labels)} "
                                 f"{_fmt(snap['count'])}")
                else:
                    lines.append(f"{fam.name}{_label_str(labels)} "
                                 f"{_fmt(child.value())}")
        return "\n".join(lines) + "\n"

    def summary(self) -> Dict[str, Any]:
        """Compact flat view for bench rows: counters/gauges map to
        their value, histograms to ``{count, sum, mean}``."""
        out: Dict[str, Any] = {}
        for fam in self._families():
            for labels, child in fam._series():
                key = fam.name + _label_str(labels)
                if fam.kind == "histogram":
                    out[key] = child.summary()
                else:
                    out[key] = child.value()
        return out


# ----------------------------------------------------------------------
# Round-trip helpers (self_check + tests): both export surfaces must
# flatten to the same {(name, labels): value} sample map.
# ----------------------------------------------------------------------
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)$")
_LABEL_PAIR_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _parse_value(raw: str) -> float:
    if raw == "+Inf":
        return float("inf")
    return float(raw)


def parse_prometheus_text(text: str
                          ) -> Dict[Tuple[str, Tuple[Tuple[str, str],
                                                     ...]], float]:
    """Parse an exposition dump back into a flat sample map keyed by
    ``(sample_name, sorted_label_items)``."""
    samples: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            raise MXNetError(f"obs: unparseable exposition line "
                             f"{line!r}")
        name, labelblob, raw = m.groups()
        labels = tuple(sorted(
            (k, _unescape_label(v))
            for k, v in _LABEL_PAIR_RE.findall(labelblob or "")))
        samples[(name, labels)] = _parse_value(raw)
    return samples


def samples_from_snapshot(snap: Dict[str, Any]
                          ) -> Dict[Tuple[str, Tuple[Tuple[str, str],
                                                     ...]], float]:
    """Flatten :meth:`MetricsRegistry.snapshot` into the same sample
    map :func:`parse_prometheus_text` produces."""
    samples: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
    for name, fam in snap.items():
        for entry in fam["series"]:
            base = tuple(sorted((k, str(v))
                                for k, v in entry["labels"].items()))
            if fam["type"] == "histogram":
                for le, cum in entry["buckets"].items():
                    key = tuple(sorted(base + (("le", le),)))
                    samples[(name + "_bucket", key)] = float(cum)
                samples[(name + "_sum", base)] = float(entry["sum"])
                samples[(name + "_count", base)] = float(entry["count"])
            else:
                samples[(name, base)] = float(entry["value"])
    return samples
