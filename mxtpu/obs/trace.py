"""Per-request tracing (ISSUE 8 tentpole b).

A trace id is minted at ``FleetRouter.submit`` (and
``InferenceServer.submit``) and rides the request object through
worker dispatch, batcher queue/assembly and runner execution.  Every
phase of the request's life — queue-wait, pad/scatter, execute,
retry/backoff, hedge, steal/requeue — is emitted as a chrome-trace
span through the existing :mod:`mxtpu.profiler` with
``args={"trace_id": ...}`` (batch-level spans carry
``args={"trace_ids": [...]}``), so one request's full story — a
mid-flight worker kill included — is reconstructible from a single
``profiler.dumps()``; :func:`trace_of` does the reconstruction
in-process.

Emission is gated on ``profiler.is_active()`` BEFORE any args dict is
built, so the profiler-off request path pays one global-bool read.
"""
from __future__ import annotations

import itertools
import os
import threading
from typing import Any, Dict, List, Optional, Sequence

from .. import profiler

__all__ = ["new_trace_id", "span", "trace_of",
           "SPAN_SUBMIT", "SPAN_QUEUE_WAIT", "SPAN_EXECUTE",
           "SPAN_BACKOFF", "SPAN_STEAL", "SPAN_REDISPATCH",
           "SPAN_HEDGE", "SPAN_PAD_SCATTER", "SPAN_RUN",
           "SPAN_REQUEUE", "SPAN_SHED", "SPAN_SCALE",
           "SPAN_PREFILL", "SPAN_TOKEN", "SPAN_REPLAY"]

# Request-phase span names (the committed vocabulary; tests and the
# README's reconstruction example key off these).
SPAN_SUBMIT = "fleet/submit"
SPAN_QUEUE_WAIT = "fleet/queue_wait"
SPAN_EXECUTE = "fleet/execute"
SPAN_BACKOFF = "fleet/backoff"
SPAN_STEAL = "fleet/steal"
SPAN_REDISPATCH = "fleet/redispatch"
SPAN_HEDGE = "fleet/hedge"
SPAN_PAD_SCATTER = "serving/pad_scatter"
SPAN_RUN = "serving/execute"
SPAN_REQUEUE = "serving/requeue"
# control-plane verdicts (ISSUE 11): instant spans, cat="fleet" —
# every shed and scale decision is reconstructable from one dump
SPAN_SHED = "fleet/shed"
SPAN_SCALE = "fleet/scale"
# generation phases (ISSUE 19): prefill (prompt → KV cache + first
# token), one instant span per emitted token, and the replay marker a
# stolen generation leaves when it resumes on a surviving worker —
# trace_of() reconstructs a kill-spanning stream from these
SPAN_PREFILL = "gen/prefill"
SPAN_TOKEN = "gen/token"
SPAN_REPLAY = "gen/replay"

_SEQ = itertools.count(1)
_SEQ_LOCK = threading.Lock()


def new_trace_id() -> str:
    """Process-unique, monotonically ordered id (``r<pid>-<seq>``).
    Deterministic modulo pid — fake-clock tests get stable ids."""
    with _SEQ_LOCK:
        seq = next(_SEQ)
    return f"r{os.getpid():x}-{seq:06d}"


def span(name: str, ts_us: float, dur_us: float,
         trace_id: Optional[str] = None, cat: str = "request",
         **args: Any) -> None:
    """Emit one request-phase span (chrome-trace "X" event) tagged
    with its trace id.  No-op unless the profiler is running — call
    sites may still pre-gate on :func:`mxtpu.profiler.is_active` to
    skip computing ``ts``/``dur``."""
    if not profiler.is_active():
        return
    a: Dict[str, Any] = dict(args)
    if trace_id is not None:
        a["trace_id"] = trace_id
    profiler.record_span(name, ts_us, max(0.0, dur_us), cat=cat,
                         args=a)


def _matches(ev: Dict[str, Any], trace_id: str) -> bool:
    args = ev.get("args")
    if not args:
        return False
    if args.get("trace_id") == trace_id:
        return True
    ids: Sequence[str] = args.get("trace_ids") or ()
    return trace_id in ids


def trace_of(trace_id: str,
             events: Optional[List[Dict[str, Any]]] = None
             ) -> List[Dict[str, Any]]:
    """Timeline of one request: every recorded span whose args carry
    its trace id (directly or in a batch-level ``trace_ids`` list),
    sorted by start timestamp.  Reads the live profiler buffer by
    default; pass ``events`` (e.g. ``json.loads(dump)["traceEvents"]``)
    to reconstruct from a saved trace file instead."""
    if events is None:
        events = profiler.events()
    picked = [ev for ev in events if _matches(ev, trace_id)]
    picked.sort(key=lambda ev: (ev.get("ts", 0.0),
                                ev.get("name", "")))
    return picked
