"""Debug HTTP endpoints over the obs stack (ISSUE 14 tentpole c).

A stdlib ``http.server`` on a daemon thread — no new dependencies, no
effect on the serving data path — that turns the in-process surfaces
into live operator endpoints:

=============  ========================================================
``/metrics``   Prometheus text exposition of the process registry
               (what a scraper ingests; parseable back by
               :func:`.metrics.parse_prometheus_text`)
``/varz``      the JSON registry snapshot (same sample values)
``/healthz``   liveness + fleet health roll-up (200 ``ok`` while any
               worker admits, ``degraded`` otherwise)
``/statusz``   the operator page: fleet health states, the
               SLO/error-budget table (:meth:`.slo.SLOEngine
               .snapshot`), sampler stats and the most recent flight-
               recorder events
``/tracez``    one request's reconstructed timeline by trace id
               (``/tracez?id=<trace_id>`` -> ``obs.trace_of``)
=============  ========================================================

Rendering is factored into pure ``render_*`` functions so
``obs.self_check()`` exercises every page without binding a socket.
``MXTPU_OBS_HTTP_PORT`` picks the port (-1 = never serve, 0 =
ephemeral — tests read the bound port back from ``server.port``).
The server binds loopback by default: these pages are diagnostics,
not a public API.

Lifecycle: the serve loop runs on one daemon thread and each request
on a daemon handler thread (``ThreadingHTTPServer.daemon_threads``);
``close()`` shuts the loop down, closes the socket and joins the
thread — the conftest thread-leak fixture sees nothing left behind.
Zero-overhead contract: ``obs.debug_server()`` returns the shared
:data:`NULL_SERVER` when obs is off (asserted by
``obs.self_check()``).
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional
from urllib.parse import parse_qs, urlparse

__all__ = ["DebugServer", "NULL_SERVER", "render_metrics",
           "render_varz", "render_healthz", "render_statusz",
           "render_tracez"]

_FLIGHT_TAIL = 20       # last events per recorder on /statusz


# -- pure renderers (self_check runs these with no socket) --------------
def render_metrics(registry=None) -> str:
    if registry is None:
        from .. import obs as _obs
        registry = _obs.registry()
    return registry.prometheus_text()


def render_varz(registry=None) -> str:
    if registry is None:
        from .. import obs as _obs
        registry = _obs.registry()
    return json.dumps(registry.snapshot(), indent=2, default=str)


def render_healthz(router=None) -> str:
    """Liveness + fleet roll-up: ``ok`` while some worker is healthy
    (or there is no fleet to judge), ``degraded`` otherwise."""
    doc: Dict[str, Any] = {"status": "ok"}
    if router is not None:
        workers = router.workers()
        doc["workers"] = workers
        if workers and not any(s == "healthy"
                               for s in workers.values()):
            doc["status"] = "degraded"
    return json.dumps(doc, default=str)


def render_statusz(router=None, slo=None, sampler=None,
                   recorders: Optional[Dict[str, Any]] = None) -> str:
    """The operator page: fleet health + SLO/error-budget table +
    last flight events, as one JSON document."""
    if recorders is None:
        from .. import obs as _obs
        recorders = _obs.flight_recorders()
    doc: Dict[str, Any] = {
        "workers": router.workers() if router is not None else {},
        "fleet": router.fleet_stats() if router is not None else None,
        "slo": slo.snapshot() if slo is not None else None,
        "sampler": sampler.summary() if sampler is not None else None,
        "flight": {name: rec.events()[-_FLIGHT_TAIL:]
                   for name, rec in sorted(recorders.items())},
    }
    return json.dumps(doc, default=str)


def render_tracez(trace_id: str) -> str:
    from .trace import trace_of
    return json.dumps(trace_of(trace_id), default=str)


class DebugServer:
    """Daemon-thread HTTP server over one router/SLO-engine/sampler
    trio.  Construct via ``obs.debug_server(...)`` (the factory owns
    the on/off gate); the caller owns ``close()``.

    >>> srv = obs.debug_server(port=0, router=router, slo=engine)
    >>> urllib.request.urlopen(f"{srv.url}/statusz")
    >>> srv.close()
    """

    def __init__(self, *, port: int = 0, host: str = "127.0.0.1",
                 router=None, slo=None, sampler=None):
        self.router = router
        self.slo = slo
        self.sampler = sampler
        self._lock = threading.Lock()
        self._closed = False            # guarded-by: _lock
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            # diagnostics must never spam the serving process's stderr
            def log_message(self, fmt: str, *args: Any) -> None:
                pass

            def do_GET(self) -> None:  # noqa: N802 (stdlib API name)
                try:
                    url = urlparse(self.path)
                    route = _ROUTES.get(url.path)
                    if route is None:
                        self._reply(404, "text/plain",
                                    f"no such page {url.path!r}; "
                                    f"have {sorted(_ROUTES)}")
                        return
                    ctype, body = route(outer, parse_qs(url.query))
                    self._reply(200, ctype, body)
                except _BadRequest as e:
                    self._reply(400, "text/plain", str(e))
                except Exception as e:  # noqa: BLE001 — a debug page
                    # must never kill the handler thread
                    self._reply(500, "text/plain",
                                f"render failed: {e}")

            def _reply(self, code: int, ctype: str,
                       body: str) -> None:
                raw = body.encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type",
                                 f"{ctype}; charset=utf-8")
                self.send_header("Content-Length", str(len(raw)))
                self.end_headers()
                self.wfile.write(raw)

        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.05},
            daemon=True, name="mxtpu-obs-http")
        self._thread.start()

    @property
    def enabled(self) -> bool:
        return True

    @property
    def port(self) -> Optional[int]:
        return self._httpd.server_address[1]

    @property
    def url(self) -> Optional[str]:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def close(self) -> None:
        """Stop serving, close the socket, join the thread.
        Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=2.0)


class _BadRequest(Exception):
    pass


def _page_metrics(srv: "DebugServer", q) -> tuple:
    return ("text/plain", render_metrics())


def _page_varz(srv: "DebugServer", q) -> tuple:
    return ("application/json", render_varz())


def _page_healthz(srv: "DebugServer", q) -> tuple:
    return ("application/json", render_healthz(srv.router))


def _page_statusz(srv: "DebugServer", q) -> tuple:
    return ("application/json",
            render_statusz(srv.router, srv.slo, srv.sampler))


def _page_tracez(srv: "DebugServer", q) -> tuple:
    ids = q.get("id")
    if not ids or not ids[0]:
        raise _BadRequest("tracez needs ?id=<trace_id>")
    return ("application/json", render_tracez(ids[0]))


_ROUTES: Dict[str, Callable] = {
    "/metrics": _page_metrics,
    "/varz": _page_varz,
    "/healthz": _page_healthz,
    "/statusz": _page_statusz,
    "/tracez": _page_tracez,
}


class _NullServer:
    """Shared no-op server behind ``MXTPU_OBS=0`` (or a disabled
    port): nothing is bound, ``close()`` is free
    (``obs.self_check()`` asserts identity)."""

    __slots__ = ()
    enabled = False
    port: Optional[int] = None
    url: Optional[str] = None
    router = None
    slo = None
    sampler = None

    def close(self) -> None:
        pass


NULL_SERVER = _NullServer()
