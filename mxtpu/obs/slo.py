"""Declarative SLOs + multi-window burn-rate alerting (ISSUE 14
tentpole b).

An SLO turns sampled series (:mod:`.timeseries`) into one number per
window — the **error ratio** (fraction of events that violated the
objective) — and the engine turns error ratios into alerts the
Google-SRE way: the **burn rate** (error ratio / error budget, where
budget = 1 - objective) must exceed the rule's factor in BOTH a fast
window (catches it quickly) and a slow window (rejects blips) before
the alert fires.  A fast-only spike never pages; a sustained burn
always does.

Two SLO kinds, matching the serving metrics the fleet already
publishes (``endpoint=`` labeled, PR 8/11):

* :class:`AvailabilitySLO` — availability = 1 - (timeouts + sheds +
  wrong) / admitted, from the windowed deltas of
  ``mxtpu_serving_timeout_total`` / ``mxtpu_serving_rejected_total``
  / ``mxtpu_fleet_events_total{kind=wrong_results}`` over
  ``completed + timeouts + sheds``;
* :class:`LatencySLO` — fraction of requests slower than the target,
  from windowed bucket deltas of ``mxtpu_serving_latency_seconds``
  (the conservative read: a request is "good" only when its bucket's
  upper bound is <= target).  Declarable per class via the
  ``MXTPU_SLO_CLASSES`` knob (:func:`parse_slo_classes`).

:class:`SLOEngine` is tick-driven on the injected clock
(``router.attach_slo(engine)`` rides the router tick with no router
lock held).  Alert edges increment
``mxtpu_slo_alerts_total{slo,window}``, append to the ``fleet/slo``
flight recorder, and land in ``FleetRouter.postmortem()`` /
``fleet_stats()`` / ``/statusz`` via :meth:`SLOEngine.snapshot`.
Error-budget accounting (consumed fraction over the sampler's whole
retained history) rides along in the snapshot.

Lock discipline (mxrace): evaluation reads the sampler lock-free from
the engine's perspective, the firing-set diff happens under the
engine's leaf ``_lock``, and counters/recorder fire after it is
released — the autoscaler pattern.  Zero-overhead contract: with
``MXTPU_OBS=0`` the ``obs.slo_engine()`` factory returns the shared
:data:`NULL_SLO_ENGINE` (asserted by ``obs.self_check()``).
"""
from __future__ import annotations

import threading
from bisect import bisect_right
from typing import (Any, Callable, Dict, List, NamedTuple, Optional,
                    Sequence, Tuple)

from ..base import MXNetError
from .metrics import _fmt

__all__ = ["AvailabilitySLO", "LatencySLO", "BurnRateRule",
           "DEFAULT_RULES", "SLOEngine", "NULL_SLO_ENGINE",
           "parse_slo_classes"]


class BurnRateRule(NamedTuple):
    """One multi-window burn-rate rule: alert only when the burn rate
    (error ratio / error budget) exceeds ``factor`` in BOTH windows."""
    fast_s: float
    slow_s: float
    factor: float

    @property
    def label(self) -> str:
        return f"{_fmt(self.fast_s)}s/{_fmt(self.slow_s)}s"


# The canonical SRE-workbook pairs: page fast on a 14.4x burn (2% of a
# 30-day budget in an hour), slower on a sustained 6x burn.
DEFAULT_RULES: Tuple[BurnRateRule, ...] = (
    BurnRateRule(fast_s=300.0, slow_s=3600.0, factor=14.4),
    BurnRateRule(fast_s=1800.0, slow_s=21600.0, factor=6.0),
)


class _SLO:
    """Shared SLO bookkeeping: a name, an objective in (0, 1), and
    the derived error budget."""

    kind = "slo"

    def __init__(self, name: str, objective: float):
        if not name:
            raise MXNetError("obs: an SLO needs a name")
        if not 0.0 < float(objective) < 1.0:
            raise MXNetError(
                f"obs: SLO {name!r} objective must be in (0, 1), "
                f"got {objective}")
        self.name = str(name)
        self.objective = float(objective)
        self.budget = 1.0 - self.objective

    def error_ratio(self, sampler,
                    window_s: Optional[float]) -> Optional[float]:
        raise NotImplementedError

    def describe(self) -> Dict[str, Any]:
        return {"kind": self.kind, "objective": self.objective,
                "budget": self.budget}

    def __repr__(self) -> str:
        return (f"{type(self).__name__}({self.name!r}, "
                f"objective={self.objective})")


class AvailabilitySLO(_SLO):
    """availability = 1 - (timeouts + sheds + wrong) / admitted, over
    one serving endpoint's counters (``endpoint="fleet"`` = the
    router-level aggregate)."""

    kind = "availability"

    def __init__(self, name: str, objective: float = 0.999,
                 endpoint: str = "fleet",
                 wrong_kinds: Sequence[str] = ("wrong_results",)):
        super().__init__(name, objective)
        self.endpoint = str(endpoint)
        self.wrong_kinds = tuple(wrong_kinds)

    def error_ratio(self, sampler,
                    window_s: Optional[float]) -> Optional[float]:
        ep = {"endpoint": self.endpoint}
        ok = sampler.delta("mxtpu_serving_completed_total", ep,
                           window_s)
        to = sampler.delta("mxtpu_serving_timeout_total", ep, window_s)
        shed = sampler.delta("mxtpu_serving_rejected_total", ep,
                             window_s)
        if ok is None and to is None and shed is None:
            return None         # series not sampled yet
        wrong = 0.0
        for kind in self.wrong_kinds:
            w = sampler.delta("mxtpu_fleet_events_total",
                              {"endpoint": self.endpoint,
                               "kind": kind}, window_s)
            wrong += w or 0.0
        bad = (to or 0.0) + (shed or 0.0) + wrong
        admitted = (ok or 0.0) + bad
        if admitted <= 0:
            return None         # no traffic in the window: no verdict
        return min(1.0, bad / admitted)

    def describe(self) -> Dict[str, Any]:
        d = super().describe()
        d["endpoint"] = self.endpoint
        return d


class LatencySLO(_SLO):
    """Fraction of requests slower than ``target_s`` over one
    endpoint's latency histogram; ``percentile`` is the display rank
    (:meth:`observed`), the error ratio itself is exact from bucket
    deltas."""

    kind = "latency"

    def __init__(self, name: str, target_s: float,
                 objective: float = 0.95, endpoint: str = "fleet",
                 percentile: float = 95.0,
                 metric: str = "mxtpu_serving_latency_seconds"):
        super().__init__(name, objective)
        if target_s <= 0:
            raise MXNetError(
                f"obs: latency SLO {name!r} target must be positive")
        self.target_s = float(target_s)
        self.endpoint = str(endpoint)
        self.percentile = float(percentile)
        # which latency histogram to burn against: the default is the
        # end-to-end request latency; generation endpoints (ISSUE 19)
        # point this at mxtpu_serving_ttft_seconds or
        # mxtpu_serving_token_seconds for TTFT / per-token objectives
        self.metric = str(metric)

    def error_ratio(self, sampler,
                    window_s: Optional[float]) -> Optional[float]:
        d = sampler.hist_delta(self.metric,
                               {"endpoint": self.endpoint}, window_s)
        if d is None:
            return None
        bounds, cum, _ = d
        total = cum[-1] if cum else 0.0
        if total <= 0:
            return None
        # conservative: good = requests in buckets whose upper bound
        # is <= target (anything straddling the target counts bad)
        i = bisect_right(bounds, self.target_s)
        good = cum[i - 1] if i > 0 else 0.0
        return min(1.0, max(0.0, 1.0 - good / total))

    def observed(self, sampler,
                 window_s: Optional[float]) -> Optional[float]:
        return sampler.quantile(self.metric,
                                {"endpoint": self.endpoint},
                                q=self.percentile, window_s=window_s)

    def describe(self) -> Dict[str, Any]:
        d = super().describe()
        d.update(endpoint=self.endpoint, target_s=self.target_s,
                 percentile=self.percentile, metric=self.metric)
        return d


def parse_slo_classes(spec: str) -> List[LatencySLO]:
    """Parse the ``MXTPU_SLO_CLASSES`` knob:
    ``name:endpoint:target_ms:objective[:percentile],...`` (e.g.
    ``interactive:fleet:50:0.95``).  Empty spec -> no latency SLOs."""
    out: List[LatencySLO] = []
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        bits = part.split(":")
        if len(bits) < 4:
            raise MXNetError(
                f"obs: bad SLO class spec {part!r} (want "
                f"name:endpoint:target_ms:objective[:percentile])")
        try:
            target_s = float(bits[2]) / 1e3
            objective = float(bits[3])
            pct = float(bits[4]) if len(bits) > 4 and bits[4] else 95.0
        except ValueError as e:
            raise MXNetError(
                f"obs: bad SLO class spec {part!r}: {e}") from None
        out.append(LatencySLO(bits[0], target_s, objective,
                              endpoint=bits[1] or "fleet",
                              percentile=pct))
    return out


_ALERT_LOG_CAP = 64


class SLOEngine:
    """Tick-driven evaluator: samples, evaluates every SLO x rule,
    edge-triggers alerts.  Construct via ``obs.slo_engine(...)`` so
    the ``MXTPU_OBS=0`` path gets the shared no-op instead.

    >>> engine = obs.slo_engine([AvailabilitySLO("avail", 0.99)],
    ...                         sampler=smp, clock=clk)
    >>> router.attach_slo(engine)     # router tick drives it
    """

    enabled = True

    def __init__(self, slos: Sequence[_SLO], sampler, *,
                 rules: Sequence[BurnRateRule] = DEFAULT_RULES,
                 clock: Optional[Callable[[], float]] = None,
                 alerts=None, recorder=None):
        names = [s.name for s in slos]
        if len(set(names)) != len(names):
            raise MXNetError(
                f"obs: duplicate SLO names {sorted(names)}")
        self.slos = list(slos)
        self.rules = tuple(rules)
        self._sampler = sampler
        self._clock = clock
        # instruments are injectable so self_check can run the whole
        # engine against a private registry with obs disabled
        if alerts is None or recorder is None:
            from .. import obs as _obs
            if alerts is None:
                alerts = _obs.counter(
                    "mxtpu_slo_alerts_total",
                    "Burn-rate alert edges (fast+slow windows both "
                    "breached).", labels=("slo", "window"))
            if recorder is None:
                recorder = _obs.flight("fleet/slo", clock=clock)
        self._alerts = alerts
        self.recorder = recorder
        self._lock = threading.Lock()
        # (slo name, rule label) pairs currently firing
        self._active: set = set()       # guarded-by: _lock
        self._alert_log: List[Dict[str, Any]] = []  # guarded-by: _lock
        self._ticks = 0                 # guarded-by: _lock

    # -- the tick ----------------------------------------------------------
    def tick(self, now: Optional[float] = None) -> List[Tuple[str, str]]:
        """One evaluation round: sample (period-gated), evaluate every
        SLO x rule, fire/clear alert edges.  Returns the NEWLY fired
        ``(slo, window)`` pairs — tests key off it.  Runs with no
        caller lock held (it is a router controller hook)."""
        if now is None:
            now = self._clock() if self._clock is not None else None
        self._sampler.maybe_sample(now)
        firing: set = set()
        detail: Dict[Tuple[str, str], Dict[str, Any]] = {}
        for slo in self.slos:
            for rule in self.rules:
                fast = slo.error_ratio(self._sampler, rule.fast_s)
                slow = slo.error_ratio(self._sampler, rule.slow_s)
                if fast is None or slow is None:
                    continue
                fast_burn = fast / slo.budget
                slow_burn = slow / slo.budget
                if fast_burn >= rule.factor and \
                        slow_burn >= rule.factor:
                    key = (slo.name, rule.label)
                    firing.add(key)
                    detail[key] = {
                        "fast_burn": round(fast_burn, 3),
                        "slow_burn": round(slow_burn, 3),
                        "factor": rule.factor,
                    }
        with self._lock:
            self._ticks += 1
            new = sorted(firing - self._active)
            cleared = sorted(self._active - firing)
            self._active = firing
            for name, window in new:
                entry = {"slo": name, "window": window, "t": now,
                         **detail[(name, window)]}
                self._alert_log.append(entry)
                del self._alert_log[:-_ALERT_LOG_CAP]
        # instruments fire OUTSIDE the engine lock (leaf discipline)
        for name, window in new:
            self._alerts.labels(slo=name, window=window).inc()
            self.recorder.record("slo_alert", slo=name, window=window,
                                 **detail[(name, window)])
        for name, window in cleared:
            self.recorder.record("slo_clear", slo=name, window=window)
        return new

    # -- read surfaces -----------------------------------------------------
    def firing(self) -> List[Tuple[str, str]]:
        """Currently-firing ``(slo, window)`` pairs — the autoscaler's
        knob-gated overload signal."""
        with self._lock:
            return sorted(self._active)

    def snapshot(self) -> Dict[str, Any]:
        """The SLO/error-budget table ``/statusz``, ``fleet_stats()``
        and ``postmortem()`` embed."""
        with self._lock:
            active = set(self._active)
            alerts = list(self._alert_log)
            ticks = self._ticks
        table: Dict[str, Any] = {}
        for slo in self.slos:
            overall = slo.error_ratio(self._sampler, None)
            consumed = None if overall is None \
                else overall / slo.budget
            windows: Dict[str, Any] = {}
            for rule in self.rules:
                fast = slo.error_ratio(self._sampler, rule.fast_s)
                slow = slo.error_ratio(self._sampler, rule.slow_s)
                windows[rule.label] = {
                    "factor": rule.factor,
                    "fast_error": fast,
                    "slow_error": slow,
                    "fast_burn": None if fast is None
                    else round(fast / slo.budget, 3),
                    "slow_burn": None if slow is None
                    else round(slow / slo.budget, 3),
                    "firing": (slo.name, rule.label) in active,
                }
            entry = {**slo.describe(), "windows": windows,
                     "budget_consumed": None if consumed is None
                     else round(consumed, 4),
                     "budget_remaining": None if consumed is None
                     else round(1.0 - consumed, 4)}
            if isinstance(slo, LatencySLO):
                entry["observed"] = slo.observed(self._sampler, None)
            table[slo.name] = entry
        return {"slos": table, "firing": sorted(active),
                "alerts": alerts, "ticks": ticks}


class _NullSLOEngine:
    """Shared no-op engine behind ``MXTPU_OBS=0``: ticks do nothing,
    nothing ever fires (``obs.self_check()`` asserts identity)."""

    __slots__ = ()
    enabled = False
    slos: tuple = ()
    rules: tuple = ()

    def tick(self, now: Optional[float] = None) -> list:
        return []

    def firing(self) -> list:
        return []

    def snapshot(self) -> Dict[str, Any]:
        return {"slos": {}, "firing": [], "alerts": [], "ticks": 0}


NULL_SLO_ENGINE = _NullSLOEngine()
