"""Bounded time-series sampler over the metrics registry (ISSUE 14
tentpole a).

The registry (PR 8) is instantaneous: a scrape sees totals, never
history, so "requests/sec over the last minute" or "p95 in the last 5
minutes" — the inputs every SLO and every ``/statusz`` row needs —
cannot be answered in-process.  :class:`Sampler` closes that gap with
a deliberately small design:

* **injected clock** — every sample is stamped with the caller's
  clock (the fleet's fake clock in tests), so windows, rates and
  quantiles are bit-reproducible with no sleeps;
* **bounded ring per series** — one ``deque(maxlen=capacity)`` per
  ``(metric, label-set)``; memory is O(series x capacity) forever;
* **windowed reads** — counters become rates/deltas between the
  oldest and newest sample inside the window, gauges read their last
  level, histograms expose windowed p50/p95/p99 from cumulative
  *bucket deltas* (:func:`.metrics.bucket_quantile`) — the standard
  Prometheus ``rate``/``histogram_quantile`` arithmetic, computed
  locally.

``sample()`` reads the registry through its public :meth:`snapshot`
surface with NO sampler lock held, then appends under ``_lock`` (a
leaf — the sampler never calls out while holding it).  Zero-overhead
contract: with ``MXTPU_OBS=0`` the ``obs.sampler()`` factory hands
back the shared :data:`NULL_SAMPLER` whose methods do nothing and
whose reads return ``None`` — asserted by ``obs.self_check()``.
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from .. import knobs
from .metrics import bucket_quantile

__all__ = ["Sampler", "NULL_SAMPLER"]

# (metric name, sorted label items) — one ring per series
_Key = Tuple[str, Tuple[Tuple[str, str], ...]]


def _key(name: str, labels: Optional[Dict[str, Any]]) -> _Key:
    items = tuple(sorted((k, str(v))
                         for k, v in (labels or {}).items()))
    return (name, items)


class Sampler:
    """Periodic snapshots of a :class:`~.metrics.MetricsRegistry`
    into bounded per-series rings, plus the windowed read API.

    >>> smp = Sampler(obs.registry(), clock=clk)
    >>> smp.maybe_sample(now)            # period-gated (tick-driven)
    >>> smp.rate("mxtpu_serving_completed_total",
    ...          {"endpoint": "fleet"}, window_s=60.0)
    >>> smp.quantile("mxtpu_serving_latency_seconds",
    ...              {"endpoint": "fleet"}, q=95, window_s=300.0)
    """

    def __init__(self, registry, *, capacity: int = 512,
                 period_us: Optional[float] = None,
                 clock: Optional[Callable[[], float]] = None):
        self._registry = registry
        self._capacity = int(capacity)
        if period_us is None:
            period_us = knobs.get("MXTPU_OBS_SAMPLE_PERIOD_US")
        self._period_s = max(0.0, float(period_us)) / 1e6
        self._clock = clock
        self._lock = threading.Lock()
        # counter/gauge rings hold (ts, value); histogram rings hold
        # (ts, cum_counts incl +Inf, sum) with bounds kept beside the
        # ring (fixed per series)
        self._series: Dict[_Key, deque] = {}       # guarded-by: _lock
        self._bounds: Dict[_Key, Tuple[float, ...]] = {}  # guarded-by: _lock
        self._kind: Dict[_Key, str] = {}           # guarded-by: _lock
        self._last_ts: Optional[float] = None      # guarded-by: _lock
        self._samples = 0                          # guarded-by: _lock

    @property
    def enabled(self) -> bool:
        return True

    # -- writing -----------------------------------------------------------
    def maybe_sample(self, now: Optional[float] = None) -> bool:
        """Period-gated :meth:`sample` — the tick-driven entry point.
        Returns True when a sample was actually taken."""
        now = self._now(now)
        with self._lock:
            if self._last_ts is not None and \
                    now - self._last_ts < self._period_s:
                return False
        self.sample(now)
        return True

    def sample(self, now: Optional[float] = None) -> None:
        """Snapshot every registered series once, stamped ``now``."""
        now = self._now(now)
        snap = self._registry.snapshot()   # registry locks; ours not held
        rows: List[Tuple[_Key, str, Any]] = []
        for name, fam in snap.items():
            kind = fam["type"]
            for entry in fam["series"]:
                key = _key(name, entry["labels"])
                if kind == "histogram":
                    buckets = entry["buckets"]
                    bounds = tuple(float(b) for b in buckets
                                   if b != "+Inf")
                    cum = tuple(float(buckets[k]) for k in buckets)
                    rows.append((key, kind,
                                 (now, bounds, cum,
                                  float(entry["sum"]))))
                else:
                    rows.append((key, kind,
                                 (now, float(entry["value"]))))
        with self._lock:
            for key, kind, point in rows:
                ring = self._series.get(key)
                if ring is None:
                    ring = self._series[key] = \
                        deque(maxlen=self._capacity)
                    self._kind[key] = kind
                if kind == "histogram":
                    ts, bounds, cum, s = point
                    self._bounds[key] = bounds
                    ring.append((ts, cum, s))
                else:
                    ring.append(point)
            self._last_ts = now
            self._samples += 1

    # -- reading -----------------------------------------------------------
    def level(self, name: str, labels: Optional[Dict[str, Any]] = None
              ) -> Optional[float]:
        """Latest sampled value of a gauge (or counter total)."""
        with self._lock:
            ring = self._series.get(_key(name, labels))
            return ring[-1][1] if ring else None

    def delta(self, name: str,
              labels: Optional[Dict[str, Any]] = None,
              window_s: Optional[float] = None) -> Optional[float]:
        """Counter increase across the window (oldest in-window sample
        vs the newest), clamped at 0 (a reset reads as no increase).
        ``window_s=None`` spans the whole retained ring.  None until
        two samples land in the window."""
        pts = self._window(_key(name, labels), window_s)
        if len(pts) < 2:
            return None
        return max(0.0, pts[-1][1] - pts[0][1])

    def rate(self, name: str,
             labels: Optional[Dict[str, Any]] = None,
             window_s: Optional[float] = None) -> Optional[float]:
        """Counter per-second rate across the window."""
        pts = self._window(_key(name, labels), window_s)
        if len(pts) < 2 or pts[-1][0] <= pts[0][0]:
            return None
        return max(0.0, (pts[-1][1] - pts[0][1])
                   / (pts[-1][0] - pts[0][0]))

    def hist_delta(self, name: str,
                   labels: Optional[Dict[str, Any]] = None,
                   window_s: Optional[float] = None
                   ) -> Optional[Tuple[Tuple[float, ...],
                                       Tuple[float, ...], float]]:
        """Windowed histogram increase: ``(bounds, cumulative bucket
        deltas incl +Inf, sum delta)``.  None until two samples land
        in the window."""
        key = _key(name, labels)
        pts = self._window(key, window_s)
        with self._lock:
            bounds = self._bounds.get(key)
        if bounds is None or len(pts) < 2:
            return None
        first, last = pts[0], pts[-1]
        if len(first[1]) != len(last[1]):
            return None     # bucket layout changed (registry reset)
        cum = tuple(max(0.0, b - a)
                    for a, b in zip(first[1], last[1]))
        return (bounds, cum, max(0.0, last[2] - first[2]))

    def quantile(self, name: str,
                 labels: Optional[Dict[str, Any]] = None,
                 q: float = 95.0,
                 window_s: Optional[float] = None) -> Optional[float]:
        """Windowed histogram quantile (``q`` in [0, 100]) from bucket
        deltas — the sampler's p50/p95/p99 surface."""
        d = self.hist_delta(name, labels, window_s)
        if d is None:
            return None
        bounds, cum, _ = d
        return bucket_quantile(bounds, cum, q)

    def series_names(self) -> List[str]:
        with self._lock:
            return sorted({name for name, _ in self._series})

    def summary(self) -> Dict[str, Any]:
        """Cheap stats block for ``/statusz`` and ``self_check``."""
        with self._lock:
            return {
                "series": len(self._series),
                "samples": self._samples,
                "capacity": self._capacity,
                "period_us": round(self._period_s * 1e6, 1),
                "last_ts": self._last_ts,
            }

    # -- internals ---------------------------------------------------------
    def _now(self, now: Optional[float]) -> float:
        if now is not None:
            return float(now)
        if self._clock is not None:
            return float(self._clock())
        import time
        return time.monotonic()

    def _window(self, key: _Key,
                window_s: Optional[float]) -> List[tuple]:
        """Points inside ``[newest_ts - window_s, newest_ts]`` —
        windows are anchored at the series' own latest sample so a
        paused fake clock still reads coherently."""
        with self._lock:
            ring = self._series.get(key)
            pts = list(ring) if ring else []
        if not pts or window_s is None:
            return pts
        horizon = pts[-1][0] - float(window_s)
        return [p for p in pts if p[0] >= horizon]


class _NullSampler:
    """Shared no-op sampler: writes do nothing, reads answer None —
    the ``MXTPU_OBS=0`` singleton (``obs.self_check()`` asserts the
    disabled factory hands back exactly this object)."""

    __slots__ = ()
    enabled = False

    def maybe_sample(self, now: Optional[float] = None) -> bool:
        return False

    def sample(self, now: Optional[float] = None) -> None:
        pass

    def level(self, name: str, labels=None) -> Optional[float]:
        return None

    def delta(self, name: str, labels=None,
              window_s=None) -> Optional[float]:
        return None

    def rate(self, name: str, labels=None,
             window_s=None) -> Optional[float]:
        return None

    def hist_delta(self, name: str, labels=None, window_s=None):
        return None

    def quantile(self, name: str, labels=None, q: float = 95.0,
                 window_s=None) -> Optional[float]:
        return None

    def series_names(self) -> List[str]:
        return []

    def summary(self) -> Dict[str, Any]:
        return {"series": 0, "samples": 0, "capacity": 0,
                "period_us": 0.0, "last_ts": None}


NULL_SAMPLER = _NullSampler()
