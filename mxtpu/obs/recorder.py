"""Flight recorder (ISSUE 8 tentpole c).

A bounded ring of structured events per component — one recorder per
fleet worker plus process-wide ones ("compile", "train") — capturing
the things a postmortem needs but metrics flatten away: health-state
transitions, canary results, compile-cache misses, deadline
evictions, fault-plan firings.  O(1) appends under a leaf lock; the
oldest event falls off when the ring (``MXTPU_OBS_FLIGHT_CAPACITY``)
is full, and ``dropped`` counts what was lost.

The router dumps a worker's recorder automatically when it declares
the worker DEAD; setting ``MXTPU_OBS_DUMP_ON_ERROR`` extends that to
terminal request failures (and, when the knob is a directory path,
writes each postmortem there as JSON).
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from .. import knobs

__all__ = ["FlightRecorder", "NULL_RECORDER"]

logger = logging.getLogger("mxtpu.obs")


class FlightRecorder:
    """Bounded ring of ``{"ts", "kind", ...details}`` events."""

    def __init__(self, name: str, capacity: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic):
        if capacity is None:
            capacity = int(knobs.get("MXTPU_OBS_FLIGHT_CAPACITY"))
        self.name = name
        self.capacity = max(1, capacity)
        self._clock = clock
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)  # guarded-by: _lock
        self.dropped = 0         # guarded-by: _lock

    def record(self, kind: str, **details: Any) -> None:
        """Append one structured event (O(1); oldest evicted when the
        ring is full)."""
        ev = {"ts": self._clock(), "kind": kind}
        ev.update(details)
        with self._lock:
            if len(self._ring) == self.capacity:
                self.dropped += 1
            self._ring.append(ev)

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(ev) for ev in self._ring]

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self.dropped = 0

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            events = [dict(ev) for ev in self._ring]
            dropped = self.dropped
        return {"recorder": self.name, "capacity": self.capacity,
                "dropped": dropped, "events": events}

    def dump(self, reason: str = "", path: Optional[str] = None
             ) -> str:
        """Postmortem: log the ring as one JSON document (and write it
        under ``path`` when given a directory).  Returns the JSON."""
        doc = self.snapshot()
        doc["reason"] = reason
        text = json.dumps(doc, default=str)
        logger.warning("mxtpu.obs flight recorder [%s] dump (%s): %s",
                       self.name, reason or "requested", text)
        if path and os.path.isdir(path):
            safe = self.name.replace("/", "_").replace(":", "_")
            fname = os.path.join(path, f"flight_{safe}.json")
            with open(fname, "w") as f:
                f.write(text)
        return text


class _NullRecorder:
    """Shared no-op recorder (obs disabled): records nothing, dumps
    nothing — the guards-style zero-overhead path."""

    __slots__ = ()
    name = "null"
    capacity = 0
    dropped = 0

    def record(self, kind: str, **details: Any) -> None:
        pass

    def events(self) -> List[Dict[str, Any]]:
        return []

    def clear(self) -> None:
        pass

    def snapshot(self) -> Dict[str, Any]:
        return {"recorder": "null", "capacity": 0, "dropped": 0,
                "events": []}

    def dump(self, reason: str = "", path: Optional[str] = None
             ) -> str:
        return ""


NULL_RECORDER = _NullRecorder()
