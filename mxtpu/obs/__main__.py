"""``python -m mxtpu.obs`` — operator CLI for the observability layer.

* ``--self-check`` (default): run :func:`mxtpu.obs.self_check` and
  print the info dict; non-zero exit on contract violation.  Covers
  the zero-overhead null singletons (instruments, sampler, SLO
  engine, debug server), the text/JSON exposition round-trip, and an
  end-to-end probe of the operator layers on a fake clock: sampler
  windows, a driven burn-rate alert, every HTTP page rendering.
  This is the stage ``tools/ci_static.py`` runs.
* ``--prom``: print the Prometheus text exposition of the process
  registry.
* ``--json``: print the JSON snapshot.
* ``--statusz``: print the ``/statusz`` operator page (SLO table,
  sampler stats, flight tails) as rendered for the debug HTTP server.
"""
from __future__ import annotations

import argparse
import json
import sys

from . import http, prometheus_text, self_check, snapshot


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m mxtpu.obs")
    ap.add_argument("--self-check", action="store_true",
                    help="assert the zero-overhead + round-trip "
                         "contracts (default action)")
    ap.add_argument("--prom", action="store_true",
                    help="print Prometheus text exposition")
    ap.add_argument("--json", action="store_true",
                    help="print JSON metrics snapshot")
    ap.add_argument("--statusz", action="store_true",
                    help="print the /statusz operator page JSON")
    args = ap.parse_args(argv)
    if args.prom:
        sys.stdout.write(prometheus_text())
        return 0
    if args.json:
        print(json.dumps(snapshot(), indent=2, default=str))
        return 0
    if args.statusz:
        print(http.render_statusz())
        return 0
    info = self_check()
    print(f"obs.self_check OK: {info}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
