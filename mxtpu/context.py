"""Device context — the thing ``mx.tpu()`` extends.

Reference: ``python/mxnet/context.py``† (``mx.cpu()/mx.gpu()``, Context
stack with ``with ctx:`` scoping) and ``include/mxnet/base.h``† Context.
TPU-native: a Context names a jax.Device; ``tpu`` is first-class, ``gpu``
is an alias for whatever accelerator backend jax exposes so reference-era
scripts (`ctx=mx.gpu(0)`) run unchanged on a TPU machine.
"""
from __future__ import annotations

import threading
from typing import List, Optional

import jax

from .base import MXNetError

__all__ = ["Context", "cpu", "gpu", "tpu", "cpu_pinned", "cpu_shared",
           "current_context", "num_gpus", "num_tpus", "device"]


class Context:
    """A device context. devtype in {'cpu','tpu','gpu','cpu_pinned',
    'cpu_shared'}; 'gpu' and the host-memory flavours map onto the jax
    backends present on the machine (on TPU hosts, gpu→tpu so reference
    scripts run unmodified; cpu_pinned/cpu_shared→cpu: XLA manages pinned
    staging buffers itself)."""

    _stack = threading.local()

    devtype2id = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "cpu_shared": 5,
                  "tpu": 6}
    devid2type = {v: k for k, v in devtype2id.items()}

    def __init__(self, device_type: str, device_id: int = 0):
        if device_type not in self.devtype2id:
            raise MXNetError(f"unknown device type {device_type}")
        self.device_type = device_type
        self.device_id = device_id

    # -- jax mapping ---------------------------------------------------
    @property
    def _backend(self) -> str:
        if self.device_type in ("cpu", "cpu_pinned", "cpu_shared"):
            return "cpu"
        # 'gpu' and 'tpu' both resolve to the accelerator backend; on a
        # TPU host jax.default_backend() is 'tpu'.
        return jax.default_backend() if jax.default_backend() != "cpu" else "cpu"

    @property
    def jax_device(self) -> jax.Device:
        # LOCAL devices: under multi-process (jax.distributed) each
        # worker's ctx ids index its own addressable devices, exactly
        # like the reference's per-worker gpu(i); global devices are
        # non-addressable from other processes
        devs = jax.local_devices(backend=self._backend)
        if self.device_id >= len(devs):
            raise MXNetError(
                f"{self} out of range: only {len(devs)} "
                f"{self._backend} device(s) visible")
        return devs[self.device_id]

    # -- context stack -------------------------------------------------
    def __enter__(self) -> "Context":
        if not hasattr(Context._stack, "ctxs"):
            Context._stack.ctxs = []
        Context._stack.ctxs.append(self)
        return self

    def __exit__(self, *exc) -> None:
        Context._stack.ctxs.pop()

    # -- value semantics -----------------------------------------------
    def __eq__(self, other) -> bool:
        return (isinstance(other, Context)
                and self.device_type == other.device_type
                and self.device_id == other.device_id)

    def __hash__(self) -> int:
        return hash((self.device_type, self.device_id))

    def __repr__(self) -> str:
        return f"{self.device_type}({self.device_id})"

    def __str__(self) -> str:
        return repr(self)

    @classmethod
    def default_ctx(cls) -> "Context":
        ctxs = getattr(cls._stack, "ctxs", None)
        if ctxs:
            return ctxs[-1]
        return _default_context()


def _default_context() -> Context:
    # Default to the accelerator if present (the reference defaults to
    # cpu; a TPU framework defaults to the chip, matching user intent of
    # `mx.tpu()` in BASELINE.json's north star).
    if jax.default_backend() != "cpu":
        return Context("tpu", 0)
    return Context("cpu", 0)


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def cpu_pinned(device_id: int = 0) -> Context:
    return Context("cpu_pinned", device_id)


def cpu_shared(device_id: int = 0) -> Context:
    return Context("cpu_shared", device_id)


def gpu(device_id: int = 0) -> Context:
    return Context("gpu", device_id)


def tpu(device_id: int = 0) -> Context:
    return Context("tpu", device_id)


def device(dev: jax.Device) -> Context:
    """Wrap a raw jax.Device in a Context.  Context ids are LOCAL
    (per-process) indices, so map through jax.local_devices — a global
    dev.id from another process would not round-trip."""
    kind = "cpu" if dev.platform == "cpu" else "tpu"
    locals_ = jax.local_devices(backend=dev.platform)
    try:
        return Context(kind, locals_.index(dev))
    except ValueError:
        # another process's device: a local Context for it would
        # silently alias the WRONG local device — refuse loudly
        raise MXNetError(
            f"device {dev} belongs to process {dev.process_index}, "
            f"not this one ({jax.process_index()}); contexts address "
            f"local devices only")


def current_context() -> Context:
    return Context.default_ctx()


def num_gpus() -> int:
    """Reference API ``mx.context.num_gpus()``†; counts accelerators."""
    return num_tpus()


def num_tpus() -> int:
    if jax.default_backend() == "cpu":
        return 0
    # local count: the reference's per-worker `gpu(i) for i in
    # range(num_gpus())` idiom must stay in range under multi-process
    return len(jax.local_devices())
