"""Symbol → ONNX export (reference
``python/mxnet/contrib/onnx/mx2onnx/``†).

Covers the classic image-classification/MLP op families the reference
exporter shipped with: Convolution, FullyConnected, Activation,
Pooling, BatchNorm, Flatten, softmax/SoftmaxOutput, element-wise
add/mul, Concat, Dropout (inference pass-through), Reshape, transpose,
LeakyReLU/ELU.  Ops outside the table raise with the op name, matching
the reference's AttributeError contract.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ...base import MXNetError
from . import _proto as P

_CONVERTERS: Dict[str, Callable] = {}


def _register(*names):
    def deco(fn):
        for n in names:
            _CONVERTERS[n] = fn
        return fn
    return deco


class _Ctx:
    def __init__(self, params):
        self.params = params
        self.nodes: List[P.Node] = []
        self.initializers: List[P.Tensor] = []
        self.renames: Dict[str, str] = {}

    def out(self, node, idx=0) -> str:
        base = node.name if node.op is None else f"{node.name}_out{idx}"
        return self.renames.get(base, base)

    def ins(self, node) -> List[str]:
        return [self.out(src, i) for src, i in node.inputs]

    def add(self, op_type, name, inputs, outputs, **attrs):
        self.nodes.append(P.Node(op_type=op_type, name=name,
                                 inputs=tuple(inputs),
                                 outputs=tuple(outputs),
                                 attributes=attrs))

    def const(self, name, array) -> str:
        self.initializers.append(P.Tensor.from_numpy(name, array))
        return name


def _ints(v, n=None):
    if v is None:
        return None
    if isinstance(v, str):
        # attrs from a loaded -symbol.json are strings: "(3, 3)", "2"
        import ast
        v = ast.literal_eval(v)
    t = tuple(int(x) for x in (v if isinstance(v, (tuple, list))
                               else (v,)))
    if n is not None and len(t) == 1:
        t = t * n
    return t


def _pads2(pad, ndim):
    p = _ints(pad or (0,) * ndim, ndim)
    return p + p  # onnx pads = begin... + end...


@_register("Convolution")
def _conv(node, ctx):
    a = node.attrs
    kernel = _ints(a.get("kernel"))
    nd_sp = len(kernel)
    attrs = dict(kernel_shape=kernel,
                 strides=_ints(a.get("stride"), nd_sp) or (1,) * nd_sp,
                 dilations=_ints(a.get("dilate"), nd_sp) or
                 (1,) * nd_sp,
                 pads=_pads2(a.get("pad"), nd_sp),
                 group=int(a.get("num_group", 1)))
    ctx.add("Conv", node.name, ctx.ins(node), [ctx.out(node)], **attrs)


@_register("FullyConnected")
def _fc(node, ctx):
    a = node.attrs
    ins = ctx.ins(node)
    data = ins[0]
    if a.get("flatten", True) in (True, "True", "true", 1):
        flat = f"{node.name}_flat"
        ctx.add("Flatten", flat, [data], [flat], axis=1)
        data = flat
    gemm_in = [data, ins[1]] + (ins[2:] if len(ins) > 2 else [])
    ctx.add("Gemm", node.name, gemm_in, [ctx.out(node)],
            alpha=1.0, beta=1.0, transA=0, transB=1)


@_register("Activation")
def _act(node, ctx):
    table = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
             "softrelu": "Softplus", "softsign": "Softsign"}
    act = node.attrs.get("act_type", "relu")
    if act not in table:
        raise MXNetError(f"ONNX export: Activation {act} unsupported")
    ctx.add(table[act], node.name, ctx.ins(node), [ctx.out(node)])


@_register("LeakyReLU")
def _leaky(node, ctx):
    act = node.attrs.get("act_type", "leaky")
    slope = float(node.attrs.get("slope", 0.25))
    if act == "leaky":
        ctx.add("LeakyRelu", node.name, ctx.ins(node), [ctx.out(node)],
                alpha=slope)
    elif act == "elu":
        ctx.add("Elu", node.name, ctx.ins(node), [ctx.out(node)],
                alpha=slope)
    else:
        raise MXNetError(f"ONNX export: LeakyReLU {act} unsupported")


@_register("Pooling")
def _pool(node, ctx):
    a = node.attrs
    ptype = a.get("pool_type", "max")
    if ptype not in ("max", "avg"):
        raise MXNetError(f"ONNX export: pool_type {ptype} unsupported")
    if a.get("global_pool") in (True, "True", "true", 1):
        op = "GlobalMaxPool" if ptype == "max" else "GlobalAveragePool"
        ctx.add(op, node.name, ctx.ins(node), [ctx.out(node)])
        return
    kernel = _ints(a.get("kernel"))
    nd_sp = len(kernel)
    attrs = dict(kernel_shape=kernel,
                 strides=_ints(a.get("stride"), nd_sp) or (1,) * nd_sp,
                 pads=_pads2(a.get("pad"), nd_sp))
    op = "MaxPool" if ptype == "max" else "AveragePool"
    if ptype == "avg":
        attrs["count_include_pad"] = \
            1 if a.get("count_include_pad", True) in \
            (True, "True", "true", 1) else 0
    ctx.add(op, node.name, ctx.ins(node), [ctx.out(node)], **attrs)


@_register("BatchNorm")
def _bn(node, ctx):
    a = node.attrs
    ins = ctx.ins(node)
    if a.get("fix_gamma", True) in (True, "True", "true", 1):
        # mx computes with gamma forced to ones when fix_gamma — the
        # stored gamma array is ignored, so export ones explicitly
        gamma_name = node.inputs[1][0].name
        g = ctx.params.get(gamma_name)
        if g is None:
            raise MXNetError(
                f"ONNX export: BatchNorm {node.name} has "
                f"fix_gamma=True and gamma {gamma_name!r} is not in "
                f"params — cannot derive the ones scale shape")
        ins[1] = ctx.const(f"{node.name}_fixed_gamma",
                           np.ones_like(np.asarray(g)))
    # default must mirror the op registry's eps (1e-5), not the
    # reference symbol-API's 1e-3 — the graph evaluates with ours
    ctx.add("BatchNormalization", node.name, ins, [ctx.out(node)],
            epsilon=float(a.get("eps", 1e-5)),
            momentum=float(a.get("momentum", 0.9)))


@_register("BatchNormRelu", "BatchNormAddRelu")
def _bn_act(node, ctx):
    # fused TPU ops decompose to the canonical ONNX sequence
    # BatchNormalization (+ Add) + Relu — the importer of any runtime
    # re-fuses as it sees fit
    a = node.attrs
    ins = ctx.ins(node)
    has_add = node.op == "BatchNormAddRelu"
    # fused input order: (data, [addend,] gamma, beta, mean, var)
    addend = ins.pop(1) if has_add else None
    gamma_idx = 2 if has_add else 1
    if a.get("fix_gamma", True) in (True, "True", "true", 1):
        gamma_name = node.inputs[gamma_idx][0].name
        g = ctx.params.get(gamma_name)
        if g is None:
            raise MXNetError(
                f"ONNX export: {node.op} {node.name} has "
                f"fix_gamma=True and gamma {gamma_name!r} is not in "
                f"params — cannot derive the ones scale shape")
        ins[1] = ctx.const(f"{node.name}_fixed_gamma",
                           np.ones_like(np.asarray(g)))
    bn_out = f"{node.name}_bn_out"
    ctx.add("BatchNormalization", f"{node.name}_bn", ins, [bn_out],
            epsilon=float(a.get("eps", 1e-5)),
            momentum=float(a.get("momentum", 0.9)))
    pre_relu = bn_out
    if has_add:
        pre_relu = f"{node.name}_sum"
        ctx.add("Add", f"{node.name}_add", [bn_out, addend],
                [pre_relu])
    ctx.add("Relu", f"{node.name}_relu", [pre_relu], [ctx.out(node)])


@_register("Flatten", "flatten")
def _flatten(node, ctx):
    ctx.add("Flatten", node.name, ctx.ins(node), [ctx.out(node)],
            axis=1)


@_register("softmax", "SoftmaxActivation")
def _softmax(node, ctx):
    ctx.add("Softmax", node.name, ctx.ins(node), [ctx.out(node)],
            axis=int(node.attrs.get("axis", -1)))


@_register("SoftmaxOutput")
def _softmax_out(node, ctx):
    # inference export: the label input drops, loss becomes Softmax
    ctx.add("Softmax", node.name, ctx.ins(node)[:1], [ctx.out(node)],
            axis=1)


@_register("elemwise_add", "_plus", "_add", "broadcast_add")
def _add(node, ctx):
    ctx.add("Add", node.name, ctx.ins(node), [ctx.out(node)])


@_register("elemwise_mul", "_mul", "broadcast_mul")
def _mul(node, ctx):
    ctx.add("Mul", node.name, ctx.ins(node), [ctx.out(node)])


@_register("Concat", "concat")
def _concat(node, ctx):
    ctx.add("Concat", node.name, ctx.ins(node), [ctx.out(node)],
            axis=int(node.attrs.get("dim", 1)))


@_register("Dropout")
def _dropout(node, ctx):
    # inference graphs: dropout is identity — alias the output name
    ctx.renames[f"{node.name}_out0"] = ctx.ins(node)[0]


@_register("Reshape", "reshape")
def _reshape(node, ctx):
    shape = _ints(node.attrs.get("shape"))
    shape_name = ctx.const(f"{node.name}_shape",
                           np.asarray(shape, np.int64))
    ctx.add("Reshape", node.name, [ctx.ins(node)[0], shape_name],
            [ctx.out(node)])


@_register("transpose")
def _transpose(node, ctx):
    axes = _ints(node.attrs.get("axes")) or None
    attrs = {"perm": axes} if axes else {}  # both default to reverse
    ctx.add("Transpose", node.name, ctx.ins(node), [ctx.out(node)],
            **attrs)


def export_model(sym, params, input_shape=None,
                 input_type=np.float32,
                 onnx_file_path="model.onnx") -> str:
    """Export (Symbol, params) to an ONNX file (reference
    ``onnx_mxnet.export_model``†).  ``params`` may use ``arg:``/
    ``aux:`` prefixes (checkpoint convention) or bare names; values are
    NDArray or numpy.  ``input_shape``: shape tuple (or list of them)
    for the graph inputs."""
    clean: Dict[str, np.ndarray] = {}
    for k, v in (params or {}).items():
        name = k.split(":", 1)[1] if k.startswith(("arg:", "aux:")) \
            else k
        clean[name] = v.asnumpy() if hasattr(v, "asnumpy") \
            else np.asarray(v)

    nodes = sym._topo()
    ctx = _Ctx(clean)
    graph = P.Graph(name=sym.name)
    shapes = list(input_shape) if isinstance(input_shape, list) \
        else [input_shape]
    data_idx = 0
    for node in nodes:
        if node.op is None:
            if node.name in clean:
                ctx.const(node.name, clean[node.name])
            else:
                shp = shapes[data_idx] if data_idx < len(shapes) \
                    else None
                data_idx += 1
                graph.inputs.append(
                    (node.name,
                     P.NP_TO_ONNX[np.dtype(input_type)],
                     tuple(shp) if shp else ()))
            continue
        conv = _CONVERTERS.get(node.op)
        if conv is None:
            raise MXNetError(
                f"ONNX export: no converter for op {node.op!r} "
                f"(node {node.name}); supported: "
                f"{sorted(_CONVERTERS)}")
        conv(node, ctx)
    graph.nodes = ctx.nodes
    graph.initializers = ctx.initializers
    # prune inputs nothing consumes (e.g. SoftmaxOutput's dropped
    # label var)
    referenced = {i for n in ctx.nodes for i in n.inputs}
    graph.inputs = [vi for vi in graph.inputs if vi[0] in referenced]
    for head, idx in sym._heads:
        graph.outputs.append((ctx.out(head, idx),
                              P.NP_TO_ONNX[np.dtype(input_type)], ()))
    model = P.Model(graph=graph)
    with open(onnx_file_path, "wb") as f:
        f.write(model.encode())
    return onnx_file_path
