"""``mx.contrib.onnx`` — ONNX interchange (reference
``python/mxnet/contrib/onnx/``†), self-contained: the protobuf wire
format is spoken directly (``_proto``), so neither the ``onnx`` nor
``protobuf`` package is required.

``export_model(sym, params, input_shape, ...)`` writes a real
``.onnx`` file; ``import_model(path)`` returns ``(sym, arg_params,
aux_params)``; ``get_model_metadata(path)`` lists graph inputs/
outputs — the reference ``onnx_mxnet`` surface.
"""
from .mx2onnx import export_model
from .onnx2mx import get_model_metadata, import_graph, import_model

# reference alias: `from mxnet.contrib import onnx as onnx_mxnet`
__all__ = ["export_model", "import_model", "import_graph",
           "get_model_metadata"]
