"""Minimal ONNX protobuf wire-format codec — no ``onnx``/``protobuf``
dependency (neither is baked into this image as an importable onnx
package; protobuf wire format is simple enough to speak directly).

Implements exactly the subset of ``onnx/onnx.proto``† needed for model
interchange: ModelProto / GraphProto / NodeProto / AttributeProto /
TensorProto / ValueInfoProto / TypeProto.Tensor / TensorShapeProto /
OperatorSetIdProto, with the official field numbers and proto3
semantics (packed repeated scalars accepted in both packed and
unpacked encodings on read).  The test suite cross-checks this codec
against a protoc-compiled oracle of the same schema.

Messages are represented as plain Python objects (SimpleNamespace-like
dataclasses) — enough structure for the mx2onnx/onnx2mx converters.
"""
from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ...base import MXNetError

# TensorProto.DataType enum (onnx.proto†)
FLOAT, UINT8, INT8, UINT16, INT16, INT32, INT64 = 1, 2, 3, 4, 5, 6, 7
STRING, BOOL, FLOAT16, DOUBLE, UINT32, UINT64 = 8, 9, 10, 11, 12, 13

NP_TO_ONNX = {np.dtype(np.float32): FLOAT, np.dtype(np.uint8): UINT8,
              np.dtype(np.int8): INT8, np.dtype(np.uint16): UINT16,
              np.dtype(np.int16): INT16, np.dtype(np.int32): INT32,
              np.dtype(np.int64): INT64, np.dtype(np.bool_): BOOL,
              np.dtype(np.float16): FLOAT16,
              np.dtype(np.float64): DOUBLE,  # mxlint: disable=dtype-hygiene (wire-format table)
              np.dtype(np.uint32): UINT32, np.dtype(np.uint64): UINT64}
ONNX_TO_NP = {v: k for k, v in NP_TO_ONNX.items()}

# AttributeProto.AttributeType
A_FLOAT, A_INT, A_STRING, A_TENSOR, A_GRAPH = 1, 2, 3, 4, 5
A_FLOATS, A_INTS, A_STRINGS = 6, 7, 8


# ----------------------------------------------------------------------
# wire primitives
# ----------------------------------------------------------------------
def _varint(n: int) -> bytes:
    if n < 0:
        n += 1 << 64  # two's-complement 64-bit (proto int64)
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(fieldnum: int, wire: int) -> bytes:
    return _varint((fieldnum << 3) | wire)


def _len_delim(fieldnum: int, payload: bytes) -> bytes:
    return _tag(fieldnum, 2) + _varint(len(payload)) + payload


def _f_varint(fieldnum: int, n: int) -> bytes:
    return _tag(fieldnum, 0) + _varint(n)


def _f_string(fieldnum: int, s) -> bytes:
    return _len_delim(fieldnum,
                      s.encode("utf-8") if isinstance(s, str) else s)


def _f_float(fieldnum: int, v: float) -> bytes:
    return _tag(fieldnum, 5) + struct.pack("<f", v)


def _packed_varints(fieldnum: int, vals) -> bytes:
    return _len_delim(fieldnum, b"".join(_varint(v) for v in vals))


class _Dec:
    def __init__(self, data: bytes):
        self.d = data
        self.p = 0

    def varint(self) -> int:
        r = s = 0
        while True:
            if self.p >= len(self.d):
                raise MXNetError("truncated protobuf varint")
            b = self.d[self.p]
            self.p += 1
            r |= (b & 0x7F) << s
            if not b & 0x80:
                if r >= 1 << 63:
                    r -= 1 << 64
                return r
            s += 7

    def bytes_(self) -> bytes:
        ln = self.varint()
        if ln < 0 or self.p + ln > len(self.d):
            raise MXNetError("truncated protobuf bytes field")
        b = self.d[self.p:self.p + ln]
        self.p += ln
        return b

    def skip(self, wire: int) -> None:
        if wire == 0:
            self.varint()
        elif wire == 1:
            self.p += 8
        elif wire == 2:
            self.bytes_()
        elif wire == 5:
            self.p += 4
        else:
            raise MXNetError(f"unsupported protobuf wire type {wire}")

    def fields(self):
        while self.p < len(self.d):
            key = self.varint()
            yield key >> 3, key & 7

    def packed_varints(self) -> List[int]:
        sub = _Dec(self.bytes_())
        out = []
        while sub.p < len(sub.d):
            out.append(sub.varint())
        return out

    def fixed32(self) -> float:
        v = struct.unpack("<f", self.d[self.p:self.p + 4])[0]
        self.p += 4
        return v


# ----------------------------------------------------------------------
# message model
# ----------------------------------------------------------------------
@dataclass
class Tensor:
    name: str = ""
    dims: Tuple[int, ...] = ()
    data_type: int = FLOAT
    raw_data: bytes = b""

    def to_numpy(self) -> np.ndarray:
        dt = ONNX_TO_NP.get(self.data_type)
        if dt is None:
            raise MXNetError(f"ONNX data_type {self.data_type} "
                             f"unsupported")
        size = int(np.prod(self.dims)) if self.dims else 1
        if len(self.raw_data) != size * dt.itemsize:
            raise MXNetError(
                f"tensor {self.name!r}: payload {len(self.raw_data)}B "
                f"does not match dims {self.dims} × {dt} (unsupported "
                f"storage field or truncated stream)")
        return np.frombuffer(self.raw_data,
                             dtype=dt.newbyteorder("<")) \
            .reshape(self.dims).astype(dt)

    @staticmethod
    def from_numpy(name: str, a: np.ndarray) -> "Tensor":
        a = np.asarray(a)
        dt = NP_TO_ONNX.get(np.dtype(a.dtype))
        if dt is None:
            raise MXNetError(f"dtype {a.dtype} unsupported in ONNX")
        return Tensor(name=name, dims=tuple(a.shape), data_type=dt,
                      raw_data=np.ascontiguousarray(a)
                      .reshape(np.shape(a))
                      .astype(np.dtype(a.dtype).newbyteorder("<"),
                              copy=False).tobytes())

    def encode(self) -> bytes:
        out = [_packed_varints(1, self.dims) if self.dims else b"",
               _f_varint(2, self.data_type),
               _f_string(8, self.name),
               _len_delim(9, self.raw_data)]
        return b"".join(out)

    @staticmethod
    def decode(data: bytes) -> "Tensor":
        t = Tensor()
        d = _Dec(data)
        dims: List[int] = []
        float_data: List[float] = []
        double_data: List[float] = []
        int_data: List[int] = []
        for f, w in d.fields():
            if f == 1 and w == 2:
                dims.extend(d.packed_varints())
            elif f == 1 and w == 0:
                dims.append(d.varint())
            elif f == 2:
                t.data_type = d.varint()
            elif f == 8:
                t.name = d.bytes_().decode("utf-8")
            elif f == 9:
                t.raw_data = d.bytes_()
            elif f == 4 and w == 2:  # packed float_data
                sub = d.bytes_()
                float_data.extend(
                    struct.unpack(f"<{len(sub) // 4}f", sub))
            elif f == 4 and w == 5:
                float_data.append(d.fixed32())
            elif f == 10 and w == 2:  # packed double_data
                sub = d.bytes_()
                double_data.extend(
                    struct.unpack(f"<{len(sub) // 8}d", sub))
            elif f == 10 and w == 1:
                double_data.append(struct.unpack(
                    "<d", d.d[d.p:d.p + 8])[0])
                d.p += 8
            elif f in (5, 7, 11) and w == 2:  # int32/int64/uint64_data
                int_data.extend(d.packed_varints())
            elif f in (5, 7, 11) and w == 0:
                int_data.append(d.varint())
            else:
                d.skip(w)
        t.dims = tuple(dims)
        if not t.raw_data and float_data:
            t.raw_data = struct.pack(f"<{len(float_data)}f",
                                     *float_data)
        elif not t.raw_data and double_data:
            t.raw_data = struct.pack(f"<{len(double_data)}d",
                                     *double_data)
        elif not t.raw_data and int_data:
            if t.data_type == FLOAT16:
                # onnx stores f16 as uint16 BIT PATTERNS in
                # int32_data — reinterpret, don't convert numerically
                t.raw_data = np.asarray(int_data, np.uint16).tobytes()
            else:
                np_dt = ONNX_TO_NP.get(t.data_type,
                                       np.dtype(np.int64))
                t.raw_data = np.asarray(int_data, np_dt).tobytes()
        return t


@dataclass
class Attribute:
    name: str = ""
    type: int = 0
    f: float = 0.0
    i: int = 0
    s: bytes = b""
    floats: Tuple[float, ...] = ()
    ints: Tuple[int, ...] = ()
    strings: Tuple[bytes, ...] = ()
    t: Optional[Tensor] = None

    @property
    def value(self) -> Any:
        return {A_FLOAT: self.f, A_INT: self.i,
                A_STRING: self.s.decode("utf-8"),
                A_FLOATS: tuple(self.floats), A_INTS: tuple(self.ints),
                A_STRINGS: tuple(x.decode("utf-8")
                                 for x in self.strings),
                A_TENSOR: self.t}.get(self.type)

    @staticmethod
    def make(name: str, value: Any) -> "Attribute":
        a = Attribute(name=name)
        if isinstance(value, bool):
            a.type, a.i = A_INT, int(value)
        elif isinstance(value, int):
            a.type, a.i = A_INT, value
        elif isinstance(value, float):
            a.type, a.f = A_FLOAT, value
        elif isinstance(value, str):
            a.type, a.s = A_STRING, value.encode("utf-8")
        elif isinstance(value, Tensor):
            a.type, a.t = A_TENSOR, value
        elif isinstance(value, (list, tuple)):
            if all(isinstance(v, (int, bool)) for v in value):
                a.type, a.ints = A_INTS, tuple(int(v) for v in value)
            elif all(isinstance(v, (int, float)) for v in value):
                a.type = A_FLOATS
                a.floats = tuple(float(v) for v in value)
            elif all(isinstance(v, str) for v in value):
                a.type = A_STRINGS
                a.strings = tuple(v.encode("utf-8") for v in value)
            else:
                raise MXNetError(f"mixed attribute list {value!r}")
        else:
            raise MXNetError(f"unsupported attribute {name}={value!r}")
        return a

    def encode(self) -> bytes:
        out = [_f_string(1, self.name), _f_varint(20, self.type)]
        if self.type == A_FLOAT:
            out.append(_f_float(2, self.f))
        elif self.type == A_INT:
            out.append(_f_varint(3, self.i))
        elif self.type == A_STRING:
            out.append(_f_string(4, self.s))
        elif self.type == A_TENSOR:
            out.append(_len_delim(5, self.t.encode()))
        elif self.type == A_FLOATS:
            out.extend(_f_float(7, v) for v in self.floats)
        elif self.type == A_INTS:
            out.extend(_f_varint(8, v) for v in self.ints)
        elif self.type == A_STRINGS:
            out.extend(_f_string(9, v) for v in self.strings)
        return b"".join(out)

    @staticmethod
    def decode(data: bytes) -> "Attribute":
        a = Attribute()
        d = _Dec(data)
        floats: List[float] = []
        ints: List[int] = []
        strings: List[bytes] = []
        for f, w in d.fields():
            if f == 1:
                a.name = d.bytes_().decode("utf-8")
            elif f == 20:
                a.type = d.varint()
            elif f == 2:
                a.f = d.fixed32()
            elif f == 3:
                a.i = d.varint()
            elif f == 4:
                a.s = d.bytes_()
            elif f == 5:
                a.t = Tensor.decode(d.bytes_())
            elif f == 7 and w == 5:
                floats.append(d.fixed32())
            elif f == 7 and w == 2:
                sub = d.bytes_()
                floats.extend(struct.unpack(f"<{len(sub) // 4}f", sub))
            elif f == 8 and w == 0:
                ints.append(d.varint())
            elif f == 8 and w == 2:
                ints.extend(d.packed_varints())
            elif f == 9:
                strings.append(d.bytes_())
            else:
                d.skip(w)
        a.floats, a.ints, a.strings = (tuple(floats), tuple(ints),
                                       tuple(strings))
        return a


@dataclass
class Node:
    op_type: str = ""
    name: str = ""
    inputs: Tuple[str, ...] = ()
    outputs: Tuple[str, ...] = ()
    attributes: Dict[str, Any] = field(default_factory=dict)

    def encode(self) -> bytes:
        out = [_f_string(1, s) for s in self.inputs]
        out += [_f_string(2, s) for s in self.outputs]
        out.append(_f_string(3, self.name))
        out.append(_f_string(4, self.op_type))
        out += [_len_delim(5, Attribute.make(k, v).encode())
                for k, v in self.attributes.items()]
        return b"".join(out)

    @staticmethod
    def decode(data: bytes) -> "Node":
        n = Node()
        d = _Dec(data)
        ins: List[str] = []
        outs: List[str] = []
        for f, w in d.fields():
            if f == 1:
                ins.append(d.bytes_().decode("utf-8"))
            elif f == 2:
                outs.append(d.bytes_().decode("utf-8"))
            elif f == 3:
                n.name = d.bytes_().decode("utf-8")
            elif f == 4:
                n.op_type = d.bytes_().decode("utf-8")
            elif f == 5:
                a = Attribute.decode(d.bytes_())
                n.attributes[a.name] = a.value
            else:
                d.skip(w)
        n.inputs, n.outputs = tuple(ins), tuple(outs)
        return n


def _encode_value_info(name: str, elem_type: int,
                       shape: Tuple[Optional[int], ...]) -> bytes:
    dims = b"".join(
        _len_delim(1, _f_varint(1, d) if d is not None
                   else _f_string(2, "?"))
        for d in shape)
    tensor_type = (_f_varint(1, elem_type) +
                   _len_delim(2, dims))
    return _f_string(1, name) + _len_delim(2, _len_delim(1, tensor_type))


def _decode_value_info(data: bytes):
    d = _Dec(data)
    name, elem, shape = "", FLOAT, []
    for f, w in d.fields():
        if f == 1:
            name = d.bytes_().decode("utf-8")
        elif f == 2:
            td = _Dec(d.bytes_())
            for f2, w2 in td.fields():
                if f2 == 1 and w2 == 2:  # tensor_type
                    tt = _Dec(td.bytes_())
                    for f3, w3 in tt.fields():
                        if f3 == 1:
                            elem = tt.varint()
                        elif f3 == 2:
                            sd = _Dec(tt.bytes_())
                            for f4, w4 in sd.fields():
                                if f4 == 1:
                                    dd = _Dec(sd.bytes_())
                                    val = None
                                    for f5, w5 in dd.fields():
                                        if f5 == 1:
                                            val = dd.varint()
                                        else:
                                            dd.skip(w5)
                                    shape.append(val)
                                else:
                                    sd.skip(w4)
                        else:
                            tt.skip(w3)
                else:
                    td.skip(w2)
        else:
            d.skip(w)
    return name, elem, tuple(shape)


@dataclass
class Graph:
    name: str = "mxtpu"
    nodes: List[Node] = field(default_factory=list)
    initializers: List[Tensor] = field(default_factory=list)
    inputs: List[Tuple[str, int, Tuple[Optional[int], ...]]] = \
        field(default_factory=list)
    outputs: List[Tuple[str, int, Tuple[Optional[int], ...]]] = \
        field(default_factory=list)

    def encode(self) -> bytes:
        out = [_len_delim(1, n.encode()) for n in self.nodes]
        out.append(_f_string(2, self.name))
        out += [_len_delim(5, t.encode()) for t in self.initializers]
        out += [_len_delim(11, _encode_value_info(*vi))
                for vi in self.inputs]
        out += [_len_delim(12, _encode_value_info(*vi))
                for vi in self.outputs]
        return b"".join(out)

    @staticmethod
    def decode(data: bytes) -> "Graph":
        g = Graph()
        d = _Dec(data)
        for f, w in d.fields():
            if f == 1:
                g.nodes.append(Node.decode(d.bytes_()))
            elif f == 2:
                g.name = d.bytes_().decode("utf-8")
            elif f == 5:
                g.initializers.append(Tensor.decode(d.bytes_()))
            elif f == 11:
                g.inputs.append(_decode_value_info(d.bytes_()))
            elif f == 12:
                g.outputs.append(_decode_value_info(d.bytes_()))
            else:
                d.skip(w)
        return g


@dataclass
class Model:
    graph: Graph = field(default_factory=Graph)
    ir_version: int = 8
    opset: int = 13
    producer_name: str = "mxtpu"
    producer_version: str = "2.0"

    def encode(self) -> bytes:
        opset = _f_string(1, "") + _f_varint(2, self.opset)
        return b"".join([
            _f_varint(1, self.ir_version),
            _f_string(2, self.producer_name),
            _f_string(3, self.producer_version),
            _len_delim(7, self.graph.encode()),
            _len_delim(8, opset),
        ])

    @staticmethod
    def decode(data: bytes) -> "Model":
        m = Model()
        d = _Dec(data)
        for f, w in d.fields():
            if f == 1:
                m.ir_version = d.varint()
            elif f == 2:
                m.producer_name = d.bytes_().decode("utf-8")
            elif f == 3:
                m.producer_version = d.bytes_().decode("utf-8")
            elif f == 7:
                m.graph = Graph.decode(d.bytes_())
            elif f == 8:
                od = _Dec(d.bytes_())
                for f2, w2 in od.fields():
                    if f2 == 2:
                        m.opset = od.varint()
                    else:
                        od.skip(w2)
            else:
                d.skip(w)
        return m
