"""ONNX → Symbol import (reference
``python/mxnet/contrib/onnx/onnx2mx/``†).

Inverse of :mod:`.mx2onnx` for the same op families; returns the
``(sym, arg_params, aux_params)`` triple the reference's
``onnx_mxnet.import_model``† returns, ready for ``SymbolBlock`` or
``Executor.bind``.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Tuple

import numpy as np

from ...base import MXNetError
from . import _proto as P

_IMPORTERS: Dict[str, Callable] = {}


def _register(*names):
    def deco(fn):
        for n in names:
            _IMPORTERS[n] = fn
        return fn
    return deco


def _sym():
    from ... import symbol
    return symbol


def _pair(pads):
    """onnx pads [b0,b1,...,e0,e1,...] → mx symmetric pad tuple."""
    if not pads:
        return None
    half = len(pads) // 2
    begin, end = pads[:half], pads[half:]
    if tuple(begin) != tuple(end):
        raise MXNetError(f"asymmetric ONNX pads {pads} unsupported")
    return tuple(int(p) for p in begin)


def _check_auto_pad(node, attrs):
    ap = attrs.get("auto_pad", "NOTSET")
    if ap not in ("NOTSET", b"NOTSET", ""):
        raise MXNetError(
            f"ONNX import: {node.op_type} auto_pad={ap!r} unsupported "
            f"(re-export with explicit pads)")
    if attrs.get("ceil_mode"):
        raise MXNetError(
            f"ONNX import: {node.op_type} ceil_mode unsupported")


@_register("Conv")
def _conv(node, ins, attrs):
    _check_auto_pad(node, attrs)
    kw = dict(kernel=tuple(attrs["kernel_shape"]),
              num_filter=0,  # patched by caller from weight shape
              stride=tuple(attrs.get("strides", ())) or None,
              dilate=tuple(attrs.get("dilations", ())) or None,
              num_group=int(attrs.get("group", 1)),
              no_bias=len(ins) < 3)
    pad = _pair(attrs.get("pads"))
    if pad:
        kw["pad"] = pad
    kw = {k: v for k, v in kw.items() if v is not None}
    return "Convolution", kw


@_register("Gemm")
def _gemm(node, ins, attrs):
    if attrs.get("transA"):
        raise MXNetError("ONNX import: Gemm transA unsupported")
    if not attrs.get("transB", 0):
        raise MXNetError("ONNX import: Gemm transB=0 unsupported "
                         "(mx FullyConnected stores weight transposed)")
    if float(attrs.get("alpha", 1.0)) != 1.0 or \
            float(attrs.get("beta", 1.0)) != 1.0:
        raise MXNetError(
            f"ONNX import: Gemm alpha/beta scaling unsupported "
            f"(alpha={attrs.get('alpha')}, beta={attrs.get('beta')})")
    return "FullyConnected", {"num_hidden": 0, "flatten": False,
                              "no_bias": len(ins) < 3}


_ACTS = {"Relu": "relu", "Sigmoid": "sigmoid", "Tanh": "tanh",
         "Softplus": "softrelu", "Softsign": "softsign"}
for _o, _m in _ACTS.items():
    _register(_o)(lambda node, ins, attrs, _m=_m:
                  ("Activation", {"act_type": _m}))

_register("LeakyRelu")(lambda node, ins, attrs: (
    "LeakyReLU", {"act_type": "leaky",
                  "slope": float(attrs.get("alpha", 0.01))}))
_register("Elu")(lambda node, ins, attrs: (
    "LeakyReLU", {"act_type": "elu",
                  "slope": float(attrs.get("alpha", 1.0))}))


@_register("MaxPool", "AveragePool")
def _pool(node, ins, attrs):
    _check_auto_pad(node, attrs)
    kw = dict(kernel=tuple(attrs["kernel_shape"]),
              pool_type="max" if node.op_type == "MaxPool" else "avg",
              stride=tuple(attrs.get("strides", ())) or None)
    pad = _pair(attrs.get("pads"))
    if pad:
        kw["pad"] = pad
    if node.op_type == "AveragePool":
        # ONNX spec default is EXCLUDE pad (0)
        kw["count_include_pad"] = \
            bool(attrs.get("count_include_pad", 0))
    return "Pooling", {k: v for k, v in kw.items() if v is not None}


_register("GlobalMaxPool")(lambda node, ins, attrs: (
    "Pooling", {"kernel": (1, 1), "pool_type": "max",
                "global_pool": True}))
_register("GlobalAveragePool")(lambda node, ins, attrs: (
    "Pooling", {"kernel": (1, 1), "pool_type": "avg",
                "global_pool": True}))


@_register("BatchNormalization")
def _bn(node, ins, attrs):
    # ONNX BatchNormalization (inference form) always normalizes with
    # the provided mean/var inputs — mx's use_global_stats=True
    return "BatchNorm", {"eps": float(attrs.get("epsilon", 1e-5)),
                         "momentum":
                             float(attrs.get("momentum", 0.9)),
                         "fix_gamma": False,
                         "use_global_stats": True}


def _flatten_imp(node, ins, attrs):
    if int(attrs.get("axis", 1)) != 1:
        raise MXNetError(
            f"ONNX import: Flatten axis={attrs['axis']} unsupported "
            f"(mx Flatten has fixed axis-1 semantics)")
    return "Flatten", {}


_register("Flatten")(_flatten_imp)
_register("Softmax")(lambda node, ins, attrs: (
    "softmax", {"axis": int(attrs.get("axis", -1))}))
_register("Add")(lambda node, ins, attrs: ("elemwise_add", {}))
_register("Mul")(lambda node, ins, attrs: ("elemwise_mul", {}))
_register("Concat")(lambda node, ins, attrs: (
    "Concat", {"dim": int(attrs.get("axis", 1))}))
_register("Transpose")(lambda node, ins, attrs: (
    # no perm = reverse dims in BOTH onnx and mx
    "transpose", {"axes": tuple(attrs["perm"])}
    if "perm" in attrs else {}))
_register("Identity")(None)
_register("Dropout")(None)
_register("Reshape")(None)


def import_model(onnx_file: str):
    """Load an ONNX file → ``(sym, arg_params, aux_params)``
    (reference ``onnx_mxnet.import_model``†)."""
    with open(onnx_file, "rb") as f:
        model = P.Model.decode(f.read())
    return import_graph(model.graph)


def get_model_metadata(onnx_file: str) -> Dict[str, Any]:
    """Input/output names+shapes (reference
    ``onnx_mxnet.get_model_metadata``†)."""
    with open(onnx_file, "rb") as f:
        model = P.Model.decode(f.read())
    g = model.graph
    return {"input_tensor_data": [(n, s) for n, _, s in g.inputs],
            "output_tensor_data": [(n, s) for n, _, s in g.outputs]}


def import_graph(g: P.Graph):
    sym_mod = _sym()
    inits = {t.name: t.to_numpy() for t in g.initializers}
    # every non-initializer referenced name becomes a var
    env: Dict[str, Any] = {}
    arg_params: Dict[str, Any] = {}
    aux_params: Dict[str, Any] = {}

    def get_in(name):
        if name in env:
            return env[name]
        v = sym_mod.var(name)
        env[name] = v
        return v

    for name, _, _ in g.inputs:
        env[name] = sym_mod.var(name)
    for t in g.initializers:
        env[t.name] = sym_mod.var(t.name)

    from ... import nd as nd_mod
    for node in g.nodes:
        imp = _IMPORTERS.get(node.op_type, "missing")
        if imp == "missing":
            raise MXNetError(
                f"ONNX import: no importer for op {node.op_type!r} "
                f"(node {node.name}); supported: "
                f"{sorted(_IMPORTERS)}")
        if imp is None:
            # pass-through (Identity / inference Dropout) or Reshape
            if node.op_type == "Reshape":
                shape = inits.get(node.inputs[1])
                if shape is None:
                    raise MXNetError(
                        "ONNX import: dynamic Reshape shape input "
                        "unsupported")
                out = sym_mod.reshape(
                    get_in(node.inputs[0]),
                    shape=tuple(int(s) for s in shape))
            else:
                out = get_in(node.inputs[0])
            env[node.outputs[0]] = out
            continue
        op_name, kw = imp(node, node.inputs, node.attributes)
        ins = [get_in(i) for i in node.inputs]
        if op_name in ("Convolution", "FullyConnected"):
            w = inits.get(node.inputs[1])
            if w is None:
                raise MXNetError(
                    f"ONNX import: {node.op_type} node {node.name!r} "
                    f"weight {node.inputs[1]!r} is a graph input, not "
                    f"an initializer — externalized weights are "
                    f"unsupported")
            kw["num_filter" if op_name == "Convolution"
               else "num_hidden"] = int(w.shape[0])
        fn = getattr(sym_mod, op_name)
        out = fn(*ins, name=node.name or None, **kw)
        for i, oname in enumerate(node.outputs):
            # a 1-output onnx node over a multi-output mx op (e.g.
            # BatchNorm's mean/var extras) binds the primary head
            env[oname] = out[i] if len(out) > 1 else out

    outs = [env[name] for name, _, _ in g.outputs]
    sym = outs[0] if len(outs) == 1 else sym_mod.Group(outs)
    aux_suffixes = ("running_mean", "running_var", "moving_mean",
                    "moving_var")
    for name, arr in inits.items():
        target = aux_params if name.endswith(aux_suffixes) \
            else arg_params
        target[name] = nd_mod.array(arr)
    return sym, arg_params, aux_params
