"""Post-training quantization calibration (reference
``python/mxnet/contrib/quantization.py``†, simplified to the min/max
calibration mode the int8 deployment path needs)."""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from ..base import MXNetError
from ..ndarray import NDArray

__all__ = ["calib_minmax", "quantize_params"]


def calib_minmax(data_iter, num_batches: int = 10,
                 layer_outputs=None) -> Dict[str, Tuple[float, float]]:
    """Collect per-input min/max ranges over calibration batches
    (the 'naive' calibration mode†)."""
    ranges: Dict[str, Tuple[float, float]] = {}
    data_iter.reset()
    for i, batch in enumerate(data_iter):
        if i >= num_batches:
            break
        for desc, arr in zip(batch.provide_data or [], batch.data):
            a = arr.asnumpy()
            lo, hi = float(a.min()), float(a.max())
            if desc.name in ranges:
                plo, phi = ranges[desc.name]
                ranges[desc.name] = (min(lo, plo), max(hi, phi))
            else:
                ranges[desc.name] = (lo, hi)
    return ranges


def quantize_params(params: Dict[str, NDArray], out_type: str = "int8"):
    """Quantize a parameter dict → (quantized arrays, ranges)
    (the weight half of ``quantize_model``†)."""
    from .. import nd
    qparams, ranges = {}, {}
    for name, arr in params.items():
        a = arr.asnumpy()
        lo, hi = float(a.min()), float(a.max())
        q, qlo, qhi = nd.quantize_v2(arr, min_calib_range=lo,
                                     max_calib_range=hi,
                                     out_type=out_type)
        qparams[name] = q
        ranges[name] = (lo, hi)
    return qparams, ranges
