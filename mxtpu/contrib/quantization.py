"""Post-training INT8 quantization (reference
``python/mxnet/contrib/quantization.py``†): calibration (naive min/max
AND entropy/KL threshold search) plus the ``quantize_model`` graph
rewrite that replaces Convolution/FullyConnected nodes with the
``_contrib_quantized_*`` execution tier between ``quantize_v2`` /
``dequantize`` nodes.

TPU notes: the quantized ops accumulate s8xs8 -> s32 on the MXU via
``preferred_element_type`` (mxtpu/ndarray/nn_extra.py); the rewrite
keeps everything static-shape so the quantized graph jits like the
float one.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..base import MXNetError
from ..ndarray import NDArray

__all__ = ["calib_minmax", "quantize_params", "collect_layer_outputs",
           "optimal_threshold", "calib_entropy", "quantize_model"]


def calib_minmax(data_iter, num_batches: int = 10,
                 layer_outputs=None) -> Dict[str, Tuple[float, float]]:
    """Collect per-input min/max ranges over calibration batches
    (the 'naive' calibration mode†)."""
    ranges: Dict[str, Tuple[float, float]] = {}
    data_iter.reset()
    for i, batch in enumerate(data_iter):
        if i >= num_batches:
            break
        for desc, arr in zip(batch.provide_data or [], batch.data):
            a = arr.asnumpy()
            lo, hi = float(a.min()), float(a.max())
            if desc.name in ranges:
                plo, phi = ranges[desc.name]
                ranges[desc.name] = (min(lo, plo), max(hi, phi))
            else:
                ranges[desc.name] = (lo, hi)
    return ranges


def quantize_params(params: Dict[str, NDArray], out_type: str = "int8"):
    """Quantize a parameter dict → (quantized arrays, ranges)
    (the weight half of ``quantize_model``†).  Symmetric ranges, to
    match the int8 execution tier's convention."""
    from .. import nd
    qparams, ranges = {}, {}
    for name, arr in params.items():
        a = arr.asnumpy()
        amax = float(np.abs(a).max()) or 1e-6
        lo, hi = -amax, amax
        q, qlo, qhi = nd.quantize_v2(arr, min_calib_range=lo,
                                     max_calib_range=hi,
                                     out_type=out_type)
        qparams[name] = q
        ranges[name] = (lo, hi)
    return qparams, ranges


# ----------------------------------------------------------------------
# layer-output collection (reference _LayerOutputCollector†)
# ----------------------------------------------------------------------

def collect_layer_outputs(sym, arg_params, aux_params, data_iter,
                          tensor_names: List[str],
                          num_batches: int = 10,
                          data_name: str = "data",
                          label_name: str = "softmax_label"):
    """Run the float symbol over calibration data and collect the named
    intermediate tensors' values (one np-array list per name)."""
    from .. import sym as sym_mod
    from ..executor import Executor
    internals = sym.get_internals()
    heads = [internals[n] for n in tensor_names]
    group = sym_mod.Group(heads)
    collected: Dict[str, List[np.ndarray]] = {n: [] for n in
                                              tensor_names}
    data_iter.reset()
    exe = None
    for i, batch in enumerate(data_iter):
        if i >= num_batches:
            break
        x = batch.data[0]
        if exe is None:
            args = dict(arg_params)
            args[data_name] = x
            if label_name in group.list_arguments() and \
                    label_name not in args:
                if not batch.label:
                    raise MXNetError(
                        f"symbol needs {label_name} but the iterator "
                        f"provides no labels")
                args[label_name] = batch.label[0]
            exe = Executor(group, args=args, grad_req="null",
                           aux_states=dict(aux_params or {}))
        kw = {data_name: x}
        if label_name in exe.arg_dict and batch.label:
            kw[label_name] = batch.label[0]
        outs = exe.forward(is_train=False, **kw)
        for name, out in zip(tensor_names, outs):
            collected[name].append(out.asnumpy())
    return collected


# ----------------------------------------------------------------------
# entropy (KL) threshold search (reference _get_optimal_threshold†)
# ----------------------------------------------------------------------

def optimal_threshold(arr, num_bins: int = 2001,
                      num_quantized_bins: int = 255) -> float:
    """KL-minimizing |x| threshold for int8 quantization — the
    reference's TensorRT-style entropy calibration."""
    # KL divergence sums tiny probabilities; f64 is the point here
    a = np.abs(np.asarray(arr, np.float64).ravel())  # mxlint: disable=dtype-hygiene
    amax = float(a.max()) if a.size else 0.0
    if amax < 1e-12:
        return 1e-6
    hist, edges = np.histogram(a, bins=num_bins, range=(0, amax))
    hist = hist.astype(np.float64)  # mxlint: disable=dtype-hygiene
    best_div = np.inf
    best_t = amax
    stride = max(1, (num_bins - num_quantized_bins) // 64)
    for i in range(num_quantized_bins, num_bins + 1, stride):
        p = hist[:i].copy()
        p[-1] += hist[i:].sum()  # outliers collapse into the clip bin
        psum = p.sum()
        if psum == 0:
            continue
        # quantize the first i bins to num_quantized_bins levels, then
        # expand back uniformly over the non-empty source bins: Q
        q = np.zeros(i)
        factor = i / num_quantized_bins
        for j in range(num_quantized_bins):
            lo = int(np.floor(j * factor))
            hi = min(int(np.ceil((j + 1) * factor)), i)
            chunk = hist[lo:hi]
            nz = int((chunk > 0).sum())
            if nz:
                q[lo:hi] = np.where(chunk > 0, chunk.sum() / nz, 0)
        qsum = q.sum()
        if qsum == 0:
            continue
        pn = p / psum
        qn = q / qsum
        mask = pn > 0
        div = float(np.sum(np.where(
            mask, pn * np.log(np.maximum(pn, 1e-30) /
                              np.maximum(qn, 1e-30)), 0)))
        if div < best_div:
            best_div = div
            best_t = float(edges[min(i, len(edges) - 1)])
    return best_t


def calib_entropy(collected: Dict[str, List[np.ndarray]],
                  num_bins: int = 2001,
                  num_quantized_bins: int = 255
                  ) -> Dict[str, Tuple[float, float]]:
    """Entropy calibration: per-tensor symmetric ranges from the
    KL-optimal |x| threshold over the collected activations."""
    out = {}
    for name, chunks in collected.items():
        t = optimal_threshold(np.concatenate(
            [c.ravel() for c in chunks]), num_bins, num_quantized_bins)
        out[name] = (-t, t)
    return out


# ----------------------------------------------------------------------
# quantize_model graph rewrite (reference quantize_model†)
# ----------------------------------------------------------------------

_QUANTIZABLE = ("Convolution", "FullyConnected")


def _producer_name(node, idx):
    """Internal-tensor name of (node, output_idx) as ``get_internals``
    exposes it (multi-output nodes get an index suffix)."""
    if node.op is None:
        return node.name
    if getattr(node, "num_outputs", 1) > 1:
        return f"{node.name}_output{idx}"
    return f"{node.name}_output"


def quantize_model(sym, arg_params: Dict[str, NDArray],
                   aux_params: Optional[Dict[str, NDArray]] = None,
                   data_iter=None, calib_mode: str = "entropy",
                   num_calib_batches: int = 10,
                   quantized_dtype: str = "int8",
                   excluded_sym_names: Tuple[str, ...] = (),
                   data_name: str = "data",
                   label_name: str = "softmax_label"):
    """Rewrite Convolution/FullyConnected into the int8 execution tier
    with calibrated ranges.  Returns (qsym, qarg_params, aux_params).

    calib_mode: 'none' (activation ranges computed per batch at
    runtime — range-exact, slower), 'naive' (abs-max over calibration
    data), 'entropy' (KL-optimal thresholds; the reference default for
    convnets).

    quantized_dtype: 'int8' (symmetric), 'uint8' (shifted range
    [0, hi] with zero point 0 — requires non-negative activations,
    i.e. post-ReLU inputs), or 'auto' (per-layer: uint8 where the
    calibrated input range is non-negative, else int8 — the
    reference's auto policy)."""
    from .. import sym as sym_mod
    if quantized_dtype not in ("int8", "uint8", "auto"):
        raise MXNetError(f"quantized_dtype must be int8/uint8/auto, "
                         f"got {quantized_dtype!r}")
    if quantized_dtype in ("uint8", "auto") and calib_mode == "none":
        # without calibration there is no evidence activations are
        # non-negative; auto degrades to int8, explicit uint8 needs data
        if quantized_dtype == "uint8":
            raise MXNetError("quantized_dtype='uint8' needs "
                             "calibration (calib_mode != 'none')")
        quantized_dtype = "int8"
    aux_params = aux_params or {}

    nodes = list(sym._topo())
    targets = [n for n in nodes
               if n.op in _QUANTIZABLE
               and n.name not in excluded_sym_names
               # grouped-conv int8 tier not implemented: keep float
               and int(n.attrs.get("num_group", 1) or 1) == 1]
    if not targets:
        return sym, dict(arg_params), dict(aux_params)
    need_ranges: List[str] = []
    for n in targets:
        src, idx = n.inputs[0]
        tname = _producer_name(src, idx)
        if src.op is not None and tname not in need_ranges:
            need_ranges.append(tname)

    ranges: Dict[str, Tuple[float, float]] = {}
    # per-tensor activation dtype: uint8 where the raw calibrated
    # minimum is non-negative (post-ReLU tensors) and policy allows
    qdtype: Dict[str, str] = {}

    def _pick(name, raw_lo, sym_hi):
        if quantized_dtype == "uint8" and raw_lo < 0.0:
            # silently clamping negative activations to 0 would wreck
            # accuracy with no signal; the reference requires
            # non-negative inputs for its u8 tier too
            raise MXNetError(
                f"quantized_dtype='uint8' but calibrated tensor "
                f"{name!r} has negative minimum {raw_lo:.4g}; use "
                f"'auto' (per-tensor choice) or 'int8'")
        u8 = (quantized_dtype == "uint8"
              or (quantized_dtype == "auto" and raw_lo >= 0.0))
        qdtype[name] = "uint8" if u8 else "int8"
        ranges[name] = (0.0, sym_hi) if u8 else (-sym_hi, sym_hi)

    if calib_mode in ("naive", "entropy"):
        if data_iter is None:
            raise MXNetError(f"calib_mode={calib_mode!r} needs "
                             f"calibration data")
        input_ranges = calib_minmax(data_iter, num_calib_batches)
        if need_ranges:
            collected = collect_layer_outputs(
                sym, arg_params, aux_params, data_iter, need_ranges,
                num_calib_batches, data_name, label_name)
            # the min-scan only matters for the uint8 policy; keep the
            # plain-int8 path free of the extra pass
            raw_lo = {name: min(float(c.min()) for c in chunks)
                      for name, chunks in collected.items()} \
                if quantized_dtype in ("uint8", "auto") else \
                {name: -1.0 for name in collected}
            if calib_mode == "entropy":
                for name, (_, t) in calib_entropy(collected).items():
                    _pick(name, raw_lo[name], t)
            else:
                for name, chunks in collected.items():
                    amax = max(float(np.abs(c).max()) for c in chunks)
                    _pick(name, raw_lo[name], amax)
        for name, (lo, hi) in input_ranges.items():
            _pick(name, lo, max(abs(lo), abs(hi)))
    elif calib_mode != "none":
        raise MXNetError(f"unknown calib_mode {calib_mode!r}")

    # quantize target weights offline (symmetric)
    qarg_params = dict(arg_params)
    wranges: Dict[str, Tuple[float, float]] = {}
    for n in targets:
        if len(n.inputs) < 2:
            continue
        wsrc, _ = n.inputs[1]
        if wsrc.op is not None or wsrc.name not in arg_params:
            continue
        qp, rr = quantize_params({wsrc.name: arg_params[wsrc.name]})
        qarg_params[wsrc.name + "_quantize"] = qp[wsrc.name]
        wranges[wsrc.name] = rr[wsrc.name]

    target_names = {n.name for n in targets
                    if len(n.inputs) >= 2
                    and n.inputs[1][0].name in wranges}
    memo: Dict[int, sym_mod.Symbol] = {}

    def rebuild(node):
        if id(node) in memo:
            return memo[id(node)]
        if node.op is None:
            out = sym_mod.Variable(node.name)
            memo[id(node)] = out
            return out
        ins = [rebuild(src)[idx] if src.num_outputs > 1
               else rebuild(src) for src, idx in node.inputs]
        attrs = {k: v for k, v in node.attrs.items()
                 if not k.startswith("__")}
        if node.name in target_names:
            out = _emit_quantized(node, ins, attrs)
        else:
            out = getattr(sym_mod, node.op)(
                *ins, name=node.name, **attrs)
        memo[id(node)] = out
        return out

    def _emit_quantized(node, ins, attrs):
        src, idx = node.inputs[0]
        tname = _producer_name(src, idx)
        kw = {}
        if tname in ranges:
            lo, hi = ranges[tname]
            kw = {"min_calib_range": lo, "max_calib_range": hi}
        qd = sym_mod.quantize_v2(ins[0],
                                 out_type=qdtype.get(tname, "int8"),
                                 name=node.name + "_quantize", **kw)
        qdata, dmin, dmax = qd[0], qd[1], qd[2]
        wsrc, _ = node.inputs[1]
        wlo, whi = wranges[wsrc.name]
        qw = sym_mod.Variable(wsrc.name + "_quantize")
        wmin = sym_mod._full(shape=(1,), value=wlo,
                             name=node.name + "_wmin")
        wmax = sym_mod._full(shape=(1,), value=whi,
                             name=node.name + "_wmax")
        no_bias = str(attrs.get("no_bias", False)).lower() in \
            ("true", "1")
        op_name = "_contrib_quantized_conv" \
            if node.op == "Convolution" \
            else "_contrib_quantized_fully_connected"
        qattrs = dict(attrs)
        qattrs["no_bias"] = True  # bias re-added in float (exact)
        q = getattr(sym_mod, op_name)(
            qdata, qw, dmin, dmax, wmin, wmax,
            name=node.name + "_quantized", **qattrs)
        deq = sym_mod.dequantize(q[0], q[1], q[2],
                                 name=node.name + "_dequantize")
        if not no_bias and len(node.inputs) > 2:
            bias = rebuild(node.inputs[2][0])
            shape = (1, -1) + ((1, 1) if node.op == "Convolution"
                               else ())
            deq = sym_mod.broadcast_add(
                deq, sym_mod.reshape(bias, shape=shape),
                name=node.name + "_bias_add")
        return deq

    heads = []
    for node, idx in sym._heads:
        s = rebuild(node)
        heads.append(s[idx] if node.num_outputs > 1 else s)
    qsym = sym_mod.Group(heads) if len(heads) > 1 else heads[0]
    return qsym, qarg_params, dict(aux_params)
