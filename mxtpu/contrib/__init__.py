"""``mx.contrib`` (reference ``python/mxnet/contrib/``†):
quantization calibration + ndarray contrib re-exports.  (ONNX
import/export is not implemented; ``onnx`` raises with guidance.)"""
from . import quantization
from ..ndarray import contrib as ndarray  # mx.contrib.ndarray.* ops

__all__ = ["quantization", "ndarray"]


def __getattr__(name):
    if name == "onnx":
        from ..base import MXNetError
        raise MXNetError(
            "contrib.onnx import/export is not implemented in this "
            "build; export via Block.export (native symbol.json + "
            "params) instead")
    raise AttributeError(f"module 'mxtpu.contrib' has no attribute "
                         f"{name!r}")
