"""``mx.contrib`` (reference ``python/mxnet/contrib/``†):
quantization calibration, text/vocabulary/embeddings, ONNX
interchange, ndarray contrib re-exports."""
from . import quantization
from ..ndarray import contrib as ndarray  # mx.contrib.ndarray.* ops

__all__ = ["quantization", "ndarray", "onnx", "text"]


def __getattr__(name):
    if name == "text":
        # lazy like onnx: numpy-heavy loaders stay off the hot
        # `import mxtpu` path
        import importlib
        mod = importlib.import_module(__name__ + ".text")
        globals()["text"] = mod
        return mod
    if name == "onnx":
        # NOT `from . import onnx` — the fromlist getattr would
        # re-enter this hook and recurse
        import importlib
        mod = importlib.import_module(__name__ + ".onnx")
        globals()["onnx"] = mod
        return mod
    raise AttributeError(f"module 'mxtpu.contrib' has no attribute "
                         f"{name!r}")
