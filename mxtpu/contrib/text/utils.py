"""Token counting helpers (reference ``contrib/text/utils.py``†)."""
from __future__ import annotations

import re
from collections import Counter
from typing import Optional

__all__ = ["count_tokens_from_str"]


def count_tokens_from_str(source_str: str, token_delim: str = " ",
                          seq_delim: str = "\n",
                          to_lower: bool = False,
                          counter_to_update: Optional[Counter] = None
                          ) -> Counter:
    """Count tokens in ``source_str`` split on ``token_delim`` and
    ``seq_delim`` (reference semantics: both delimiters are literal
    strings, empty tokens are dropped, counts accumulate into
    ``counter_to_update`` when given)."""
    source = source_str.lower() if to_lower else source_str
    tokens = [t for t in
              re.split(re.escape(token_delim) + "|"
                       + re.escape(seq_delim), source) if t]
    counter = counter_to_update if counter_to_update is not None \
        else Counter()
    counter.update(tokens)
    return counter
