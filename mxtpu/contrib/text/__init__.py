"""Text utilities: vocabulary, token indexing, pretrained embeddings
(reference ``python/mxnet/contrib/text/``† — utils.py, vocab.py,
embedding.py).

DIVERGENCE: the reference downloads pretrained GloVe/fastText archives
on demand; this environment has no network egress, so embeddings load
from a local file path (``CustomEmbedding``-style) or from a directory
given via ``embedding_root``.  File formats are compatible with the
published GloVe (``token v1 .. vn``) and fastText (header line
``count dim`` then rows) text formats.
"""
from . import embedding, utils, vocab
from .embedding import (CompositeEmbedding, CustomEmbedding, FastText,
                        GloVe, TokenEmbedding)
from .utils import count_tokens_from_str
from .vocab import Vocabulary

__all__ = ["utils", "vocab", "embedding", "Vocabulary",
           "count_tokens_from_str", "TokenEmbedding", "GloVe",
           "FastText", "CustomEmbedding", "CompositeEmbedding"]
