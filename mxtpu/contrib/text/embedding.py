"""Pretrained token embeddings (reference
``contrib/text/embedding.py``†): TokenEmbedding base + GloVe/FastText
text-format loaders, CustomEmbedding, CompositeEmbedding.

DIVERGENCE (documented): no network egress here, so nothing downloads;
``GloVe``/``FastText`` read ``<embedding_root>/<file_name>`` that the
user provides offline, with the published text formats:

- GloVe:    each line ``token v1 v2 ... vn``
- fastText: optional first line ``vocab_size dim`` header, then rows

Unknown tokens vectorize through ``init_unknown_vec`` (zeros by
default), matching the reference.
"""
from __future__ import annotations

import io
import os
from typing import Callable, List, Optional, Sequence

import numpy as np

from ...base import MXNetError
from .vocab import Vocabulary

__all__ = ["TokenEmbedding", "GloVe", "FastText", "CustomEmbedding",
           "CompositeEmbedding", "get_pretrained_file_names"]

_REGISTRY = {}


def _register(cls):
    _REGISTRY[cls.__name__.lower()] = cls
    return cls


def get_pretrained_file_names(embedding_name: Optional[str] = None):
    """Known pretrained file names per embedding family (the
    reference's catalogue; files must be provided offline)."""
    cat = {
        "glove": ["glove.6B.50d.txt", "glove.6B.100d.txt",
                  "glove.6B.200d.txt", "glove.6B.300d.txt",
                  "glove.42B.300d.txt", "glove.840B.300d.txt"],
        "fasttext": ["wiki.simple.vec", "wiki.en.vec"],
    }
    if embedding_name is None:
        return cat
    try:
        return cat[embedding_name.lower()]
    except KeyError:
        raise MXNetError(f"unknown embedding family {embedding_name!r};"
                         f" choices: {sorted(cat)}")


class TokenEmbedding:
    """Base: token -> vector store over an index (reference
    ``_TokenEmbedding``†)."""

    def __init__(self, unknown_token: str = "<unk>",
                 init_unknown_vec: Callable = np.zeros):
        self._unknown_token = unknown_token
        self._init_unknown_vec = init_unknown_vec
        self._idx_to_token: List[str] = [unknown_token]
        self._token_to_idx = {unknown_token: 0}
        self._idx_to_vec: Optional[np.ndarray] = None

    # -- loading -------------------------------------------------------
    def _load_embedding(self, path: str, elem_delim: str = " ",
                        encoding: str = "utf8",
                        skip_header: bool = False):
        if not os.path.isfile(path):
            raise MXNetError(
                f"pretrained embedding file {path!r} not found; this "
                f"build has no network egress — place the file there "
                f"(published GloVe/fastText text formats)")
        vecs: List[np.ndarray] = []
        dim = None
        with io.open(path, "r", encoding=encoding) as f:
            for lineno, line in enumerate(f):
                parts = line.rstrip("\n").split(elem_delim)
                if lineno == 0 and (skip_header or (
                        len(parts) == 2
                        and all(x.isdigit() for x in parts))):
                    # fastText 'count dim' header: BOTH fields integral
                    # — a dim-1 embedding row like "a 1.0" is data
                    continue
                if len(parts) < 2:
                    continue
                tok = parts[0]
                try:
                    vec = np.asarray([float(x) for x in parts[1:] if x],
                                     np.float32)
                except ValueError:
                    raise MXNetError(
                        f"{path}:{lineno + 1}: malformed vector row")
                if dim is None:
                    dim = vec.size
                elif vec.size != dim:
                    raise MXNetError(
                        f"{path}:{lineno + 1}: dim {vec.size} != {dim}")
                if tok in self._token_to_idx:
                    continue  # first occurrence wins (reference ditto)
                self._token_to_idx[tok] = len(self._idx_to_token)
                self._idx_to_token.append(tok)
                vecs.append(vec)
        if dim is None:
            raise MXNetError(f"no vectors found in {path!r}")
        unk = np.asarray(self._init_unknown_vec((dim,)), np.float32)
        self._idx_to_vec = np.vstack([unk[None, :]] + [v[None, :]
                                                       for v in vecs])

    # -- API -----------------------------------------------------------
    @property
    def vec_len(self) -> int:
        return 0 if self._idx_to_vec is None \
            else int(self._idx_to_vec.shape[1])

    @property
    def unknown_token(self) -> str:
        return self._unknown_token

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self) -> List[str]:
        return self._idx_to_token

    @property
    def idx_to_vec(self):
        from ... import nd
        return nd.array(self._idx_to_vec)

    def __len__(self):
        return len(self._idx_to_token)

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        """Vectors for token(s); unknown tokens get the unknown
        vector.  With ``lower_case_backup``, miss -> try lowercase."""
        from ... import nd
        single = isinstance(tokens, str)
        toks = [tokens] if single else list(tokens)
        idx = []
        for t in toks:
            i = self._token_to_idx.get(t)
            if i is None and lower_case_backup:
                i = self._token_to_idx.get(t.lower())
            idx.append(0 if i is None else i)
        out = self._idx_to_vec[np.asarray(idx, np.int64)]
        return nd.array(out[0] if single else out)

    def update_token_vectors(self, tokens, new_vectors):
        """Overwrite vectors of known tokens (reference semantics:
        unknown tokens raise)."""
        if isinstance(tokens, str):
            tokens = [tokens]
        arr = new_vectors.asnumpy() if hasattr(new_vectors, "asnumpy") \
            else np.asarray(new_vectors, np.float32)
        arr = arr.reshape(len(tokens), -1)
        for t, v in zip(tokens, arr):
            if t not in self._token_to_idx:
                raise MXNetError(f"token {t!r} is unknown; only known "
                                 f"tokens can be updated")
            self._idx_to_vec[self._token_to_idx[t]] = v


@_register
class GloVe(TokenEmbedding):
    """GloVe text-format loader (``glove.*.txt``)."""

    def __init__(self, pretrained_file_name: str = "glove.6B.50d.txt",
                 embedding_root: str = os.path.join(
                     os.path.expanduser("~"), ".mxtpu", "embedding"),
                 init_unknown_vec: Callable = np.zeros, **kwargs):
        super().__init__(init_unknown_vec=init_unknown_vec, **kwargs)
        self._load_embedding(
            os.path.join(embedding_root, "glove",
                         pretrained_file_name))


@_register
class FastText(TokenEmbedding):
    """fastText ``.vec`` text-format loader (header line skipped)."""

    def __init__(self, pretrained_file_name: str = "wiki.simple.vec",
                 embedding_root: str = os.path.join(
                     os.path.expanduser("~"), ".mxtpu", "embedding"),
                 init_unknown_vec: Callable = np.zeros, **kwargs):
        super().__init__(init_unknown_vec=init_unknown_vec, **kwargs)
        self._load_embedding(
            os.path.join(embedding_root, "fasttext",
                         pretrained_file_name), skip_header=True)


class CustomEmbedding(TokenEmbedding):
    """Load any token-vector text file by explicit path (reference
    ``CustomEmbedding``†)."""

    def __init__(self, pretrained_file_path: str,
                 elem_delim: str = " ", encoding: str = "utf8",
                 init_unknown_vec: Callable = np.zeros, **kwargs):
        super().__init__(init_unknown_vec=init_unknown_vec, **kwargs)
        self._load_embedding(pretrained_file_path,
                             elem_delim=elem_delim, encoding=encoding)


class CompositeEmbedding(TokenEmbedding):
    """Index a vocabulary into one or more TokenEmbeddings,
    concatenating their vectors (reference ``CompositeEmbedding``†) —
    the matrix that seeds ``gluon.nn.Embedding.weight``."""

    def __init__(self, vocabulary: Vocabulary, token_embeddings):
        if not isinstance(vocabulary, Vocabulary):
            raise MXNetError("vocabulary must be a Vocabulary")
        embs = token_embeddings if isinstance(
            token_embeddings, (list, tuple)) else [token_embeddings]
        super().__init__(unknown_token=vocabulary.unknown_token)
        self._vocabulary = vocabulary
        self._idx_to_token = list(vocabulary.idx_to_token)
        self._token_to_idx = dict(vocabulary.token_to_idx)
        parts = []
        for emb in embs:
            if emb._idx_to_vec is None:
                raise MXNetError("token_embeddings must be loaded")
            rows = np.zeros((len(self._idx_to_token), emb.vec_len),
                            np.float32)
            for i, tok in enumerate(self._idx_to_token):
                j = emb._token_to_idx.get(tok, 0)
                rows[i] = emb._idx_to_vec[j]
            parts.append(rows)
        self._idx_to_vec = np.concatenate(parts, axis=1)

    @property
    def vocabulary(self) -> Vocabulary:
        return self._vocabulary
