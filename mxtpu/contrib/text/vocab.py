"""Vocabulary: token <-> index mapping (reference
``contrib/text/vocab.py``†).

Indexing contract (the reference's): index 0 is ``unknown_token``,
reserved tokens follow, then counter tokens sorted by frequency
(descending) with ties broken alphabetically; ``most_freq_count`` and
``min_freq`` prune the counter part.
"""
from __future__ import annotations

from collections import Counter
from typing import List, Optional, Sequence, Union

from ...base import MXNetError

__all__ = ["Vocabulary"]


class Vocabulary:
    def __init__(self, counter: Optional[Counter] = None,
                 most_freq_count: Optional[int] = None,
                 min_freq: int = 1,
                 unknown_token: str = "<unk>",
                 reserved_tokens: Optional[Sequence[str]] = None):
        if min_freq < 1:
            raise MXNetError("min_freq must be >= 1")
        reserved_tokens = list(reserved_tokens or [])
        if len(set(reserved_tokens)) != len(reserved_tokens):
            raise MXNetError("reserved_tokens must not repeat")
        if unknown_token in reserved_tokens:
            raise MXNetError("unknown_token must not be a reserved "
                             "token")
        self._unknown_token = unknown_token
        self._reserved_tokens = reserved_tokens or None
        self._idx_to_token: List[str] = [unknown_token] + reserved_tokens
        if counter is not None:
            # frequency-descending, ties alphabetical — the reference's
            # deterministic ordering
            pairs = sorted(counter.items(),
                           key=lambda kv: (-kv[1], kv[0]))
            taken = set(self._idx_to_token)
            kept = 0
            for tok, freq in pairs:
                if freq < min_freq:
                    break
                if most_freq_count is not None and \
                        kept >= most_freq_count:
                    break
                if tok in taken:
                    continue
                self._idx_to_token.append(tok)
                kept += 1
        self._token_to_idx = {t: i
                              for i, t in enumerate(self._idx_to_token)}

    def __len__(self) -> int:
        return len(self._idx_to_token)

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self) -> List[str]:
        return self._idx_to_token

    @property
    def unknown_token(self) -> str:
        return self._unknown_token

    @property
    def reserved_tokens(self):
        return self._reserved_tokens

    def to_indices(self, tokens: Union[str, Sequence[str]]):
        """Token(s) -> index/indices; unknown tokens map to index 0."""
        single = isinstance(tokens, str)
        toks = [tokens] if single else list(tokens)
        idx = [self._token_to_idx.get(t, 0) for t in toks]
        return idx[0] if single else idx

    def to_tokens(self, indices: Union[int, Sequence[int]]):
        single = not isinstance(indices, (list, tuple))
        idxs = [indices] if single else list(indices)
        out = []
        for i in idxs:
            if not 0 <= int(i) < len(self._idx_to_token):
                raise MXNetError(f"token index {i} out of range "
                                 f"[0, {len(self._idx_to_token)})")
            out.append(self._idx_to_token[int(i)])
        return out[0] if single else out
