"""Faster R-CNN — the reference's two-stage detector
(``example/rcnn/``†, ``src/operator/contrib/proposal.cc``† +
``ROIPooling``†), rebuilt as HybridBlocks.

Stage 1: a conv backbone feeds an RPN head whose per-anchor
objectness/deltas run through the ``Proposal`` op (decode → clip →
top-k → NMS, all static-shape).  Stage 2: ``ROIPooling`` crops each
proposal to a fixed grid, a dense head predicts class scores and
per-class box deltas.  Inference post-processing (per-class decode +
NMS) runs eagerly over the static-shape op outputs.

Training here covers the RPN (objectness + box regression via
``MultiBoxTarget`` assignment on the generated anchors) — the
reference's alternating/approximate-joint schemes build on exactly
these pieces.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from ..gluon import nn
from ..gluon.block import HybridBlock

__all__ = ["RPN", "FasterRCNN", "faster_rcnn_small", "rpn_anchors"]


def rpn_anchors(height, width, feature_stride, scales, ratios,
                im_size):
    """All RPN anchors for an (height×width) feature map, normalized
    to [0,1] by ``im_size`` — ready for ``MultiBoxTarget``.  Order
    matches the RPN head layout (position-major, anchor-minor)."""
    from ..ndarray.detection_impl import _anchor_grid
    from .. import nd
    anchors = _anchor_grid(height, width, feature_stride, scales,
                           ratios)
    return nd.array((anchors / float(im_size))[None].astype(np.float32))


class RPN(HybridBlock):
    """Region proposal head: 3×3 conv → 1×1 objectness (2A channels,
    background-first) + 1×1 deltas (4A channels)."""

    def __init__(self, channels, num_anchors, **kwargs):
        super().__init__(**kwargs)
        self._A = num_anchors
        self.conv = nn.Conv2D(channels, 3, padding=1,
                              activation="relu")
        self.cls = nn.Conv2D(2 * num_anchors, 1)
        self.reg = nn.Conv2D(4 * num_anchors, 1)

    def hybrid_forward(self, F, x):
        t = self.conv(x)
        return self.cls(t), self.reg(t)


class FasterRCNN(HybridBlock):
    """Two-stage detector over ``Proposal`` + ``ROIPooling``.

    ``forward(x, im_info)`` → ``(rois, cls_scores, bbox_deltas,
    rpn_raw, rpn_reg)``: rois (N·R, 5); cls_scores (N·R, C+1);
    bbox_deltas (N·R, 4(C+1)).
    """

    def __init__(self, num_classes, body_channels=(16, 32, 64),
                 rpn_channels=64, scales=(2.0, 4.0), ratios=(0.5, 1.0,
                                                             2.0),
                 post_nms=64, pooled_size=(7, 7), head_units=128,
                 **kwargs):
        super().__init__(**kwargs)
        self._classes = num_classes
        self._stride = 2 ** len(body_channels)
        self._scales = tuple(float(s) for s in scales)
        self._ratios = tuple(float(r) for r in ratios)
        self._A = len(scales) * len(ratios)
        self._post_nms = int(post_nms)
        self._pooled = tuple(pooled_size)
        self.body = nn.HybridSequential()
        for c in body_channels:
            self.body.add(nn.Conv2D(c, 3, padding=1, use_bias=False),
                          nn.BatchNorm(), nn.Activation("relu"),
                          nn.MaxPool2D(2, strides=2))
        self.rpn = RPN(rpn_channels, self._A)
        self.head = nn.HybridSequential()
        for _ in range(2):
            self.head.add(nn.Dense(head_units, activation="relu"))
        self.cls_head = nn.Dense(num_classes + 1)
        self.reg_head = nn.Dense(4 * (num_classes + 1))

    def hybrid_forward(self, F, x, im_info):
        feat = self.body(x)
        rpn_raw, rpn_reg = self.rpn(feat)
        # pairwise bg/fg softmax without reshape tricks: channel a
        # (background) pairs with channel A+a (foreground)
        A = self._A
        bg = F.slice_axis(rpn_raw, axis=1, begin=0, end=A)
        fg = F.slice_axis(rpn_raw, axis=1, begin=A, end=2 * A)
        m = F.maximum(bg, fg)
        eb = F.exp(bg - m)
        ef = F.exp(fg - m)
        denom = eb + ef
        prob = F.concat(eb / denom, ef / denom, dim=1)
        rois = F.Proposal(
            prob, rpn_reg, im_info, scales=self._scales,
            ratios=self._ratios, feature_stride=self._stride,
            rpn_pre_nms_top_n=4 * self._post_nms,
            rpn_post_nms_top_n=self._post_nms, threshold=0.7,
            rpn_min_size=self._stride)
        pooled = F.ROIPooling(feat, rois, pooled_size=self._pooled,
                              spatial_scale=1.0 / self._stride)
        h = self.head(F.Flatten(pooled))
        return (rois, self.cls_head(h), self.reg_head(h), rpn_raw,
                rpn_reg)

    # -- inference ------------------------------------------------------
    def detect(self, x, im_info, score_threshold=0.05,
               nms_threshold=0.3):
        """Per-class decode + NMS over the head outputs.  Returns
        (N, R·C, 6) rows [cls_id, score, x1, y1, x2, y2] in pixels,
        suppressed rows -1."""
        from .. import nd
        rois, scores, deltas, _, _ = self(x, im_info)
        N = x.shape[0]
        R = self._post_nms
        C = self._classes
        probs = nd.softmax(scores, axis=-1).asnumpy()
        deltas = deltas.asnumpy().reshape(-1, C + 1, 4)
        boxes = rois.asnumpy()[:, 1:]
        widths = boxes[:, 2] - boxes[:, 0] + 1.0
        heights = boxes[:, 3] - boxes[:, 1] + 1.0
        ctr_x = boxes[:, 0] + 0.5 * (widths - 1)
        ctr_y = boxes[:, 1] + 0.5 * (heights - 1)
        info = im_info.asnumpy() if hasattr(im_info, "asnumpy") \
            else np.asarray(im_info)
        per_image = []
        for n in range(N):
            rows = np.full((C, R, 6), -1.0, np.float32)
            sl = slice(n * R, (n + 1) * R)
            for c in range(1, C + 1):
                d = deltas[sl, c]
                cx = d[:, 0] * widths[sl] + ctr_x[sl]
                cy = d[:, 1] * heights[sl] + ctr_y[sl]
                w = np.exp(np.clip(d[:, 2], -10, 10)) * widths[sl]
                h = np.exp(np.clip(d[:, 3], -10, 10)) * heights[sl]
                b = np.stack([cx - (w - 1) / 2, cy - (h - 1) / 2,
                              cx + (w - 1) / 2, cy + (h - 1) / 2], 1)
                b[:, 0::2] = np.clip(b[:, 0::2], 0, info[n, 1] - 1)
                b[:, 1::2] = np.clip(b[:, 1::2], 0, info[n, 0] - 1)
                rows[c - 1, :, 0] = c - 1.0
                rows[c - 1, :, 1] = probs[sl, c]
                rows[c - 1, :, 2:] = b
            # per-class greedy NMS = ONE box_nms call over the stacked
            # classes with class-masked suppression (id_index)
            kept = nd.contrib.box_nms(
                nd.array(rows.reshape(-1, 6)),
                overlap_thresh=nms_threshold,
                valid_thresh=score_threshold, coord_start=2,
                score_index=1, id_index=0,
                force_suppress=False).asnumpy()
            per_image.append(kept)
        return np.stack(per_image)


def faster_rcnn_small(num_classes=2):
    """Test/tutorial-scale Faster R-CNN (stride-8 backbone)."""
    return FasterRCNN(num_classes)
