"""SSD single-shot detector — the reference's flagship detection model
(``example/ssd/``†, ``symbol/symbol_builder.py``†), rebuilt as
HybridBlocks over the framework's MultiBox op family
(``MultiBoxPrior``/``MultiBoxTarget``/``MultiBoxDetection``,
``src/operator/contrib/multibox_*.cc``†).

Structure matches the reference recipe: a downsampling conv body, a
chain of extra feature scales, and per-scale 3×3 class/box predictor
convs whose outputs concatenate over all anchors.  Anchors come from
``MultiBoxPrior`` per scale; training targets (with hard-negative
mining) from ``MultiBoxTarget``; NMS'd inference from
``MultiBoxDetection`` — all static-shape TPU-friendly ops (suppressed
entries = -1, the documented padded-NMS contract).
"""
from __future__ import annotations

from ..base import MXNetError
from ..gluon import nn
from ..gluon.block import HybridBlock
from ..gluon.loss import Loss

__all__ = ["SSD", "SSDLoss", "toy_ssd", "ssd_300"]


def _conv_block(channels):
    """Conv-BN-ReLU ×2 then 2× downsample (reference ``legacy_conv_act_layer``†
    pattern)."""
    blk = nn.HybridSequential()
    for _ in range(2):
        blk.add(nn.Conv2D(channels, 3, padding=1, use_bias=False),
                nn.BatchNorm(), nn.Activation("relu"))
    blk.add(nn.MaxPool2D(2, strides=2))
    return blk


class SSD(HybridBlock):
    """Multi-scale single-shot detector.

    ``body_channels``: channels of the downsampling body blocks;
    ``scale_channels``: channels of the extra scales appended after the
    body.  ``sizes``/``ratios``: per-scale anchor configs (len =
    len(scale_channels) + 2: body output scale + extra scales + the
    global scale).  Forward returns ``(anchors (1, A, 4), cls_preds
    (N, C+1, A), box_preds (N, A*4))`` — the exact triple
    ``MultiBoxTarget``/``MultiBoxDetection`` consume.
    """

    def __init__(self, num_classes, body_channels=(16, 32, 64),
                 scale_channels=(64, 64), sizes=None, ratios=None,
                 **kwargs):
        super().__init__(**kwargs)
        self._classes = num_classes
        n_scales = len(scale_channels) + 2
        if sizes is None:
            # linearly spaced anchor sizes, small→large (reference
            # ssd default progression)
            lo, hi = 0.2, 0.9
            step = (hi - lo) / (n_scales - 1) if n_scales > 1 else 0.0
            sizes = [(lo + i * step,
                      (lo + i * step) * 1.3) for i in range(n_scales)]
        if ratios is None:
            ratios = [(1.0, 2.0, 0.5)] * n_scales
        if len(sizes) != n_scales or len(ratios) != n_scales:
            raise MXNetError(
                f"sizes/ratios must have {n_scales} entries "
                f"(body + {len(scale_channels)} extra + global)")
        self._sizes = [tuple(float(s) for s in sz) for sz in sizes]
        self._ratios = [tuple(float(r) for r in rt) for rt in ratios]

        self.body = nn.HybridSequential()
        for c in body_channels:
            self.body.add(_conv_block(c))
        self.scales = nn.HybridSequential()
        for c in scale_channels:
            self.scales.add(_conv_block(c))
        self.cls_preds = nn.HybridSequential()
        self.box_preds = nn.HybridSequential()
        for i in range(n_scales):
            k = len(self._sizes[i]) + len(self._ratios[i]) - 1
            self.cls_preds.add(
                nn.Conv2D(k * (num_classes + 1), 3, padding=1))
            self.box_preds.add(nn.Conv2D(k * 4, 3, padding=1))

    def hybrid_forward(self, F, x):
        feats = []
        x = self.body(x)
        feats.append(x)
        for i in range(len(self.scales)):
            x = self.scales[i](x)
            feats.append(x)
        # global scale: collapse to 1×1 (reference ``global pooling``
        # last scale)
        feats.append(F.Pooling(x, global_pool=True, pool_type="max",
                               kernel=(2, 2)))

        anchors, cls_out, box_out = [], [], []
        for i, feat in enumerate(feats):
            anchors.append(F.MultiBoxPrior(
                feat, sizes=self._sizes[i], ratios=self._ratios[i]))
            c = self.cls_preds[i](feat)
            # (N, K*(C+1), H, W) → (N, H*W*K, C+1)
            c = F.transpose(c, axes=(0, 2, 3, 1))
            cls_out.append(F.reshape(c,
                                     shape=(0, -1, self._classes + 1)))
            b = self.box_preds[i](feat)
            b = F.transpose(b, axes=(0, 2, 3, 1))
            box_out.append(F.reshape(b, shape=(0, -1)))
        anchors = F.concat(*anchors, dim=1)
        cls_preds = F.concat(*cls_out, dim=1)
        box_preds = F.concat(*box_out, dim=1)
        # (N, A, C+1) → (N, C+1, A): MultiBox target/detection layout
        cls_preds = F.transpose(cls_preds, axes=(0, 2, 1))
        return anchors, cls_preds, box_preds

    # -- inference ------------------------------------------------------
    def detect(self, x, nms_threshold=0.5, force_suppress=False,
               nms_topk=400):
        """End-to-end detection: forward → class softmax →
        ``MultiBoxDetection``.  Rows: [cls_id, score, x1, y1, x2, y2],
        suppressed entries -1."""
        from .. import nd
        anchors, cls_preds, box_preds = self(x)
        probs = nd.softmax(cls_preds, axis=1)
        return nd.MultiBoxDetection(
            probs, box_preds, anchors, nms_threshold=nms_threshold,
            force_suppress=force_suppress, nms_topk=nms_topk)


class SSDLoss(Loss):
    """Joint detection loss (reference ``example/ssd/train/metric``†
    recipe): softmax CE on mined class targets + smooth-L1 on masked
    box offsets, normalized by the positive count.

    Call as ``loss(cls_preds, box_preds, cls_target, box_target,
    box_mask)`` with the ``MultiBoxTarget`` outputs.
    """

    def __init__(self, box_loss_weight=1.0, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._box_w = float(box_loss_weight)

    def hybrid_forward(self, F, cls_preds, box_preds, cls_target,
                       box_target, box_mask):
        # class CE over (N, C+1, A) with sparse targets (N, A).
        # MultiBoxTarget marks non-mined anchors with the ignore label
        # -1 when negative_mining_ratio > 0 — mask them out (pick
        # would wrap -1 to the last class and easy negatives would
        # swamp the loss); without mining no -1 exists and this is the
        # plain mean
        logp = F.log_softmax(cls_preds, axis=1)
        keep = cls_target >= 0
        safe_t = F.maximum(cls_target, F.zeros_like(cls_target))
        ce = -F.pick(logp, safe_t, axis=1) * keep
        # mean over KEPT anchors (== plain anchor mean when no ignore
        # labels are present)
        frac_keep = F.mean(keep, axis=0, exclude=True)
        n_keep = F.maximum(frac_keep, 1e-8 * F.ones_like(frac_keep))
        cls_loss = F.mean(ce, axis=0, exclude=True) / n_keep
        sl1 = F.smooth_l1((box_preds - box_target) * box_mask,
                          scalar=1.0)
        box_loss = F.mean(sl1, axis=0, exclude=True)
        # normalize by positives (mask counts 4 per positive anchor)
        npos = F.mean(box_mask, axis=0, exclude=True)
        return cls_loss + self._box_w * box_loss / \
            F.maximum(npos, F.ones_like(npos) * 1e-8)


def toy_ssd(num_classes=2):
    """Small SSD for tests/tutorial-scale data (the reference gluon
    tutorial's toy detector)."""
    return SSD(num_classes, body_channels=(8, 16),
               scale_channels=(16,))


def ssd_300(num_classes=20):
    """SSD-300-class config (VGG-reduced-style body depth; reference
    ``ssd_vgg16_reduced_300``† capacity class)."""
    return SSD(num_classes, body_channels=(32, 64, 128, 256),
               scale_channels=(256, 128, 128))
