"""Flagship model builders used by bench.py / __graft_entry__ / tests.

The full reference-parity zoo lives in ``mxtpu.gluon.model_zoo``;
these are the canonical training configurations from BASELINE.md
(LeNet-MNIST is north-star workload 1, ``example/image-classification/
train_mnist.py``†).
"""
from __future__ import annotations

from ..gluon import nn

__all__ = ["lenet", "mlp", "resnet50", "rcnn", "ssd", "transformer"]

from . import rcnn  # noqa: E402,F401  (Faster R-CNN family)
from . import ssd  # noqa: E402,F401  (SSD detector family)
from . import transformer  # noqa: E402,F401  (BERT/Transformer family)


def resnet50(classes: int = 1000, thumbnail: bool = False):
    """ResNet-50 v1 — north-star workload 2 (BASELINE.md; reference
    ``example/image-classification/symbols/resnet.py``†)."""
    from ..gluon.model_zoo import vision
    return vision.get_resnet(1, 50, thumbnail=thumbnail,
                             classes=classes)


def lenet(classes: int = 10):
    """LeNet-5 as in the reference MNIST example
    (``example/image-classification/symbols/lenet.py``†)."""
    net = nn.HybridSequential(prefix="lenet_")
    net.add(nn.Conv2D(20, kernel_size=5, activation="tanh"),
            nn.MaxPool2D(pool_size=2, strides=2),
            nn.Conv2D(50, kernel_size=5, activation="tanh"),
            nn.MaxPool2D(pool_size=2, strides=2),
            nn.Flatten(),
            nn.Dense(500, activation="tanh"),
            nn.Dense(classes))
    return net


def mlp(classes: int = 10, hidden=(128, 64)):
    """The reference's canonical MLP (``symbols/mlp.py``†)."""
    net = nn.HybridSequential(prefix="mlp_")
    for h in hidden:
        net.add(nn.Dense(h, activation="relu"))
    net.add(nn.Dense(classes))
    return net
