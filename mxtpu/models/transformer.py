"""Transformer / BERT model family — north-star workloads 3 & 4
(BASELINE.md: BERT-Large pretrain, Transformer-big WMT).

The reference repo itself carries no transformer (2018-era; BERT lived
in GluonNLP downstream) — this supplies the same capability class,
built on the framework's fused kernels: ``flash_attention`` for the
attention core and the Pallas fused ``LayerNorm``.  Everything is
HybridBlocks, so a full encoder stack hybridizes into one XLA program;
``mxtpu.parallel.build_train_step`` adds dp/tp sharding and bf16.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from ..gluon import nn
from ..gluon.block import HybridBlock

__all__ = ["MultiHeadAttention", "PositionwiseFFN",
           "TransformerEncoderCell", "TransformerEncoder",
           "TransformerDecoderCell", "TransformerDecoder",
           "TransformerModel", "BERTModel", "bert_base", "bert_large",
           "transformer_encoder", "transformer_base",
           "transformer_big"]


class MultiHeadAttention(HybridBlock):
    """Self- or cross-attention over (N, T, C) via the fused attention
    op.  Pass a second input (``memory``) at call time for
    cross-attention: queries come from ``x``, keys/values from
    ``memory`` (the decoder->encoder path of the seq2seq
    transformer)."""

    def __init__(self, units, num_heads, dropout=0.0, causal=False,
                 proj_bias=True, **kwargs):
        super().__init__(**kwargs)
        if units % num_heads:
            raise MXNetError(f"units {units} not divisible by "
                             f"num_heads {num_heads}")
        self._units = units
        self._heads = num_heads
        self._causal = causal
        self.qkv = nn.Dense(3 * units, flatten=False, use_bias=True)
        # proj_bias=False when a FusedResidualLayerNorm epilogue folds
        # the output bias (and dropout) into its fused kernel
        self.proj = nn.Dense(units, flatten=False, use_bias=proj_bias)
        self.drop = nn.Dropout(dropout) if dropout else None

    def _split_heads(self, F, t):
        # (N, T, u) -> (N, h, T, u/h)
        t = F.reshape(t, shape=(0, -1, self._heads,
                                self._units // self._heads))
        return F.transpose(t, axes=(0, 2, 1, 3))

    def hybrid_forward(self, F, x, *args):
        u = self._units
        split = lambda t: self._split_heads(F, t)
        if len(args) == 2:
            # incremental decode: (x, step, cache) — x holds the T new
            # tokens, cache is (2, B, H, L, u/h) [k; v], step (B,) is
            # each lane's write frontier.  Returns (out, new_cache).
            step, cache = args
            qkv = self.qkv(x)
            q = split(F.slice_axis(qkv, axis=-1, begin=0, end=u))
            k = split(F.slice_axis(qkv, axis=-1, begin=u, end=2 * u))
            v = split(F.slice_axis(qkv, axis=-1, begin=2 * u,
                                   end=3 * u))
            k_cache = F.squeeze(
                F.slice_axis(cache, axis=0, begin=0, end=1), axis=0)
            v_cache = F.squeeze(
                F.slice_axis(cache, axis=0, begin=1, end=2), axis=0)
            k_cache = F.kv_cache_write(k_cache, k, step)
            v_cache = F.kv_cache_write(v_cache, v, step)
            out = F.cached_attention(q, k_cache, v_cache, step)
            out = F.reshape(F.transpose(out, axes=(0, 2, 1, 3)),
                            shape=(0, -1, u))
            out = self.proj(out)
            if self.drop is not None:
                out = self.drop(out)
            return out, F.stack(k_cache, v_cache, axis=0)
        memory = args[0] if args else None
        if memory is None:
            qkv = self.qkv(x)
            q = split(F.slice_axis(qkv, axis=-1, begin=0, end=u))
            k = split(F.slice_axis(qkv, axis=-1, begin=u, end=2 * u))
            v = split(F.slice_axis(qkv, axis=-1, begin=2 * u,
                                   end=3 * u))
        else:
            # cross-attention reuses the fused qkv weights: the q rows
            # project x, the kv rows project memory (one GEMM each)
            qkv_x = self.qkv(x)
            qkv_m = self.qkv(memory)
            q = split(F.slice_axis(qkv_x, axis=-1, begin=0, end=u))
            k = split(F.slice_axis(qkv_m, axis=-1, begin=u, end=2 * u))
            v = split(F.slice_axis(qkv_m, axis=-1, begin=2 * u,
                                   end=3 * u))
        out = F.flash_attention(q, k, v, causal=self._causal)
        out = F.reshape(F.transpose(out, axes=(0, 2, 1, 3)),
                        shape=(0, -1, u))
        out = self.proj(out)
        if self.drop is not None:
            out = self.drop(out)
        return out


class PositionwiseFFN(HybridBlock):
    """Dense → gelu → Dense (the transformer MLP)."""

    def __init__(self, units, hidden_size, dropout=0.0, out_bias=True,
                 **kwargs):
        super().__init__(**kwargs)
        self.ffn1 = nn.Dense(hidden_size, flatten=False)
        self.ffn2 = nn.Dense(units, flatten=False, use_bias=out_bias)
        self.drop = nn.Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, x):
        out = self.ffn2(F.LeakyReLU(self.ffn1(x), act_type="gelu"))
        if self.drop is not None:
            out = self.drop(out)
        return out


class TransformerEncoderCell(HybridBlock):
    """Post-LN encoder layer (BERT convention): LN(x + attn),
    LN(x + ffn)."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 causal=False, **kwargs):
        super().__init__(**kwargs)
        # output bias + dropout + residual + LN run as ONE fused
        # epilogue (kernels/layer_norm.py), so the sub-blocks emit the
        # raw GEMM output: no proj bias, no separate Dropout
        self.attn = MultiHeadAttention(units, num_heads, 0.0, causal,
                                       proj_bias=False)
        self.ffn = PositionwiseFFN(units, hidden_size, 0.0,
                                   out_bias=False)
        self.ln1 = nn.FusedResidualLayerNorm(dropout)
        self.ln2 = nn.FusedResidualLayerNorm(dropout)

    def hybrid_forward(self, F, x, *args):
        if args:
            step, cache = args
            a, cache = self.attn(x, step, cache)
            x = self.ln1(a, x)
            x = self.ln2(self.ffn(x), x)
            return x, cache
        x = self.ln1(self.attn(x), x)
        x = self.ln2(self.ffn(x), x)
        return x


class TransformerEncoder(HybridBlock):
    """Stack of encoder cells."""

    def __init__(self, num_layers, units, hidden_size, num_heads,
                 dropout=0.0, causal=False, remat=False, **kwargs):
        super().__init__(**kwargs)
        self.layers = nn.HybridSequential()
        for i in range(num_layers):
            cell = TransformerEncoderCell(
                units, hidden_size, num_heads, dropout, causal)
            if remat:
                # per-layer activation rematerialization: O(sqrt)-style
                # memory for deep stacks (SURVEY §0)
                cell.set_remat(True)
            self.layers.add(cell)

    def hybrid_forward(self, F, x, *args):
        if args:
            # incremental: cache is (num_layers, 2, B, H, L, u/h);
            # per-layer slices are static (python loop over cells), so
            # the whole stack still traces into one XLA program
            step, cache = args
            outs = []
            for i, cell in enumerate(self.layers):
                c = F.squeeze(F.slice_axis(cache, axis=0, begin=i,
                                           end=i + 1), axis=0)
                x, c = cell(x, step, c)
                outs.append(c)
            return x, F.stack(*outs, axis=0)
        return self.layers(x)


class TransformerDecoderCell(HybridBlock):
    """Post-LN decoder layer: causal self-attn, cross-attn over the
    encoder memory, FFN — the WMT transformer decoder (Vaswani et al.
    2017; capability class of the reference's ``example/nmt``†-era
    seq2seq line)."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 **kwargs):
        super().__init__(**kwargs)
        self.self_attn = MultiHeadAttention(units, num_heads, 0.0,
                                            causal=True,
                                            proj_bias=False)
        self.cross_attn = MultiHeadAttention(units, num_heads, 0.0,
                                             proj_bias=False)
        self.ffn = PositionwiseFFN(units, hidden_size, 0.0,
                                   out_bias=False)
        self.ln1 = nn.FusedResidualLayerNorm(dropout)
        self.ln2 = nn.FusedResidualLayerNorm(dropout)
        self.ln3 = nn.FusedResidualLayerNorm(dropout)

    def hybrid_forward(self, F, x, memory, *args):
        if args:
            # incremental: only self-attention is cached; cross-attn
            # keys/values are recomputed from the (fixed) memory each
            # step — stateless and correct, at a small recompute cost
            step, cache = args
            a, cache = self.self_attn(x, step, cache)
            x = self.ln1(a, x)
            x = self.ln2(self.cross_attn(x, memory), x)
            x = self.ln3(self.ffn(x), x)
            return x, cache
        x = self.ln1(self.self_attn(x), x)
        x = self.ln2(self.cross_attn(x, memory), x)
        x = self.ln3(self.ffn(x), x)
        return x


class TransformerDecoder(HybridBlock):
    """Stack of decoder cells (memory threaded to every layer)."""

    def __init__(self, num_layers, units, hidden_size, num_heads,
                 dropout=0.0, remat=False, **kwargs):
        super().__init__(**kwargs)
        self.layers = nn.HybridSequential()
        for _ in range(num_layers):
            cell = TransformerDecoderCell(units, hidden_size,
                                          num_heads, dropout)
            if remat:
                cell.set_remat(True)
            self.layers.add(cell)

    def hybrid_forward(self, F, x, memory, *args):
        if args:
            step, cache = args
            outs = []
            for i, cell in enumerate(self.layers):
                c = F.squeeze(F.slice_axis(cache, axis=0, begin=i,
                                           end=i + 1), axis=0)
                x, c = cell(x, memory, step, c)
                outs.append(c)
            return x, F.stack(*outs, axis=0)
        for cell in self.layers:
            x = cell(x, memory)
        return x


class TransformerModel(HybridBlock):
    """Encoder-decoder transformer for translation (WMT config):
    shared source/target vocabulary embedding, sinusoid-free learned
    positions, tied output projection left separate (the reference
    recipe's default)."""

    def __init__(self, vocab_size, units=1024, hidden_size=4096,
                 num_layers=6, num_heads=16, max_length=256,
                 dropout=0.1, remat=False, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._num_layers = num_layers
        self._num_heads = num_heads
        self._max_length = max_length
        self.embed = nn.Embedding(vocab_size, units)
        self.pos_embed = self.params.get(
            "pos_embed", shape=(max_length, units), init="normal")
        self.embed_ln = nn.LayerNorm()
        self.drop = nn.Dropout(dropout) if dropout else None
        self.encoder = TransformerEncoder(num_layers, units,
                                          hidden_size, num_heads,
                                          dropout, remat=remat)
        self.decoder = TransformerDecoder(num_layers, units,
                                          hidden_size, num_heads,
                                          dropout, remat=remat)
        self.out_proj = nn.Dense(vocab_size, flatten=False)

    def _embed(self, F, tokens, pos_embed):
        x = self.embed(tokens) * float(np.sqrt(self._units))
        # length-polymorphic position add: slice_like keyed on the
        # embedded activations instead of a static T makes ONE exported
        # graph valid for every sequence length <= max_length — what
        # bucketed serving (mxtpu.serving) relies on
        pe = F.slice_like(F.expand_dims(pos_embed, axis=0), x,
                          axes=(1,))
        x = x + pe
        x = self.embed_ln(x)
        if self.drop is not None:
            x = self.drop(x)
        return x

    def _embed_at(self, F, tokens, step, pos_embed, scale):
        """Embedding + position add for incremental decode: token t of
        lane b sits at absolute position ``step_b + t``, so positions
        are *gathered* from the table (``take``) instead of sliced —
        the dynamic-offset twin of the slice_like trick."""
        x = self.embed(tokens) if scale is None else \
            self.embed(tokens) * scale
        pos = F.slice_like(
            F.expand_dims(F._arange(start=0, stop=self._max_length),
                          axis=0), x, axes=(1,))
        pos = F.broadcast_add(pos, F.expand_dims(step, axis=1))
        x = x + F.take(pos_embed, pos, axis=0)
        x = self.embed_ln(x)
        if self.drop is not None:
            x = self.drop(x)
        return x

    def kv_cache_spec(self, batch_size, max_len=None):
        """Shape of the stacked decoder self-attention KV cache this
        model consumes/returns in incremental mode."""
        L = self._max_length if max_len is None else int(max_len)
        return (self._num_layers, 2, int(batch_size), self._num_heads,
                L, self._units // self._num_heads)

    def hybrid_forward(self, F, src, tgt, *args, pos_embed=None):
        if args:
            # incremental decode: (src, tgt_new, step, cache).  The
            # encoder runs full on src each call (prefill recomputes
            # it; the decode path feeds the same bucketed src), the
            # decoder consumes/returns per-layer KV state.
            step, cache = args
            memory = self.encoder(self._embed(F, src, pos_embed))
            x = self._embed_at(F, tgt, step, pos_embed,
                               float(np.sqrt(self._units)))
            dec, cache = self.decoder(x, memory, step, cache)
            return self.out_proj(dec), cache
        memory = self.encoder(self._embed(F, src, pos_embed))
        dec = self.decoder(self._embed(F, tgt, pos_embed), memory)
        return self.out_proj(dec)


class BERTModel(HybridBlock):
    """BERT-style encoder LM: token + position (+ type) embeddings,
    encoder stack, MLM head over tied-or-separate projection."""

    def __init__(self, vocab_size, units, hidden_size, num_layers,
                 num_heads, max_length=512, dropout=0.1,
                 use_token_type=True, causal=False, remat=False,
                 **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._num_layers = num_layers
        self._num_heads = num_heads
        self._max_length = max_length
        self.word_embed = nn.Embedding(vocab_size, units)
        self.pos_embed = self.params.get(
            "pos_embed", shape=(max_length, units), init="normal")
        self.type_embed = nn.Embedding(2, units) \
            if use_token_type else None
        self.embed_ln = nn.LayerNorm()
        self.embed_drop = nn.Dropout(dropout) if dropout else None
        # causal=True turns the encoder stack into a decoder-only LM
        # (GPT-style) — the configuration mxtpu.serving.generate serves
        self.encoder = TransformerEncoder(num_layers, units,
                                          hidden_size, num_heads,
                                          dropout, causal=causal,
                                          remat=remat)
        self.mlm = nn.Dense(vocab_size, flatten=False)

    def kv_cache_spec(self, batch_size, max_len=None):
        """Shape of the stacked per-layer KV cache this model
        consumes/returns in incremental mode:
        (num_layers, 2, B, num_heads, L, units // num_heads)."""
        L = self._max_length if max_len is None else int(max_len)
        return (self._num_layers, 2, int(batch_size), self._num_heads,
                L, self._units // self._num_heads)

    def hybrid_forward(self, F, tokens, *args, pos_embed=None):
        if len(args) == 2:
            # incremental decode: (tokens, step, cache) — tokens are
            # the T new tokens per lane, positions step_b + t gathered
            # from the table; token-type embeddings don't apply to the
            # generation path.  Returns (logits, new_cache).
            step, cache = args
            x = self.word_embed(tokens)
            pos = F.slice_like(
                F.expand_dims(
                    F._arange(start=0, stop=self._max_length), axis=0),
                x, axes=(1,))
            pos = F.broadcast_add(pos, F.expand_dims(step, axis=1))
            x = x + F.take(pos_embed, pos, axis=0)
            x = self.embed_ln(x)
            if self.embed_drop is not None:
                x = self.embed_drop(x)
            x, cache = self.encoder(x, step, cache)
            return self.mlm(x), cache
        token_types = args[0] if args else None
        x = self.word_embed(tokens)
        # slice_like (not a static-T slice_axis) keeps the exported
        # graph valid for ANY sequence length <= max_length: the
        # position table is sliced against the activations at run/trace
        # time, which is what lets mxtpu.serving compile one export
        # into many sequence buckets
        pe = F.slice_like(F.expand_dims(pos_embed, axis=0), x,
                          axes=(1,))
        x = x + pe
        if self.type_embed is not None and token_types is not None:
            x = x + self.type_embed(token_types)
        x = self.embed_ln(x)
        if self.embed_drop is not None:
            x = self.embed_drop(x)
        x = self.encoder(x)
        return self.mlm(x)


def bert_base(vocab_size=30522, max_length=512, dropout=0.1):
    """BERT-Base: 12 layers, 768 units, 12 heads."""
    return BERTModel(vocab_size, 768, 3072, 12, 12, max_length, dropout)


def bert_large(vocab_size=30522, max_length=512, dropout=0.1,
               remat=False):
    """BERT-Large: 24 layers, 1024 units, 16 heads — north-star
    workload 3."""
    return BERTModel(vocab_size, 1024, 4096, 24, 16, max_length,
                     dropout, remat=remat)


def transformer_encoder(num_layers=6, units=512, hidden_size=2048,
                        num_heads=8, dropout=0.1, causal=False):
    """Transformer-base encoder stack (WMT-style config 4)."""
    return TransformerEncoder(num_layers, units, hidden_size, num_heads,
                              dropout, causal)


def transformer_big(vocab_size=32768, max_length=256, dropout=0.1,
                    remat=False):
    """Transformer-big WMT config (north-star workload 4, SURVEY M6):
    6+6 layers, 1024 units, 16 heads, 4096 FFN."""
    return TransformerModel(vocab_size, 1024, 4096, 6, 16, max_length,
                            dropout, remat=remat)


def transformer_base(vocab_size=32768, max_length=256, dropout=0.1):
    """Transformer-base WMT config: 6+6 layers, 512 units, 8 heads."""
    return TransformerModel(vocab_size, 512, 2048, 6, 8, max_length,
                            dropout)
