"""Data iterators — the ``mx.io`` surface.

Reference: ``python/mxnet/io.py``† (``DataIter``, ``DataBatch``,
``DataDesc``, ``NDArrayIter``, ``ResizeIter``, ``PrefetchingIter``) and
the C++ iterators in ``src/io/``† (``MNISTIter``, ``CSVIter``,
``ImageRecordIter``).

TPU-native notes: iterators yield host-side batches; placement onto the
chip is the consumer's job (gluon ``split_and_load`` / the compiled
train step), so the pipeline overlaps host decode with device compute
the way the reference's PrefetcherIter overlaps H2D copies
(``src/io/iter_prefetcher.h``†).  Batches are padded, never ragged —
static shapes keep XLA from recompiling per batch.
"""
from __future__ import annotations

import os
import queue
import struct
import threading
from collections import namedtuple
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from . import obs
from .base import MXNetError
from .ndarray import NDArray, array

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter",
           "ResizeIter", "PrefetchingIter", "DeviceFeedIter", "CSVIter",
           "LibSVMIter", "MNISTIter", "ImageRecordIter"]


class DataDesc(namedtuple("DataDesc", ["name", "shape", "dtype", "layout"])):
    """Shape/dtype descriptor of one input (reference ``DataDesc``†)."""

    def __new__(cls, name, shape, dtype=np.float32, layout="NCHW"):
        return super().__new__(cls, name, tuple(shape), np.dtype(dtype),
                               layout)

    @staticmethod
    def get_batch_axis(layout: Optional[str]) -> int:
        if layout is None:
            return 0
        return layout.find("N")


class DataBatch:
    """One batch (reference ``DataBatch``†). ``pad`` = #samples at the
    tail that are padding (replicated), to be ignored by metrics."""

    def __init__(self, data, label=None, pad=0, index=None,
                 provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        shapes = [getattr(d, "shape", None) for d in (self.data or [])]
        return f"DataBatch: data shapes {shapes} pad {self.pad}"


class DataIter:
    """Iterator base (reference ``DataIter``†)."""

    def __init__(self, batch_size: int = 0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self) -> DataBatch:
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self) -> bool:
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        return 0


def _init_data(data, allow_empty: bool, default_name: str):
    """Normalize data/label argument into an ordered name→ndarray list
    (reference ``_init_data``†)."""
    if data is None:
        if not allow_empty:
            raise MXNetError("data cannot be None")
        return []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        if not allow_empty and len(data) == 0:
            raise MXNetError("empty data list")
        if len(data) == 1:
            items = [(default_name, data[0])]
        else:
            items = [(f"_{i}_{default_name}", d)
                     for i, d in enumerate(data)]
    elif isinstance(data, dict):
        items = sorted(data.items())
    else:
        raise MXNetError(f"unsupported data type {type(data)}")
    out = []
    for name, arr in items:
        if isinstance(arr, NDArray):
            arr = arr.asnumpy()
        out.append((name, np.asarray(arr)))
    return out


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (reference ``NDArrayIter``†).

    last_batch_handle: 'pad' (replicate from the head; ``batch.pad``
    reports the count), 'discard', or 'roll_over' (leftover prepends the
    next epoch).
    """

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False,
                               default_name=data_name)
        self.label = _init_data(label, allow_empty=True,
                                default_name=label_name)
        self.num_data = self.data[0][1].shape[0]
        for name, arr in self.data + self.label:
            if arr.shape[0] != self.num_data:
                raise MXNetError(
                    f"{name} has {arr.shape[0]} samples, expected "
                    f"{self.num_data}")
        if last_batch_handle not in ("pad", "discard", "roll_over"):
            raise MXNetError(f"bad last_batch_handle {last_batch_handle}")
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self._rollover_remainder: Optional[np.ndarray] = None
        self._order = np.arange(self.num_data)
        self.reset()

    @property
    def provide_data(self) -> List[DataDesc]:
        return [DataDesc(name, (self.batch_size,) + arr.shape[1:],
                         arr.dtype)
                for name, arr in self.data]

    @property
    def provide_label(self) -> List[DataDesc]:
        return [DataDesc(name, (self.batch_size,) + arr.shape[1:],
                         arr.dtype)
                for name, arr in self.label]

    def reset(self):
        order = np.arange(self.num_data)
        if self.shuffle:
            np.random.shuffle(order)
        if self._rollover_remainder is not None and \
                self.last_batch_handle == "roll_over":
            order = np.concatenate([self._rollover_remainder, order])
            self._rollover_remainder = None
        self._order = order
        self.cursor = 0

    def __len__(self):
        """Batches per epoch.  For 'roll_over' this is the carry-free
        count (n // batch_size); epochs consuming a previous epoch's
        remainder may yield one more batch."""
        n = self.num_data
        if self.last_batch_handle == "pad":
            return (n + self.batch_size - 1) // self.batch_size
        return n // self.batch_size

    def iter_next(self) -> bool:
        n = len(self._order)
        if self.last_batch_handle == "discard":
            return self.cursor + self.batch_size <= n
        if self.cursor >= n:
            return False
        if self.cursor + self.batch_size > n and \
                self.last_batch_handle == "roll_over":
            self._rollover_remainder = self._order[self.cursor:]
            return False
        return True

    def next(self) -> DataBatch:
        if not self.iter_next():
            raise StopIteration
        idx = self._order[self.cursor:self.cursor + self.batch_size]
        pad = self.batch_size - len(idx)
        if pad:
            # wrap from the head as many times as needed (batch_size may
            # exceed the dataset) — batches are never ragged
            reps = [idx]
            need = pad
            while need > 0:
                take = self._order[:need]
                reps.append(take)
                need -= len(take)
            idx = np.concatenate(reps)
        self.cursor += self.batch_size
        data = [array(arr[idx]) for _, arr in self.data]
        label = [array(arr[idx]) for _, arr in self.label]
        return DataBatch(data=data, label=label, pad=pad,
                         index=idx.copy(),
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)


class ResizeIter(DataIter):
    """Resize another iterator to a fixed number of batches per epoch
    (reference ``ResizeIter``†)."""

    def __init__(self, data_iter: DataIter, size: int,
                 reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch: Optional[DataBatch] = None

    @property
    def provide_data(self):
        return self.data_iter.provide_data

    @property
    def provide_label(self):
        return self.data_iter.provide_label

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self) -> bool:
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def next(self) -> DataBatch:
        if not self.iter_next():
            raise StopIteration
        return self.current_batch


class PrefetchingIter(DataIter):
    """Background-thread prefetch over one or more iterators
    (reference ``PrefetchingIter``†, the python face of
    ``iter_prefetcher.h``†'s double buffering)."""

    def __init__(self, iters, rename_data=None, rename_label=None):
        self.iters = iters if isinstance(iters, (list, tuple)) else [iters]
        super().__init__(self.iters[0].batch_size)
        self.rename_data = rename_data
        self.rename_label = rename_label
        self._queue: "queue.Queue" = queue.Queue(maxsize=2)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._start()

    def _start(self):
        def worker():
            while not self._stop.is_set():
                try:
                    batches = [it.next() for it in self.iters]
                except StopIteration:
                    self._queue.put(None)
                    return
                self._queue.put(batches)
        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()

    @property
    def provide_data(self):
        return sum([it.provide_data for it in self.iters], [])

    @property
    def provide_label(self):
        return sum([it.provide_label for it in self.iters], [])

    def reset(self):
        self._stop.set()
        # drain so the worker can exit a blocking put
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        self._thread.join()
        for it in self.iters:
            it.reset()
        self._stop = threading.Event()
        self._queue = queue.Queue(maxsize=2)
        self._start()

    def next(self) -> DataBatch:
        batches = self._queue.get()
        if batches is None:
            raise StopIteration
        if len(batches) == 1:
            return batches[0]
        return DataBatch(
            data=sum([b.data for b in batches], []),
            label=sum([b.label for b in batches], []),
            pad=max(b.pad for b in batches))

    def iter_next(self):
        raise MXNetError("use next() on PrefetchingIter")

    def __del__(self):
        self._stop.set()
        try:
            while True:
                self._queue.get_nowait()
        except Exception:
            pass


class DeviceFeedIter(DataIter):
    """Double-buffered host→device feed — the H2D half of the
    reference's PrefetcherIter (``iter_prefetcher.h``†).

    Keeps ONE staged batch in flight ahead of the consumer: when
    ``next()`` hands back batch N, batch N+1's ``device_put`` has
    already been issued.  jax transfers are asynchronous — the call
    returns immediately with the host→HBM copy running in the
    background, and the compiled step's own input dependency is the
    sync point — so the copy for N+1 overlaps the step for N.

    Compose with :class:`PrefetchingIter` for the full pipeline::

        disk → assemble (worker thread) → H2D (in flight) → step

    with ``host_batches=True`` on the inner :class:`ImageRecordIter`
    so the worker thread hands over raw numpy and the single
    ``device_put`` per array happens here, one batch ahead.
    """

    def __init__(self, data_iter: DataIter, ctx=None):
        super().__init__(data_iter.batch_size)
        import jax
        self.data_iter = data_iter
        self._device = ctx.jax_device if ctx is not None \
            else jax.devices()[0]
        self._pending: Optional[DataBatch] = None
        self._done = False
        # ISSUE 8: staged-batch throughput in the obs registry
        self._obs = obs.enabled()
        self._m_batches = obs.counter(
            "mxtpu_io_batches_total",
            "Batches staged to device, per iterator kind.",
            labels=("iter",)).labels(iter="device_feed")

    @property
    def provide_data(self):
        return self.data_iter.provide_data

    @property
    def provide_label(self):
        return self.data_iter.provide_label

    def _stage(self, batch: DataBatch) -> DataBatch:
        import jax

        def put(arrs):
            out = []
            for a in arrs or []:
                raw = a.data if isinstance(a, NDArray) else a
                out.append(NDArray(jax.device_put(raw, self._device),
                                   None, _placed=True))
            return out

        return DataBatch(data=put(batch.data), label=put(batch.label),
                         pad=batch.pad, index=batch.index,
                         provide_data=batch.provide_data,
                         provide_label=batch.provide_label)

    def _pull(self) -> Optional[DataBatch]:
        try:
            batch = self._stage(self.data_iter.next())
        except StopIteration:
            return None
        if self._obs:
            self._m_batches.inc()
        return batch

    def reset(self):
        self.data_iter.reset()
        self._pending = None
        self._done = False

    def next(self) -> DataBatch:
        if self._pending is None:
            if self._done:
                self._done = False  # epoch boundary consumed
                raise StopIteration
            self._pending = self._pull()
            if self._pending is None:
                raise StopIteration
        out = self._pending
        self._pending = self._pull()  # issue N+1's H2D before handing N
        if self._pending is None:
            self._done = True
        return out

    def iter_next(self):
        raise MXNetError("use next() on DeviceFeedIter")


class CSVIter(DataIter):
    """CSV file iterator (reference C++ ``CSVIter``,
    ``src/io/iter_csv.cc``†) — host-side parse, padded final batch."""

    def __init__(self, data_csv: str, data_shape, label_csv=None,
                 label_shape=(1,), batch_size=1, round_batch=True,
                 **_ignored):
        super().__init__(batch_size)
        data = np.loadtxt(data_csv, delimiter=",", ndmin=2,
                          dtype=np.float32)
        self._data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", ndmin=2,
                               dtype=np.float32)
            label = label.reshape((-1,) + tuple(label_shape))
        else:
            label = np.zeros((len(self._data),) + tuple(label_shape),
                             np.float32)
        self._inner = NDArrayIter(
            {"data": self._data}, {"label": label}, batch_size=batch_size,
            last_batch_handle="pad" if round_batch else "discard")

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    def iter_next(self):
        return self._inner.iter_next()


class LibSVMIter(DataIter):
    """libsvm-format iterator (reference C++ ``LibSVMIter``,
    ``src/io/iter_libsvm.cc``†).

    Line format: ``label [qid:n] idx:val idx:val ...``.  Feature
    indices are ZERO-based like the reference's LibSVMIter† (set
    ``indexing='one'`` for conventional 1-based files — never guessed
    silently).  Multi-dimensional labels come from a SECOND libsvm
    file via ``label_libsvm`` (the reference's mechanism).
    DIVERGENCE (SURVEY §7 hard-part 3): the reference yields CSR
    batches; the TPU build densifies into ``(batch, *data_shape)`` —
    same API, dense storage, documented in COVERAGE.md."""

    def __init__(self, data_libsvm: str, data_shape, label_shape=(1,),
                 label_libsvm=None, batch_size=1, round_batch=True,
                 indexing="zero", **_ignored):
        super().__init__(batch_size)
        if indexing not in ("zero", "one"):
            raise MXNetError("indexing must be 'zero' or 'one'")
        off = 1 if indexing == "one" else 0
        data, labels = self._parse(data_libsvm,
                                   int(np.prod(data_shape)), off)
        if label_libsvm is not None:
            lab, _ = self._parse(label_libsvm,
                                 int(np.prod(label_shape)), off)
            lab = lab.reshape((-1,) + tuple(label_shape))
            if len(lab) != len(data):
                raise MXNetError(
                    f"label file has {len(lab)} rows, data has "
                    f"{len(data)}")
        elif tuple(label_shape) not in ((1,), ()):
            raise MXNetError(
                f"label_shape {tuple(label_shape)} needs label_libsvm "
                f"(the inline label is a single float per line)")
        else:
            lab = np.asarray(labels, np.float32).reshape(-1, 1)
        self._inner = NDArrayIter(
            {"data": data.reshape((-1,) + tuple(data_shape))},
            {"label": lab},
            batch_size=batch_size,
            last_batch_handle="pad" if round_batch else "discard")

    @staticmethod
    def _parse(path, dim, off):
        rows = []
        labels = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = line.split()
                labels.append(float(parts[0]))
                feats = []
                for tok in parts[1:]:
                    if tok.startswith("qid:"):
                        continue
                    idx, val = tok.split(":")
                    feats.append((int(idx) - off, float(val)))
                rows.append(feats)
        data = np.zeros((len(rows), dim), np.float32)
        for r, feats in enumerate(rows):
            for j, v in feats:
                if not 0 <= j < dim:
                    raise MXNetError(
                        f"libsvm feature index {j + off} out of range "
                        f"for dim {dim} (indexing="
                        f"{'one' if off else 'zero'} — wrong "
                        f"`indexing=`?)")
                data[r, j] = v
        return data, labels

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    def iter_next(self):
        return self._inner.iter_next()


def _read_idx_ubyte(path: str) -> np.ndarray:
    """Read an IDX-format file (the MNIST container)."""
    with open(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        dtype_code = (magic >> 8) & 0xFF
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        dtypes = {0x08: np.uint8, 0x09: np.int8, 0x0B: np.int16,
                  0x0C: np.int32, 0x0D: np.float32,
                  0x0E: np.float64}  # mxlint: disable=dtype-hygiene (IDX wire format)
        data = np.frombuffer(f.read(), dtype=np.dtype(dtypes[dtype_code])
                             .newbyteorder(">"))
        return data.reshape(dims).astype(dtypes[dtype_code])


class MNISTIter(DataIter):
    """MNIST idx-file iterator (reference ``MNISTIter``,
    ``src/io/iter_mnist.cc``†)."""

    def __init__(self, image: str, label: str, batch_size=128,
                 shuffle=True, flat=False, seed=0, silent=True,
                 **_ignored):
        super().__init__(batch_size)
        imgs = _read_idx_ubyte(image).astype(np.float32) / 255.0
        labels = _read_idx_ubyte(label).astype(np.float32)
        if flat:
            imgs = imgs.reshape(len(imgs), -1)
        else:
            imgs = imgs.reshape(len(imgs), 1, imgs.shape[1], imgs.shape[2])
        if shuffle:
            order = np.random.RandomState(seed).permutation(len(imgs))
            imgs, labels = imgs[order], labels[order]
        self._inner = NDArrayIter({"data": imgs}, {"label": labels},
                                  batch_size=batch_size,
                                  last_batch_handle="discard")

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    def iter_next(self):
        return self._inner.iter_next()


class ImageRecordIter(DataIter):
    """RecordIO image iterator with decode + augmentation
    (reference ``ImageRecordIter``, ``src/io/iter_image_recordio_2.cc``†).

    Python threads do the JPEG decode (the C++ pipeline in ``core/`` is
    the fast path once built); augmentation params mirror the reference's
    ``image_aug_default.cc``† subset that TPU input pipelines use.
    """

    def __init__(self, path_imgrec: str, data_shape, batch_size=1,
                 path_imgidx: Optional[str] = None, shuffle=False,
                 rand_crop=False, rand_mirror=False, mean_r=0.0,
                 mean_g=0.0, mean_b=0.0, std_r=1.0, std_g=1.0, std_b=1.0,
                 scale=1.0, label_width=1, round_batch=True,
                 preprocess_threads=4, seed=0, raw_records=False,
                 dtype="float32", host_batches=False, **_ignored):
        super().__init__(batch_size)
        from . import recordio as rio
        # raw_records: records hold pre-decoded CHW pixel bytes at
        # data_shape (no JPEG decode).  dtype="uint8" emits uint8
        # batches WITHOUT host-side mean/std — pair with device-side
        # normalization (the cast + normalize fuses into the first
        # conv's XLA program; the TPU input-pipeline recipe for
        # single-core hosts, BASELINE.md "Input pipeline").
        # Raw batches are assembled VECTORIZED: the whole batch is read
        # in one call (native read_batch_into when core/ is built),
        # then one frombuffer + blockwise mirror/normalize — NumPy
        # releases the GIL on the big copies, so assembly no longer
        # serializes against training dispatch (VERDICT r5 item 2).
        self.raw_records = bool(raw_records)
        # host_batches: yield numpy instead of NDArray — the producer
        # side of a DeviceFeedIter pipeline, where the single
        # device_put per array is issued one batch ahead
        self.host_batches = bool(host_batches)
        self._raw_batched = True      # drops to per-record on ragged files
        self._raw_meta = None         # (header_bytes, flag), lazy
        self._out_dtype = np.dtype(dtype)
        if self._out_dtype not in (np.dtype(np.float32),
                                   np.dtype(np.uint8)):
            raise MXNetError("ImageRecordIter dtype must be float32 "
                             "or uint8")
        self.data_shape = tuple(data_shape)
        self.rand_crop = rand_crop
        self.rand_mirror = rand_mirror
        self.mean = np.array([mean_r, mean_g, mean_b], np.float32)
        self.std = np.array([std_r, std_g, std_b], np.float32)
        self.scale = scale
        self.label_width = label_width
        self.shuffle = shuffle
        self._rng = np.random.RandomState(seed)
        # decode pool: cv2.imdecode/resize release the GIL, so N
        # threads give ~N× decode throughput (the role of the
        # reference's N decode threads in iter_image_recordio_2.cc†)
        self._threads = max(1, int(preprocess_threads))
        self._pool = None
        if path_imgidx and os.path.exists(path_imgidx):
            self._rec = rio.MXIndexedRecordIO(path_imgidx, path_imgrec,
                                              "r")
            self._keys = list(self._rec.keys)
        else:
            self._rec = rio.MXRecordIO(path_imgrec, "r")
            self._keys = None
            if shuffle:
                raise MXNetError("shuffle requires path_imgidx")
        self.last_batch_handle = "pad" if round_batch else "discard"
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shp = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        return [DataDesc("softmax_label", shp)]

    def reset(self):
        if self._keys is not None:
            self._order = list(self._keys)
            if self.shuffle:
                self._rng.shuffle(self._order)
            self._pos = 0
        else:
            self._rec.reset()
        self._exhausted = False

    def _read_raw(self) -> Optional[bytes]:
        if self._keys is not None:
            if self._pos >= len(self._order):
                return None
            raw = self._rec.read_idx(self._order[self._pos])
            self._pos += 1
            return raw
        return self._rec.read()

    def close(self) -> None:
        """Release the decode pool (also runs at GC — the reference
        iterator had no explicit close either)."""
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def _decode_one(self, raw: bytes, aug_u=(0.0, 0.0, 0.0)):
        """``aug_u``: three pre-drawn uniforms (crop-y, crop-x, mirror)
        — drawn serially on the consumer thread so seeded runs are
        reproducible regardless of decode-pool scheduling."""
        from . import recordio as rio
        if self.raw_records:
            header, body = rio.unpack(raw)
            arr = np.frombuffer(body, np.uint8).reshape(self.data_shape)
            if self.rand_mirror and aug_u[2] < 0.5:
                arr = arr[:, :, ::-1]
            label = header.label
            if isinstance(label, np.ndarray) and self.label_width == 1:
                label = float(label[0])
            if self._out_dtype == np.uint8:
                return arr, label
            img32 = (arr.astype(np.float32) -
                     self.mean.reshape(3, 1, 1)) * self.scale / \
                self.std.reshape(3, 1, 1)
            return img32, label
        header, img = rio.unpack_img(raw, iscolor=1)
        c, h, w = self.data_shape
        ih, iw = img.shape[:2]
        if self.rand_crop and ih >= h and iw >= w:
            y0 = int(aug_u[0] * (ih - h + 1))
            x0 = int(aug_u[1] * (iw - w + 1))
            img = img[y0:y0 + h, x0:x0 + w]
        elif (ih, iw) != (h, w):
            import cv2
            img = cv2.resize(img, (w, h))
        if self.rand_mirror and aug_u[2] < 0.5:
            img = img[:, ::-1]
        img = img[:, :, ::-1]  # BGR→RGB
        if self._out_dtype == np.uint8:
            img = np.ascontiguousarray(img)
        else:
            # reference order (iter_image_recordio_2.cc†): mean
            # subtraction happens in pixel units, THEN scale, then
            # std division
            img = (img.astype(np.float32) - self.mean) * self.scale / \
                self.std
        label = header.label
        if isinstance(label, np.ndarray) and self.label_width == 1:
            label = float(label[0])
        return img.transpose(2, 0, 1), label

    # -- vectorized raw-record batch assembly --------------------------

    def _raw_init_meta(self, first_raw: bytes):
        """Derive (header_bytes, flag) from the first record; raw files
        are homogeneous (fixed shape, fixed label flag) by contract."""
        from . import recordio as rio
        header, body = rio.unpack(first_raw)
        nbytes = int(np.prod(self.data_shape))
        if len(body) != nbytes:
            raise MXNetError(
                f"raw record payload is {len(body)} bytes but "
                f"data_shape {self.data_shape} needs {nbytes}")
        self._raw_meta = (len(first_raw) - nbytes, int(header.flag))

    def _parse_raw_headers(self, hdrs: bytes, n: int) -> np.ndarray:
        """Vectorized IRHeader parse → (n, label_width) float32."""
        from . import recordio as rio
        hdr_bytes, flag = self._raw_meta
        h = np.frombuffer(hdrs, np.uint8).reshape(n, hdr_bytes)
        if flag == 0:
            lab = h[:, 4:8].copy().view(np.float32)
            if self.label_width > 1:
                lab = np.broadcast_to(lab, (n, self.label_width))
        else:
            if flag < self.label_width:
                raise MXNetError(
                    f"records carry {flag} labels, label_width is "
                    f"{self.label_width}")
            lab = h[:, rio._IR_SIZE:rio._IR_SIZE + 4 * flag].copy() \
                .view(np.float32)[:, :self.label_width]
        return np.ascontiguousarray(lab, np.float32)

    def _next_raw_batch(self) -> DataBatch:
        from . import recordio as rio
        if self._exhausted:
            raise StopIteration
        B = self.batch_size
        nbytes = int(np.prod(self.data_shape))
        pix = np.empty((B,) + self.data_shape, np.uint8)
        if self._keys is not None:
            n = min(B, len(self._order) - self._pos)
            keys = self._order[self._pos:self._pos + n]
            self._pos += n
            if n:
                if self._raw_meta is None:
                    self._raw_init_meta(self._rec.read_idx(keys[0]))
                hdr_bytes, _ = self._raw_meta
                try:
                    hdrs = rio.read_batch_into(
                        self._rec.uri, [self._rec.idx[k] for k in keys],
                        [hdr_bytes + nbytes] * n, pix[:n], hdr_bytes,
                        self._threads)
                except (OSError, ValueError, MXNetError):
                    # irregular records: rewind and let the per-record
                    # path (which re-frames every record) handle them
                    self._pos -= n
                    self._raw_batched = False
                    return self._next_per_record()
        else:
            raws = []
            while len(raws) < B:
                raw = self._rec.read()
                if raw is None:
                    break
                raws.append(raw)
            n = len(raws)
            if n:
                if self._raw_meta is None:
                    self._raw_init_meta(raws[0])
                hdr_bytes, _ = self._raw_meta
                if any(len(r) != hdr_bytes + nbytes for r in raws):
                    raise MXNetError(
                        "ragged raw records (lengths differ); cannot "
                        "batch-assemble")
                rows = np.frombuffer(b"".join(raws), np.uint8) \
                    .reshape(n, hdr_bytes + nbytes)
                pix[:n].reshape(n, nbytes)[...] = rows[:, hdr_bytes:]
                hdrs = rows[:, :hdr_bytes].tobytes()
        if n == 0:
            self._exhausted = True
            raise StopIteration
        labels = self._parse_raw_headers(hdrs, n)
        aug = self._rng.rand(n, 3)
        if self.rand_mirror:
            m = np.nonzero(aug[:, 2] < 0.5)[0]
            if m.size:
                pix[m] = pix[m][..., ::-1]
        pad = B - n
        if pad:
            self._exhausted = True
            if self.last_batch_handle == "discard":
                raise StopIteration
            reps = np.arange(n, B) % n
            pix[n:] = pix[reps]
            labels = np.concatenate([labels, labels[reps]], axis=0)
        if self._out_dtype == np.uint8:
            data = pix
        else:
            data = (pix.astype(np.float32) -
                    self.mean.reshape(1, 3, 1, 1)) * self.scale / \
                self.std.reshape(1, 3, 1, 1)
        lab = labels[:, 0] if self.label_width == 1 else labels
        wrap = (lambda a: a) if self.host_batches else array
        return DataBatch(data=[wrap(data)], label=[wrap(lab)], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)

    def next(self) -> DataBatch:
        if self.raw_records and self._raw_batched:
            return self._next_raw_batch()
        return self._next_per_record()

    def _next_per_record(self) -> DataBatch:
        if self._exhausted:
            raise StopIteration
        c, h, w = self.data_shape
        data = np.zeros((self.batch_size, c, h, w), self._out_dtype)
        labels = np.zeros((self.batch_size, self.label_width), np.float32)
        raws = []
        while len(raws) < self.batch_size:
            raw = self._read_raw()
            if raw is None:
                break
            raws.append(raw)
        n = len(raws)
        # augmentation uniforms drawn serially from the seeded stream:
        # identical seeds give identical augmentations no matter how
        # the decode pool schedules
        aug = self._rng.rand(n, 3) if n else None
        if n and self._threads > 1:
            if self._pool is None:
                from concurrent.futures import ThreadPoolExecutor
                self._pool = ThreadPoolExecutor(self._threads)
            for i, (img, label) in enumerate(
                    self._pool.map(self._decode_one, raws, aug)):
                data[i] = img
                labels[i] = label
        else:
            for i, raw in enumerate(raws):
                img, label = self._decode_one(raw, aug[i])
                data[i] = img
                labels[i] = label
        if n == 0:
            self._exhausted = True
            raise StopIteration
        pad = self.batch_size - n
        if pad:
            self._exhausted = True
            if self.last_batch_handle == "discard":
                raise StopIteration
            for i in range(n, self.batch_size):
                data[i] = data[i - n]
                labels[i] = labels[i - n]
        lab = labels[:, 0] if self.label_width == 1 else labels
        wrap = (lambda a: a) if self.host_batches else array
        return DataBatch(data=[wrap(data)], label=[wrap(lab)], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)

    def iter_next(self):
        try:
            self._batch = self.next()
            return True
        except StopIteration:
            return False
