"""Base utilities: errors, env-flag config, generic registry.

TPU-native re-design of the roles played by dmlc-core in the reference
(``3rdparty/dmlc-core/``†: logging/CHECK, ``dmlc::GetEnv`` env-var config
catalogued in ``docs/faq/env_var.md``†, and ``DMLC_REGISTRY_*`` generic
registries).  († = canonical upstream Apache MXNet v1.x path, cited per
SURVEY.md convention — the reference mount was empty this round.)
"""
from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, Generic, List, Optional, TypeVar

__all__ = [
    "MXNetError",
    "check_call",
    "get_env",
    "env_flags",
    "Registry",
    "string_types",
    "numeric_types",
    "integer_types",
    "_as_list",
]


def _as_list(x):
    """Wrap a non-list value in a list (lists/tuples pass through as
    lists) — shared by kvstore/metric/io."""
    return list(x) if isinstance(x, (list, tuple)) else [x]


class MXNetError(RuntimeError):
    """Framework error type (parity with ``mxnet.base.MXNetError``,
    ``python/mxnet/base.py``†). There is no C ABI error TLS here; Python
    exceptions propagate directly, including asynchronous XLA errors
    re-raised at sync points (see ndarray.NDArray.wait_to_read)."""


def check_call(ret: int) -> None:
    """Compat shim for code written against the reference's ctypes protocol
    (``python/mxnet/base.py``† ``check_call``)."""
    if ret != 0:
        raise MXNetError("non-zero return code %d" % ret)


string_types = (str,)
numeric_types = (float, int)
integer_types = (int,)

_TRUTHY = {"1", "true", "yes", "on"}
_FALSY = {"0", "false", "no", "off", ""}


def get_env(name: str, default: Any = None, dtype: type = str) -> Any:
    """``dmlc::GetEnv`` equivalent. Accepts both the new ``MXTPU_*`` and the
    reference's ``MXNET_*`` spelling (``MXNET_`` is consulted as a fallback
    so reference-era scripts keep working)."""
    val = os.environ.get(name)
    if val is None and name.startswith("MXTPU_"):
        val = os.environ.get("MXNET_" + name[len("MXTPU_"):])
    if val is None:
        return default
    if dtype is bool:
        low = val.strip().lower()
        if low in _TRUTHY:
            return True
        if low in _FALSY:
            return False
        raise MXNetError(f"invalid boolean env value {name}={val!r}")
    return dtype(val)


class _EnvFlags:
    """Lazy accessors over the knob registry (``mxtpu/knobs.py`` — the
    role of ``docs/faq/env_var.md``†).  Each flag is read live so tests
    can monkeypatch os.environ; knobs is imported lazily because it
    imports this module for MXNetError."""

    @property
    def engine_type(self) -> str:
        # MXNET_ENGINE_TYPE=NaiveEngine forces synchronous execution for
        # debugging (reference: src/engine/engine.cc† engine selection).
        from . import knobs
        return knobs.get("MXTPU_ENGINE_TYPE")

    @property
    def synchronous(self) -> bool:
        return self.engine_type == "NaiveEngine"

    @property
    def exec_bulk(self) -> bool:
        from . import knobs
        return knobs.get("MXTPU_EXEC_BULK_EXEC_TRAIN")

    @property
    def profiler_autostart(self) -> bool:
        from . import knobs
        return knobs.get("MXTPU_PROFILER_AUTOSTART")

    @property
    def test_seed(self) -> Optional[int]:
        from . import knobs
        return knobs.get("MXTPU_TEST_SEED", default=None)

    @property
    def kvstore_bigarray_bound(self) -> int:
        from . import knobs
        return knobs.get("MXTPU_KVSTORE_BIGARRAY_BOUND")

    @property
    def default_dtype(self) -> str:
        from . import knobs
        return knobs.get("MXTPU_DEFAULT_DTYPE")


env_flags = _EnvFlags()

T = TypeVar("T")


class Registry(Generic[T]):
    """Generic name->entry registry (role of ``DMLC_REGISTRY_*``†).

    Used for ops, optimizers, metrics, initializers, data iterators and
    KVStore types, mirroring how the reference registers each of those
    through dmlc registries (e.g. ``MXNET_REGISTER_IO_ITER``†,
    ``NNVM_REGISTER_OP``†)."""

    def __init__(self, kind: str):
        self.kind = kind
        self._entries: Dict[str, T] = {}
        self._lower: Dict[str, T] = {}
        self._lock = threading.Lock()

    def register(self, name: Optional[str] = None, *, aliases: tuple = (),
                 allow_override: bool = False) -> Callable[[T], T]:
        def _do(entry: T) -> T:
            key = name or getattr(entry, "__name__", None)
            if key is None:
                raise MXNetError(f"cannot infer registry name for {entry!r}")
            keys = []
            for k in (key,) + tuple(aliases):
                if k not in keys:
                    keys.append(k)
            with self._lock:
                for k in keys:
                    if k in self._entries and not allow_override:
                        raise MXNetError(
                            f"{self.kind} '{k}' already registered")
                    self._entries[k] = entry
                    self._lower.setdefault(k.lower(), entry)
            return entry
        return _do

    def get(self, name: str) -> T:
        e = self._entries.get(name) or self._lower.get(name.lower())
        if e is None:
            raise MXNetError(
                f"unknown {self.kind} '{name}'. known: "
                f"{sorted(self._entries)[:40]}")
        return e

    def find(self, name: str) -> Optional[T]:
        return self._entries.get(name) or self._lower.get(name.lower())

    def __contains__(self, name: str) -> bool:
        return name in self._entries or name.lower() in self._lower

    def list(self) -> List[str]:
        return sorted(self._entries)
