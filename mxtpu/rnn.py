"""Legacy ``mx.rnn`` namespace (reference ``python/mxnet/rnn/``†):
symbol-era cell aliases + ``BucketSentenceIter``.  New code should use
``gluon.rnn``; this module keeps reference-era scripts importable.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from .base import MXNetError
from .gluon.rnn import (RNNCell, LSTMCell, GRUCell, SequentialRNNCell,
                        BidirectionalCell, DropoutCell, ResidualCell)
from .io import DataBatch, DataDesc, DataIter
from .ndarray import array

__all__ = ["RNNCell", "LSTMCell", "GRUCell", "SequentialRNNCell",
           "BidirectionalCell", "DropoutCell", "ResidualCell",
           "BucketSentenceIter"]


class BucketSentenceIter(DataIter):
    """Bucketed sentence iterator (reference ``BucketSentenceIter``†):
    sorts variable-length integer sequences into the tightest bucket,
    pads to the bucket length, yields batches with ``bucket_key`` for
    ``BucketingModule``."""

    def __init__(self, sentences: Sequence[Sequence[int]],
                 batch_size: int, buckets: Optional[List[int]] = None,
                 invalid_label: int = -1, data_name: str = "data",
                 label_name: str = "softmax_label", dtype=np.float32):
        super().__init__(batch_size)
        if buckets is None:
            lens = np.bincount([len(s) for s in sentences])
            buckets = [i for i, n in enumerate(lens)
                       if n >= batch_size]
        buckets = sorted(buckets)
        if not buckets:
            raise MXNetError("no usable buckets")
        self.buckets = buckets
        self.data_name = data_name
        self.label_name = label_name
        self.invalid_label = invalid_label
        self.dtype = dtype
        self.data: List[List[np.ndarray]] = [[] for _ in buckets]
        for s in sentences:
            buck = next((i for i, b in enumerate(buckets)
                         if b >= len(s)), None)
            if buck is None:
                continue  # longer than the largest bucket: dropped
            buf = np.full((buckets[buck],), invalid_label, dtype)
            buf[:len(s)] = s
            self.data[buck].append(buf)
        self.data = [np.asarray(x, dtype) if len(x) else
                     np.empty((0, b), dtype)
                     for x, b in zip(self.data, buckets)]
        self.default_bucket_key = max(buckets)
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(self.data_name,
                         (self.batch_size, self.default_bucket_key),
                         self.dtype)]

    @property
    def provide_label(self):
        return [DataDesc(self.label_name,
                         (self.batch_size, self.default_bucket_key),
                         self.dtype)]

    def reset(self):
        self.curr_idx = 0
        self.idx = []
        for i, buck in enumerate(self.data):
            np.random.shuffle(buck)
            for j in range(0, len(buck) - self.batch_size + 1,
                           self.batch_size):
                self.idx.append((i, j))
        np.random.shuffle(self.idx)

    def next(self) -> DataBatch:
        if self.curr_idx >= len(self.idx):
            raise StopIteration
        i, j = self.idx[self.curr_idx]
        self.curr_idx += 1
        buck_len = self.buckets[i]
        chunk = self.data[i][j:j + self.batch_size]
        # label = next-token shift (the language-model convention)
        label = np.full_like(chunk, self.invalid_label)
        label[:, :-1] = chunk[:, 1:]
        batch = DataBatch(
            data=[array(chunk)], label=[array(label)], pad=0,
            provide_data=[DataDesc(self.data_name,
                                   (self.batch_size, buck_len),
                                   self.dtype)],
            provide_label=[DataDesc(self.label_name,
                                    (self.batch_size, buck_len),
                                    self.dtype)])
        batch.bucket_key = buck_len
        return batch

    def iter_next(self):
        return self.curr_idx < len(self.idx)
