# mxlint: disable-file=dtype-hygiene  (f64 oracle harness on purpose:
# finite-difference gradients and numpy references need the headroom)
"""Testing utilities — the backend-equivalence and gradient-check harness.

Reference: ``python/mxnet/test_utils.py``† — ``assert_almost_equal``,
``rand_ndarray``, ``check_numeric_gradient`` (finite differences vs the
framework backward), ``check_symbolic_forward/backward`` (vs numpy
references), and ``check_consistency`` (the cpu↔accelerator oracle,
SURVEY.md §4.2: "the single most important harness to replicate").

TPU-native notes: tolerances are keyed per dtype AND widened on the
accelerator backend, because TPU matmuls default to bf16-accumulated
f32 which an exact-f32 CPU reference will not match bitwise.
"""
from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence, Union

import jax
import numpy as np

from .base import MXNetError
from .context import Context, cpu, current_context
from .ndarray import NDArray, array

__all__ = [
    "default_context", "set_default_context", "default_dtype",
    "default_rtols", "default_atols", "get_tolerance",
    "same", "almost_equal", "assert_almost_equal", "assert_allclose",
    "rand_ndarray", "random_arrays", "rand_shape_2d", "rand_shape_3d",
    "rand_shape_nd", "create_vector",
    "simple_forward", "check_numeric_gradient", "numeric_grad",
    "check_symbolic_forward", "check_symbolic_backward",
    "check_consistency", "assert_exception", "retry",
]

_default_ctx: Optional[Context] = None


def default_context() -> Context:
    """Current default test context (reference ``default_context()``†)."""
    return _default_ctx if _default_ctx is not None else current_context()


def set_default_context(ctx: Context) -> None:
    global _default_ctx
    _default_ctx = ctx


def default_dtype():
    return np.float32


# ----------------------------------------------------------------------
# tolerances
# ----------------------------------------------------------------------

#: per-dtype rtol/atol, split by backend class.  The accelerator column is
#: looser for f32 because the MXU accumulates bf16 products (SURVEY §7
#: hard-part 9: "bf16-default matmuls vs fp32 CPU refs").
# CPU column stays at the tight historical values (f32 1e-5/1e-6) so
# the deterministic backend keeps catching ~1e-5-relative regressions;
# only the accel column absorbs TPU numerics.
default_rtols = {
    "cpu": {np.dtype(np.float16): 1e-2, np.dtype(np.float32): 1e-5,
            np.dtype(np.float64): 1e-6, "bfloat16": 2e-2},
    "accel": {np.dtype(np.float16): 2e-2, np.dtype(np.float32): 1e-2,
              np.dtype(np.float64): 1e-5, "bfloat16": 4e-2},
}
default_atols = {
    "cpu": {np.dtype(np.float16): 1e-3, np.dtype(np.float32): 1e-6,
            np.dtype(np.float64): 1e-8, "bfloat16": 1e-2},
    "accel": {np.dtype(np.float16): 1e-2, np.dtype(np.float32): 1e-3,
              np.dtype(np.float64): 1e-6, "bfloat16": 2e-2},
}


def _backend_class() -> str:
    return "cpu" if jax.default_backend() == "cpu" else "accel"


def _dtype_key(dtype):
    name = np.dtype(dtype).name if not isinstance(dtype, str) else dtype
    if "bfloat16" in str(name):
        return "bfloat16"
    try:
        return np.dtype(dtype)
    except TypeError:
        return "bfloat16"


def get_tolerance(dtype, rtol=None, atol=None, backend=None):
    """(rtol, atol) for a dtype on the current backend."""
    backend = backend or _backend_class()
    key = _dtype_key(dtype)
    if rtol is None:
        rtol = default_rtols[backend].get(key, 1e-5)
    if atol is None:
        atol = default_atols[backend].get(key, 1e-7)
    return rtol, atol


# ----------------------------------------------------------------------
# comparisons
# ----------------------------------------------------------------------

def _as_numpy(x) -> np.ndarray:
    if isinstance(x, NDArray):
        return x.asnumpy()
    if isinstance(x, jax.Array):
        return np.asarray(x)
    return np.asarray(x)


def same(a, b) -> bool:
    """Exact equality (reference ``same``†)."""
    return np.array_equal(_as_numpy(a), _as_numpy(b))


def _ref_dtype(a: np.ndarray):
    """Tolerance-table key for an array — bfloat16 (ml_dtypes) has
    dtype.kind 'V', so match it by name before the float check."""
    if "bfloat16" in str(a.dtype):
        return "bfloat16"
    return a.dtype if a.dtype.kind == "f" else np.float32


def almost_equal(a, b, rtol=None, atol=None, equal_nan=False) -> bool:
    a, b = _as_numpy(a), _as_numpy(b)
    rtol, atol = get_tolerance(_ref_dtype(a), rtol, atol)
    return np.allclose(a.astype(np.float64), b.astype(np.float64),
                       rtol=rtol, atol=atol, equal_nan=equal_nan)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b"),
                        equal_nan=False):
    """Reference ``assert_almost_equal``† — reports the worst-offending
    location on failure."""
    a_np, b_np = _as_numpy(a), _as_numpy(b)
    if a_np.shape != b_np.shape:
        raise AssertionError(
            f"shape mismatch: {names[0]}{a_np.shape} vs {names[1]}{b_np.shape}")
    rtol, atol = get_tolerance(_ref_dtype(a_np), rtol, atol)
    if np.allclose(a_np.astype(np.float64), b_np.astype(np.float64),
                   rtol=rtol, atol=atol, equal_nan=equal_nan):
        return
    af, bf = a_np.astype(np.float64), b_np.astype(np.float64)
    err = np.abs(af - bf) - (atol + rtol * np.abs(bf))
    err = np.where(np.isnan(err), np.inf, err)
    idx = np.unravel_index(int(np.argmax(err)), err.shape) if err.shape else ()
    raise AssertionError(
        f"{names[0]} != {names[1]} (rtol={rtol}, atol={atol}): "
        f"worst at {idx}: {af[idx]!r} vs {bf[idx]!r}; "
        f"max |a-b| = {np.nanmax(np.abs(af - bf)):.6g}")


assert_allclose = assert_almost_equal


def assert_exception(fn, exception_type, *args, **kwargs):
    """Assert fn(*args, **kwargs) raises exception_type (reference†)."""
    try:
        fn(*args, **kwargs)
    except exception_type:
        return
    raise AssertionError(f"did not raise {exception_type.__name__}")


def retry(n):
    """Retry a flaky (statistical) test up to n times (reference ``retry``†)."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            last = None
            for _ in range(n):
                try:
                    return fn(*args, **kwargs)
                except AssertionError as e:  # pragma: no cover - flake path
                    last = e
            raise last
        return wrapper
    return deco


# ----------------------------------------------------------------------
# random data
# ----------------------------------------------------------------------

def rand_ndarray(shape, stype="default", density=None, dtype=None,
                 ctx=None, scale=1.0):
    """Random NDArray, dense or (API-parity) sparse
    (reference ``rand_ndarray``†)."""
    dtype = dtype or default_dtype()
    data = (np.random.uniform(-1, 1, size=shape) * scale).astype(dtype)
    if stype in ("row_sparse", "csr"):
        density = 0.5 if density is None else density
        mask = np.random.uniform(0, 1, size=shape) < density
        data = data * mask
        dense = array(data, ctx=ctx)
        return dense.tostype(stype) if hasattr(dense, "tostype") else dense
    return array(data, ctx=ctx)


def random_arrays(*shapes) -> List[np.ndarray]:
    """Numpy arrays of the given shapes (reference ``random_arrays``†)."""
    arrays = [np.random.randn(*s).astype(default_dtype()) if s else
              np.array(np.random.randn(), dtype=default_dtype())
              for s in shapes]
    return arrays if len(arrays) > 1 else arrays[0]


def rand_shape_2d(dim0=10, dim1=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1),
            np.random.randint(1, dim2 + 1))


def rand_shape_nd(ndim, dim=10):
    return tuple(np.random.randint(1, dim + 1, size=ndim))


def create_vector(size, dtype=np.int64):
    return array(np.arange(size, dtype=dtype))


# ----------------------------------------------------------------------
# executor plumbing shared by the check_* harnesses
# ----------------------------------------------------------------------

def _normalize_location(sym, location) -> Dict[str, np.ndarray]:
    """location may be a list (positional over ``list_arguments``) or a
    dict name→array, as in the reference harness."""
    args = sym.list_arguments()
    if isinstance(location, dict):
        return {k: _as_numpy(v) for k, v in location.items()}
    if len(location) != len(args):
        raise MXNetError(
            f"location has {len(location)} entries for {len(args)} args")
    return {name: _as_numpy(v) for name, v in zip(args, location)}


def _bind(sym, location, aux_states=None, grad_req="write", ctx=None):
    from .executor import Executor
    ctx = ctx or default_context()
    loc = {k: array(v, ctx=ctx) for k, v in location.items()}
    grads = None
    if grad_req != "null":
        grads = {k: array(np.zeros_like(v), ctx=ctx)
                 for k, v in location.items()}
    aux = None
    if aux_states:
        aux = {k: array(_as_numpy(v), ctx=ctx) for k, v in aux_states.items()}
    return sym.bind(ctx=ctx, args=loc, args_grad=grads, grad_req=grad_req,
                    aux_states=aux)


def simple_forward(sym, ctx=None, is_train=False, **inputs):
    """Forward a symbol on numpy inputs, return numpy outputs
    (reference ``simple_forward``†)."""
    loc = {k: _as_numpy(v) for k, v in inputs.items()}
    exe = _bind(sym, loc, grad_req="null", ctx=ctx)
    outs = [o.asnumpy() for o in exe.forward(is_train=is_train)]
    return outs if len(outs) > 1 else outs[0]


# ----------------------------------------------------------------------
# gradient checking
# ----------------------------------------------------------------------

def numeric_grad(f, location: Dict[str, np.ndarray], eps=1e-4,
                 grad_nodes: Optional[Sequence[str]] = None,
                 dtype=np.float64) -> Dict[str, np.ndarray]:
    """Central-difference gradient of scalar ``f(location)`` w.r.t. each
    entry (reference's numeric side of ``check_numeric_gradient``†)."""
    grad_nodes = list(grad_nodes) if grad_nodes else list(location)
    grads = {}
    base = {k: v.astype(dtype) for k, v in location.items()}
    for name in grad_nodes:
        x = base[name]
        g = np.zeros_like(x)
        flat = x.reshape(-1)
        gflat = g.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + eps
            fp = f({k: v for k, v in base.items()})
            flat[i] = orig - eps
            fm = f({k: v for k, v in base.items()})
            flat[i] = orig
            gflat[i] = (fp - fm) / (2 * eps)
        grads[name] = g
    return grads


def check_numeric_gradient(sym, location, aux_states=None, numeric_eps=1e-3,
                           rtol=1e-2, atol=None, grad_nodes=None,
                           ctx=None, dtype=np.float64):
    """Finite-difference-check the framework backward of ``sym``
    (reference ``check_numeric_gradient``†).  The symbol's outputs are
    contracted against a fixed random projection to produce the scalar
    objective, exactly as the reference does."""
    location = _normalize_location(sym, location)
    location = {k: v.astype(dtype) for k, v in location.items()}
    grad_nodes = list(grad_nodes) if grad_nodes else list(location)
    ctx = ctx or default_context()

    exe = _bind(sym, location, aux_states=aux_states, ctx=ctx)
    outs = exe.forward(is_train=True)
    proj = [np.random.normal(0, 0.01, size=o.shape).astype(dtype)
            for o in outs]
    exe.backward(out_grads=[array(p, ctx=ctx) for p in proj])
    sym_grads = {name: g.asnumpy()
                 for name, g in zip(sym.list_arguments(), exe.grad_arrays)
                 if g is not None and name in grad_nodes}

    # one executor reused across all finite-difference probes — only the
    # perturbed arrays change, via forward(**kwargs)
    probe_exe = _bind(sym, location, aux_states=aux_states,
                      grad_req="null", ctx=ctx)

    def objective(loc_np):
        os_ = probe_exe.forward(is_train=True,
                                **{k: array(v, ctx=ctx)
                                   for k, v in loc_np.items()})
        return float(sum((o.asnumpy().astype(dtype) * p).sum()
                         for o, p in zip(os_, proj)))

    num_grads = numeric_grad(objective, location, eps=numeric_eps,
                             grad_nodes=grad_nodes, dtype=dtype)
    atol = atol if atol is not None else 1e-4
    for name in grad_nodes:
        assert_almost_equal(sym_grads[name], num_grads[name], rtol=rtol,
                            atol=atol,
                            names=(f"autograd[{name}]", f"numeric[{name}]"))


def check_symbolic_forward(sym, location, expected, rtol=None, atol=None,
                           aux_states=None, ctx=None):
    """Compare sym's forward against numpy-reference outputs
    (reference ``check_symbolic_forward``†)."""
    location = _normalize_location(sym, location)
    exe = _bind(sym, location, aux_states=aux_states, grad_req="null",
                ctx=ctx)
    outs = exe.forward(is_train=False)
    if not isinstance(expected, (list, tuple)):
        expected = [expected]
    for i, (o, e) in enumerate(zip(outs, expected)):
        assert_almost_equal(o, e, rtol=rtol, atol=atol,
                            names=(f"forward[{i}]", f"expected[{i}]"))
    return [o.asnumpy() for o in outs]


def check_symbolic_backward(sym, location, out_grads, expected, rtol=None,
                            atol=None, aux_states=None, grad_req="write",
                            ctx=None):
    """Compare sym's backward against numpy-reference input grads
    (reference ``check_symbolic_backward``†)."""
    location = _normalize_location(sym, location)
    ctx = ctx or default_context()
    exe = _bind(sym, location, aux_states=aux_states, grad_req=grad_req,
                ctx=ctx)
    exe.forward(is_train=True)
    exe.backward(out_grads=[array(_as_numpy(g), ctx=ctx) for g in out_grads])
    got = {name: g.asnumpy()
           for name, g in zip(sym.list_arguments(), exe.grad_arrays)
           if g is not None}
    if isinstance(expected, (list, tuple)):
        expected = dict(zip(sym.list_arguments(), expected))
    for name, e in expected.items():
        assert_almost_equal(got[name], e, rtol=rtol, atol=atol,
                            names=(f"grad[{name}]", f"expected[{name}]"))
    return got


# ----------------------------------------------------------------------
# cross-backend consistency — the cpu↔tpu oracle
# ----------------------------------------------------------------------

def check_consistency(sym, ctx_list, scale=1.0, grad_req="write",
                      rtol=None, atol=None, aux_states=None,
                      arg_params=None):
    """Run the same symbol on every context in ``ctx_list`` and
    cross-compare forward outputs and input gradients within per-dtype
    tolerance (reference ``check_consistency``†, the main backend
    equivalence oracle per SURVEY §4.2).

    ctx_list entries are either Contexts or dicts
    ``{"ctx": Context, "type_dict": {argname: dtype}}`` as in the
    reference.  The highest-precision run is the comparison baseline.
    On a single-backend machine (tests on CPU) this still exercises
    dtype consistency (e.g. f32 vs f16 variants).
    """
    assert len(ctx_list) > 1, "need at least two contexts/variants"
    norm = []
    for entry in ctx_list:
        if isinstance(entry, Context):
            norm.append({"ctx": entry, "type_dict": {}})
        else:
            norm.append({"ctx": entry.get("ctx", default_context()),
                         "type_dict": dict(entry.get("type_dict", {}))})

    args = sym.list_arguments()
    shapes_known = arg_params is not None
    if not shapes_known:
        raise MXNetError("check_consistency requires arg_params "
                         "(dict name→numpy array) to fix shapes")
    base_loc = {k: _as_numpy(v) * scale for k, v in arg_params.items()}

    import jax as _jax
    runs = []
    for entry in norm:
        loc = {k: v.astype(entry["type_dict"].get(k, v.dtype))
               for k, v in base_loc.items()}
        exe = _bind(sym, loc, aux_states=aux_states, grad_req=grad_req,
                    ctx=entry["ctx"])
        # true-f32 matmuls for the oracle runs: the TPU default feeds
        # bf16 multiplicands to f32 dots (~3 decimal digits loose),
        # which would measure platform rounding, not lowering-rule
        # equivalence (SURVEY §7 hard-part 9).  Explicit low-precision
        # type_dict variants (bf16/f16) are unaffected — precision
        # only changes f32-input contractions.
        with _jax.default_matmul_precision("highest"):
            outs = [o.asnumpy()
                    for o in exe.forward(is_train=grad_req != "null")]
            grads = None
            if grad_req != "null":
                # identical head grads across runs (seeded
                # independently of the per-test global stream)
                rs = np.random.RandomState(0)
                ograds = [rs.normal(0, 1, size=o.shape).astype(o.dtype)
                          for o in outs]
                exe.backward(out_grads=[array(g, ctx=entry["ctx"])
                                        for g in ograds])
                grads = {name: g.asnumpy() for name, g in
                         zip(args, exe.grad_arrays) if g is not None}
        runs.append({"entry": entry, "outs": outs, "grads": grads})

    # baseline = widest dtype
    def _prec(run):
        dts = list(run["entry"]["type_dict"].values()) or [np.float32]
        return max(np.dtype(d).itemsize if d != "bfloat16" else 2
                   for d in dts)
    base = max(runs, key=_prec)

    for run in runs:
        if run is base:
            continue
        dts = list(run["entry"]["type_dict"].values()) or [np.float32]
        worst = min(dts, key=lambda d: 8 if d == "bfloat16" else
                    np.dtype(d).itemsize * 4)
        for i, (o, bo) in enumerate(zip(run["outs"], base["outs"])):
            assert_almost_equal(o.astype(np.float64), bo.astype(np.float64),
                                *get_tolerance(worst, rtol, atol),
                                names=(f"{run['entry']['ctx']}.out[{i}]",
                                       f"{base['entry']['ctx']}.out[{i}]"))
        if run["grads"] is not None:
            for name in run["grads"]:
                assert_almost_equal(
                    run["grads"][name].astype(np.float64),
                    base["grads"][name].astype(np.float64),
                    *get_tolerance(worst, rtol, atol),
                    names=(f"{run['entry']['ctx']}.grad[{name}]",
                           f"{base['entry']['ctx']}.grad[{name}]"))
    return runs
