"""Operator registry — the single source of truth for ops.

Re-design of the reference's NNVM op registry (``nnvm::Op`` with attributes
``FCompute``/``FInferShape``/``FGradient``…, registered per-op via
``NNVM_REGISTER_OP`` across ``src/operator/``†).  The TPU-native difference:
an op's "FCompute" is a *lowering rule* — a pure jax function from arrays to
arrays.  Shape/dtype inference falls out of ``jax.eval_shape`` on the same
rule (one definition serves eager, symbolic, and jit paths), and gradients
fall out of jax AD instead of hand-written FGradient passes.

Every op registered here is automatically exposed:
  * eagerly  as ``mxtpu.nd.<name>``   (NDArray in/out, autograd-taped)
  * lazily   as ``mxtpu.sym.<name>``  (Symbol graph nodes)
"""
from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax

from ..base import MXNetError, Registry
from .params import Param, ParamSet

__all__ = ["Op", "register_op", "get_op", "list_ops", "OP_REGISTRY", "Param"]


@dataclass
class Op:
    """Op metadata + lowering rule.

    fn: the jax lowering rule ``fn(*arrays, **resolved_params) -> array
        or tuple of arrays``.  Must be pure & traceable (no data-dependent
        python control flow) so it works under jit/vmap/grad.
    num_inputs: -1 for variadic (list input ops like concat/add_n).
    differentiable: ops like argmax/topk-indices get zero/None grads.
    """
    name: str
    fn: Callable[..., Any]
    params: ParamSet = field(default_factory=ParamSet)
    num_inputs: int = 1
    num_outputs: int = 1
    differentiable: bool = True
    grad_argnums: Optional[Tuple[int, ...]] = None
    doc: str = ""
    aliases: Tuple[str, ...] = ()
    #: optional callable(attrs_dict) -> int for ops whose output count
    #: depends on their params (e.g. RNN's state_outputs/mode)
    num_outputs_fn: Optional[Callable[[Dict[str, Any]], int]] = None

    def resolve_params(self, kwargs: Dict[str, Any]) -> Dict[str, Any]:
        return self.params.resolve(kwargs)

    def infer(self, *avals, **kwargs):
        """Shape/dtype inference via abstract evaluation — the role of the
        reference's ``InferShape``/``InferType`` NNVM passes
        (``src/executor/infer_graph_attr_pass.cc``†)."""
        resolved = self.resolve_params(kwargs)
        return jax.eval_shape(functools.partial(self.fn, **resolved), *avals)

    def __call__(self, *arrays, **kwargs):
        resolved = self.resolve_params(kwargs)
        return self.fn(*arrays, **resolved)


OP_REGISTRY: Registry[Op] = Registry("operator")


def register_op(name: str, *, params: Sequence[Param] = (),
                num_inputs: int = 1, num_outputs: int = 1,
                differentiable: bool = True,
                grad_argnums: Optional[Tuple[int, ...]] = None,
                aliases: Sequence[str] = (), doc: str = "",
                num_outputs_fn: Optional[Callable] = None):
    """Decorator registering a lowering rule as a framework op."""
    def _wrap(fn: Callable[..., Any]) -> Callable[..., Any]:
        op = Op(name=name, fn=fn, params=ParamSet(*params),
                num_inputs=num_inputs, num_outputs=num_outputs,
                differentiable=differentiable, grad_argnums=grad_argnums,
                doc=doc or (fn.__doc__ or ""), aliases=tuple(aliases),
                num_outputs_fn=num_outputs_fn)
        OP_REGISTRY.register(name, aliases=tuple(aliases))(op)
        return fn
    return _wrap


def get_op(name: str) -> Op:
    return OP_REGISTRY.get(name)


def list_ops() -> List[str]:
    return OP_REGISTRY.list()
