"""Typed op-parameter descriptors.

TPU-native equivalent of ``dmlc::Parameter`` (``3rdparty/dmlc-core/
include/dmlc/parameter.h``†): declarative, typed, range-checked kwargs that
form the public op API surface, (de)serializable to strings so symbol JSON
round-trips the way the reference's ``Symbol.tojson`` does (attrs are
string-valued in nnvm JSON).
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..base import MXNetError

__all__ = ["Param", "ParamSet"]

_MISSING = object()


@dataclass
class Param:
    name: str
    dtype: type = float            # python type: int, float, bool, str, tuple
    default: Any = _MISSING        # _MISSING => required
    lower: Optional[float] = None
    upper: Optional[float] = None
    enum: Optional[Sequence[Any]] = None
    doc: str = ""

    @property
    def required(self) -> bool:
        return self.default is _MISSING

    def validate(self, value: Any) -> Any:
        value = self._coerce(value)
        if self.lower is not None and value < self.lower:
            raise MXNetError(
                f"param {self.name}={value} below lower bound {self.lower}")
        if self.upper is not None and value > self.upper:
            raise MXNetError(
                f"param {self.name}={value} above upper bound {self.upper}")
        if self.enum is not None and value not in self.enum:
            raise MXNetError(
                f"param {self.name}={value!r} not in {tuple(self.enum)}")
        return value

    def _coerce(self, value: Any) -> Any:
        if value is None:
            return None
        if self.dtype is tuple:
            if isinstance(value, (list, tuple)):
                return tuple(value)
            if isinstance(value, str):
                parsed = ast.literal_eval(value)
                return tuple(parsed) if isinstance(parsed, (list, tuple)) \
                    else (parsed,)
            return (value,)
        if self.dtype is bool and isinstance(value, str):
            return value.strip().lower() in ("1", "true", "yes", "on")
        if isinstance(value, str) and self.dtype is not str:
            return self.dtype(ast.literal_eval(value))
        return self.dtype(value)

    def serialize(self, value: Any) -> str:
        return str(value)


class ParamSet:
    """Ordered collection of Param descriptors attached to an op."""

    def __init__(self, *params: Param):
        self.params: Dict[str, Param] = {p.name: p for p in params}

    def resolve(self, kwargs: Dict[str, Any]) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for name, p in self.params.items():
            if name in kwargs:
                out[name] = p.validate(kwargs[name])
            elif p.required:
                raise MXNetError(f"required param '{name}' missing")
            else:
                out[name] = p.default
        unknown = set(kwargs) - set(self.params)
        if unknown:
            raise MXNetError(
                f"unknown params {sorted(unknown)}; "
                f"accepted: {sorted(self.params)}")
        return out

    def serialize(self, resolved: Dict[str, Any]) -> Dict[str, str]:
        return {k: self.params[k].serialize(v) for k, v in resolved.items()
                if k in self.params}

    def __iter__(self):
        return iter(self.params.values())

    def __len__(self):
        return len(self.params)
