"""Op registry package — single source of truth for operator metadata.

TPU-native analogue of NNVM's op registry (``3rdparty/tvm/nnvm/``†,
SURVEY.md §2.1-N3): op descriptors with typed params whose lowering target
is XLA HLO via jax rules.
"""
from .params import Param, ParamSet
from .registry import Op, OP_REGISTRY, get_op, list_ops, register_op

__all__ = ["Param", "ParamSet", "Op", "OP_REGISTRY", "get_op", "list_ops",
           "register_op"]
