"""Evaluation metrics (reference ``python/mxnet/metric.py``†).

Metrics update on host from (label, pred) NDArray lists.  Note the
reference's known TPU foot-gun: ``update()`` calls ``asnumpy()`` — a
device sync per batch.  Keep metric updates OUT of the hot loop (or use
a CompositeEvalMetric at epoch granularity) on real chips; SURVEY.md
§5.5.
"""
from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

import numpy as _np

from .base import MXNetError, Registry
from .ndarray.ndarray import NDArray

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy",
           "F1", "Perplexity", "MAE", "MSE", "RMSE", "CrossEntropy",
           "NegativeLogLikelihood", "PearsonCorrelation", "Loss", "Torch",
           "Caffe", "CustomMetric", "VOC07MApMetric", "MApMetric", "np",
           "create", "register"]

_REGISTRY: Registry[type] = Registry("metric")


def register(klass=None, *, aliases=()):
    def _do(k):
        _REGISTRY.register(k.__name__, aliases=(k.__name__.lower(),)
                           + tuple(aliases))(k)
        return k
    return _do(klass) if klass is not None else _do


def create(metric, *args, **kwargs) -> "EvalMetric":
    """Reference ``metric.create``† — name / callable / list."""
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, (list, tuple)):
        composite = CompositeEvalMetric()
        for m in metric:
            composite.add(create(m, *args, **kwargs))
        return composite
    try:
        cls = _REGISTRY.get(str(metric))
    except KeyError:
        raise MXNetError(f"unknown metric {metric!r}")
    return cls(*args, **kwargs)


def _as_numpy(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return _np.asarray(x)


from .base import _as_list  # noqa: E402  (shared helper)


def check_label_shapes(labels, preds, wrap=False, shape=False):
    """Reference ``metric.check_label_shapes``†."""
    if not shape:
        label_shape, pred_shape = len(labels), len(preds)
    else:
        label_shape, pred_shape = labels.shape, preds.shape
    if label_shape != pred_shape:
        raise MXNetError(
            f"shape of labels {label_shape} does not match shape of "
            f"predictions {pred_shape}")
    if wrap:
        if isinstance(labels, NDArray):
            labels = [labels]
        if isinstance(preds, NDArray):
            preds = [preds]
    return labels, preds


class EvalMetric:
    """Base metric (reference ``metric.EvalMetric``†)."""

    def __init__(self, name, output_names=None, label_names=None,
                 **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def __str__(self):
        return f"EvalMetric: {dict(zip(*self.get()))}"

    def get_config(self):
        config = dict(self._kwargs)
        config.update({"metric": type(self).__name__, "name": self.name,
                       "output_names": self.output_names,
                       "label_names": self.label_names})
        return config

    def update_dict(self, label: Dict[str, Any], pred: Dict[str, Any]):
        if self.output_names is not None:
            pred = [pred[name] for name in self.output_names]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[name] for name in self.label_names]
        else:
            label = list(label.values())
        self.update(label, pred)

    def update(self, labels, preds):
        raise NotImplementedError

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))


@register
class CompositeEvalMetric(EvalMetric):
    """Manage several metrics at once (reference
    ``CompositeEvalMetric``†)."""

    def __init__(self, metrics=None, name="composite", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        return self.metrics[index]

    def update_dict(self, labels, preds):
        for metric in self.metrics:
            metric.update_dict(labels, preds)

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        for metric in getattr(self, "metrics", []):
            metric.reset()

    def get(self):
        names, values = [], []
        for metric in self.metrics:
            name, value = metric.get()
            names.extend(_as_list(name))
            values.extend(_as_list(value))
        return names, values


@register(aliases=("acc",))
class Accuracy(EvalMetric):
    """Classification accuracy (reference ``metric.Accuracy``†)."""

    def __init__(self, axis=1, name="accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, axis=axis)
        self.axis = axis

    def update(self, labels, preds):
        labels, preds = check_label_shapes(_as_list(labels),
                                           _as_list(preds), wrap=False)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            if pred.ndim > label.ndim:
                pred = _np.argmax(pred, axis=self.axis)
            pred = pred.astype("int32").ravel()
            label = label.astype("int32").ravel()
            check_label_shapes(label, pred, shape=True)
            self.sum_metric += float((pred == label).sum())
            self.num_inst += len(label)


@register(aliases=("top_k_accuracy", "top_k_acc"))
class TopKAccuracy(EvalMetric):
    """Top-k accuracy (reference ``metric.TopKAccuracy``†)."""

    def __init__(self, top_k=1, name="top_k_accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, top_k=top_k)
        self.top_k = top_k
        if top_k <= 1:
            raise MXNetError("top_k should be >1; use Accuracy otherwise")
        self.name += f"_{top_k}"

    def update(self, labels, preds):
        labels, preds = check_label_shapes(_as_list(labels),
                                           _as_list(preds), wrap=False)
        for label, pred in zip(labels, preds):
            pred = _as_numpy(pred)
            label = _as_numpy(label).astype("int32")
            assert pred.ndim == 2, "TopKAccuracy expects 2-D predictions"
            pred = _np.argpartition(pred.astype("float32"), -self.top_k,
                                   axis=1)[:, -self.top_k:]
            for j in range(self.top_k):
                self.sum_metric += float(
                    (pred[:, j].astype("int32") == label.ravel()).sum())
            self.num_inst += len(label)


@register(aliases=("f1_score",))
class F1(EvalMetric):
    """Binary F1 (reference ``metric.F1``†)."""

    def __init__(self, name="f1", output_names=None, label_names=None,
                 average="macro"):
        self.average = average
        self._tp = self._fp = self._fn = 0.0
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(_as_list(labels),
                                           _as_list(preds), wrap=False)
        for label, pred in zip(labels, preds):
            pred = _as_numpy(pred)
            label = _as_numpy(label).astype("int32").ravel()
            if pred.ndim > 1:
                pred = _np.argmax(pred, axis=-1)
            pred = pred.astype("int32").ravel()
            if set(_np.unique(label)) - {0, 1}:
                raise MXNetError("F1 supports binary classification only")
            self._tp += float(((pred == 1) & (label == 1)).sum())
            self._fp += float(((pred == 1) & (label == 0)).sum())
            self._fn += float(((pred == 0) & (label == 1)).sum())
            self.num_inst += 1

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        precision = self._tp / max(self._tp + self._fp, 1e-12)
        recall = self._tp / max(self._tp + self._fn, 1e-12)
        f1 = 2 * precision * recall / max(precision + recall, 1e-12)
        return (self.name, f1)

    def reset(self):
        self._tp = self._fp = self._fn = 0.0
        super().reset()


@register
class Perplexity(EvalMetric):
    """exp(mean NLL) (reference ``metric.Perplexity``†)."""

    def __init__(self, ignore_label=None, axis=-1, name="perplexity",
                 output_names=None, label_names=None):
        super().__init__(name, output_names, label_names,
                         ignore_label=ignore_label)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        labels, preds = check_label_shapes(_as_list(labels),
                                           _as_list(preds), wrap=False)
        loss = 0.0
        num = 0
        for label, pred in zip(labels, preds):
            pred = _as_numpy(pred)
            label = _as_numpy(label)
            label = label.reshape(-1).astype("int64")
            pred = pred.reshape(-1, pred.shape[-1])
            probs = pred[_np.arange(label.shape[0]), label]
            if self.ignore_label is not None:
                ignore = (label == self.ignore_label)
                probs = _np.where(ignore, 1.0, probs)
                num -= int(ignore.sum())
            loss -= float(_np.sum(_np.log(_np.maximum(1e-10, probs))))
            num += label.shape[0]
        self.sum_metric += loss
        self.num_inst += num

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))


@register
class MAE(EvalMetric):
    """Mean absolute error (reference ``metric.MAE``†)."""

    def __init__(self, name="mae", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(_as_list(labels),
                                           _as_list(preds), wrap=False)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            if label.ndim == 1:
                label = label.reshape(label.shape[0], 1)
            if pred.ndim == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += float(_np.abs(label - pred).mean())
            self.num_inst += 1


@register
class MSE(EvalMetric):
    """Mean squared error (reference ``metric.MSE``†)."""

    def __init__(self, name="mse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(_as_list(labels),
                                           _as_list(preds), wrap=False)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            if label.ndim == 1:
                label = label.reshape(label.shape[0], 1)
            if pred.ndim == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += float(((label - pred) ** 2).mean())
            self.num_inst += 1


@register
class RMSE(EvalMetric):
    """Root mean squared error (reference ``metric.RMSE``†)."""

    def __init__(self, name="rmse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(_as_list(labels),
                                           _as_list(preds), wrap=False)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            if label.ndim == 1:
                label = label.reshape(label.shape[0], 1)
            if pred.ndim == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += float(
                _np.sqrt(((label - pred) ** 2).mean()))
            self.num_inst += 1


@register(aliases=("ce",))
class CrossEntropy(EvalMetric):
    """Cross entropy over class probabilities (reference
    ``metric.CrossEntropy``†)."""

    def __init__(self, eps=1e-12, name="cross-entropy",
                 output_names=None, label_names=None):
        super().__init__(name, output_names, label_names, eps=eps)
        self.eps = eps

    def update(self, labels, preds):
        labels, preds = check_label_shapes(_as_list(labels),
                                           _as_list(preds), wrap=False)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label).ravel()
            pred = _as_numpy(pred)
            assert label.shape[0] == pred.shape[0]
            prob = pred[_np.arange(label.shape[0]), label.astype("int64")]
            self.sum_metric += float(
                (-_np.log(prob + self.eps)).sum())
            self.num_inst += label.shape[0]


@register(aliases=("nll_loss",))
class NegativeLogLikelihood(EvalMetric):
    """NLL over class probabilities (reference
    ``metric.NegativeLogLikelihood``†)."""

    def __init__(self, eps=1e-12, name="nll-loss", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, eps=eps)
        self.eps = eps

    update = CrossEntropy.update


@register(aliases=("pearsonr",))
class PearsonCorrelation(EvalMetric):
    """Pearson correlation (reference ``metric.PearsonCorrelation``†)."""

    def __init__(self, name="pearsonr", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(_as_list(labels),
                                           _as_list(preds), wrap=False)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label).ravel()
            pred = _as_numpy(pred).ravel()
            check_label_shapes(label, pred, shape=True)
            self.sum_metric += float(_np.corrcoef(pred, label)[0, 1])
            self.num_inst += 1


@register
class Loss(EvalMetric):
    """Mean of a loss output (reference ``metric.Loss``†)."""

    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, _, preds):
        for pred in _as_list(preds):
            loss = float(_as_numpy(pred).sum())
            self.sum_metric += loss
            self.num_inst += _as_numpy(pred).size


@register
class Torch(Loss):
    """Legacy alias (reference ``metric.Torch``†)."""

    def __init__(self, name="torch", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class Caffe(Loss):
    """Legacy alias (reference ``metric.Caffe``†)."""

    def __init__(self, name="caffe", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class CustomMetric(EvalMetric):
    """Wrap ``feval(label, pred) -> float`` (reference
    ``metric.CustomMetric``†)."""

    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        if name is None:
            name = feval.__name__
            if name.find("<") != -1:
                name = "custom(" + name + ")"
        super().__init__(name, output_names, label_names,
                         feval=feval, allow_extra_outputs=allow_extra_outputs)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            labels, preds = check_label_shapes(_as_list(labels),
                                               _as_list(preds), wrap=False)
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            reval = self._feval(label, pred)
            if isinstance(reval, tuple):
                sum_metric, num_inst = reval
                self.sum_metric += sum_metric
                self.num_inst += num_inst
            else:
                self.sum_metric += reval
                self.num_inst += 1


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Create a CustomMetric from a numpy function (reference
    ``metric.np``†)."""
    def feval(label, pred):
        return numpy_feval(label, pred)
    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)


@register(aliases=("voc07_map",))
class VOC07MApMetric(EvalMetric):
    """Mean average precision with VOC07's 11-point interpolation
    (reference ``example/ssd/evaluate/eval_metric.py``† MApMetric /
    VOC07MApMetric).

    update(labels, preds):
      * ``preds``: (B, N, 6) detector output rows
        ``[cls_id, score, x1, y1, x2, y2]``; rows with cls_id < 0 are
        padding (the MultiBoxDetection / SSD contract).
      * ``labels``: (B, M, 5+) ground truth rows
        ``[cls_id, x1, y1, x2, y2, (difficult)]``; rows with
        cls_id < 0 are padding.
    """

    def __init__(self, iou_thresh=0.5, class_names=None,
                 name="mAP", pred_idx=0):
        self.iou_thresh = iou_thresh
        self.class_names = class_names
        self._pred_idx = int(pred_idx)
        super().__init__(name)

    def reset(self):
        super().reset()
        # per-class: list of (score, tp) + gt count
        self._records: Dict[int, List] = {}
        self._gt_counts: Dict[int, int] = {}

    @staticmethod
    def _iou(box, gts):
        ix1 = _np.maximum(box[0], gts[:, 0])
        iy1 = _np.maximum(box[1], gts[:, 1])
        ix2 = _np.minimum(box[2], gts[:, 2])
        iy2 = _np.minimum(box[3], gts[:, 3])
        iw = _np.maximum(ix2 - ix1, 0)
        ih = _np.maximum(iy2 - iy1, 0)
        inter = iw * ih
        a = (box[2] - box[0]) * (box[3] - box[1])
        b = (gts[:, 2] - gts[:, 0]) * (gts[:, 3] - gts[:, 1])
        return inter / _np.maximum(a + b - inter, 1e-12)

    def update(self, labels, preds):
        labels = _as_list(labels)
        preds = _as_list(preds)
        pred = _as_numpy(preds[self._pred_idx])
        label = _as_numpy(labels[0])
        if pred.ndim == 2:
            pred = pred[None]
        if label.ndim == 2:
            label = label[None]
        for b in range(pred.shape[0]):
            gts = label[b]
            gts = gts[gts[:, 0] >= 0]
            # VOC protocol: difficult ground truths (column 5, when
            # present) are excluded from npos, and detections matching
            # them are neutral — neither tp nor fp
            difficult = gts[:, 5] > 0 if gts.shape[1] > 5 else \
                _np.zeros(len(gts), bool)
            for c in set(gts[:, 0].astype(int).tolist()):
                self._gt_counts[c] = self._gt_counts.get(c, 0) + int(
                    ((gts[:, 0] == c) & ~difficult).sum())
            dets = pred[b]
            dets = dets[dets[:, 0] >= 0]
            order = _np.argsort(-dets[:, 1])
            matched = _np.zeros(len(gts), bool)
            for i in order:
                c = int(dets[i, 0])
                rec = self._records.setdefault(c, [])
                cls_mask = gts[:, 0] == c
                if not cls_mask.any():
                    rec.append((float(dets[i, 1]), 0))
                    continue
                ious = self._iou(dets[i, 2:6], gts[:, 1:5])
                ious = _np.where(cls_mask, ious, -1.0)
                j = int(_np.argmax(ious))
                if ious[j] >= self.iou_thresh:
                    if difficult[j]:
                        continue  # neutral: matched a difficult gt
                    if not matched[j]:
                        matched[j] = True
                        rec.append((float(dets[i, 1]), 1))
                    else:
                        rec.append((float(dets[i, 1]), 0))
                else:
                    rec.append((float(dets[i, 1]), 0))
        self.num_inst = 1  # aggregate metric; get() computes live

    def _class_ap(self, c):
        npos = self._gt_counts.get(c, 0)
        rec = self._records.get(c, [])
        if npos == 0:
            return None
        if not rec:
            return 0.0
        arr = _np.asarray(sorted(rec, key=lambda t: -t[0]), _np.float64)
        tp = _np.cumsum(arr[:, 1])
        fp = _np.cumsum(1 - arr[:, 1])
        recall = tp / npos
        precision = tp / _np.maximum(tp + fp, 1e-12)
        # VOC07 11-point interpolation
        ap = 0.0
        for t in _np.arange(0.0, 1.01, 0.1):
            p = precision[recall >= t].max() if (recall >= t).any() \
                else 0.0
            ap += p / 11.0
        return float(ap)

    def get(self):
        classes = sorted(set(self._gt_counts) | set(self._records))
        aps = [ap for ap in (self._class_ap(c) for c in classes)
               if ap is not None]
        if not aps:
            return (self.name, float("nan"))
        return (self.name, float(_np.mean(aps)))


@register(aliases=("det_map",))
class MApMetric(VOC07MApMetric):
    """Area-under-PR-curve mAP (reference ``MApMetric``†): the same
    matching, with exact AP integration instead of 11-point."""

    def __init__(self, iou_thresh=0.5, class_names=None, name="mAP",
                 pred_idx=0):
        super().__init__(iou_thresh, class_names, name, pred_idx)

    def _class_ap(self, c):
        npos = self._gt_counts.get(c, 0)
        rec = self._records.get(c, [])
        if npos == 0:
            return None
        if not rec:
            return 0.0
        arr = _np.asarray(sorted(rec, key=lambda t: -t[0]), _np.float64)
        tp = _np.cumsum(arr[:, 1])
        fp = _np.cumsum(1 - arr[:, 1])
        recall = _np.concatenate([[0.0], tp / npos])
        precision = _np.concatenate(
            [[1.0], tp / _np.maximum(tp + fp, 1e-12)])
        # monotone precision envelope, then integrate
        for i in range(len(precision) - 2, -1, -1):
            precision[i] = max(precision[i], precision[i + 1])
        return float(_np.sum(_np.diff(recall) * precision[1:]))
