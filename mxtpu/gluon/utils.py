"""Gluon utilities (reference ``python/mxnet/gluon/utils.py``†).

``split_and_load`` keeps its reference signature but on TPU the fast path
is SPMD: one global device-sharded array instead of a Python list of
per-device copies.  ``split_and_load(..., even_split=True)`` returns the
per-shard views the Trainer/KVStore API expects.
"""
from __future__ import annotations

import hashlib
import os

import numpy as np

from ..base import MXNetError
from .. import ndarray as nd
from ..ndarray.ndarray import NDArray

__all__ = ["split_data", "split_and_load", "clip_global_norm",
           "check_sha1", "download"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Split along ``batch_axis`` into ``num_slice`` pieces
    (reference ``utils.split_data``†)."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise MXNetError(
            f"cannot evenly split axis {batch_axis} of size {size} into "
            f"{num_slice} slices (set even_split=False)")
    if num_slice == 1:
        return [data]
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        slices.append(nd.slice_axis(data, axis=batch_axis,
                                    begin=begin, end=end))
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split ``data`` across ``ctx_list`` (reference
    ``utils.split_and_load``†)."""
    if not isinstance(data, NDArray):
        data = nd.array(data)
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescale so the joint L2 norm ≤ max_norm (reference
    ``utils.clip_global_norm``†).  Returns the pre-clip global norm."""
    if not arrays:
        raise MXNetError("arrays must be nonempty")
    total = None
    for a in arrays:
        sq = nd.sum(nd.square(a))
        total = sq if total is None else total + sq
    total_norm = float(nd.sqrt(total).asscalar())
    if check_isfinite and not np.isfinite(total_norm):
        import warnings
        warnings.warn("nan or inf found during clip_global_norm")
        return total_norm
    scale = max_norm / (total_norm + 1e-8)
    if scale < 1.0:
        for a in arrays:
            a[:] = a * scale
    return total_norm


def check_sha1(filename, sha1_hash):
    """Verify a file's sha1 (reference ``utils.check_sha1``†)."""
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1 << 20)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest().startswith(sha1_hash)


def download(url, path=None, overwrite=False, sha1_hash=None,
             retries=5, verify_ssl=True):
    """Reference ``utils.download``† — this build runs with zero egress;
    only file:// URLs and already-present files are served."""
    fname = path if path and not os.path.isdir(path) else os.path.join(
        path or ".", url.split("/")[-1])
    if os.path.exists(fname) and not overwrite and (
            sha1_hash is None or check_sha1(fname, sha1_hash)):
        return fname
    if url.startswith("file://"):
        import shutil
        shutil.copyfile(url[len("file://"):], fname)
        return fname
    raise MXNetError(
        f"download({url!r}): no network access in this environment; "
        f"place the file at {fname} manually")
