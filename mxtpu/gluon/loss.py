"""Gluon loss functions (reference ``python/mxnet/gluon/loss.py``†).

Each loss is a HybridBlock lowering to registry ops so a hybridized
net+loss compiles into one XLA executable.  ``sample_weight`` and
``batch_axis`` semantics follow the reference: losses are averaged over
all axes except ``batch_axis``, producing a per-sample loss vector.
"""
from __future__ import annotations

from ..base import MXNetError
from .block import HybridBlock

__all__ = ["Loss", "L2Loss", "L1Loss", "SigmoidBinaryCrossEntropyLoss",
           "SigmoidBCELoss", "SoftmaxCrossEntropyLoss", "SoftmaxCELoss",
           "KLDivLoss", "HuberLoss", "HingeLoss", "SquaredHingeLoss",
           "LogisticLoss", "TripletLoss", "CosineEmbeddingLoss"]


def _apply_weighting(F, loss, weight=None, sample_weight=None):
    """Reference ``loss._apply_weighting``†."""
    if sample_weight is not None:
        loss = F.broadcast_mul(loss, sample_weight)
    if weight is not None:
        if not isinstance(weight, (int, float)):
            raise MXNetError("weight must be a number")
        loss = loss * weight
    return loss


def _reshape_like(F, x, y):
    return F.reshape_like(x, y) if x.shape != y.shape else x


class Loss(HybridBlock):
    """Base loss (reference ``gluon.loss.Loss``†)."""

    def __init__(self, weight, batch_axis, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def __repr__(self):
        return (f"{type(self).__name__}(batch_axis={self._batch_axis}, "
                f"w={self._weight})")

    def _mean_nonbatch(self, F, loss):
        axes = tuple(i for i in range(loss.ndim) if i != self._batch_axis)
        return F.mean(loss, axis=axes) if axes else loss

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class L2Loss(Loss):
    """``0.5 * (pred - label)^2`` (reference ``L2Loss``†)."""

    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(label - pred)
        loss = _apply_weighting(F, loss, self._weight / 2, sample_weight)
        return self._mean_nonbatch(F, loss)


class L1Loss(Loss):
    """``|pred - label|`` (reference ``L1Loss``†)."""

    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(label - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_nonbatch(F, loss)


class SigmoidBinaryCrossEntropyLoss(Loss):
    """BCE with optional logits input (reference
    ``SigmoidBinaryCrossEntropyLoss``†); the from-logits form uses the
    stable ``max(x,0) - x*z + log(1+exp(-|x|))`` identity."""

    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def hybrid_forward(self, F, pred, label, sample_weight=None,
                       pos_weight=None):
        label = _reshape_like(F, label, pred)
        if not self._from_sigmoid:
            if pos_weight is None:
                loss = F.relu(pred) - pred * label + \
                    F.Activation(-F.abs(pred), act_type="softrelu")
            else:
                log_weight = 1 + F.broadcast_mul(pos_weight - 1, label)
                loss = pred - pred * label + log_weight * (
                    F.Activation(-F.abs(pred), act_type="softrelu")
                    + F.relu(-pred))
        else:
            eps = 1e-12
            if pos_weight is None:
                loss = -(F.log(pred + eps) * label
                         + F.log(1.0 - pred + eps) * (1.0 - label))
            else:
                loss = -(F.broadcast_mul(F.log(pred + eps) * label,
                                         pos_weight)
                         + F.log(1.0 - pred + eps) * (1.0 - label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_nonbatch(F, loss)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    """Softmax + CE fused (reference ``SoftmaxCrossEntropyLoss``†) —
    the canonical classification loss; XLA fuses the log-softmax with
    the gather/sum."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        if self._sparse_label:
            loss = -F.pick(pred, label, axis=self._axis, keepdims=True)
        else:
            label = _reshape_like(F, label, pred)
            loss = -F.sum(pred * label, axis=self._axis, keepdims=True)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_nonbatch(F, loss)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    """Kullback-Leibler divergence (reference ``KLDivLoss``†)."""

    def __init__(self, from_logits=True, axis=-1, weight=None,
                 batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        loss = label * (F.log(label + 1e-12) - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_nonbatch(F, loss)


class HuberLoss(Loss):
    """Smoothed L1 (reference ``HuberLoss``†)."""

    def __init__(self, rho=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(label - pred)
        loss = F.where(loss > self._rho,
                       loss - 0.5 * self._rho,
                       (0.5 / self._rho) * F.square(loss))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_nonbatch(F, loss)


class HingeLoss(Loss):
    """``max(0, margin - pred*label)`` (reference ``HingeLoss``†)."""

    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.relu(self._margin - pred * label)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_nonbatch(F, loss)


class SquaredHingeLoss(Loss):
    """``max(0, margin - pred*label)^2`` (reference ``SquaredHingeLoss``†)."""

    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(F.relu(self._margin - pred * label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_nonbatch(F, loss)


class LogisticLoss(Loss):
    """Logistic regression loss (reference ``LogisticLoss``†)."""

    def __init__(self, weight=None, batch_axis=0, label_format="signed",
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        if label_format not in ("signed", "binary"):
            raise MXNetError(f"bad label_format {label_format}")
        self._label_format = label_format

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        if self._label_format == "signed":
            label = (label + 1.0) / 2.0
        loss = F.relu(pred) - pred * label + \
            F.Activation(-F.abs(pred), act_type="softrelu")
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_nonbatch(F, loss)


class TripletLoss(Loss):
    """``max(0, |a-p|^2 - |a-n|^2 + margin)`` (reference ``TripletLoss``†)."""

    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, positive, negative,
                       sample_weight=None):
        positive = _reshape_like(F, positive, pred)
        negative = _reshape_like(F, negative, pred)
        axes = tuple(range(1, pred.ndim))
        loss = F.sum(F.square(positive - pred) - F.square(negative - pred),
                     axis=axes) + self._margin
        loss = F.relu(loss)
        return _apply_weighting(F, loss, self._weight, sample_weight)


class CosineEmbeddingLoss(Loss):
    """Cosine-distance pair loss (reference ``CosineEmbeddingLoss``†,
    label=1 similar / label=-1 dissimilar)."""

    def __init__(self, weight=None, batch_axis=0, margin=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, input1, input2, label, sample_weight=None):
        eps = 1e-12
        prod = F.sum(input1 * input2, axis=-1)
        n1 = F.sqrt(F.sum(F.square(input1), axis=-1) + eps)
        n2 = F.sqrt(F.sum(F.square(input2), axis=-1) + eps)
        cos = prod / (n1 * n2)
        label = label.reshape(cos.shape)
        loss = F.where(label == 1, 1.0 - cos,
                       F.relu(cos - self._margin))
        return _apply_weighting(F, loss, self._weight, sample_weight)
