"""Recurrent cells (reference ``python/mxnet/gluon/rnn/rnn_cell.py``†).

Cells are step functions ``cell(input_t, states) -> (output, states)``;
``unroll`` composes them over time.  A hybridized stack of cells traces
into one XLA program — the per-step python loop disappears at compile
time, so unrolled cells cost the same as the fused op for moderate T
(for long T prefer ``rnn.LSTM``'s ``lax.scan`` path: O(1) program
size).
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ...base import MXNetError
from ... import ndarray as nd_mod
from ..block import Block, HybridBlock
from ..parameter import Parameter

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "DropoutCell", "ResidualCell",
           "BidirectionalCell"]


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


def _format_sequence(length, inputs, layout, merge):
    """Normalize inputs to a list of (N,C) steps or a merged (T,N,C)/
    (N,T,C) tensor (reference ``_format_sequence``†)."""
    axis = layout.find("T")
    batch_axis = layout.find("N")
    if isinstance(inputs, (list, tuple)):
        seq = list(inputs)
        if length is not None and len(seq) != length:
            raise MXNetError(f"got {len(seq)} steps, expected {length}")
        if merge:
            stacked = nd_mod.stack(*seq, axis=axis)
            return stacked, axis, len(seq)
        return seq, axis, len(seq)
    T = inputs.shape[axis]
    if length is not None and T != length:
        raise MXNetError(f"inputs have {T} steps, expected {length}")
    if merge:
        return inputs, axis, T
    if axis == 0:
        steps = [inputs[t] for t in range(T)]
    else:
        steps = [inputs[:, t] for t in range(T)]
    return steps, axis, T


class RecurrentCell(Block):
    """Base cell (reference ``RecurrentCell``†)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix, params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """Initial states (reference ``begin_state``†)."""
        if func is None:
            func = nd_mod.zeros
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            info = dict(info)
            shape = info.pop("shape")
            info.pop("__layout__", None)
            states.append(func(shape, **kwargs))
        return states

    def __call__(self, inputs, states, *args):
        self._counter += 1
        return super().__call__(inputs, states, *args)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Unroll the cell over ``length`` steps (reference†)."""
        self.reset()
        steps, axis, T = _format_sequence(length, inputs, layout, False)
        if begin_state is None:
            begin_state = self.begin_state(batch_size=steps[0].shape[0])
        states = begin_state
        outputs = []
        step_states = []
        for t in range(T):
            out, states = self(steps[t], states)
            outputs.append(out)
            if valid_length is not None:
                step_states.append(states)
        if valid_length is not None:
            # outputs beyond each sample's length are zeroed, and the
            # returned states are the ones at t = valid_length (not the
            # padding-contaminated final step) — reference semantics.
            stacked = nd_mod.stack(*outputs, axis=0)  # (T, N, C)
            masked = nd_mod.SequenceMask(stacked, valid_length,
                                         use_sequence_length=True)
            outputs = [masked[t] for t in range(T)]
            states = [
                nd_mod.SequenceLast(
                    nd_mod.stack(*[s[i] for s in step_states], axis=0),
                    valid_length, use_sequence_length=True)
                for i in range(len(states))]
        if merge_outputs:
            out_axis = layout.find("T")
            return nd_mod.stack(*outputs, axis=out_axis), states
        return outputs, states

    def _get_param(self, name, shape, init):
        return self.params.get(name, shape=shape, init=init,
                               allow_deferred_init=True)


class HybridRecurrentCell(RecurrentCell, HybridBlock):
    """Cells whose step is a pure hybrid_forward (reference†)."""

    def forward(self, inputs, states, *args):
        return HybridBlock.forward(self, inputs, states, *args)


class RNNCell(HybridRecurrentCell):
    """Elman cell ``h' = act(W x + b + R h + r)``
    (reference ``RNNCell``†)."""

    def __init__(self, hidden_size, activation="tanh", input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", prefix=None, params=None):
        super().__init__(prefix, params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        self.i2h_weight = self._get_param(
            "i2h_weight", (hidden_size, input_size),
            i2h_weight_initializer)
        self.h2h_weight = self._get_param(
            "h2h_weight", (hidden_size, hidden_size),
            h2h_weight_initializer)
        self.i2h_bias = self._get_param("i2h_bias", (hidden_size,),
                                        i2h_bias_initializer)
        self.h2h_bias = self._get_param("h2h_bias", (hidden_size,),
                                        h2h_bias_initializer)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _infer_params(self, x, *args):
        if self.i2h_weight.shape and self.i2h_weight.shape[1] == 0:
            self.i2h_weight.shape = (self._hidden_size, int(x.shape[-1]))

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        out = F.Activation(i2h + h2h, act_type=self._activation)
        return out, [out]


class LSTMCell(HybridRecurrentCell):
    """LSTM cell, gate order [i, f, g, o] (reference ``LSTMCell``†)."""

    def __init__(self, hidden_size, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", prefix=None, params=None):
        super().__init__(prefix, params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        H = hidden_size
        self.i2h_weight = self._get_param("i2h_weight", (4 * H, input_size),
                                          i2h_weight_initializer)
        self.h2h_weight = self._get_param("h2h_weight", (4 * H, H),
                                          h2h_weight_initializer)
        self.i2h_bias = self._get_param("i2h_bias", (4 * H,),
                                        i2h_bias_initializer)
        self.h2h_bias = self._get_param("h2h_bias", (4 * H,),
                                        h2h_bias_initializer)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _infer_params(self, x, *args):
        if self.i2h_weight.shape and self.i2h_weight.shape[1] == 0:
            self.i2h_weight.shape = (4 * self._hidden_size,
                                     int(x.shape[-1]))

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        H = self._hidden_size
        gates = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                                 num_hidden=4 * H) + \
            F.FullyConnected(states[0], h2h_weight, h2h_bias,
                             num_hidden=4 * H)
        i = F.sigmoid(F.slice_axis(gates, axis=-1, begin=0, end=H))
        f = F.sigmoid(F.slice_axis(gates, axis=-1, begin=H, end=2 * H))
        g = F.tanh(F.slice_axis(gates, axis=-1, begin=2 * H, end=3 * H))
        o = F.sigmoid(F.slice_axis(gates, axis=-1, begin=3 * H,
                                   end=4 * H))
        c = f * states[1] + i * g
        h = o * F.tanh(c)
        return h, [h, c]


class GRUCell(HybridRecurrentCell):
    """GRU cell, gate order [r, z, n] (reference ``GRUCell``†)."""

    def __init__(self, hidden_size, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", prefix=None, params=None):
        super().__init__(prefix, params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        H = hidden_size
        self.i2h_weight = self._get_param("i2h_weight", (3 * H, input_size),
                                          i2h_weight_initializer)
        self.h2h_weight = self._get_param("h2h_weight", (3 * H, H),
                                          h2h_weight_initializer)
        self.i2h_bias = self._get_param("i2h_bias", (3 * H,),
                                        i2h_bias_initializer)
        self.h2h_bias = self._get_param("h2h_bias", (3 * H,),
                                        h2h_bias_initializer)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _infer_params(self, x, *args):
        if self.i2h_weight.shape and self.i2h_weight.shape[1] == 0:
            self.i2h_weight.shape = (3 * self._hidden_size,
                                     int(x.shape[-1]))

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        H = self._hidden_size
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=3 * H)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=3 * H)
        ir = F.slice_axis(i2h, axis=-1, begin=0, end=H)
        iz = F.slice_axis(i2h, axis=-1, begin=H, end=2 * H)
        inn = F.slice_axis(i2h, axis=-1, begin=2 * H, end=3 * H)
        hr = F.slice_axis(h2h, axis=-1, begin=0, end=H)
        hz = F.slice_axis(h2h, axis=-1, begin=H, end=2 * H)
        hn = F.slice_axis(h2h, axis=-1, begin=2 * H, end=3 * H)
        r = F.sigmoid(ir + hr)
        z = F.sigmoid(iz + hz)
        n = F.tanh(inn + r * hn)
        out = (1.0 - z) * n + z * states[0]
        return out, [out]


class SequentialRNNCell(RecurrentCell):
    """Stack cells (reference ``SequentialRNNCell``†)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix, params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        return _cells_begin_state(self._children.values(), **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        pos = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            state = states[pos:pos + n]
            pos += n
            inputs, state = cell(inputs, state)
            next_states.extend(state)
        return inputs, next_states

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def forward(self, *args):
        raise MXNetError("use __call__(inputs, states)")


class DropoutCell(HybridRecurrentCell):
    """Apply dropout to the input stream (reference ``DropoutCell``†)."""

    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix, params)
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def hybrid_forward(self, F, inputs, states):
        if self._rate > 0:
            inputs = F.Dropout(inputs, p=self._rate, axes=self._axes)
        return inputs, states


class ResidualCell(HybridRecurrentCell):
    """Add a skip connection around a base cell (reference†)."""

    def __init__(self, base_cell):
        super().__init__()
        self.base_cell = base_cell
        self.register_child(base_cell)

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, **kwargs):
        return self.base_cell.begin_state(**kwargs)

    def hybrid_forward(self, F, inputs, states):
        output, states = self.base_cell(inputs, states)
        return output + inputs, states


class BidirectionalCell(RecurrentCell):
    """Run two cells over opposite time directions; outputs concatenate
    (reference ``BidirectionalCell``†).  Only usable via ``unroll``."""

    def __init__(self, l_cell, r_cell):
        super().__init__()
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")

    @property
    def _l_cell(self):
        return self._children["l_cell"]

    @property
    def _r_cell(self):
        return self._children["r_cell"]

    def state_info(self, batch_size=0):
        return _cells_state_info([self._l_cell, self._r_cell],
                                 batch_size)

    def begin_state(self, **kwargs):
        return _cells_begin_state([self._l_cell, self._r_cell], **kwargs)

    def __call__(self, inputs, states):
        raise MXNetError("BidirectionalCell cannot be stepped; "
                         "use unroll()")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        steps, axis, T = _format_sequence(length, inputs, layout, False)
        if begin_state is None:
            begin_state = self.begin_state(
                batch_size=steps[0].shape[0])
        n_l = len(self._l_cell.state_info())
        l_out, l_states = self._l_cell.unroll(
            length, steps, begin_state[:n_l], layout="TNC",
            merge_outputs=False, valid_length=valid_length)
        # reverse direction: with valid_length, reverse only each
        # sample's valid prefix (SequenceReverse) so padding stays at
        # the tail and never contaminates the reverse states
        if valid_length is not None:
            stacked = nd_mod.stack(*steps, axis=0)  # (T, N, C)
            rev = nd_mod.SequenceReverse(stacked, valid_length,
                                         use_sequence_length=True)
            rev_steps = [rev[t] for t in range(T)]
        else:
            rev_steps = list(reversed(steps))
        r_out, r_states = self._r_cell.unroll(
            length, rev_steps, begin_state[n_l:], layout="TNC",
            merge_outputs=False, valid_length=valid_length)
        r_stacked = nd_mod.stack(*r_out, axis=0)
        if valid_length is not None:
            r_stacked = nd_mod.SequenceReverse(r_stacked, valid_length,
                                               use_sequence_length=True)
        else:
            r_stacked = nd_mod.SequenceReverse(r_stacked)
        r_out = [r_stacked[t] for t in range(T)]
        outputs = [nd_mod.concat(lo, ro, dim=-1)
                   for lo, ro in zip(l_out, r_out)]
        if merge_outputs:
            out_axis = layout.find("T")
            return nd_mod.stack(*outputs, axis=out_axis), \
                l_states + r_states
        return outputs, l_states + r_states
