"""Fused recurrent layers (reference
``python/mxnet/gluon/rnn/rnn_layer.py``†: ``RNN``/``LSTM``/``GRU`` over
the fused ``RNN`` op).

Parameters are stored unfused per layer/direction
(``l0_i2h_weight``, ``r0_h2h_bias``, …) exactly like the reference, and
``hybrid_forward`` concatenates them into the op's flat vector — so
checkpoints are layer-structured and the whole multi-layer scan still
compiles into one XLA program (``lax.scan`` per layer/direction, i2h
GEMMs hoisted; see ``mxtpu/ndarray/rnn_impl.py``).
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ...base import MXNetError
from ... import autograd
from ... import ndarray as nd_mod
from ...ndarray import rnn_impl
from ..block import HybridBlock

__all__ = ["RNN", "LSTM", "GRU"]


class _RNNLayer(HybridBlock):
    """Base fused layer (reference ``_RNNLayer``†)."""

    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, i2h_weight_initializer,
                 h2h_weight_initializer, i2h_bias_initializer,
                 h2h_bias_initializer, mode, prefix=None, params=None):
        super().__init__(prefix, params)
        if layout not in ("TNC", "NTC"):
            raise MXNetError(f"layout must be TNC or NTC, got {layout}")
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._mode = mode
        self._gates = rnn_impl._GATES[mode]
        G, H = self._gates, hidden_size
        ng, ni, nh = G * H, input_size, hidden_size
        for i in range(num_layers):
            for j in (["l", "r"] if bidirectional else ["l"]):
                self._register_param(f"{j}{i}_i2h_weight", (ng, ni),
                                     i2h_weight_initializer)
                self._register_param(f"{j}{i}_h2h_weight", (ng, nh),
                                     h2h_weight_initializer)
                self._register_param(f"{j}{i}_i2h_bias", (ng,),
                                     i2h_bias_initializer)
                self._register_param(f"{j}{i}_h2h_bias", (ng,),
                                     h2h_bias_initializer)
            ni = nh * self._dir

    def _register_param(self, name, shape, init):
        p = self.params.get(name, shape=shape, init=init,
                            allow_deferred_init=True)
        setattr(self, name, p)

    def __repr__(self):
        s = (f"{type(self).__name__}({self._input_size or '?'} -> "
             f"{self._hidden_size}, {self._layout}")
        if self._num_layers != 1:
            s += f", num_layers={self._num_layers}"
        if self._dropout:
            s += f", dropout={self._dropout}"
        if self._dir == 2:
            s += ", bidirectional"
        return s + ")"

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        if func is None:
            func = nd_mod.zeros
        states = []
        for info in self.state_info(batch_size):
            info = dict(info)
            shape = info.pop("shape")
            info.pop("__layout__", None)
            states.append(func(shape, **kwargs))
        return states

    def _infer_params(self, x, *args):
        if self._input_size == 0:
            ni = int(x.shape[-1])
            self._input_size = ni
            G, H = self._gates, self._hidden_size
            for j in (["l", "r"] if self._dir == 2 else ["l"]):
                p = getattr(self, f"{j}0_i2h_weight")
                if p.shape and p.shape[1] == 0:
                    p.shape = (G * H, ni)

    def hybrid_forward(self, F, inputs, states=None, **params):
        """inputs: (T,N,C) for TNC / (N,T,C) for NTC; states optional."""
        skip_states = states is None
        sym_mode = not hasattr(inputs, "shape")  # Symbol composition
        if self._layout == "NTC":
            inputs = F.transpose(inputs, axes=(1, 0, 2))
        if skip_states:
            if sym_mode:
                # zero initial states become named graph inputs whose
                # shapes are inferred at bind time (the auto-var
                # convention, like SoftmaxOutput's label)
                states = [F.var(f"{self.prefix}begin_state_{i}")
                          for i in range(len(self.state_info(0)))]
            else:
                states = self._make_begin_state(F, inputs.shape[1])
        if not isinstance(states, (list, tuple)):
            states = [states]

        # flat vector: weights (layer, dir) then biases (layer, dir)
        order = []
        for i in range(self._num_layers):
            for j in (["l", "r"] if self._dir == 2 else ["l"]):
                order.extend([f"{j}{i}_i2h_weight", f"{j}{i}_h2h_weight"])
        for i in range(self._num_layers):
            for j in (["l", "r"] if self._dir == 2 else ["l"]):
                order.extend([f"{j}{i}_i2h_bias", f"{j}{i}_h2h_bias"])
        flat = F.concat(*[F.reshape(params[n], shape=(-1,))
                          for n in order], dim=0)

        op_inputs = [inputs, flat] + list(states)
        if self._dropout > 0 and autograd.is_training() and not sym_mode:
            from ...ndarray import random as _rnd
            op_inputs.append(_rnd._next_key_nd())
        elif self._dropout > 0 and sym_mode and autograd.is_training():
            # only worth flagging when a training graph is being built;
            # inference exports correctly run with dropout off
            import warnings
            warnings.warn(
                "inter-layer RNN dropout is inactive in symbolic "
                "graphs (no PRNG key input); train through the eager/"
                "hybridize path for dropout", stacklevel=2)
        out = F.RNN(*op_inputs, state_size=self._hidden_size,
                    num_layers=self._num_layers, mode=self._mode,
                    bidirectional=self._dir == 2, p=self._dropout,
                    state_outputs=True)
        out, states_out = out[0], list(out[1:])
        if self._layout == "NTC":
            out = F.transpose(out, axes=(1, 0, 2))
        if skip_states:
            return out
        return out, states_out

    def _make_begin_state(self, F, batch_size):
        return self.begin_state(batch_size=batch_size)


class RNN(_RNNLayer):
    """Multi-layer Elman RNN (reference ``rnn.RNN``†)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size,
                         i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer,
                         "rnn_" + activation, prefix, params)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class LSTM(_RNNLayer):
    """Multi-layer LSTM (reference ``rnn.LSTM``†)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC",
                 dropout=0, bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", prefix=None, params=None):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size,
                         i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer,
                         "lstm", prefix, params)

    def state_info(self, batch_size=0):
        shape = (self._num_layers * self._dir, batch_size,
                 self._hidden_size)
        return [{"shape": shape, "__layout__": "LNC"},
                {"shape": shape, "__layout__": "LNC"}]


class GRU(_RNNLayer):
    """Multi-layer GRU (reference ``rnn.GRU``†)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC",
                 dropout=0, bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", prefix=None, params=None):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size,
                         i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer,
                         "gru", prefix, params)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]
