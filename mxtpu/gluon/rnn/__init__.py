"""Gluon recurrent layers & cells
(reference ``python/mxnet/gluon/rnn/``†)."""
from .rnn_cell import (RecurrentCell, HybridRecurrentCell, RNNCell,
                       LSTMCell, GRUCell, SequentialRNNCell, DropoutCell,
                       ResidualCell, BidirectionalCell)
from .rnn_layer import RNN, LSTM, GRU

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "DropoutCell", "ResidualCell",
           "BidirectionalCell", "RNN", "LSTM", "GRU"]
