"""Contrib layers (reference
``python/mxnet/gluon/contrib/nn/basic_layers.py``†)."""
from __future__ import annotations

from ...base import MXNetError
from .. import nn
from ..block import Block, HybridBlock

__all__ = ["Concurrent", "HybridConcurrent", "Identity"]


class Concurrent(nn.Sequential):
    """Run children on the same input, concat outputs
    (reference ``Concurrent``†)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        from ... import ndarray as nd_mod
        return nd_mod.concat(*[block(x)
                               for block in self._children.values()],
                             dim=self.axis)


class HybridConcurrent(nn.HybridSequential):
    """Hybridizable Concurrent (reference ``HybridConcurrent``†)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def hybrid_forward(self, F, x):
        return F.concat(*[block(x)
                          for block in self._children.values()],
                        dim=self.axis)

    def forward(self, x):
        from ... import ndarray as nd_mod
        return nd_mod.concat(*[block(x)
                               for block in self._children.values()],
                             dim=self.axis)


class Identity(HybridBlock):
    """Identity block (reference ``Identity``†)."""

    def hybrid_forward(self, F, x):
        return x
