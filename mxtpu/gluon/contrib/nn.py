"""Contrib layers (reference
``python/mxnet/gluon/contrib/nn/basic_layers.py``†)."""
from __future__ import annotations

from ...base import MXNetError
from .. import nn
from ..block import Block, HybridBlock

__all__ = ["Concurrent", "HybridConcurrent", "Identity",
           "MoEDense"]


class Concurrent(nn.Sequential):
    """Run children on the same input, concat outputs
    (reference ``Concurrent``†)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        from ... import ndarray as nd_mod
        return nd_mod.concat(*[block(x)
                               for block in self._children.values()],
                             dim=self.axis)


class HybridConcurrent(nn.HybridSequential):
    """Hybridizable Concurrent (reference ``HybridConcurrent``†)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def hybrid_forward(self, F, x):
        return F.concat(*[block(x)
                          for block in self._children.values()],
                        dim=self.axis)

    def forward(self, x):
        from ... import ndarray as nd_mod
        return nd_mod.concat(*[block(x)
                               for block in self._children.values()],
                             dim=self.axis)


class Identity(HybridBlock):
    """Identity block (reference ``Identity``†)."""

    def hybrid_forward(self, F, x):
        return x


class MoEDense(HybridBlock):
    """Switch-MoE feed-forward layer (``_contrib_MoEFFN`` op;
    ``mxtpu.parallel.moe`` is the functional core).  New capability —
    the reference era predates MoE.

    Returns ``(y, aux_loss)``: compose the load-balancing aux into the
    training loss (``loss = task_loss + alpha * aux``).  For expert
    parallelism, shard the expert-axis parameters over an ``ep`` mesh
    axis via ``build_train_step(param_spec_fn=...)`` — GSPMD turns the
    dispatch/return einsums into all-to-alls.
    """

    def __init__(self, units, hidden, num_experts,
                 capacity_factor=1.25, activation="relu",
                 weight_initializer=None, in_units=0, prefix=None,
                 params=None):
        super().__init__(prefix, params)
        self._units = units
        self._hidden = hidden
        self._E = num_experts
        self._cf = capacity_factor
        self._act = activation
        self.gate_weight = self.params.get(
            "gate_weight", shape=(in_units, num_experts),
            init=weight_initializer, allow_deferred_init=True)
        self.expert_w1 = self.params.get(
            "expert_w1", shape=(num_experts, in_units, hidden),
            init=weight_initializer, allow_deferred_init=True)
        self.expert_b1 = self.params.get(
            "expert_b1", shape=(num_experts, hidden), init="zeros",
            allow_deferred_init=True)
        self.expert_w2 = self.params.get(
            "expert_w2", shape=(num_experts, hidden, units),
            init=weight_initializer, allow_deferred_init=True)
        self.expert_b2 = self.params.get(
            "expert_b2", shape=(num_experts, units), init="zeros",
            allow_deferred_init=True)

    def _infer_params(self, x, *args):
        d = int(x.shape[-1])
        if self.gate_weight.shape and self.gate_weight.shape[0] == 0:
            self.gate_weight.shape = (d, self._E)
            self.expert_w1.shape = (self._E, d, self._hidden)

    def hybrid_forward(self, F, x, gate_weight, expert_w1, expert_b1,
                       expert_w2, expert_b2):
        return F._contrib_MoEFFN(
            x, gate_weight, expert_w1, expert_b1, expert_w2,
            expert_b2, capacity_factor=self._cf,
            activation=self._act)

    def __repr__(self):
        return (f"MoEDense({self._E} experts, "
                f"hidden={self._hidden} -> {self._units}, "
                f"{self._act})")
