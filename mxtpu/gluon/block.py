"""Gluon Block / HybridBlock — the imperative API and its JIT boundary.

Reference: ``python/mxnet/gluon/block.py``† (Block, HybridBlock whose
``hybridize()`` builds a ``CachedOp``, ``src/imperative/cached_op.cc``†).

TPU-native: ``hybridize()`` makes the block's forward trace ONCE per
(input shapes/dtypes, train-flag) into a jitted function over
(param arrays, input arrays, rng key) — i.e. the CachedOp becomes an XLA
executable cache keyed the way the reference's bucketed executors were.
Under ``autograd.record`` a hybridized call contributes a single tape
node whose vjp is the transposed XLA program, so fwd+bwd are two compiled
executables instead of per-op dispatch (SURVEY.md §3.2 call stack).

Mutable layer state (BatchNorm running stats) flows through an aux-update
channel: during a traced call layers emit (param, new_value) pairs that
become extra jit outputs written back after the call — replacing the
reference's in-op aux mutation (FMutateInputs).
"""
from __future__ import annotations

import re
import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError, _as_list as _as_list_names
from ..context import Context, current_context
from .. import autograd
from .. import ndarray as nd_mod
from ..ndarray import random as _rnd
from ..ndarray.ndarray import NDArray
from .parameter import (Parameter, ParameterDict, Constant,
                        DeferredInitializationError, _TRACE)

__all__ = ["Block", "HybridBlock", "SymbolBlock", "_flatten_args"]

_NAME_COUNTERS: Dict[str, int] = {}
_NAME_LOCK = threading.Lock()


def _gen_prefix(hint: str) -> str:
    with _NAME_LOCK:
        idx = _NAME_COUNTERS.get(hint, 0)
        _NAME_COUNTERS[hint] = idx + 1
    return f"{hint}{idx}_"


# trace-time parameter substitution lives first-class in parameter._TRACE
# (Parameter.data consults it natively — no monkey-patching)
def _emit_aux_update(param: Parameter, value: NDArray) -> None:
    """BatchNorm-style running-stat update; buffered during trace,
    immediate otherwise."""
    if _TRACE.aux_sink is not None:
        _TRACE.aux_sink.append((param, value))
    else:
        param._data._data = value.data \
            if isinstance(value, NDArray) else value


def _is_nd(x) -> bool:
    return isinstance(x, NDArray)


def _is_symbol(x) -> bool:
    from ..symbol import Symbol
    return isinstance(x, Symbol)


def _traced_forward(block, params, param_vals, nd_ins, training, key_data):
    """Run ``block.forward`` with parameters substituted by traced values —
    the trace half of the CachedOp (and of ``mxtpu.parallel``'s fused
    train step).  Returns (raw_outs, out_treedef, aux_params, raw_aux):
    flattened raw output arrays + treedef, and the running-stat updates
    (Parameter, new_value) emitted through the aux channel during the
    trace."""
    sub = {id(p): NDArray(v, None, _placed=True)
           for p, v in zip(params, param_vals)}
    prev_sub, prev_sink = _TRACE.param_sub, _TRACE.aux_sink
    sink: List[Tuple[Parameter, NDArray]] = []
    _TRACE.param_sub, _TRACE.aux_sink = sub, sink
    prev_rec = autograd.set_recording(False)
    prev_train = autograd.set_training(training)
    provider = _rnd._TraceKeyProvider(jax.random.wrap_key_data(key_data))
    _rnd._push_trace_provider(provider)
    try:
        # honour set_remat on the ROOT block too (child blocks route
        # through __call__, which carries the remat dispatch)
        if getattr(block, "_remat", False) and \
                hasattr(block, "_forward_remat"):
            out = block._forward_remat(tuple(nd_ins), {})
        else:
            out = block.forward(*nd_ins)
    finally:
        _rnd._pop_trace_provider()
        autograd.set_training(prev_train)
        autograd.set_recording(prev_rec)
        _TRACE.param_sub, _TRACE.aux_sink = prev_sub, prev_sink
    outs_flat, out_treedef = jax.tree_util.tree_flatten(out, is_leaf=_is_nd)
    raw_outs = [o.data if isinstance(o, NDArray) else o for o in outs_flat]
    aux_params = [p for p, _ in sink]
    raw_aux = [v.data if isinstance(v, NDArray) else v for _, v in sink]
    return raw_outs, out_treedef, aux_params, raw_aux


def _flatten_args(args):
    # NDArray is a registered pytree node: without is_leaf it dissolves
    # into raw jax.Array leaves, which broke the CachedOp path entirely
    flat, treedef = jax.tree_util.tree_flatten(args, is_leaf=_is_nd)
    return flat, treedef


class Block:
    """Base imperative building block (reference ``gluon.Block``†)."""

    def __init__(self, prefix: Optional[str] = None,
                 params: Optional[ParameterDict] = None):
        cls = type(self).__name__.lower()
        self._prefix = prefix if prefix is not None else _gen_prefix(cls)
        self._params = ParameterDict(self._prefix, shared=params)
        self._children: "OrderedDict[str, Block]" = OrderedDict()
        self._reg_params: Dict[str, Parameter] = {}
        self._forward_hooks: List[Callable] = []
        self._forward_pre_hooks: List[Callable] = []

    # -- attribute registration ---------------------------------------
    def __setattr__(self, name, value):
        if isinstance(value, Block):
            existing = self.__dict__.get("_children")
            if existing is not None:
                existing[name] = value
        elif isinstance(value, Parameter):
            reg = self.__dict__.get("_reg_params")
            if reg is not None:
                reg[name] = value
        super().__setattr__(name, value)

    # -- naming / params ----------------------------------------------
    @property
    def prefix(self) -> str:
        return self._prefix

    @property
    def name(self) -> str:
        return self._prefix[:-1] if self._prefix.endswith("_") \
            else self._prefix

    @property
    def params(self) -> ParameterDict:
        return self._params

    def name_scope(self):
        class _NS:
            def __enter__(s):
                return s

            def __exit__(s, *a):
                return None
        return _NS()

    def collect_params(self, select: Optional[str] = None) -> ParameterDict:
        """All params of self + descendants, optionally regex-filtered
        (reference semantics)."""
        out = ParameterDict(self._params.prefix)
        pattern = re.compile(select) if select else None

        def visit(b: Block):
            for k, v in b._params.items():
                if pattern is None or pattern.match(k):
                    if k not in out:
                        out._params[k] = v
            for c in b._children.values():
                visit(c)
        visit(self)
        return out

    # structural parameter map for save/load (stable across runs —
    # the newer-gluon "structure based" naming)
    def _collect_params_with_prefix(self, prefix: str = "") \
            -> Dict[str, Parameter]:
        if prefix:
            prefix += "."
        out: Dict[str, Parameter] = {}
        for name, p in self._reg_params.items():
            out[prefix + name] = p
        for cname, child in self._children.items():
            out.update(child._collect_params_with_prefix(prefix + cname))
        return out

    # -- lifecycle ------------------------------------------------------
    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        self.collect_params().initialize(init, ctx, verbose, force_reinit)

    def cast(self, dtype):
        for p in self.collect_params().values():
            p.cast(dtype)
        for child in self._children.values():
            pass  # params already covered by collect_params
        if hasattr(self, "_dtype"):
            self._dtype = dtype

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    def register_forward_hook(self, hook):
        self._forward_hooks.append(hook)

    def register_forward_pre_hook(self, hook):
        self._forward_pre_hooks.append(hook)

    def register_child(self, block, name=None):
        self._children[name or str(len(self._children))] = block

    # -- persistence ----------------------------------------------------
    def save_parameters(self, filename: str) -> None:
        params = self._collect_params_with_prefix()
        arrays = {k: p.data() for k, p in params.items()
                  if p._data is not None}
        nd_mod.save(filename, arrays)

    def load_parameters(self, filename: str, ctx=None,
                        allow_missing: bool = False,
                        ignore_extra: bool = False,
                        cast_dtype: bool = False) -> None:
        loaded = nd_mod.load(filename)
        params = self._collect_params_with_prefix()
        if not isinstance(loaded, dict):
            raise MXNetError("invalid parameter file")
        for k, p in params.items():
            if k in loaded:
                p.set_data(loaded[k])
            elif not allow_missing:
                raise MXNetError(f"missing parameter {k} in {filename}")
        extra = set(loaded) - set(params)
        if extra and not ignore_extra:
            raise MXNetError(f"extra parameters in file: {sorted(extra)}")

    # legacy aliases (reference deprecated save_params/load_params)
    save_params = save_parameters
    load_params = load_parameters

    # -- call -----------------------------------------------------------
    def __call__(self, *args, **kwargs):
        for hook in self._forward_pre_hooks:
            hook(self, args)
        out = self.forward(*args, **kwargs)
        for hook in self._forward_hooks:
            hook(self, args, out)
        return out

    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def hybridize(self, active: bool = True, **kwargs):
        """No-op on plain Blocks except propagation (reference parity)."""
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def summary(self, *inputs):
        from ..visualization import summary as _summary
        return _summary(self, *inputs)

    def __repr__(self):
        lines = [f"{type(self).__name__}("]
        for key, child in self._children.items():
            mod = repr(child).replace("\n", "\n  ")
            lines.append(f"  ({key}): {mod}")
        lines.append(")")
        return "\n".join(lines)


class HybridBlock(Block):
    """Block that can be traced into cached XLA executables."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix, params)
        self._active = False
        self._flags: Dict[str, Any] = {}
        self._cached_entries: Dict[Any, Dict[str, Any]] = {}

    def hybridize(self, active: bool = True, static_alloc: bool = False,
                  static_shape: bool = False, **kwargs):
        self._active = active
        self._flags = dict(static_alloc=static_alloc,
                           static_shape=static_shape, **kwargs)
        self._cached_entries.clear()
        super().hybridize(active, static_alloc=static_alloc,
                          static_shape=static_shape, **kwargs)

    # children of a hybridized top block execute inside the parent's
    # trace; their own __call__ must stay imperative then.
    def __call__(self, *args, **kwargs):
        if args:
            self._num_inputs = len(args)  # recorded for export()
        if getattr(self, "_remat", False) and not \
                getattr(self, "_in_remat", False) \
                and _TRACE.param_sub is not None:
            return self._forward_remat(args, kwargs)
        if self._active and _TRACE.param_sub is None \
                and not kwargs and args:
            leaves, treedef = _flatten_args(args)
            if leaves and all(isinstance(a, NDArray) for a in leaves):
                for hook in self._forward_pre_hooks:
                    hook(self, args)
                out = self._call_cached(args, leaves, treedef)
                for hook in self._forward_hooks:
                    hook(self, args, out)
                return out
        return super().__call__(*args, **kwargs)

    # -- imperative dispatch: hybrid_forward(F, x, **param_values) ------
    def forward(self, *args, **kwargs):
        # Symbolic composition: net(sym.var('data')) builds a graph by
        # running the same hybrid_forward with F = mxtpu.symbol and
        # parameters as named variables (the reference's F-switch).
        if args and _is_symbol(args[0]):
            from .. import symbol as sym_mod
            pvals = {name: sym_mod.var(p.name)
                     for name, p in self._reg_params.items()}
            return self.hybrid_forward(sym_mod, *args, **pvals, **kwargs)
        self._ensure_init(*args)
        pvals = {name: p.data() for name, p in self._reg_params.items()}
        return self.hybrid_forward(nd_mod, *args, **pvals, **kwargs)

    _REMAT_GENERATION = [0]  # class-level; bumped by every set_remat

    def set_remat(self, active: bool = True):
        """Rematerialize this block's activations in the backward pass
        (``jax.checkpoint`` around the block when traced) — trades
        recompute FLOPs for HBM, the lever for long-sequence /
        large-batch training (SURVEY §0: use jax.checkpoint to trade
        FLOPs for memory).  Apply to repeated layers (transformer
        cells), NOT to blocks emitting BatchNorm aux updates in
        training (their running-stat tracers must not cross the
        checkpoint boundary)."""
        self._remat = active
        # invalidate every hybridize cache: a parent block's compiled
        # executable may have been traced with the old remat setting
        # and its cache key cannot see a child's flag — the generation
        # counter is part of every cache key
        HybridBlock._REMAT_GENERATION[0] += 1
        return self

    def _forward_remat(self, args, kwargs):
        leaves, treedef = _flatten_args(args)
        nd_idx = [i for i, a in enumerate(leaves)
                  if isinstance(a, NDArray)]
        if not nd_idx:
            raise MXNetError(
                f"{type(self).__name__}.set_remat: no NDArray inputs "
                f"to checkpoint — remat cannot engage on this call "
                f"(disable remat on this block or pass tensor inputs)")
        raw = [leaves[i].data for i in nd_idx]
        sink_before = len(_TRACE.aux_sink) if _TRACE.aux_sink is not None \
            else None
        box = {}

        def _pure(*raw_in):
            # rebuild the arg tree: tensor leaves from the checkpoint
            # inputs, non-tensor leaves (python scalars/config) closed
            # over unchanged
            all_leaves = list(leaves)
            for i, r in zip(nd_idx, raw_in):
                all_leaves[i] = NDArray(r, None, _placed=True)
            rebuilt = jax.tree_util.tree_unflatten(treedef, all_leaves)
            # re-enter the normal call path (guarded against recursing
            # back here); params resolve to the substituted trace
            # values inside and become checkpoint constants (saved,
            # not recomputed)
            self._in_remat = True
            try:
                out = self.__call__(*rebuilt, **kwargs)
            finally:
                self._in_remat = False
            outs_flat, out_tree = _flatten_args((out,))
            box["tree"] = out_tree
            return tuple(o.data if isinstance(o, NDArray) else o
                         for o in outs_flat)

        outs = jax.checkpoint(_pure)(*raw)
        if sink_before is not None and \
                len(_TRACE.aux_sink) != sink_before:
            raise MXNetError(
                f"{type(self).__name__}.set_remat: block emitted aux "
                f"(BatchNorm running-stat) updates inside the "
                f"checkpoint region — their tracers cannot cross the "
                f"boundary; remat a smaller block or disable remat")
        outs_nd = [NDArray(o, None, _placed=True) for o in outs]
        (out,) = jax.tree_util.tree_unflatten(box["tree"], outs_nd)
        return out

    def hybrid_forward(self, F, *args, **kwargs):
        raise NotImplementedError(
            f"{type(self).__name__} must implement hybrid_forward or "
            f"override forward")

    # -- deferred shape inference --------------------------------------
    def infer_shape(self, *args) -> None:
        """Layer-specific parameter shape inference from inputs; layers
        with input-dependent param shapes override _infer_params."""
        self._infer_params(*args)

    def _infer_params(self, *args) -> None:
        return None

    def _ensure_init(self, *args) -> None:
        deferred = [p for p in self._reg_params.values()
                    if p._data is None and p._deferred_init_args is not None]
        if deferred:
            self._infer_params(*args)
            for p in deferred:
                p._finish_deferred_init()

    def _ensure_init_recursive(self, *args) -> bool:
        """True if every param in the subtree is materialized."""
        ok = True
        for p in self.collect_params().values():
            if p._data is None:
                ok = False
        return ok

    # -- the JIT boundary ----------------------------------------------
    def _call_cached(self, args, leaves, in_treedef):
        if not self._ensure_init_recursive():
            # one imperative pass completes deferred shape inference
            # (the reference runs graph InferShape; eager works too)
            with autograd.pause():
                self.forward(*args)
            if not self._ensure_init_recursive():
                raise DeferredInitializationError(
                    f"{self.name}: parameters still deferred after a "
                    f"shape-inference forward")

        params = [p for p in self.collect_params().values()
                  if p._data is not None]
        training = autograd.is_training()
        key = (in_treedef,
               tuple((tuple(a.shape), str(a.data.dtype)) for a in leaves),
               training, len(params),
               HybridBlock._REMAT_GENERATION[0])
        entry = self._cached_entries.get(key)
        if entry is None:
            entry = self._build_cached(key, in_treedef, leaves, params,
                                       training)
            self._cached_entries[key] = entry

        param_arrays = [p.data().data for p in params]
        rng = _rnd._next_key(None)
        flat_in = [a.data for a in leaves]

        nd_inputs = list(leaves) + [p.data() for p in params]

        if autograd.is_recording() and any(
                autograd._needs_grad(x) for x in nd_inputs):
            raw_arrays = flat_in + param_arrays + [jax.random.key_data(rng)]
            out, node = autograd.record_op(
                f"CachedOp[{self.name}]", entry["flat_fn"],
                nd_inputs + [NDArray(raw_arrays[-1], None, _placed=True)],
                raw_arrays)
            outs_flat = list(out[:entry["n_out"]])
            aux_flat = list(out[entry["n_out"]:])
            wrapped = []
            for i, o in enumerate(outs_flat):
                w = NDArray(o, None, _placed=True)
                autograd.attach_output(w, node, i)
                wrapped.append(w)
        else:
            out = entry["flat_fn"](*flat_in, *param_arrays,
                                   jax.random.key_data(rng))
            outs_flat = list(out[:entry["n_out"]])
            aux_flat = list(out[entry["n_out"]:])
            wrapped = [NDArray(o, None, _placed=True) for o in outs_flat]

        # write back aux (running stats) updates
        for p, new in zip(entry["aux_params"], aux_flat):
            p._data._data = new

        result = jax.tree_util.tree_unflatten(entry["out_treedef"], wrapped)
        return result

    def _build_cached(self, key, in_treedef, leaves, params, training):
        """Trace self.forward into a jitted flat function."""
        n_in = len(leaves)
        n_p = len(params)
        aux_params_order: List[Parameter] = []
        out_treedef_box = {}

        def raw_fn(*flat):
            ins = flat[:n_in]
            pvals = flat[n_in:n_in + n_p]
            key_data = flat[n_in + n_p]
            nd_ins = jax.tree_util.tree_unflatten(
                in_treedef, [NDArray(a, None, _placed=True) for a in ins])
            raw_outs, out_treedef, aux_params, raw_aux = _traced_forward(
                self, params, pvals, nd_ins, training, key_data)
            out_treedef_box["treedef"] = out_treedef
            out_treedef_box["n_out"] = len(raw_outs)
            aux_params_order.clear()
            aux_params_order.extend(aux_params)
            return tuple(raw_outs) + tuple(raw_aux)

        flat_fn = jax.jit(raw_fn)
        # force one trace now to learn output structure (compiles lazily
        # on first real call; eval_shape avoids device work)
        jax.eval_shape(raw_fn, *[a.data for a in leaves],
                       *[p.data().data for p in params],
                       jax.random.key_data(jax.random.PRNGKey(0)))
        return {
            "flat_fn": flat_fn,
            "out_treedef": out_treedef_box["treedef"],
            "n_out": out_treedef_box["n_out"],
            "aux_params": list(aux_params_order),
        }

    # -- deployment -----------------------------------------------------
    def export(self, path: str, epoch: int = 0):
        """Serialize for deployment (reference ``HybridBlock.export``†
        writes ``-symbol.json`` + ``-%04d.params``): trace the block
        symbolically and write the real graph, loadable by
        ``SymbolBlock.imports`` (round-trip tested)."""
        from .. import symbol as sym_mod
        if not self._ensure_init_recursive():
            raise MXNetError(
                "export() needs initialized parameters — run a forward "
                "pass first (reference requires hybridize + forward)")
        n_in = getattr(self, "_num_inputs", 1)
        ins = [sym_mod.var("data" if n_in == 1 else f"data{i}")
               for i in range(n_in)]
        out = self(*ins)
        sym = out if isinstance(out, sym_mod.Symbol) \
            else sym_mod.Group([o for o in out])
        sym.save(f"{path}-symbol.json")
        from ..symbol import _is_aux_name
        arrays = {}
        for p in self.collect_params().values():
            if p._data is None:
                continue
            tag = "aux:" if _is_aux_name(p.name) else "arg:"
            arrays[tag + p.name] = p.data()
        nd_mod.save(f"{path}-{epoch:04d}.params", arrays)
        return f"{path}-symbol.json", f"{path}-{epoch:04d}.params"


class SymbolBlock(HybridBlock):
    """Wrap a Symbol + params as a block (reference ``SymbolBlock``†)."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix="symbolblock_")
        self._outputs = outputs
        self._inputs = inputs if isinstance(inputs, (list, tuple)) \
            else [inputs]
        if params:
            for k, v in params.items():
                self._params._params[k] = v

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        from ..symbol import load as sym_load, var as sym_var
        sym = sym_load(symbol_file)
        inputs = [sym_var(n) if isinstance(n, str) else n
                  for n in _as_list_names(input_names)]
        blk = SymbolBlock(sym, inputs)
        if param_file:
            loaded = nd_mod.load(param_file)
            for k, v in loaded.items():
                name = k.split(":", 1)[-1]
                p = Parameter(name, shape=v.shape)
                p.set_data(v)
                blk._params._params[name] = p
        return blk

    def forward(self, *args):
        from ..symbol import _eval_symbol
        bindings = {}
        for inp, val in zip(self._inputs, args):
            bindings[inp.name] = val
        for name, p in self.collect_params().items():
            if p._data is not None:
                bindings[name] = p.data()
        outs = _eval_symbol(self._outputs, bindings)
        return outs[0] if len(outs) == 1 else outs
