"""DataLoader (reference ``python/mxnet/gluon/data/dataloader.py``†).

TPU-native divergence from the reference: the reference forks
multiprocessing workers that write batches into POSIX-shm NDArrays
(``cpu_shared_storage_manager.h``†).  Forking a process that holds a
live TPU/PjRt client is unsafe (and jax state is not fork-inheritable),
so ``num_workers > 0`` here means a **thread pool** — batchify runs in
numpy (releasing the GIL for decode/copy) and the device transfer stays
on the consumer thread.  The C++ pipeline in ``core/`` supplies true
parallel decode underneath when built.
"""
from __future__ import annotations

import queue as _queue
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Optional

import numpy as np

from ...base import MXNetError
from ...ndarray import NDArray, array
from .dataset import Dataset
from .sampler import BatchSampler, RandomSampler, SequentialSampler, Sampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (reference ``default_batchify_fn``†)."""
    if isinstance(data[0], NDArray):
        return array(np.stack([d.asnumpy() for d in data]))
    if isinstance(data[0], tuple):
        transposed = zip(*data)
        return tuple(default_batchify_fn(list(col)) for col in transposed)
    arr = np.asarray(data)
    return array(arr)


class DataLoader:
    """Loads batches from a Dataset (reference ``DataLoader``†)."""

    def __init__(self, dataset: Dataset, batch_size: Optional[int] = None,
                 shuffle: bool = False, sampler: Optional[Sampler] = None,
                 last_batch: Optional[str] = None,
                 batch_sampler: Optional[BatchSampler] = None,
                 batchify_fn: Optional[Callable] = None,
                 num_workers: int = 0, prefetch: Optional[int] = None):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise MXNetError("need batch_size unless batch_sampler "
                                 "is given")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle else \
                    SequentialSampler(len(dataset))
            elif shuffle:
                raise MXNetError("shuffle and sampler are exclusive")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif (batch_size is not None or shuffle or sampler is not None
              or last_batch is not None):
            raise MXNetError("batch_sampler is exclusive with batch_size/"
                             "shuffle/sampler/last_batch")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)

    def __len__(self):
        return len(self._batch_sampler)

    def _load_batch(self, indices):
        return self._batchify_fn([self._dataset[i] for i in indices])

    def __iter__(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._load_batch(indices)
            return

        # Thread-pool pipeline with bounded in-flight futures — the
        # prefetcher's double buffering generalized.
        with ThreadPoolExecutor(self._num_workers) as pool:
            batches = iter(self._batch_sampler)
            inflight: _queue.Queue = _queue.Queue()
            depth = max(1, self._prefetch)

            def submit_next():
                try:
                    indices = next(batches)
                except StopIteration:
                    return False
                inflight.put(pool.submit(self._load_batch, indices))
                return True

            for _ in range(depth):
                if not submit_next():
                    break
            while not inflight.empty():
                fut = inflight.get()
                submit_next()
                yield fut.result()
