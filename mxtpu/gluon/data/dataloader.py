"""DataLoader (reference ``python/mxnet/gluon/data/dataloader.py``†).

Worker model: the reference forks multiprocessing workers that write
batches into POSIX-shm NDArrays (``cpu_shared_storage_manager.h``†).
Forking a process that holds a live TPU/PjRt client is unsafe (and jax
state is not fork-inheritable), so two worker types exist here:

- ``worker_type='thread'`` (default): a thread pool — batchify runs in
  numpy (cv2/numpy release the GIL for decode/copy) and the device
  transfer stays on the consumer thread.
- ``worker_type='process'``: SPAWNED process workers for pure-python
  transforms that would serialize on the GIL.  Workers never touch
  jax (children force ``JAX_PLATFORMS=cpu`` defensively); the dataset
  is pickled once to each worker and batches come back as numpy,
  converted to NDArray on the consumer.  Datasets/transforms must be
  picklable and numpy-level (NDArray-returning datasets need the
  thread mode).

The C++ pipeline in ``core/`` supplies true parallel decode underneath
when built.
"""
from __future__ import annotations

import os
import pickle
import queue as _queue
import threading
from concurrent.futures import ProcessPoolExecutor, ThreadPoolExecutor
from typing import Callable, Optional

import numpy as np

from ...base import MXNetError
from ...ndarray import NDArray, array
from .dataset import Dataset
from .sampler import BatchSampler, RandomSampler, SequentialSampler, Sampler

__all__ = ["DataLoader", "default_batchify_fn"]

# -- process-worker plumbing (module-level: must be picklable) ---------
_WORKER_DATASET = None


def _proc_worker_init(dataset_blob: bytes) -> None:
    global _WORKER_DATASET
    # never let a child spin up a TPU client
    os.environ["JAX_PLATFORMS"] = "cpu"
    _WORKER_DATASET = pickle.loads(dataset_blob)  # mxlint: disable=raw-deserialize (in-process IPC: bytes this parent just pickled, never touch disk)


def _np_batchify(samples):
    """Numpy-only batchify for process workers (NDArray construction
    happens on the consumer side)."""
    first = samples[0]
    if isinstance(first, tuple):
        return tuple(_np_batchify([s[i] for s in samples])
                     for i in range(len(first)))
    return np.stack([np.asarray(s) for s in samples])


def _proc_worker_load(indices):
    return _np_batchify([_WORKER_DATASET[i] for i in indices])


def default_batchify_fn(data):
    """Stack samples into a batch (reference ``default_batchify_fn``†)."""
    if isinstance(data[0], NDArray):
        return array(np.stack([d.asnumpy() for d in data]))
    if isinstance(data[0], tuple):
        transposed = zip(*data)
        return tuple(default_batchify_fn(list(col)) for col in transposed)
    arr = np.asarray(data)
    return array(arr)


class DataLoader:
    """Loads batches from a Dataset (reference ``DataLoader``†)."""

    def __init__(self, dataset: Dataset, batch_size: Optional[int] = None,
                 shuffle: bool = False, sampler: Optional[Sampler] = None,
                 last_batch: Optional[str] = None,
                 batch_sampler: Optional[BatchSampler] = None,
                 batchify_fn: Optional[Callable] = None,
                 num_workers: int = 0, prefetch: Optional[int] = None,
                 worker_type: str = "thread"):
        self._dataset = dataset
        if worker_type not in ("thread", "process"):
            raise MXNetError(f"worker_type {worker_type!r}: choose "
                             f"'thread' or 'process'")
        self._worker_type = worker_type
        if worker_type == "process" and batchify_fn is not None:
            raise MXNetError("custom batchify_fn runs on the consumer "
                             "only in thread mode; process workers use "
                             "the numpy batchifier")
        if batch_sampler is None:
            if batch_size is None:
                raise MXNetError("need batch_size unless batch_sampler "
                                 "is given")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle else \
                    SequentialSampler(len(dataset))
            elif shuffle:
                raise MXNetError("shuffle and sampler are exclusive")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif (batch_size is not None or shuffle or sampler is not None
              or last_batch is not None):
            raise MXNetError("batch_sampler is exclusive with batch_size/"
                             "shuffle/sampler/last_batch")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)
        self._proc_pool = None
        self._thread_pool = None

    def __len__(self):
        return len(self._batch_sampler)

    def _load_batch(self, indices):
        return self._batchify_fn([self._dataset[i] for i in indices])

    @staticmethod
    def _to_nd(batch):
        if isinstance(batch, tuple):
            return tuple(DataLoader._to_nd(b) for b in batch)
        return array(batch)

    def __iter__(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._load_batch(indices)
            return

        if self._worker_type == "process":
            # persistent workers (the reference's worker pool outlives
            # epochs): spawn + dataset pickle happen ONCE, not per
            # __iter__
            if self._proc_pool is None:
                import multiprocessing as mp
                blob = pickle.dumps(self._dataset)
                self._proc_pool = ProcessPoolExecutor(
                    self._num_workers,
                    mp_context=mp.get_context("spawn"),
                    initializer=_proc_worker_init, initargs=(blob,))
            pool = self._proc_pool
            load = _proc_worker_load
            wrap = self._to_nd
        else:
            if self._thread_pool is None:
                self._thread_pool = ThreadPoolExecutor(
                    self._num_workers)
            pool = self._thread_pool
            load = self._load_batch
            wrap = lambda b: b  # noqa: E731

        # Bounded in-flight futures — the prefetcher's double
        # buffering generalized.
        batches = iter(self._batch_sampler)
        inflight: _queue.Queue = _queue.Queue()
        depth = max(1, self._prefetch)

        def submit_next():
            try:
                indices = next(batches)
            except StopIteration:
                return False
            inflight.put(pool.submit(load, list(indices)))
            return True

        for _ in range(depth):
            if not submit_next():
                break
        while not inflight.empty():
            fut = inflight.get()
            submit_next()
            yield wrap(fut.result())

    def close(self) -> None:
        """Shut the persistent worker pools down."""
        if self._proc_pool is not None:
            self._proc_pool.shutdown(wait=False)
            self._proc_pool = None
        if self._thread_pool is not None:
            self._thread_pool.shutdown(wait=False)
            self._thread_pool = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
