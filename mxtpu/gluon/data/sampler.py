"""Samplers (reference ``python/mxnet/gluon/data/sampler.py``†)."""
from __future__ import annotations

import numpy as np

from ...base import MXNetError

__all__ = ["Sampler", "SequentialSampler", "RandomSampler",
           "BatchSampler"]


class Sampler:
    """Yields sample indices (reference†)."""

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class SequentialSampler(Sampler):
    def __init__(self, length: int):
        self._length = length

    def __iter__(self):
        return iter(range(self._length))

    def __len__(self):
        return self._length


class RandomSampler(Sampler):
    def __init__(self, length: int):
        self._length = length

    def __iter__(self):
        return iter(np.random.permutation(self._length).tolist())

    def __len__(self):
        return self._length


class BatchSampler(Sampler):
    """Groups a sampler into batches; last_batch in
    {'keep','discard','rollover'} (reference†)."""

    def __init__(self, sampler: Sampler, batch_size: int,
                 last_batch: str = "keep"):
        if last_batch not in ("keep", "discard", "rollover"):
            raise MXNetError(f"bad last_batch {last_batch!r}")
        self._sampler = sampler
        self._batch_size = batch_size
        self._last_batch = last_batch
        self._prev: list = []

    def __iter__(self):
        batch, self._prev = self._prev, []
        for idx in self._sampler:
            batch.append(idx)
            if len(batch) == self._batch_size:
                yield batch
                batch = []
        if batch:
            if self._last_batch == "keep":
                yield batch
            elif self._last_batch == "rollover":
                self._prev = batch

    def __len__(self):
        n = len(self._sampler) + len(self._prev)
        if self._last_batch == "keep":
            return (n + self._batch_size - 1) // self._batch_size
        if self._last_batch == "discard":
            return n // self._batch_size
        if self._last_batch == "rollover":
            return n // self._batch_size
        raise MXNetError("unreachable")
