"""Vision transforms (reference
``python/mxnet/gluon/data/vision/transforms.py``†).

Transforms are HybridBlocks over HWC uint8/float NDArrays so a
``Compose`` chain can hybridize into one XLA program and fuse with the
first model layers when used on-device; on the host path they run as
eager jax ops on CPU.
"""
from __future__ import annotations

import numpy as np

from ....base import MXNetError
from ....ndarray import NDArray, array
from ... import nn
from ...block import Block, HybridBlock

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize",
           "CenterCrop", "RandomResizedCrop", "RandomFlipLeftRight",
           "RandomFlipTopBottom", "RandomBrightness", "RandomContrast"]


class Compose(nn.Sequential):
    """Sequentially compose transforms (reference ``Compose``†)."""

    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return x.astype(self._dtype)


class ToTensor(HybridBlock):
    """HWC uint8 [0,255] → CHW float32 [0,1] (reference ``ToTensor``†)."""

    def hybrid_forward(self, F, x):
        x = x.astype("float32") / 255.0
        if len(x.shape) == 3:
            return F.transpose(x, axes=(2, 0, 1))
        return F.transpose(x, axes=(0, 3, 1, 2))


class Normalize(HybridBlock):
    """(x - mean) / std over channels of a CHW tensor (reference†).
    mean/std are placed on device once at construction, not per call."""

    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = array(np.asarray(mean, np.float32).reshape(-1, 1, 1))
        self._std = array(np.asarray(std, np.float32).reshape(-1, 1, 1))

    def hybrid_forward(self, F, x):
        return (x - self._mean) / self._std


def _resize_hwc(x: NDArray, size) -> NDArray:
    import jax
    w, h = (size, size) if isinstance(size, int) else size
    raw = x.data.astype("float32")
    if raw.ndim == 2:
        raw = raw[:, :, None]
    out = jax.image.resize(raw, (h, w, raw.shape[2]), method="bilinear")
    return NDArray(out, None, _placed=True)


class Resize(Block):
    """Resize HWC image (reference ``Resize``†; ``jax.image.resize`` is
    the interpolator — runs on whatever backend holds the array)."""

    def __init__(self, size, keep_ratio=False):
        super().__init__()
        self._size = size
        self._keep = keep_ratio

    def forward(self, x):
        if self._keep and isinstance(self._size, int):
            h, w = x.shape[:2]
            if h < w:
                size = (int(self._size * w / h), self._size)
            else:
                size = (self._size, int(self._size * h / w))
        else:
            size = self._size
        return _resize_hwc(x, size)


class CenterCrop(Block):
    def __init__(self, size):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size

    def forward(self, x):
        w, h = self._size
        ih, iw = x.shape[:2]
        if ih < h or iw < w:
            return _resize_hwc(x, self._size)
        y0 = (ih - h) // 2
        x0 = (iw - w) // 2
        return x[y0:y0 + h, x0:x0 + w]


class RandomResizedCrop(Block):
    """Random area/aspect crop then resize (reference†, simplified to
    the same parameter surface)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3)):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        ih, iw = x.shape[:2]
        area = ih * iw
        for _ in range(10):
            target = np.random.uniform(*self._scale) * area
            aspect = np.random.uniform(*self._ratio)
            w = int(round(np.sqrt(target * aspect)))
            h = int(round(np.sqrt(target / aspect)))
            if w <= iw and h <= ih:
                x0 = np.random.randint(0, iw - w + 1)
                y0 = np.random.randint(0, ih - h + 1)
                return _resize_hwc(x[y0:y0 + h, x0:x0 + w], self._size)
        return _resize_hwc(x, self._size)


class RandomFlipLeftRight(Block):
    def forward(self, x):
        if np.random.rand() < 0.5:
            return x[:, ::-1]
        return x


class RandomFlipTopBottom(Block):
    def forward(self, x):
        if np.random.rand() < 0.5:
            return x[::-1]
        return x


class RandomBrightness(Block):
    def __init__(self, brightness):
        super().__init__()
        self._b = brightness

    def forward(self, x):
        f = 1.0 + np.random.uniform(-self._b, self._b)
        return x * f


class RandomContrast(Block):
    def __init__(self, contrast):
        super().__init__()
        self._c = contrast

    def forward(self, x):
        f = 1.0 + np.random.uniform(-self._c, self._c)
        mean = x.mean()
        return x * f + mean * (1.0 - f)
