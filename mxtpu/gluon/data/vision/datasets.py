"""Vision datasets (reference
``python/mxnet/gluon/data/vision/datasets.py``†).

No-network environment note: the reference downloads archives on first
use.  Here datasets read pre-placed files from ``root`` (same filenames
as upstream) and raise a clear error when absent — the download step is
the deployment's job, not the framework's.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile
from typing import Callable, Optional

import numpy as np

from ....base import MXNetError
from ....ndarray import array
from ..dataset import Dataset, RecordFileDataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageRecordDataset", "ImageFolderDataset"]


class _DownloadedDataset(Dataset):
    def __init__(self, root: str, train: bool,
                 transform: Optional[Callable]):
        self._root = os.path.expanduser(root)
        self._train = train
        self._transform = transform
        self._data = None
        self._label = None
        self._get_data()

    def __len__(self):
        return len(self._label)

    def __getitem__(self, idx):
        img = array(self._data[idx])
        label = self._label[idx]
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def _get_data(self):
        raise NotImplementedError


def _read_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        return np.frombuffer(f.read(), np.uint8).reshape(dims)


class MNIST(_DownloadedDataset):
    """MNIST from pre-placed idx files (reference ``MNIST``†).
    Accepts both gzipped and raw idx files."""

    _train_files = ("train-images-idx3-ubyte", "train-labels-idx1-ubyte")
    _test_files = ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte")

    def __init__(self, root="~/.mxnet/datasets/mnist", train=True,
                 transform=None):
        super().__init__(root, train, transform)

    def _find(self, base: str) -> str:
        for cand in (base, base + ".gz"):
            p = os.path.join(self._root, cand)
            if os.path.exists(p):
                return p
        raise MXNetError(
            f"{base}[.gz] not found under {self._root}; place the MNIST "
            f"idx files there (no network access to download)")

    def _get_data(self):
        imgs, labels = (self._train_files if self._train
                        else self._test_files)
        data = _read_idx(self._find(imgs))
        self._data = data.reshape(-1, 28, 28, 1)
        self._label = _read_idx(self._find(labels)).astype(np.int32)


class FashionMNIST(MNIST):
    """Same container as MNIST (reference ``FashionMNIST``†)."""

    def __init__(self, root="~/.mxnet/datasets/fashion-mnist", train=True,
                 transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    """CIFAR-10 from the pre-placed python-pickle archive
    (reference ``CIFAR10``†)."""

    _archive = "cifar-10-batches-py"

    def __init__(self, root="~/.mxnet/datasets/cifar10", train=True,
                 transform=None):
        super().__init__(root, train, transform)

    def _batches(self):
        if self._train:
            return [f"data_batch_{i}" for i in range(1, 6)]
        return ["test_batch"]

    def _get_data(self):
        base = os.path.join(self._root, self._archive)
        if not os.path.isdir(base):
            tar = os.path.join(self._root, "cifar-10-python.tar.gz")
            if os.path.exists(tar):
                with tarfile.open(tar) as tf:
                    if hasattr(tarfile, "data_filter"):
                        tf.extractall(self._root, filter="data")
                    else:  # pre-3.12 point releases
                        tf.extractall(self._root)
            else:
                raise MXNetError(
                    f"CIFAR-10 not found under {self._root} (no network "
                    f"access to download)")
        data, labels = [], []
        for name in self._batches():
            with open(os.path.join(base, name), "rb") as f:
                batch = pickle.load(f, encoding="latin1")  # mxlint: disable=raw-deserialize (upstream CIFAR archive format is pickle; file came from the pinned download)
            data.append(batch["data"].reshape(-1, 3, 32, 32)
                        .transpose(0, 2, 3, 1))
            labels.extend(batch["labels"])
        self._data = np.concatenate(data)
        self._label = np.asarray(labels, np.int32)


class CIFAR100(CIFAR10):
    """CIFAR-100 (reference ``CIFAR100``†)."""

    _archive = "cifar-100-python"

    def __init__(self, root="~/.mxnet/datasets/cifar100", train=True,
                 fine_label=True, transform=None):
        self._fine = fine_label
        super().__init__(root, train, transform)

    def _batches(self):
        return ["train"] if self._train else ["test"]

    def _get_data(self):
        base = os.path.join(self._root, self._archive)
        if not os.path.isdir(base):
            raise MXNetError(
                f"CIFAR-100 not found under {self._root} (no network "
                f"access to download)")
        data, labels = [], []
        for name in self._batches():
            with open(os.path.join(base, name), "rb") as f:
                batch = pickle.load(f, encoding="latin1")  # mxlint: disable=raw-deserialize (upstream CIFAR archive format is pickle; file came from the pinned download)
            data.append(batch["data"].reshape(-1, 3, 32, 32)
                        .transpose(0, 2, 3, 1))
            key = "fine_labels" if self._fine else "coarse_labels"
            labels.extend(batch[key])
        self._data = np.concatenate(data)
        self._label = np.asarray(labels, np.int32)


class ImageRecordDataset(RecordFileDataset):
    """Image dataset over an im2rec-style .rec file
    (reference ``ImageRecordDataset``†)."""

    def __init__(self, filename: str, flag: int = 1, transform=None):
        super().__init__(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        from .... import recordio
        record = super().__getitem__(idx)
        header, img = recordio.unpack_img(record, iscolor=self._flag)
        img = array(img[:, :, ::-1] if self._flag else img)  # BGR→RGB
        label = header.label
        if isinstance(label, np.ndarray) and label.size == 1:
            label = float(label[0])
        if self._transform is not None:
            return self._transform(img, label)
        return img, label


class ImageFolderDataset(Dataset):
    """``root/class_name/*.jpg`` layout (reference†)."""

    def __init__(self, root: str, flag: int = 1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = {".jpg", ".jpeg", ".png"}
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(self._root)):
            path = os.path.join(self._root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for fname in sorted(os.listdir(path)):
                if os.path.splitext(fname)[1].lower() in self._exts:
                    self.items.append((os.path.join(path, fname), label))

    def __len__(self):
        return len(self.items)

    def __getitem__(self, idx):
        import cv2
        fname, label = self.items[idx]
        img = cv2.imread(fname,
                         cv2.IMREAD_COLOR if self._flag
                         else cv2.IMREAD_GRAYSCALE)
        if img is None:
            raise MXNetError(f"failed to read image {fname}")
        if self._flag:
            img = img[:, :, ::-1]  # BGR→RGB
        img = array(img)
        if self._transform is not None:
            return self._transform(img, label)
        return img, label
