"""Gluon Trainer (reference ``python/mxnet/gluon/trainer.py``†).

Applies an Optimizer to a set of Parameters:
``step(batch_size)`` = allreduce_grads (KVStore/in-graph psum when data
parallel) + update (fused optimizer ops).  In SPMD mode the gradients
are already globally reduced inside the compiled step (psum over the
mesh), so ``_allreduce_grads`` is a no-op there — the KVStore facade
(``mxtpu.kvstore``) documents the mapping from push/pull to in-graph
collectives.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ..base import MXNetError
from .. import optimizer as opt_mod
from ..ndarray.ndarray import NDArray
from .parameter import Parameter, ParameterDict

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None,
                 update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise MXNetError(
                "params must be a ParameterDict or list of Parameters")
        self._params: List[Parameter] = []
        self._param2idx: Dict[str, int] = {}
        for i, p in enumerate(params):
            if not isinstance(p, Parameter):
                raise MXNetError(f"invalid parameter {p!r}")
            self._param2idx[p.name] = i
            self._params.append(p)
        self._compression_params = compression_params
        optimizer_params = optimizer_params or {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._init_optimizer(optimizer, optimizer_params)
        self._kvstore_type = kvstore
        self._kvstore = None
        self._kv_initialized = False
        self._update_on_kvstore = update_on_kvstore
        self._optimizer_applied = False

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: p for i, p in enumerate(self._params)}
        if isinstance(optimizer, opt_mod.Optimizer):
            if optimizer_params and set(optimizer_params) - {"rescale_grad"}:
                raise MXNetError(
                    "optimizer_params must be None when optimizer is an "
                    "Optimizer instance")
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt_mod.create(
                optimizer, param_dict=param_dict, **optimizer_params)
        self._updaters = [opt_mod.get_updater(self._optimizer)]

    def _init_kvstore(self):
        """Create the KVStore lazily on first step (reference behavior).
        Types 'local'/'device' map to in-graph reduction; with one
        device there is nothing to reduce."""
        if self._kvstore_type in (None, "nccl") or self._kv_initialized:
            if not self._kv_initialized and self._compression_params:
                raise MXNetError(
                    f"compression_params given but kvstore="
                    f"{self._kvstore_type!r} creates no store to carry "
                    f"the compressed gradients")
            self._kv_initialized = True
            return
        try:
            from .. import kvstore as kv_mod
            self._kvstore = kv_mod.create(self._kvstore_type)
            if self._kvstore is not None and self._kvstore.num_devices <= 1 \
                    and not self._compression_params:
                # with one device there is nothing to reduce — unless
                # compression is requested, whose error-feedback
                # quantization changes the update numerics even solo
                self._kvstore = None
        except (ImportError, MXNetError):
            self._kvstore = None
        if self._compression_params:
            if self._kvstore is None:
                # a silently-uncompressed run is worse than an error
                raise MXNetError(
                    "compression_params given but no kvstore is "
                    f"available (type={self._kvstore_type!r})")
            # outside the try: invalid compression params must raise,
            # not silently disable the kvstore
            self._kvstore.set_gradient_compression(
                self._compression_params)
        self._kv_initialized = True

    # ------------------------------------------------------------------
    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    @property
    def optimizer(self):
        return self._optimizer

    # ------------------------------------------------------------------
    def _check_grads(self):
        missing = [p.name for p in self._params
                   if p.grad_req != "null" and
                   (p._data is None or p._data.grad is None)]
        if missing:
            raise MXNetError(
                f"cannot step: parameters {missing} have no gradient; "
                f"run forward+backward inside autograd.record() first")

    def step(self, batch_size, ignore_stale_grad=False):
        """allreduce + update (reference ``Trainer.step``†)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._allreduce_grads()
        self._update(ignore_stale_grad)

    def allreduce_grads(self):
        if not self._kv_initialized:
            self._init_kvstore()
        self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore is None:
            return
        for i, param in enumerate(self._params):
            if param.grad_req != "null" and param._data is not None \
                    and param._data.grad is not None:
                self._kvstore.push(i, param.grad(), priority=-i)
                self._kvstore.pull(i, param.grad(), priority=-i)

    def update(self, batch_size, ignore_stale_grad=False):
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        self._check_grads()
        updater = self._updaters[0]
        for i, param in enumerate(self._params):
            if param.grad_req == "null" or param._data is None:
                continue
            updater(i, param.grad(), param.data())

    # ------------------------------------------------------------------
    def save_states(self, fname):
        """Serialize updater states (reference ``Trainer.save_states``†)."""
        with open(fname, "wb") as f:
            f.write(self._updaters[0].get_states(dump_optimizer=True))

    def load_states(self, fname):
        with open(fname, "rb") as f:
            data = f.read()
        self._updaters[0].set_states(data)
        self._optimizer = self._updaters[0].optimizer
        self._optimizer.param_dict = {
            i: p for i, p in enumerate(self._params)}
