"""``mxtpu.gluon`` — the imperative API with a JIT boundary
(reference ``python/mxnet/gluon/``†).

``HybridBlock.hybridize()`` compiles the forward (and, under
``autograd.record``, the backward) into cached XLA executables — the
TPU-native CachedOp (SURVEY.md §3.2).
"""
from .parameter import (Parameter, ParameterDict, Constant,
                        DeferredInitializationError)
from .block import Block, HybridBlock, SymbolBlock
from .trainer import Trainer
from . import nn
from . import loss
from . import utils

__all__ = ["Parameter", "ParameterDict", "Constant",
           "DeferredInitializationError", "Block", "HybridBlock",
           "SymbolBlock", "Trainer", "nn", "loss", "utils", "rnn", "data",
           "model_zoo", "contrib"]


def __getattr__(name):
    import importlib
    if name in ("rnn", "data", "model_zoo", "contrib"):
        mod = importlib.import_module("." + name, __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'mxtpu.gluon' has no attribute {name!r}")
