"""ResNet V1/V2 (reference
``python/mxnet/gluon/model_zoo/vision/resnet.py``†; He et al. 2015/16).

TPU notes: NCHW layout feeding ``lax.conv_general_dilated``; BatchNorm
running stats flow through the aux-update channel, so the whole model
hybridizes into one XLA program with the conv+BN+relu chains fused.
"""
from __future__ import annotations

from ....base import MXNetError
from ... import nn
from ...block import HybridBlock

__all__ = ["ResNetV1", "ResNetV2", "BasicBlockV1", "BasicBlockV2",
           "BottleneckV1", "BottleneckV2", "get_resnet",
           "resnet18_v1", "resnet34_v1", "resnet50_v1", "resnet101_v1",
           "resnet152_v1", "resnet18_v2", "resnet34_v2", "resnet50_v2",
           "resnet101_v2", "resnet152_v2"]


def _conv3x3(channels, stride, in_channels, layout="NCHW"):
    return nn.Conv2D(channels, kernel_size=3, strides=stride, padding=1,
                     use_bias=False, in_channels=in_channels,
                     layout=layout)


def _bn_axis(layout):
    # channel axis for BatchNorm under the given data layout
    return 1 if layout.startswith("NC") else 3


class BasicBlockV1(HybridBlock):
    """Pre-pooling residual block (resnet18/34 v1; reference†).

    TPU note: the BN->relu pairs and the final BN->(+shortcut)->relu
    go through the fused BatchNorm(Add)Relu ops — same math; XLA fuses
    the epilogue into the apply pass by default, and the one-HBM-pass
    Pallas kernel is opt-in via MXTPU_FUSED_BN=1 (measured verdict in
    BASELINE.md "Fused-BN verdict"; reference's ``BatchNormAddRelu``
    tier, SURVEY §2.1-N8)."""

    def __init__(self, channels, stride, downsample=False,
                 in_channels=0, layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        ax = _bn_axis(layout)
        self.body = nn.HybridSequential(prefix="")
        self.body.add(_conv3x3(channels, stride, in_channels, layout))
        self.body.add(nn.BatchNorm(axis=ax, act_type="relu"))
        self.body.add(_conv3x3(channels, 1, channels, layout))
        self.bn_out = nn.BatchNorm(axis=ax, act_type="relu")
        if downsample:
            self.downsample = nn.HybridSequential(prefix="")
            self.downsample.add(nn.Conv2D(
                channels, kernel_size=1, strides=stride, use_bias=False,
                in_channels=in_channels, layout=layout))
            self.downsample.add(nn.BatchNorm(axis=ax))
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.body(x)
        if self.downsample is not None:
            residual = self.downsample(residual)
        return self.bn_out(x, residual)


class BottleneckV1(HybridBlock):
    """1x1-3x3-1x1 bottleneck (resnet50+ v1; reference†)."""

    def __init__(self, channels, stride, downsample=False,
                 in_channels=0, layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        ax = _bn_axis(layout)
        self.body = nn.HybridSequential(prefix="")
        self.body.add(nn.Conv2D(channels // 4, kernel_size=1,
                                strides=stride, layout=layout))
        self.body.add(nn.BatchNorm(axis=ax, act_type="relu"))
        self.body.add(_conv3x3(channels // 4, 1, channels // 4, layout))
        self.body.add(nn.BatchNorm(axis=ax, act_type="relu"))
        self.body.add(nn.Conv2D(channels, kernel_size=1, strides=1,
                                layout=layout))
        self.bn_out = nn.BatchNorm(axis=ax, act_type="relu")
        if downsample:
            self.downsample = nn.HybridSequential(prefix="")
            self.downsample.add(nn.Conv2D(
                channels, kernel_size=1, strides=stride, use_bias=False,
                in_channels=in_channels, layout=layout))
            self.downsample.add(nn.BatchNorm(axis=ax))
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.body(x)
        if self.downsample is not None:
            residual = self.downsample(residual)
        return self.bn_out(x, residual)


class BasicBlockV2(HybridBlock):
    """Pre-activation residual block (resnet18/34 v2; reference†)."""

    def __init__(self, channels, stride, downsample=False,
                 in_channels=0, layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        ax = _bn_axis(layout)
        self.bn1 = nn.BatchNorm(axis=ax, act_type="relu")
        self.conv1 = _conv3x3(channels, stride, in_channels, layout)
        self.bn2 = nn.BatchNorm(axis=ax, act_type="relu")
        self.conv2 = _conv3x3(channels, 1, channels, layout)
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride,
                                        use_bias=False,
                                        in_channels=in_channels,
                                        layout=layout)
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.bn1(x)
        if self.downsample is not None:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = self.conv2(x)
        return x + residual


class BottleneckV2(HybridBlock):
    """Pre-activation bottleneck (resnet50+ v2; reference†)."""

    def __init__(self, channels, stride, downsample=False,
                 in_channels=0, layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        ax = _bn_axis(layout)
        self.bn1 = nn.BatchNorm(axis=ax, act_type="relu")
        self.conv1 = nn.Conv2D(channels // 4, kernel_size=1, strides=1,
                               use_bias=False, layout=layout)
        self.bn2 = nn.BatchNorm(axis=ax, act_type="relu")
        self.conv2 = _conv3x3(channels // 4, stride, channels // 4,
                              layout)
        self.bn3 = nn.BatchNorm(axis=ax, act_type="relu")
        self.conv3 = nn.Conv2D(channels, kernel_size=1, strides=1,
                               use_bias=False, layout=layout)
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride,
                                        use_bias=False,
                                        in_channels=in_channels,
                                        layout=layout)
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.bn1(x)
        if self.downsample is not None:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = self.conv2(x)
        x = self.bn3(x)
        x = self.conv3(x)
        return x + residual


class ResNetV1(HybridBlock):
    """ResNet V1 (reference ``ResNetV1``†)."""

    def __init__(self, block, layers, channels, classes=1000,
                 thumbnail=False, layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(channels) - 1
        # layout="NHWC" keeps channels minormost end-to-end — the TPU's
        # native conv layout (no transposes inside the hot loop)
        self._layout = layout
        ax = _bn_axis(layout)
        self.features = nn.HybridSequential(prefix="")
        if thumbnail:
            self.features.add(_conv3x3(channels[0], 1, 0, layout))
        else:
            self.features.add(nn.Conv2D(channels[0], 7, 2, 3,
                                        use_bias=False, layout=layout))
            self.features.add(nn.BatchNorm(axis=ax, act_type="relu"))
            self.features.add(nn.MaxPool2D(3, 2, 1, layout=layout))
        for i, num_layer in enumerate(layers):
            stride = 1 if i == 0 else 2
            self.features.add(self._make_layer(
                block, num_layer, channels[i + 1], stride,
                in_channels=channels[i], layout=layout))
        self.features.add(nn.GlobalAvgPool2D(layout=layout))
        self.output = nn.Dense(classes, in_units=channels[-1])

    def _make_layer(self, block, layers, channels, stride,
                    in_channels=0, layout="NCHW"):
        layer = nn.HybridSequential(prefix="")
        layer.add(block(channels, stride, channels != in_channels,
                        in_channels=in_channels, layout=layout))
        for _ in range(layers - 1):
            layer.add(block(channels, 1, False, in_channels=channels,
                            layout=layout))
        return layer

    def hybrid_forward(self, F, x):
        x = self.features(x)
        return self.output(x)


class ResNetV2(HybridBlock):
    """ResNet V2 (reference ``ResNetV2``†)."""

    def __init__(self, block, layers, channels, classes=1000,
                 thumbnail=False, layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(channels) - 1
        self._layout = layout
        ax = _bn_axis(layout)
        self.features = nn.HybridSequential(prefix="")
        self.features.add(nn.BatchNorm(axis=ax, scale=False,
                                       center=False))
        if thumbnail:
            self.features.add(_conv3x3(channels[0], 1, 0, layout))
        else:
            self.features.add(nn.Conv2D(channels[0], 7, 2, 3,
                                        use_bias=False, layout=layout))
            self.features.add(nn.BatchNorm(axis=ax, act_type="relu"))
            self.features.add(nn.MaxPool2D(3, 2, 1, layout=layout))
        in_channels = channels[0]
        for i, num_layer in enumerate(layers):
            stride = 1 if i == 0 else 2
            self.features.add(self._make_layer(
                block, num_layer, channels[i + 1], stride,
                in_channels=in_channels, layout=layout))
            in_channels = channels[i + 1]
        self.features.add(nn.BatchNorm(axis=ax))
        self.features.add(nn.Activation("relu"))
        self.features.add(nn.GlobalAvgPool2D(layout=layout))
        self.output = nn.Dense(classes, in_units=channels[-1])

    def _make_layer(self, block, layers, channels, stride,
                    in_channels=0, layout="NCHW"):
        layer = nn.HybridSequential(prefix="")
        layer.add(block(channels, stride, channels != in_channels,
                        in_channels=in_channels, layout=layout))
        for _ in range(layers - 1):
            layer.add(block(channels, 1, False, in_channels=channels,
                            layout=layout))
        return layer

    def hybrid_forward(self, F, x):
        x = self.features(x)
        return self.output(x)


_resnet_spec = {
    18: ("basic_block", [2, 2, 2, 2], [64, 64, 128, 256, 512]),
    34: ("basic_block", [3, 4, 6, 3], [64, 64, 128, 256, 512]),
    50: ("bottle_neck", [3, 4, 6, 3], [64, 256, 512, 1024, 2048]),
    101: ("bottle_neck", [3, 4, 23, 3], [64, 256, 512, 1024, 2048]),
    152: ("bottle_neck", [3, 8, 36, 3], [64, 256, 512, 1024, 2048]),
}
_resnet_net_versions = [ResNetV1, ResNetV2]
_resnet_block_versions = [
    {"basic_block": BasicBlockV1, "bottle_neck": BottleneckV1},
    {"basic_block": BasicBlockV2, "bottle_neck": BottleneckV2},
]


def get_resnet(version, num_layers, pretrained=False, **kwargs):
    """Construct a ResNet (reference ``get_resnet``†)."""
    if num_layers not in _resnet_spec:
        raise MXNetError(f"invalid depth {num_layers}; "
                         f"choices {sorted(_resnet_spec)}")
    if version not in (1, 2):
        raise MXNetError("version must be 1 or 2")
    if pretrained:
        raise MXNetError("pretrained weights are not bundled (no "
                         "network access); load_parameters() from a "
                         "local file instead")
    block_type, layers, channels = _resnet_spec[num_layers]
    net_cls = _resnet_net_versions[version - 1]
    block_cls = _resnet_block_versions[version - 1][block_type]
    return net_cls(block_cls, layers, channels, **kwargs)


def resnet18_v1(**kwargs):
    return get_resnet(1, 18, **kwargs)


def resnet34_v1(**kwargs):
    return get_resnet(1, 34, **kwargs)


def resnet50_v1(**kwargs):
    return get_resnet(1, 50, **kwargs)


def resnet101_v1(**kwargs):
    return get_resnet(1, 101, **kwargs)


def resnet152_v1(**kwargs):
    return get_resnet(1, 152, **kwargs)


def resnet18_v2(**kwargs):
    return get_resnet(2, 18, **kwargs)


def resnet34_v2(**kwargs):
    return get_resnet(2, 34, **kwargs)


def resnet50_v2(**kwargs):
    return get_resnet(2, 50, **kwargs)


def resnet101_v2(**kwargs):
    return get_resnet(2, 101, **kwargs)


def resnet152_v2(**kwargs):
    return get_resnet(2, 152, **kwargs)
