"""Inception V3 (reference
``python/mxnet/gluon/model_zoo/vision/inception.py``†)."""
from __future__ import annotations

from ....base import MXNetError
from ... import nn
from ...block import HybridBlock

__all__ = ["Inception3", "inception_v3"]


def _make_basic_conv(**kwargs):
    out = nn.HybridSequential(prefix="")
    out.add(nn.Conv2D(use_bias=False, **kwargs))
    out.add(nn.BatchNorm(epsilon=0.001))
    out.add(nn.Activation("relu"))
    return out


class _Branched(HybridBlock):
    """Concats parallel branches along channels."""

    def __init__(self, *branches, **kwargs):
        super().__init__(**kwargs)
        self._branches = []
        for i, b in enumerate(branches):
            setattr(self, f"branch{i}", b)
            self._branches.append(b)

    def hybrid_forward(self, F, x):
        return F.concat(*[b(x) for b in self._branches], dim=1)


def _make_branch(use_pool, *conv_settings):
    out = nn.HybridSequential(prefix="")
    if use_pool == "avg":
        out.add(nn.AvgPool2D(pool_size=3, strides=1, padding=1))
    elif use_pool == "max":
        out.add(nn.MaxPool2D(pool_size=3, strides=2))
    for setting in conv_settings:
        kernel_size, strides, padding, channels = setting
        kw = {"channels": channels, "kernel_size": kernel_size}
        if strides is not None:
            kw["strides"] = strides
        if padding is not None:
            kw["padding"] = padding
        out.add(_make_basic_conv(**kw))
    return out


def _make_A(pool_features):
    return _Branched(
        _make_branch(None, (1, None, None, 64)),
        _make_branch(None, (1, None, None, 48), (5, None, 2, 64)),
        _make_branch(None, (1, None, None, 64), (3, None, 1, 96),
                     (3, None, 1, 96)),
        _make_branch("avg", (1, None, None, pool_features)))


def _make_B():
    return _Branched(
        _make_branch(None, (3, 2, None, 384)),
        _make_branch(None, (1, None, None, 64), (3, None, 1, 96),
                     (3, 2, None, 96)),
        _make_branch("max"))


def _make_C(channels_7x7):
    return _Branched(
        _make_branch(None, (1, None, None, 192)),
        _make_branch(None, (1, None, None, channels_7x7),
                     ((1, 7), None, (0, 3), channels_7x7),
                     ((7, 1), None, (3, 0), 192)),
        _make_branch(None, (1, None, None, channels_7x7),
                     ((7, 1), None, (3, 0), channels_7x7),
                     ((1, 7), None, (0, 3), channels_7x7),
                     ((7, 1), None, (3, 0), channels_7x7),
                     ((1, 7), None, (0, 3), 192)),
        _make_branch("avg", (1, None, None, 192)))


def _make_D():
    return _Branched(
        _make_branch(None, (1, None, None, 192), (3, 2, None, 320)),
        _make_branch(None, (1, None, None, 192),
                     ((1, 7), None, (0, 3), 192),
                     ((7, 1), None, (3, 0), 192), (3, 2, None, 192)),
        _make_branch("max"))


class _SplitConcat(HybridBlock):
    """branch → two sub-convs concatenated (the E-block fan-out)."""

    def __init__(self, stem, sub1, sub2, **kwargs):
        super().__init__(**kwargs)
        self.stem = stem
        self.sub1 = sub1
        self.sub2 = sub2

    def hybrid_forward(self, F, x):
        x = self.stem(x)
        return F.concat(self.sub1(x), self.sub2(x), dim=1)


def _make_E():
    return _Branched(
        _make_branch(None, (1, None, None, 320)),
        _SplitConcat(
            _make_basic_conv(channels=384, kernel_size=1),
            _make_basic_conv(channels=384, kernel_size=(1, 3),
                             padding=(0, 1)),
            _make_basic_conv(channels=384, kernel_size=(3, 1),
                             padding=(1, 0))),
        _SplitConcat(
            nn.HybridSequential(prefix=""),
            _make_basic_conv(channels=384, kernel_size=(1, 3),
                             padding=(0, 1)),
            _make_basic_conv(channels=384, kernel_size=(3, 1),
                             padding=(1, 0))),
        _make_branch("avg", (1, None, None, 192)))


class Inception3(HybridBlock):
    """Inception V3 (reference ``Inception3``†)."""

    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential(prefix="")
        self.features.add(_make_basic_conv(channels=32, kernel_size=3,
                                           strides=2))
        self.features.add(_make_basic_conv(channels=32, kernel_size=3))
        self.features.add(_make_basic_conv(channels=64, kernel_size=3,
                                           padding=1))
        self.features.add(nn.MaxPool2D(pool_size=3, strides=2))
        self.features.add(_make_basic_conv(channels=80, kernel_size=1))
        self.features.add(_make_basic_conv(channels=192, kernel_size=3))
        self.features.add(nn.MaxPool2D(pool_size=3, strides=2))
        self.features.add(_make_A(32))
        self.features.add(_make_A(64))
        self.features.add(_make_A(64))
        self.features.add(_make_B())
        self.features.add(_make_C(128))
        self.features.add(_make_C(160))
        self.features.add(_make_C(160))
        self.features.add(_make_C(192))
        self.features.add(_make_D())
        self.features.add(_make_E())
        self.features.add(_make_E())
        self.features.add(nn.AvgPool2D(pool_size=8))
        self.features.add(nn.Dropout(0.5))
        self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def inception_v3(pretrained=False, **kwargs):
    if pretrained:
        raise MXNetError("pretrained weights are not bundled")
    return Inception3(**kwargs)


# _SplitConcat with an empty stem means "apply subs to the raw input";
# nn.HybridSequential() with no children is the identity.
