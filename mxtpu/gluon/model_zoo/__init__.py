"""Model zoo (reference ``python/mxnet/gluon/model_zoo/``†).

``pretrained=True`` requires pre-placed weight files (no network in
this environment); architectures themselves are fully constructible and
trainable.
"""
from . import vision
from .vision import get_model

__all__ = ["vision", "get_model"]
