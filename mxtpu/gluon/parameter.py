"""Gluon Parameter / ParameterDict.

Reference: ``python/mxnet/gluon/parameter.py``† — deferred shape-inferred
initialization, per-parameter ``grad_req``/``lr_mult``/``wd_mult``,
ParameterDict with prefix namespacing and shared-param support.

TPU-native deltas: a Parameter holds ONE NDArray (SPMD sharding replaces
the reference's per-context replica list — ``list_ctx``/``list_data``
return views for API parity), and its gradient buffer participates in the
autograd tape as a leaf.
"""
from __future__ import annotations

import re
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from ..base import MXNetError
from ..context import Context, current_context
from .. import initializer as init_mod
from ..ndarray import ndarray as _nda
from ..ndarray.ndarray import NDArray

__all__ = ["Parameter", "ParameterDict", "Constant",
           "DeferredInitializationError"]


class _TraceState(threading.local):
    """Trace-time parameter substitution (first-class, not monkey-patched).

    While a HybridBlock trace is active, ``param_sub`` maps id(Parameter) →
    traced NDArray so any ``Parameter.data()`` call inside the traced
    forward sees the traced value; ``aux_sink`` buffers BatchNorm-style
    running-stat updates emitted during the trace (they become extra jit
    outputs written back after the call — replacing the reference's in-op
    aux mutation, FMutateInputs†)."""

    def __init__(self):
        self.param_sub: Optional[Dict[int, NDArray]] = None
        self.aux_sink: Optional[List[Tuple["Parameter", NDArray]]] = None


_TRACE = _TraceState()


class DeferredInitializationError(MXNetError):
    """Raised when .data() is called before shapes are known."""


class Parameter:
    def __init__(self, name: str, grad_req: str = "write", shape=None,
                 dtype="float32", lr_mult: float = 1.0, wd_mult: float = 1.0,
                 init=None, allow_deferred_init: bool = False,
                 differentiable: bool = True, stype: str = "default",
                 grad_stype: str = "default"):
        self.name = name
        self._grad_req = grad_req if differentiable else "null"
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._differentiable = differentiable
        self.stype = stype
        self.grad_stype = grad_stype
        self._data: Optional[NDArray] = None
        self._deferred_init_args = None

    # ------------------------------------------------------------------
    @property
    def grad_req(self) -> str:
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req: str) -> None:
        if req not in ("write", "add", "null"):
            raise MXNetError(f"invalid grad_req {req}")
        self._grad_req = req
        if self._data is not None:
            self._data.attach_grad(req)

    def _shape_is_known(self) -> bool:
        return self.shape is not None and all(
            s > 0 for s in self.shape)

    # ------------------------------------------------------------------
    def initialize(self, init=None, ctx: Optional[Context] = None,
                   default_init=None, force_reinit: bool = False) -> None:
        if self._data is not None and not force_reinit:
            return
        if not self._shape_is_known():
            if self.allow_deferred_init:
                self._deferred_init_args = (init, ctx, default_init)
                return
            raise MXNetError(
                f"cannot initialize parameter {self.name}: shape "
                f"{self.shape} not fully known and deferred init not "
                f"allowed")
        self._do_init(init, ctx, default_init)

    def _do_init(self, init, ctx, default_init) -> None:
        ctx = ctx or current_context()
        # parameter-specific init rides in InitDesc attrs so it bypasses
        # the global initializer's name-suffix dispatch (reference gluon
        # Parameter._init_impl† protocol)
        specific = init if init is not None else self.init
        global_init = init_mod.create(
            default_init if default_init is not None else "uniform")
        attrs = {"__init__": specific} if specific is not None else {}
        arr = _nda.zeros(self.shape, ctx=ctx, dtype=self.dtype)
        global_init(init_mod.InitDesc(self.name, attrs), arr)
        self._data = arr
        self._data.attach_grad(self._grad_req)

    def _finish_deferred_init(self, inferred_shape=None) -> None:
        if self._data is not None:
            return
        if inferred_shape is not None:
            if self.shape is not None:
                merged = tuple(
                    i if s in (0, -1, None) else s
                    for s, i in zip(self.shape, inferred_shape))
            else:
                merged = tuple(inferred_shape)
            self.shape = merged
        if self._deferred_init_args is None:
            raise DeferredInitializationError(
                f"parameter {self.name} was never initialize()d")
        init, ctx, default_init = self._deferred_init_args
        if not self._shape_is_known():
            raise MXNetError(
                f"deferred init of {self.name} could not infer shape "
                f"{self.shape}")
        self._do_init(init, ctx, default_init)
        self._deferred_init_args = None

    # ------------------------------------------------------------------
    def data(self, ctx: Optional[Context] = None) -> NDArray:
        sub = _TRACE.param_sub
        if sub is not None:
            traced = sub.get(id(self))
            if traced is not None:
                return traced
        if self._data is None:
            if self._deferred_init_args is not None:
                raise DeferredInitializationError(
                    f"parameter {self.name} deferred; run a forward pass "
                    f"or call initialize() with a known shape")
            raise MXNetError(
                f"parameter {self.name} not initialized; call "
                f".initialize() first")
        return self._data

    def list_data(self) -> List[NDArray]:
        return [self.data()]

    def list_ctx(self) -> List[Context]:
        return [self.data().context]

    def grad(self, ctx: Optional[Context] = None) -> NDArray:
        d = self.data(ctx)
        if d.grad is None:
            raise MXNetError(
                f"parameter {self.name} has grad_req='null'")
        return d.grad

    def list_grad(self) -> List[NDArray]:
        return [self.grad()]

    def zero_grad(self) -> None:
        if self._data is not None and self._data.grad is not None:
            self._data.grad[:] = 0.0

    def set_data(self, data) -> None:
        nd_data = data if isinstance(data, NDArray) else _nda.array(data)
        if self._data is None:
            self.shape = nd_data.shape
            self._data = nd_data.astype(self.dtype) \
                if str(nd_data.data.dtype) != self.dtype else nd_data
            self._data.attach_grad(self._grad_req)
            self._deferred_init_args = None
        else:
            self._data._data = nd_data.astype(
                str(self._data.data.dtype)).data

    def cast(self, dtype) -> None:
        self.dtype = dtype
        if self._data is not None:
            req = self._grad_req
            self._data = self._data.astype(dtype)
            self._data.attach_grad(req)

    def reset_ctx(self, ctx) -> None:
        if self._data is not None:
            self._data = self._data.as_in_context(
                ctx if isinstance(ctx, Context) else ctx[0])
            self._data.attach_grad(self._grad_req)

    def var(self):
        from ..symbol import var
        return var(self.name, shape=self.shape, dtype=self.dtype)

    def __repr__(self):
        return (f"Parameter {self.name} (shape={self.shape}, "
                f"dtype={self.dtype})")


class Constant(Parameter):
    """Non-learnable constant parameter (reference ``gluon.Constant``†)."""

    def __init__(self, name, value):
        value = value if isinstance(value, NDArray) else _nda.array(value)
        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=str(value.data.dtype),
                         init=init_mod.Constant(0), differentiable=False)
        self._value = value

    def _do_init(self, init, ctx, default_init):
        self._data = self._value.copy()
        self._data.attach_grad("null")


class ParameterDict:
    """Prefix-namespaced dict of Parameters (reference
    ``gluon.ParameterDict``†) with sharing support."""

    def __init__(self, prefix: str = "", shared: Optional["ParameterDict"]
                 = None):
        self._prefix = prefix
        self._params: "OrderedDict[str, Parameter]" = OrderedDict()
        self._shared = shared

    @property
    def prefix(self) -> str:
        return self._prefix

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def __iter__(self):
        return iter(self._params)

    def __len__(self):
        return len(self._params)

    def __contains__(self, name):
        return name in self._params

    def __getitem__(self, name) -> Parameter:
        return self._params[name]

    def __repr__(self):
        lines = "\n".join(f"  {v}" for v in self._params.values())
        return f"ParameterDict '{self._prefix}' (\n{lines}\n)"

    def get(self, name: str, **kwargs) -> Parameter:
        """Get-or-create ``prefix+name`` (sharing consulted first)."""
        full = self._prefix + name
        if full in self._params:
            param = self._params[full]
            for k, v in kwargs.items():
                if v is not None and getattr(param, k, None) in (None, 0):
                    setattr(param, k, v)
            return param
        if self._shared is not None and full in self._shared:
            param = self._shared[full]
            self._params[full] = param
            return param
        param = Parameter(full, **kwargs)
        self._params[full] = param
        return param

    def get_constant(self, name: str, value=None) -> Constant:
        full = self._prefix + name
        if full in self._params:
            return self._params[full]
        c = Constant(full, value)
        self._params[full] = c
        return c

    def update(self, other: "ParameterDict") -> None:
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise MXNetError(f"parameter name clash on {k}")
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False) -> None:
        for p in self._params.values():
            p.initialize(init=None, ctx=ctx, default_init=init,
                         force_reinit=force_reinit)

    def zero_grad(self) -> None:
        for p in self._params.values():
            p.zero_grad()

    def setattr(self, name, value) -> None:
        for p in self._params.values():
            setattr(p, name, value)

    def reset_ctx(self, ctx) -> None:
        for p in self._params.values():
            p.reset_ctx(ctx)

    # ------------------------------------------------------------------
    def save(self, filename: str, strip_prefix: str = "") -> None:
        arg = {}
        for name, p in self._params.items():
            if p._data is None:
                continue
            key = name[len(strip_prefix):] if name.startswith(strip_prefix) \
                else name
            arg[key] = p.data()
        _nda.save(filename, arg)

    def load(self, filename: str, ctx=None, allow_missing: bool = False,
             ignore_extra: bool = False, restore_prefix: str = "") -> None:
        loaded = _nda.load(filename)
        if not isinstance(loaded, dict):
            raise MXNetError("parameter file must hold a name->array dict")
        loaded = {restore_prefix + k: v for k, v in loaded.items()}
        for name, p in self._params.items():
            if name in loaded:
                p.set_data(loaded[name])
            elif not allow_missing:
                raise MXNetError(f"parameter {name} missing in {filename}")
        if not ignore_extra:
            extra = set(loaded) - set(self._params)
            if extra:
                raise MXNetError(
                    f"file {filename} has extra parameters {sorted(extra)}")
