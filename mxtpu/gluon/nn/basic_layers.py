"""Gluon basic neural-network layers.

Reference: ``python/mxnet/gluon/nn/basic_layers.py``† (Dense, Dropout,
BatchNorm, InstanceNorm, LayerNorm, Embedding, Flatten, Lambda,
Sequential/HybridSequential).

TPU-native notes: every layer is a thin parameter container whose
``hybrid_forward`` calls registry ops (jax/lax lowering rules), so a
hybridized net compiles into ONE XLA executable.  BatchNorm running-stat
updates flow through the aux-update channel (extra jit outputs) instead
of the reference's in-op aux mutation (``FMutateInputs``†).
"""
from __future__ import annotations

import numpy as np

from ...base import MXNetError
from ... import autograd
from ..block import Block, HybridBlock, _emit_aux_update
from ..parameter import Parameter

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout",
           "BatchNorm", "InstanceNorm", "LayerNorm",
           "FusedResidualLayerNorm", "Embedding",
           "Flatten", "Lambda", "HybridLambda"]


class Sequential(Block):
    """Stacks Blocks sequentially (reference ``nn.Sequential``†)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix, params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x, *args):
        for block in self._children.values():
            x = block(x, *args)
            args = ()
            if isinstance(x, (tuple, list)):
                args = tuple(x[1:])
                x = x[0]
        if args:
            return (x,) + args
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())
        if isinstance(key, slice):
            net = type(self)(prefix=self._prefix)
            for l in layers[key]:
                net.add(l)
            return net
        return layers[key]

    def __iter__(self):
        return iter(self._children.values())

    def hybridize(self, active=True, **kwargs):
        """Plain Sequential only propagates (children may hybridize)."""
        super().hybridize(active, **kwargs)


class HybridSequential(HybridBlock):
    """Stacks HybridBlocks; hybridizes into one executable
    (reference ``nn.HybridSequential``†)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix, params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_forward(self, F, x, *args):
        for block in self._children.values():
            x = block(x, *args)
            args = ()
        return x

    def forward(self, x, *args):
        # no own params; just chain children imperatively
        for block in self._children.values():
            x = block(x, *args)
            args = ()
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())
        if isinstance(key, slice):
            net = type(self)(prefix=self._prefix)
            for l in layers[key]:
                net.add(l)
            return net
        return layers[key]

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """Fully-connected layer ``y = act(xW^T + b)``
    (reference ``nn.Dense``† → ``FullyConnected`` op†)."""

    def __init__(self, units, activation=None, use_bias=True,
                 flatten=True, dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, prefix=None,
                 params=None):
        super().__init__(prefix, params)
        self._units = units
        self._flatten = flatten
        self._act = activation
        self.weight = self.params.get(
            "weight", shape=(units, in_units), dtype=dtype,
            init=weight_initializer, allow_deferred_init=True)
        if use_bias:
            self.bias = self.params.get(
                "bias", shape=(units,), dtype=dtype,
                init=bias_initializer, allow_deferred_init=True)
        else:
            self.bias = None

    def _infer_params(self, x, *args):
        if self.weight.shape and self.weight.shape[1] == 0:
            in_units = int(np.prod(x.shape[1:])) if self._flatten \
                else int(x.shape[-1])
            self.weight.shape = (self._units, in_units)

    def hybrid_forward(self, F, x, weight, bias=None):
        if bias is None:
            out = F.FullyConnected(x, weight, no_bias=True,
                                   num_hidden=self._units,
                                   flatten=self._flatten)
        else:
            out = F.FullyConnected(x, weight, bias,
                                   num_hidden=self._units,
                                   flatten=self._flatten)
        if self._act is not None:
            out = F.Activation(out, act_type=self._act)
        return out

    def __repr__(self):
        shape = self.weight.shape
        return (f"Dense({shape[1] if shape and len(shape) > 1 else None} "
                f"-> {self._units}, "
                f"{'linear' if self._act is None else self._act})")


class Dropout(HybridBlock):
    """Dropout (reference ``nn.Dropout``†); active only under
    ``autograd.record(train_mode=True)``."""

    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix, params)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        if self._rate <= 0:
            return x
        return F.Dropout(x, p=self._rate, axes=self._axes)

    def __repr__(self):
        return f"Dropout(p = {self._rate}, axes={self._axes})"


class BatchNorm(HybridBlock):
    """Batch normalization (reference ``nn.BatchNorm``† →
    ``BatchNorm`` op†).  Running statistics update via the aux channel.

    TPU extension: ``act_type="relu"`` fuses the activation (and, when
    a second ``residual`` input is passed at call time, the shortcut
    add) into the BN op — the reference's fused ``BatchNormAddRelu``
    tier (``src/operator/nn/batch_norm.cu``†).  Numerically identical
    to BatchNorm -> (+residual) -> relu on every path; the epilogue is
    XLA-fused by default, with the one-HBM-pass channel-blocked Pallas
    kernel opt-in via MXTPU_FUSED_BN=1 (BASELINE.md "Fused-BN
    verdict")."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0,
                 act_type=None, prefix=None, params=None):
        super().__init__(prefix, params)
        if act_type not in (None, "relu"):
            raise MXNetError(
                f"BatchNorm act_type must be None or 'relu', "
                f"got {act_type!r}")
        self._act_type = act_type
        self._axis = axis
        self._momentum = momentum
        self._eps = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        self.gamma = self.params.get(
            "gamma", shape=(in_channels,), init=gamma_initializer,
            allow_deferred_init=True,
            grad_req="write" if scale else "null")
        self.beta = self.params.get(
            "beta", shape=(in_channels,), init=beta_initializer,
            allow_deferred_init=True,
            grad_req="write" if center else "null")
        self.running_mean = self.params.get(
            "running_mean", shape=(in_channels,),
            init=running_mean_initializer, allow_deferred_init=True,
            differentiable=False)
        self.running_var = self.params.get(
            "running_var", shape=(in_channels,),
            init=running_variance_initializer, allow_deferred_init=True,
            differentiable=False)

    def _infer_params(self, x, *args):
        c = int(x.shape[self._axis])
        for p in (self.gamma, self.beta, self.running_mean,
                  self.running_var):
            if p.shape and p.shape[0] == 0:
                p.shape = (c,)

    def hybrid_forward(self, F, x, residual=None, gamma=None,
                       beta=None, running_mean=None, running_var=None):
        training = autograd.is_training()
        use_global = self._use_global_stats or not training
        kw = dict(eps=self._eps, momentum=self._momentum,
                  fix_gamma=not self._scale,
                  use_global_stats=use_global, axis=self._axis)
        if residual is not None:
            if self._act_type != "relu":
                raise MXNetError("BatchNorm residual input requires "
                                 "act_type='relu'")
            out, mean, var = F.BatchNormAddRelu(
                x, residual, gamma, beta, running_mean, running_var,
                **kw)
        elif self._act_type == "relu":
            out, mean, var = F.BatchNormRelu(
                x, gamma, beta, running_mean, running_var, **kw)
        else:
            out, mean, var = F.BatchNorm(
                x, gamma, beta, running_mean, running_var, **kw)
        if training and not self._use_global_stats:
            m = self._momentum
            _emit_aux_update(self.running_mean,
                             running_mean * m + mean * (1 - m))
            _emit_aux_update(self.running_var,
                             running_var * m + var * (1 - m))
        return out

    def __repr__(self):
        return (f"BatchNorm(axis={self._axis}, eps={self._eps}, "
                f"momentum={self._momentum}, "
                f"in_channels={self.gamma.shape[0] if self.gamma.shape else None})")


class InstanceNorm(HybridBlock):
    """Instance normalization (reference ``nn.InstanceNorm``†)."""

    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix, params)
        self._axis = axis
        self._eps = epsilon
        self.gamma = self.params.get(
            "gamma", shape=(in_channels,), init=gamma_initializer,
            allow_deferred_init=True,
            grad_req="write" if scale else "null")
        self.beta = self.params.get(
            "beta", shape=(in_channels,), init=beta_initializer,
            allow_deferred_init=True,
            grad_req="write" if center else "null")

    def _infer_params(self, x, *args):
        c = int(x.shape[self._axis])
        for p in (self.gamma, self.beta):
            if p.shape and p.shape[0] == 0:
                p.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, eps=self._eps)


class LayerNorm(HybridBlock):
    """Layer normalization (reference ``nn.LayerNorm``†); lowers to the
    ``LayerNorm`` op, which uses the Pallas fused kernel on TPU when
    shapes allow."""

    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix, params)
        self._axis = axis
        self._eps = epsilon
        self.gamma = self.params.get(
            "gamma", shape=(in_channels,), init=gamma_initializer,
            allow_deferred_init=True,
            grad_req="write" if scale else "null")
        self.beta = self.params.get(
            "beta", shape=(in_channels,), init=beta_initializer,
            allow_deferred_init=True,
            grad_req="write" if center else "null")

    def _infer_params(self, x, *args):
        c = int(x.shape[self._axis])
        for p in (self.gamma, self.beta):
            if p.shape and p.shape[0] == 0:
                p.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis, eps=self._eps)


class FusedResidualLayerNorm(HybridBlock):
    """Transformer post-LN epilogue as one layer:
    ``LN(residual + dropout(x + bias))`` over the last axis, lowered to
    the fused ``FusedResidualLayerNorm`` op (Pallas kernel on TPU).

    Owns the bias that the preceding projection would otherwise apply —
    build that ``Dense`` with ``use_bias=False`` and let this layer
    fold the bias into the epilogue kernel.  Call as
    ``layer(x, residual)``."""

    def __init__(self, dropout=0.1, epsilon=1e-5,
                 beta_initializer="zeros", gamma_initializer="ones",
                 bias_initializer="zeros", in_channels=0, prefix=None,
                 params=None):
        super().__init__(prefix, params)
        self._p = dropout
        self._eps = epsilon
        self.bias = self.params.get(
            "bias", shape=(in_channels,), init=bias_initializer,
            allow_deferred_init=True)
        self.gamma = self.params.get(
            "gamma", shape=(in_channels,), init=gamma_initializer,
            allow_deferred_init=True)
        self.beta = self.params.get(
            "beta", shape=(in_channels,), init=beta_initializer,
            allow_deferred_init=True)

    def _infer_params(self, x, *args):
        c = int(x.shape[-1])
        for p in (self.bias, self.gamma, self.beta):
            if p.shape and p.shape[0] == 0:
                p.shape = (c,)

    def hybrid_forward(self, F, x, residual, bias, gamma, beta):
        return F.FusedResidualLayerNorm(x, bias, residual, gamma, beta,
                                        p=self._p, eps=self._eps)


class Embedding(HybridBlock):
    """Index → dense vector lookup (reference ``nn.Embedding``† →
    ``Embedding`` op†, a gather on TPU)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, prefix=None,
                 params=None):
        super().__init__(prefix, params)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self.weight = self.params.get(
            "weight", shape=(input_dim, output_dim), dtype=dtype,
            init=weight_initializer,
            grad_stype="row_sparse" if sparse_grad else "default")

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim)

    def __repr__(self):
        return f"Embedding({self._input_dim} -> {self._output_dim})"


class Flatten(HybridBlock):
    """Flattens to (batch, -1) (reference ``nn.Flatten``†)."""

    def hybrid_forward(self, F, x):
        return F.flatten(x)

    def __repr__(self):
        return "Flatten"


class Lambda(Block):
    """Wraps a function as a Block (reference ``nn.Lambda``†)."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix)
        if isinstance(function, str):
            from ... import ndarray as nd
            if not hasattr(nd, function):
                raise MXNetError(f"no such nd function {function}")
            self._func = getattr(nd, function)
            self._name = function
        elif callable(function):
            self._func = function
            self._name = getattr(function, "__name__", "lambda")
        else:
            raise MXNetError("function must be str or callable")

    def forward(self, *args):
        return self._func(*args)

    def __repr__(self):
        return f"Lambda({self._name})"


class HybridLambda(HybridBlock):
    """Wraps a function as a HybridBlock (reference ``nn.HybridLambda``†)."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix)
        if isinstance(function, str):
            self._func_name = function
            self._func = None
        elif callable(function):
            self._func = function
            self._func_name = getattr(function, "__name__", "lambda")
        else:
            raise MXNetError("function must be str or callable")

    def hybrid_forward(self, F, *args):
        if self._func is not None:
            return self._func(F, *args)
        return getattr(F, self._func_name)(*args)

    def __repr__(self):
        return f"HybridLambda({self._func_name})"
