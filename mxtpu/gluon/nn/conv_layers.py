"""Gluon convolution / pooling layers.

Reference: ``python/mxnet/gluon/nn/conv_layers.py``† (Conv1D-3D,
Conv1-3DTranspose, Max/Avg/GlobalMax/GlobalAvg pooling, ReflectionPad2D).

All lower to the ``Convolution``/``Deconvolution``/``Pooling`` registry
ops — thin wrappers over ``lax.conv_general_dilated`` /
``lax.reduce_window``, which XLA tiles onto the MXU / vector units.
"""
from __future__ import annotations

from ...base import MXNetError
from ..block import HybridBlock
from ..parameter import Parameter

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose",
           "Conv2DTranspose", "Conv3DTranspose", "MaxPool1D", "MaxPool2D",
           "MaxPool3D", "AvgPool1D", "AvgPool2D", "AvgPool3D",
           "GlobalMaxPool1D", "GlobalMaxPool2D", "GlobalMaxPool3D",
           "GlobalAvgPool1D", "GlobalAvgPool2D", "GlobalAvgPool3D",
           "ReflectionPad2D"]


def _to_tuple(v, n):
    if isinstance(v, (tuple, list)):
        if len(v) != n:
            raise MXNetError(f"expected {n}-tuple, got {v}")
        return tuple(int(x) for x in v)
    return (int(v),) * n


class _Conv(HybridBlock):
    """Shared implementation for N-D convolution layers."""

    _ndim = 2
    _op = "Convolution"

    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, layout, in_channels=0, activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", output_padding=None,
                 prefix=None, params=None):
        super().__init__(prefix, params)
        n = self._ndim
        self._channels = channels
        self._in_channels = in_channels
        self._kernel = _to_tuple(kernel_size, n)
        self._strides = _to_tuple(strides, n)
        self._padding = _to_tuple(padding, n)
        self._dilation = _to_tuple(dilation, n)
        self._groups = groups
        self._layout = layout
        self._act = activation
        self._output_padding = (_to_tuple(output_padding, n)
                                if output_padding is not None else None)
        in_g = in_channels // groups if in_channels else 0
        channels_last = not layout.startswith("NC")
        if self._op == "Convolution":
            # OI<spatial> for NC* layouts, O<spatial>I for channels-last
            # (reference kernel-layout convention)
            wshape = ((channels,) + self._kernel + (in_g,)
                      if channels_last
                      else (channels, in_g) + self._kernel)
        else:  # Deconvolution: weight is (in, out//groups, *kernel)
            wshape = (in_channels, channels // groups) + self._kernel
            if channels_last and in_channels:
                wshape = (in_channels,) + self._kernel \
                    + (channels // groups,)
        self.weight = self.params.get(
            "weight", shape=wshape, init=weight_initializer,
            allow_deferred_init=True)
        if use_bias:
            self.bias = self.params.get(
                "bias", shape=(channels,), init=bias_initializer,
                allow_deferred_init=True)
        else:
            self.bias = None

    def _infer_params(self, x, *args):
        channels_last = not self._layout.startswith("NC")
        c_axis = -1 if channels_last else 1
        in_c = int(x.shape[c_axis])
        w = self.weight
        if w.shape and 0 in w.shape:
            if self._op == "Convolution":
                w.shape = ((self._channels,) + self._kernel
                           + (in_c // self._groups,)) if channels_last \
                    else (self._channels, in_c // self._groups) \
                    + self._kernel
            else:
                w.shape = ((in_c,) + self._kernel
                           + (self._channels // self._groups,)) \
                    if channels_last \
                    else (in_c, self._channels // self._groups) \
                    + self._kernel
            self._in_channels = in_c

    def hybrid_forward(self, F, x, weight, bias=None):
        op = getattr(F, self._op)
        kwargs = dict(kernel=self._kernel, stride=self._strides,
                      dilate=self._dilation, pad=self._padding,
                      num_filter=self._channels, num_group=self._groups,
                      layout=self._layout)
        if self._op == "Deconvolution" and self._output_padding:
            kwargs["adj"] = self._output_padding
        if bias is None:
            out = op(x, weight, no_bias=True, **kwargs)
        else:
            out = op(x, weight, bias, **kwargs)
        if self._act is not None:
            out = F.Activation(out, act_type=self._act)
        return out

    def __repr__(self):
        return (f"{type(self).__name__}({self._in_channels or None} -> "
                f"{self._channels}, kernel_size={self._kernel}, "
                f"stride={self._strides}, padding={self._padding})")


class Conv1D(_Conv):
    """1-D convolution (reference ``nn.Conv1D``†)."""
    _ndim = 1

    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 dilation=1, groups=1, layout="NCW", activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, **kwargs)


class Conv2D(_Conv):
    """2-D convolution (reference ``nn.Conv2D``†)."""
    _ndim = 2

    def __init__(self, channels, kernel_size, strides=(1, 1),
                 padding=(0, 0), dilation=(1, 1), groups=1, layout="NCHW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, **kwargs)


class Conv3D(_Conv):
    """3-D convolution (reference ``nn.Conv3D``†)."""
    _ndim = 3

    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout="NCDHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, **kwargs)


class Conv1DTranspose(_Conv):
    """1-D transposed convolution (reference ``nn.Conv1DTranspose``†)."""
    _ndim = 1
    _op = "Deconvolution"

    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         output_padding=output_padding, **kwargs)


class Conv2DTranspose(_Conv):
    """2-D transposed convolution (reference ``nn.Conv2DTranspose``†)."""
    _ndim = 2
    _op = "Deconvolution"

    def __init__(self, channels, kernel_size, strides=(1, 1),
                 padding=(0, 0), output_padding=(0, 0), dilation=(1, 1),
                 groups=1, layout="NCHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         output_padding=output_padding, **kwargs)


class Conv3DTranspose(_Conv):
    """3-D transposed convolution (reference ``nn.Conv3DTranspose``†)."""
    _ndim = 3
    _op = "Deconvolution"

    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), output_padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout="NCDHW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         output_padding=output_padding, **kwargs)


class _Pooling(HybridBlock):
    _ndim = 2
    _pool_type = "max"
    _global = False

    def __init__(self, pool_size, strides, padding, ceil_mode=False,
                 count_include_pad=True, layout=None, prefix=None,
                 params=None):
        super().__init__(prefix, params)
        n = self._ndim
        self._layout = layout or {1: "NCW", 2: "NCHW", 3: "NCDHW"}[n]
        if not self._global:
            self._kernel = _to_tuple(pool_size, n)
            strides = strides if strides is not None else pool_size
            self._strides = _to_tuple(strides, n)
            self._padding = _to_tuple(padding, n)
        self._ceil = ceil_mode
        self._count_include_pad = count_include_pad

    def hybrid_forward(self, F, x):
        if self._global:
            return F.Pooling(x, pool_type=self._pool_type,
                             global_pool=True, layout=self._layout)
        return F.Pooling(x, kernel=self._kernel, pool_type=self._pool_type,
                         stride=self._strides, pad=self._padding,
                         count_include_pad=self._count_include_pad,
                         layout=self._layout)

    def __repr__(self):
        if self._global:
            return f"{type(self).__name__}()"
        return (f"{type(self).__name__}(size={self._kernel}, "
                f"stride={self._strides}, padding={self._padding})")


class MaxPool1D(_Pooling):
    """Reference ``nn.MaxPool1D``†."""
    _ndim = 1

    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, **kwargs):
        super().__init__(pool_size, strides, padding, ceil_mode,
                         layout=layout, **kwargs)


class MaxPool2D(_Pooling):
    """Reference ``nn.MaxPool2D``†."""
    _ndim = 2

    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, **kwargs):
        super().__init__(pool_size, strides, padding, ceil_mode,
                         layout=layout, **kwargs)


class MaxPool3D(_Pooling):
    """Reference ``nn.MaxPool3D``†."""
    _ndim = 3

    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, **kwargs):
        super().__init__(pool_size, strides, padding, ceil_mode,
                         layout=layout, **kwargs)


class AvgPool1D(_Pooling):
    """Reference ``nn.AvgPool1D``†."""
    _ndim = 1
    _pool_type = "avg"

    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(pool_size, strides, padding, ceil_mode,
                         count_include_pad, layout=layout, **kwargs)


class AvgPool2D(_Pooling):
    """Reference ``nn.AvgPool2D``†."""
    _ndim = 2
    _pool_type = "avg"

    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(pool_size, strides, padding, ceil_mode,
                         count_include_pad, layout=layout, **kwargs)


class AvgPool3D(_Pooling):
    """Reference ``nn.AvgPool3D``†."""
    _ndim = 3
    _pool_type = "avg"

    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(pool_size, strides, padding, ceil_mode,
                         count_include_pad, layout=layout, **kwargs)


class _GlobalPool(_Pooling):
    _global = True

    def __init__(self, layout=None, **kwargs):
        super().__init__(None, None, None, layout=layout, **kwargs)


class GlobalMaxPool1D(_GlobalPool):
    """Reference ``nn.GlobalMaxPool1D``†."""
    _ndim = 1


class GlobalMaxPool2D(_GlobalPool):
    """Reference ``nn.GlobalMaxPool2D``†."""
    _ndim = 2


class GlobalMaxPool3D(_GlobalPool):
    """Reference ``nn.GlobalMaxPool3D``†."""
    _ndim = 3


class GlobalAvgPool1D(_GlobalPool):
    """Reference ``nn.GlobalAvgPool1D``†."""
    _ndim = 1
    _pool_type = "avg"


class GlobalAvgPool2D(_GlobalPool):
    """Reference ``nn.GlobalAvgPool2D``†."""
    _ndim = 2
    _pool_type = "avg"


class GlobalAvgPool3D(_GlobalPool):
    """Reference ``nn.GlobalAvgPool3D``†."""
    _ndim = 3
    _pool_type = "avg"


class ReflectionPad2D(HybridBlock):
    """Reflection padding on H and W (reference ``nn.ReflectionPad2D``†)."""

    def __init__(self, padding=0, prefix=None, params=None):
        super().__init__(prefix, params)
        if isinstance(padding, int):
            padding = (padding,) * 4  # (left, right, top, bottom)
        self._padding = tuple(int(p) for p in padding)

    def hybrid_forward(self, F, x):
        l, r, t, b = self._padding
        return F.pad(x, mode="reflect",
                     pad_width=(0, 0, 0, 0, t, b, l, r))
