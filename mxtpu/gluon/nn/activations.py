"""Gluon activation layers (reference
``python/mxnet/gluon/nn/activations.py``†)."""
from __future__ import annotations

from ..block import HybridBlock

__all__ = ["Activation", "LeakyReLU", "PReLU", "ELU", "SELU", "GELU",
           "Swish"]


class Activation(HybridBlock):
    """Elementwise activation by name (reference ``nn.Activation``†)."""

    def __init__(self, activation, prefix=None, params=None):
        super().__init__(prefix, params)
        self._act_type = activation

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)

    def __repr__(self):
        return f"Activation({self._act_type})"


class LeakyReLU(HybridBlock):
    """``max(x, alpha*x)`` (reference ``nn.LeakyReLU``†)."""

    def __init__(self, alpha, prefix=None, params=None):
        super().__init__(prefix, params)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)

    def __repr__(self):
        return f"LeakyReLU({self._alpha})"


class PReLU(HybridBlock):
    """Learnable leaky slope (reference ``nn.PReLU``†)."""

    def __init__(self, alpha_initializer="zeros", prefix=None, params=None):
        super().__init__(prefix, params)
        self.alpha = self.params.get("alpha", shape=(1,),
                                     init=alpha_initializer)

    def hybrid_forward(self, F, x, alpha):
        return F.LeakyReLU(x, alpha, act_type="prelu")


class ELU(HybridBlock):
    """Exponential linear unit (reference ``nn.ELU``†)."""

    def __init__(self, alpha=1.0, prefix=None, params=None):
        super().__init__(prefix, params)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    """Scaled ELU (reference ``nn.SELU``†)."""

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="selu")


class GELU(HybridBlock):
    """Gaussian error linear unit (reference ``nn.GELU``†)."""

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="gelu")


class Swish(HybridBlock):
    """``x * sigmoid(beta x)`` (reference ``nn.Swish``†)."""

    def __init__(self, beta=1.0, prefix=None, params=None):
        super().__init__(prefix, params)
        self._beta = beta

    def hybrid_forward(self, F, x):
        return x * F.sigmoid(self._beta * x)
