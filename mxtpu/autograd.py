"""Autograd — imperative tape-based differentiation.

Reference: ``python/mxnet/autograd.py``† (record/pause scopes, backward,
grad, Function) over ``src/imperative/imperative.cc``† (tape recording,
``Imperative::Backward`` building and executing the gradient graph).

TPU-native: each recorded eager op is invoked through ``jax.vjp`` so the
tape stores a ready-made cotangent closure (XLA-compiled on first call);
``backward`` is a reverse topological sweep accumulating cotangents into
``attach_grad``-marked leaves.  Hybridized blocks record ONE tape node for
their whole cached graph, so a hybridized forward+backward is two XLA
executables, not per-op dispatch (the reference gets the same effect from
``CachedOp::Backward``, ``src/imperative/cached_op.cc``†).
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .base import MXNetError

__all__ = ["record", "pause", "train_mode", "predict_mode", "is_recording",
           "is_training", "backward", "grad", "mark_variables", "Function",
           "set_recording", "set_training"]


class _State(threading.local):
    def __init__(self):
        self.recording = False
        self.training = False


_STATE = _State()


def is_recording() -> bool:
    return _STATE.recording


def is_training() -> bool:
    return _STATE.training


def set_recording(is_rec: bool) -> bool:
    prev, _STATE.recording = _STATE.recording, is_rec
    return prev


def set_training(train: bool) -> bool:
    prev, _STATE.training = _STATE.training, train
    return prev


class _Scope:
    def __init__(self, recording: Optional[bool], training: Optional[bool]):
        self._rec, self._train = recording, training

    def __enter__(self):
        if self._rec is not None:
            self._prev_rec = set_recording(self._rec)
        if self._train is not None:
            self._prev_train = set_training(self._train)
        return self

    def __exit__(self, *exc):
        if self._rec is not None:
            set_recording(self._prev_rec)
        if self._train is not None:
            set_training(self._prev_train)


def record(train_mode: bool = True) -> _Scope:
    return _Scope(True, train_mode)


def pause(train_mode: bool = False) -> _Scope:
    return _Scope(False, train_mode)


def train_mode() -> _Scope:
    return _Scope(None, True)


def predict_mode() -> _Scope:
    return _Scope(None, False)


# ======================================================================
# tape
# ======================================================================
class TapeNode:
    """One recorded computation: vjp closure + wiring.

    parents[i] describes where input i came from:
      ("node", TapeNode, out_idx) | ("leaf", NDArray) | None (constant)
    """
    __slots__ = ("name", "vjp_fn", "parents", "n_outputs", "out_grads",
                 "out_avals", "out_is_tuple", "_visited", "fn",
                 "arrays", "input_refs")

    def __init__(self, name, vjp_fn, parents, n_outputs, out_avals=None,
                 out_is_tuple=False, fn=None, arrays=None):
        self.name = name
        self.vjp_fn = vjp_fn
        self.parents = parents
        self.n_outputs = n_outputs
        self.out_grads: List[Optional[Any]] = [None] * n_outputs
        self.out_avals = out_avals or [None] * n_outputs
        # jax.vjp cotangents must mirror the primal output structure: a
        # 1-element tuple primal still needs a 1-element tuple cotangent
        self.out_is_tuple = out_is_tuple
        self._visited = False
        # primal fn + input buffers, kept for create_graph=True: the
        # recorded backward re-derives vjp INSIDE a traced function so
        # the gradient's dependence on the primals differentiates too.
        # Memory note: for matmul/conv-class ops these buffers overlap
        # the vjp residuals jax already keeps; the extra retention is
        # the price of always-available higher-order (the reference
        # retains its graph the same way).
        self.fn = fn
        self.arrays = arrays


def _needs_grad(x) -> bool:
    from .ndarray.ndarray import NDArray
    return isinstance(x, NDArray) and (
        x._grad_req != "null" or x._tape is not None)


def record_op(name: str, fn: Callable, inputs: Sequence[Any],
              arrays: Sequence[Any]) -> Any:
    """Run fn through jax.vjp and put a node on the implicit tape.

    Returns the raw output (array or tuple)."""
    from .ndarray.ndarray import NDArray
    out, vjp_fn = jax.vjp(fn, *arrays)
    parents: List[Optional[Tuple]] = []
    for x in inputs:
        if isinstance(x, NDArray) and x._tape is not None:
            parents.append(("node",) + x._tape)
        elif isinstance(x, NDArray) and x._grad_req != "null":
            parents.append(("leaf", x))
        else:
            parents.append(None)
    outs_t = out if isinstance(out, tuple) else (out,)
    avals = [jax.ShapeDtypeStruct(o.shape, o.dtype) for o in outs_t]
    node = TapeNode(name, vjp_fn, parents, len(outs_t), avals,
                    out_is_tuple=isinstance(out, tuple), fn=fn,
                    arrays=tuple(arrays))
    return out, node


def attach_output(nd, node: TapeNode, idx: int) -> None:
    nd._tape = (node, idx)


def mark_variables(variables, gradients, grad_reqs="write") -> None:
    """Reference API parity (autograd.mark_variables†)."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v._grad_req = req
        v.grad = g


# ======================================================================
# backward
# ======================================================================
def _toposort(roots: List[TapeNode]) -> List[TapeNode]:
    order: List[TapeNode] = []
    seen = set()

    def visit(n: TapeNode):
        if id(n) in seen:
            return
        seen.add(id(n))
        for p in n.parents:
            if p is not None and p[0] == "node":
                visit(p[1])
        order.append(n)

    for r in roots:
        visit(r)
    return order


def backward(heads, head_grads=None, retain_graph: bool = False,
             train_mode: bool = True, _sink: Optional[dict] = None,
             _watch: Optional[dict] = None) -> None:
    """Compute gradients of heads w.r.t. all attach_grad leaves reachable
    on the tape (reference MXAutogradBackwardEx†).

    _sink/_watch are internal hooks for ``grad()``: when _sink is given,
    leaf gradients are collected into it (id(leaf) -> (leaf, grad)) and
    ``.grad`` buffers are left untouched; _watch maps (id(node), out_idx)
    -> cotangent for requested non-leaf variables."""
    from .ndarray.ndarray import NDArray

    heads = [heads] if isinstance(heads, NDArray) else list(heads)
    if head_grads is None:
        head_grads = [None] * len(heads)
    else:
        head_grads = [head_grads] if isinstance(head_grads, NDArray) \
            else list(head_grads)

    roots = []
    for h, hg in zip(heads, head_grads):
        if h._tape is None:
            continue
        node, idx = h._tape
        seed = jnp.ones_like(h.data) if hg is None else jnp.asarray(
            hg.data if isinstance(hg, NDArray) else hg)
        if node.out_grads[idx] is None:
            node.out_grads[idx] = seed
        else:
            node.out_grads[idx] = node.out_grads[idx] + seed
        roots.append(node)
    if not roots:
        raise MXNetError(
            "backward called on arrays not produced under autograd.record "
            "with gradients attached")

    order = _toposort(roots)
    leaf_grads: dict = {}   # id(leaf NDArray) -> (leaf, accumulated grad)
    for node in reversed(order):
        if all(g is None for g in node.out_grads):
            continue
        # reversed-topological order means every consumer of this node has
        # already run: out_grads are final here — snapshot watched ones
        if _watch:
            for i, g in enumerate(node.out_grads):
                if g is not None and (id(node), i) in _watch:
                    _watch[(id(node), i)] = g
        # fill missing cotangents with zeros of the right aval; the
        # cotangent structure must mirror the primal output structure
        if node.out_is_tuple:
            ct = tuple(
                c if c is not None else jnp.zeros(
                    node.out_avals[i].shape, node.out_avals[i].dtype)
                for i, c in enumerate(node.out_grads))
            in_grads = node.vjp_fn(ct)
        else:
            in_grads = node.vjp_fn(node.out_grads[0])
        for parent, ig in zip(node.parents, in_grads):
            if parent is None or ig is None:
                continue
            if _is_float0(ig):
                continue
            if parent[0] == "node":
                _, pnode, pidx = parent
                if pnode.out_grads[pidx] is None:
                    pnode.out_grads[pidx] = ig
                else:
                    pnode.out_grads[pidx] = pnode.out_grads[pidx] + ig
            else:
                leaf = parent[1]
                k = id(leaf)
                if k in leaf_grads:
                    leaf_grads[k] = (leaf, leaf_grads[k][1] + ig)
                else:
                    leaf_grads[k] = (leaf, ig)
        # out_grads are per-backward-call scratch: clear even when the
        # graph is retained, else a second backward accumulates stale
        # cotangents on top of fresh seeds.
        node.out_grads = [None] * node.n_outputs

    if _sink is not None:
        _sink.update(leaf_grads)
        return

    for leaf, g in leaf_grads.values():
        if leaf._grad_req == "add" and leaf.grad is not None:
            leaf.grad._data = leaf.grad._data + g
        elif leaf.grad is None:
            leaf.grad = NDArray(g, None, _placed=True)
        else:
            leaf.grad._data = g


def _is_float0(x) -> bool:
    return hasattr(x, "dtype") and x.dtype == jax.dtypes.float0


def _grad_recorded(heads, variables, head_grads, train_mode):
    """``grad(create_graph=True)``: replay the tape backward while
    RECORDING — every vjp application and cotangent accumulation goes
    through ``record_op``, so the returned gradients carry their own
    tape nodes and differentiate again (arbitrary order).  jax's vjp
    closures are themselves jax-differentiable, which is what makes
    this a pure tape-layer feature."""
    from .ndarray.ndarray import NDArray

    heads = [heads] if isinstance(heads, NDArray) else list(heads)
    if head_grads is None:
        head_grads = [None] * len(heads)
    else:
        head_grads = [head_grads] if isinstance(head_grads, NDArray) \
            else list(head_grads)

    roots = []
    seeds: Dict[Tuple[int, int], NDArray] = {}
    for h, hg in zip(heads, head_grads):
        if h._tape is None:
            continue
        node, idx = h._tape
        roots.append(node)
        seed = hg if hg is not None else NDArray(
            jnp.ones(h.data.shape, h.data.dtype), None, _placed=True)
        key = (id(node), idx)
        seeds[key] = seed if key not in seeds else seeds[key] + seed
    if not roots:
        raise MXNetError("heads are not on the tape; call inside "
                         "autograd.record()")

    order = _toposort(roots)
    # cotangents as NDArrays keyed by (node, out_idx) — NDArray `+`
    # records accumulation nodes, chaining the second-order graph
    cots: Dict[Tuple[int, int], NDArray] = dict(seeds)
    leaf_cots: Dict[int, Tuple[Any, NDArray]] = {}
    # requested intermediate variables: snapshot their cotangent at
    # consumption time (the sweep pops cots as it goes)
    watch_keys = {(id(v._tape[0]), v._tape[1])
                  for v in variables if v._tape is not None}
    watched: Dict[Tuple[int, int], NDArray] = {}
    for node in reversed(order):
        cts = []
        any_seen = False
        for i in range(node.n_outputs):
            key = (id(node), i)
            c = cots.pop(key, None)
            if c is not None and key in watch_keys:
                watched[key] = c
            if c is None:
                c = NDArray(jnp.zeros(node.out_avals[i].shape,
                                      node.out_avals[i].dtype), None,
                            _placed=True)
            else:
                any_seen = True
            cts.append(c)
        if not any_seen:
            continue

        if node.fn is None:
            raise MXNetError(
                f"create_graph=True through node {node.name!r} is "
                f"unsupported: it carries no replayable primal "
                f"(autograd.Function nodes define only a first-order "
                f"backward)")
        n_ct = len(cts)
        primal_fn = node.fn
        out_is_tuple = node.out_is_tuple

        def apply_vjp(*args, _fn=primal_fn, _tup=out_is_tuple,
                      _n=n_ct):
            # re-derive the vjp INSIDE the traced function: the
            # result depends differentiably on BOTH the cotangents
            # and the primal inputs (closure-captured vjp_fn would
            # hide the primal dependence from the second order)
            raw_cts, prim = args[:_n], args[_n:]
            _, vjp = jax.vjp(_fn, *prim)
            ct = tuple(raw_cts) if _tup else raw_cts[0]
            return tuple(vjp(ct))

        # rebuild tape-connected handles for the primal inputs from
        # the parent edges (no extra wrapper retention on the node)
        prim_refs = []
        for parent, arr in zip(node.parents, node.arrays):
            if parent is None:
                prim_refs.append(None)
            elif parent[0] == "leaf":
                prim_refs.append(parent[1])
            else:
                ref = NDArray(arr, None, _placed=True)
                ref._tape = (parent[1], parent[2])
                prim_refs.append(ref)
        rec_inputs = list(cts) + prim_refs
        rec_arrays = [c.data for c in cts] + list(node.arrays)
        raw_out, n2 = record_op(f"{node.name}_bwd", apply_vjp,
                                rec_inputs, rec_arrays)
        outs = raw_out if isinstance(raw_out, tuple) else (raw_out,)
        for j, (parent, ig) in enumerate(zip(node.parents, outs)):
            if parent is None or ig is None or _is_float0(ig):
                continue
            ig_nd = NDArray(ig, None, _placed=True)
            attach_output(ig_nd, n2, j)
            if parent[0] == "node":
                _, pnode, pidx = parent
                key = (id(pnode), pidx)
                cots[key] = ig_nd if key not in cots \
                    else cots[key] + ig_nd
            else:
                leaf = parent[1]
                k = id(leaf)
                leaf_cots[k] = (leaf, ig_nd) if k not in leaf_cots \
                    else (leaf, leaf_cots[k][1] + ig_nd)

    outs = []
    for v in variables:
        g = None
        if v._tape is not None:
            key = (id(v._tape[0]), v._tape[1])
            g = watched.get(key)
            if g is None:
                g = cots.get(key)
        if g is None:
            got = leaf_cots.get(id(v))
            g = got[1] if got is not None else None
        if g is None:
            raise MXNetError(
                "some variables are unreachable from the heads' graph; "
                "mark them with attach_grad() before recording")
        outs.append(g)
    return outs[0] if len(outs) == 1 else outs


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode=True):
    """Return gradients of heads w.r.t. variables without touching .grad
    (reference ``autograd.grad``†).  With ``create_graph=True`` the
    backward pass itself is recorded, so the results differentiate
    again (higher-order)."""
    from .ndarray.ndarray import NDArray
    if create_graph:
        variables = [variables] if isinstance(variables, NDArray) \
            else list(variables)
        # create_graph implies recording the backward (reference
        # semantics) — force a record scope so the cotangent
        # accumulations and vjp replays land on the tape even when
        # called outside the user's record() block
        with record(train_mode=train_mode):
            return _grad_recorded(heads, variables, head_grads,
                                  train_mode)
    variables = [variables] if isinstance(variables, NDArray) \
        else list(variables)
    # gradients flow into a side map — no .grad buffer (of the requested
    # variables OR of bystander leaves) is ever touched by this API
    sink: dict = {}
    watch: dict = {}
    for v in variables:
        if v._tape is not None:
            node, idx = v._tape
            watch[(id(node), idx)] = None
    backward(heads, head_grads, retain_graph=bool(retain_graph),
             train_mode=train_mode, _sink=sink, _watch=watch)
    outs = []
    for v in variables:
        g = None
        if v._tape is not None:
            g = watch.get((id(v._tape[0]), v._tape[1]))
        if g is None:
            got = sink.get(id(v))
            g = got[1] if got is not None else None
        if g is None:
            raise MXNetError(
                "some variables are unreachable from the heads' graph; "
                "mark them with attach_grad() before recording")
        outs.append(NDArray(g, None, _placed=True))
    return outs[0] if len(outs) == 1 else outs


# ======================================================================
# custom differentiable Function (reference autograd.Function† /
# src/c_api/c_api_function.cc†)
# ======================================================================
class Function:
    """User-defined differentiable op.

    Subclass and implement ``forward(self, *inputs)`` and
    ``backward(self, *output_grads)`` using nd ops.  Gradients flow
    through the user backward, not jax AD."""

    def __init__(self):
        self._saved = None

    def save_for_backward(self, *arrays):
        self._saved = arrays

    @property
    def saved_tensors(self):
        return self._saved

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray.ndarray import NDArray
        with pause():
            outs = self.forward(*inputs)
        single = not isinstance(outs, (tuple, list))
        outs_t = (outs,) if single else tuple(outs)
        if is_recording() and any(_needs_grad(x) for x in inputs):
            fn_self = self

            def _vjp_fn(cotangents):
                cts = cotangents if isinstance(cotangents, tuple) \
                    else (cotangents,)
                with pause():
                    gin = fn_self.backward(
                        *[NDArray(c, None, _placed=True) for c in cts])
                gin_t = (gin,) if isinstance(gin, NDArray) else tuple(gin)
                return tuple(g.data if isinstance(g, NDArray) else g
                             for g in gin_t)

            parents = []
            for x in inputs:
                if isinstance(x, NDArray) and x._tape is not None:
                    parents.append(("node",) + x._tape)
                elif isinstance(x, NDArray) and x._grad_req != "null":
                    parents.append(("leaf", x))
                else:
                    parents.append(None)
            avals = [jax.ShapeDtypeStruct(o.shape, o.data.dtype)
                     for o in outs_t]
            node = TapeNode(type(self).__name__, _vjp_fn, parents,
                            len(outs_t), avals, out_is_tuple=not single)
            for i, o in enumerate(outs_t):
                attach_output(o, node, i)
        return outs if not single else outs_t[0]
