"""Module — symbol + executor trainer
(reference ``python/mxnet/module/module.py``†).

TPU-native note: the reference's ``DataParallelExecutorGroup`` slices
each batch over per-device executors and all-reduces through KVStore;
here one executor evaluates the graph and multi-device execution is the
compiled SPMD path (``mxtpu.parallel``) — Module keeps the legacy API
surface on top of the same engine.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..base import MXNetError
from .. import initializer as init_mod
from .. import ndarray as nd_mod
from .. import optimizer as opt_mod
from ..ndarray import NDArray
from .base_module import BaseModule

__all__ = ["Module"]


class Module(BaseModule):
    """Single-symbol trainer (reference ``Module``†)."""

    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=None,
                 context=None, work_load_list=None,
                 fixed_param_names=None, state_names=None):
        import logging
        super().__init__(logger or logging)
        self._symbol = symbol
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        self._context = context
        self._fixed_param_names = set(fixed_param_names or [])
        arg_names = symbol.list_arguments()
        self._param_names = [n for n in arg_names
                             if n not in self._data_names
                             and n not in self._label_names]
        self._aux_names = symbol.list_auxiliary_states()
        self._exec = None
        self._optimizer = None
        self._updater = None
        self._data_shapes = None
        self._label_shapes = None

    @property
    def symbol(self):
        return self._symbol

    @property
    def data_names(self):
        return self._data_names

    @property
    def output_names(self):
        return self._symbol.list_outputs()

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        outs = [o.shape for o in self._exec.outputs] if self._exec and \
            self._exec._outputs else None
        return outs

    # -- bind -----------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req="write"):
        if self.binded and not force_rebind:
            return
        norm = []
        for d in data_shapes:
            if isinstance(d, tuple) and not hasattr(d, "name"):
                from ..io import DataDesc
                d = DataDesc(d[0], d[1])
            norm.append(d)
        self._data_shapes = norm
        norm_l = []
        for d in (label_shapes or []):
            if isinstance(d, tuple) and not hasattr(d, "name"):
                from ..io import DataDesc
                d = DataDesc(d[0], d[1])
            norm_l.append(d)
        self._label_shapes = norm_l

        shapes = {d.name: d.shape for d in norm}
        shapes.update({d.name: d.shape for d in norm_l})
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**shapes)
        arg_names = self._symbol.list_arguments()
        self._arg_shape = dict(zip(arg_names, arg_shapes))
        self._aux_shape = dict(zip(self._aux_names, aux_shapes))
        for n, s in self._arg_shape.items():
            if s is None:
                raise MXNetError(f"cannot infer shape of {n}")

        args = {n: nd_mod.zeros(s) for n, s in self._arg_shape.items()}
        aux = {n: nd_mod.zeros(s) for n, s in self._aux_shape.items()}
        req = {}
        for n in arg_names:
            if n in self._data_names:
                req[n] = "write" if inputs_need_grad else "null"
            elif n in self._label_names or \
                    n in self._fixed_param_names:
                req[n] = "null"
            else:
                req[n] = grad_req if for_training else "null"
        self._exec = self._symbol.bind(ctx=self._context, args=args,
                                       grad_req=req, aux_states=aux)
        self.binded = True
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad

    # -- params ---------------------------------------------------------
    def init_params(self, initializer="uniform", arg_params=None,
                    aux_params=None, allow_missing=False,
                    force_init=False, allow_extra=False):
        assert self.binded, "bind before init_params"
        if self.params_initialized and not force_init:
            return
        init = init_mod.create(initializer) \
            if not isinstance(initializer, init_mod.Initializer) \
            else initializer
        for name in self._param_names:
            arr = self._exec.arg_dict[name]
            if arg_params is not None and name in arg_params:
                arr._data = arg_params[name]._data \
                    if isinstance(arg_params[name], NDArray) \
                    else nd_mod.array(arg_params[name])._data
            else:
                # missing params run the initializer (reference
                # semantics — allow_missing only waives the error)
                init(init_mod.InitDesc(name), arr)
        for name in self._aux_names:
            arr = self._exec.aux_dict[name]
            if aux_params is not None and name in aux_params:
                arr._data = aux_params[name]._data
            else:
                init(init_mod.InitDesc(name), arr)
        self.params_initialized = True

    def get_params(self):
        assert self.binded and self.params_initialized
        arg = {n: self._exec.arg_dict[n].copy()
               for n in self._param_names}
        aux = {n: self._exec.aux_dict[n].copy()
               for n in self._aux_names}
        return arg, aux

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(arg_params=arg_params, aux_params=aux_params,
                         allow_missing=allow_missing,
                         force_init=force_init)

    # -- optimizer ------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=None, force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        if not isinstance(optimizer, opt_mod.Optimizer):
            optimizer = opt_mod.create(optimizer,
                                       **(optimizer_params or {}))
        idx2name = {i: n for i, n in enumerate(self._param_names)}
        optimizer.idx2name = idx2name
        self._optimizer = optimizer
        self._updater = opt_mod.get_updater(optimizer)
        states_path = getattr(self, "_preload_states", None)
        if states_path is not None:
            with open(states_path, "rb") as f:
                self._updater.set_states(f.read())
            self._optimizer = self._updater.optimizer
            self._preload_states = None
        self.optimizer_initialized = True

    # -- execution ------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        is_train = self.for_training if is_train is None else is_train
        feeds = {}
        for name, arr in zip(self._data_names, data_batch.data):
            feeds[name] = arr
        if data_batch.label is not None:
            for name, arr in zip(self._label_names, data_batch.label):
                feeds[name] = arr
        self._exec.forward(is_train=is_train, **feeds)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._exec.backward(out_grads=out_grads)

    def update(self):
        """Apply one optimizer step from accumulated grads
        (reference ``update``† via kvstore+updater)."""
        assert self.optimizer_initialized
        for i, name in enumerate(self._param_names):
            grad = self._exec.grad_dict.get(name)
            if grad is None:
                continue
            weight = self._exec.arg_dict[name]
            self._updater(i, grad, weight)

    def get_outputs(self, merge_multi_context=True):
        return self._exec.outputs

    def get_input_grads(self, merge_multi_context=True):
        assert self.inputs_need_grad
        return [self._exec.grad_dict.get(n) for n in self._data_names]

    def update_metric(self, eval_metric, labels):
        eval_metric.update(labels, self.get_outputs())

    def install_monitor(self, monitor):
        monitor.install(self._exec)

    # -- persistence ----------------------------------------------------
    def save_checkpoint(self, prefix, epoch,
                        save_optimizer_states=False):
        from .. import model
        arg, aux = self.get_params()
        model.save_checkpoint(prefix, epoch, self._symbol, arg, aux)
        if save_optimizer_states and self._updater is not None:
            with open(f"{prefix}-{epoch:04d}.states", "wb") as f:
                f.write(self._updater.get_states())

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        from .. import model
        sym, arg, aux = model.load_checkpoint(prefix, epoch)
        mod = Module(sym, **kwargs)
        mod._preloaded = (arg, aux)
        mod._preload_states = f"{prefix}-{epoch:04d}.states" \
            if load_optimizer_states else None
        # params applied at bind+init time
        orig_init = mod.init_params

        def init_with_loaded(initializer="uniform", arg_params=None,
                             aux_params=None, **kw):
            orig_init(initializer=initializer,
                      arg_params=arg_params or arg,
                      aux_params=aux_params or aux, **kw)
        mod.init_params = init_with_loaded
        return mod
