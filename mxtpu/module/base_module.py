"""BaseModule with the canonical ``fit`` loop
(reference ``python/mxnet/module/base_module.py``†; SURVEY §3.3)."""
from __future__ import annotations

import logging
from collections import namedtuple
from typing import Any, List, Optional

import numpy as np

from ..base import MXNetError
from .. import metric as metric_mod
from .. import io as io_mod
from ..ndarray import NDArray

__all__ = ["BaseModule", "BatchEndParam"]

BatchEndParam = namedtuple("BatchEndParam",
                           ["epoch", "nbatch", "eval_metric", "locals"])


def _as_metric(eval_metric):
    if isinstance(eval_metric, metric_mod.EvalMetric):
        return eval_metric
    return metric_mod.create(eval_metric)


class BaseModule:
    """Abstract trainer interface (reference ``BaseModule``†)."""

    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.params_initialized = False
        self.optimizer_initialized = False

    # -- abstract surface ----------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             **kwargs):
        raise NotImplementedError

    def init_params(self, initializer="uniform", arg_params=None,
                    aux_params=None, allow_missing=False,
                    force_init=False, allow_extra=False):
        raise NotImplementedError

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=None, force_init=False):
        raise NotImplementedError

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError

    def backward(self, out_grads=None):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def get_outputs(self):
        raise NotImplementedError

    def get_params(self):
        raise NotImplementedError

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError

    # -- shared conveniences -------------------------------------------
    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, reset=True, epoch=0):
        """Evaluate on a DataIter (reference ``score``†)."""
        assert self.binded and self.params_initialized
        eval_metric = _as_metric(eval_metric)
        eval_metric.reset()
        if reset:
            eval_data.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                batch_end_callback(BatchEndParam(
                    epoch=epoch, nbatch=nbatch,
                    eval_metric=eval_metric, locals=locals()))
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True):
        """Run inference over a DataIter (reference ``predict``†)."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        outputs_list: List[List[NDArray]] = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            outs = self.get_outputs()
            if eval_batch.pad:
                outs = [o[:o.shape[0] - eval_batch.pad] for o in outs]
            outputs_list.append([o.copy() for o in outs])
        if not outputs_list:
            return []
        if merge_batches:
            num_outputs = len(outputs_list[0])
            from .. import ndarray as nd_mod
            merged = [nd_mod.concat(*[b[i] for b in outputs_list], dim=0)
                      for i in range(num_outputs)]
            return merged[0] if num_outputs == 1 else merged
        return outputs_list

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, initializer="uniform",
            arg_params=None, aux_params=None, allow_missing=False,
            force_rebind=False, force_init=False, begin_epoch=0,
            num_epoch=None, validation_metric=None, monitor=None):
        """The canonical training loop (reference ``fit``†; call stack
        SURVEY §3.3)."""
        assert num_epoch is not None, "num_epoch required"
        if not self.binded or force_rebind:
            self.bind(data_shapes=train_data.provide_data,
                      label_shapes=train_data.provide_label,
                      for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params,
                         allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=dict(optimizer_params)
                            if not isinstance(optimizer_params, dict)
                            else optimizer_params)
        eval_metric = _as_metric(eval_metric)
        validation_metric = validation_metric or eval_metric

        for epoch in range(begin_epoch, num_epoch):
            eval_metric.reset()
            train_data.reset()
            for nbatch, data_batch in enumerate(train_data):
                if monitor is not None:
                    monitor.tic()
                self.forward_backward(data_batch)
                self.update()
                self.update_metric(eval_metric, data_batch.label)
                if monitor is not None:
                    monitor.toc_print()
                if batch_end_callback is not None:
                    cbs = batch_end_callback if isinstance(
                        batch_end_callback, (list, tuple)) \
                        else [batch_end_callback]
                    for cb in cbs:
                        cb(BatchEndParam(epoch=epoch, nbatch=nbatch,
                                         eval_metric=eval_metric,
                                         locals=locals()))
            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name,
                                 val)
            if epoch_end_callback is not None:
                arg_params, aux_params = self.get_params()
                cbs = epoch_end_callback if isinstance(
                    epoch_end_callback, (list, tuple)) \
                    else [epoch_end_callback]
                for cb in cbs:
                    cb(epoch, self.symbol, arg_params, aux_params)
            if eval_data is not None:
                res = self.score(eval_data, validation_metric,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f",
                                     epoch, name, val)

    def install_monitor(self, monitor):
        raise NotImplementedError

    def get_input_grads(self):
        raise NotImplementedError
