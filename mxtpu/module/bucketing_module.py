"""BucketingModule — per-bucket executors sharing parameters
(reference ``python/mxnet/module/bucketing_module.py``†; the
reference's answer to variable-length sequences, SURVEY §5.7).

TPU-native note: each bucket is a distinct static shape → a distinct
XLA executable; the module keeps one Module per bucket with shared
parameter arrays, exactly mirroring the per-bucket executors sharing
memory upstream.  Keep the bucket count small (compile cost per
bucket).
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

from ..base import MXNetError
from .base_module import BaseModule
from .module import Module

__all__ = ["BucketingModule"]


class BucketingModule(BaseModule):
    """sym_gen(bucket_key) -> (symbol, data_names, label_names)
    (reference ``BucketingModule``†)."""

    def __init__(self, sym_gen: Callable, default_bucket_key=None,
                 logger=None, context=None, fixed_param_names=None):
        import logging
        super().__init__(logger or logging)
        if default_bucket_key is None:
            raise MXNetError("default_bucket_key required")
        self._sym_gen = sym_gen
        self._default_key = default_bucket_key
        self._context = context
        self._fixed = fixed_param_names
        self._buckets: Dict = {}
        self._curr_mod: Optional[Module] = None
        self._curr_key = None
        self._init_args = None
        self._opt_args = None
        self._monitor = None

    @property
    def symbol(self):
        return self._curr_mod.symbol if self._curr_mod else \
            self._sym_gen(self._default_key)[0]

    def _get_module(self, bucket_key, data_shapes, label_shapes,
                    for_training=True):
        if bucket_key not in self._buckets:
            sym, data_names, label_names = self._sym_gen(bucket_key)
            mod = Module(sym, data_names=data_names,
                         label_names=label_names, logger=self.logger,
                         context=self._context,
                         fixed_param_names=self._fixed)
            mod.bind(data_shapes, label_shapes,
                     for_training=for_training)
            if self._curr_mod is not None and \
                    self._curr_mod.params_initialized:
                self._share_params(mod)
            if self._monitor is not None:
                mod.install_monitor(self._monitor)
            self._buckets[bucket_key] = mod
        return self._buckets[bucket_key]

    def _share_params(self, mod):
        """Alias the default bucket's arrays into ``mod`` — one set of
        weights/grads/aux across buckets."""
        default = self._buckets[self._default_key]
        for name in mod._param_names:
            if name in default._exec.arg_dict:
                mod._exec.arg_dict[name] = default._exec.arg_dict[name]
                if name in default._exec.grad_dict:
                    mod._exec.grad_dict[name] = \
                        default._exec.grad_dict[name]
        for name in mod._aux_names:
            if name in default._exec.aux_dict:
                mod._exec.aux_dict[name] = default._exec.aux_dict[name]
        mod.params_initialized = True

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             force_rebind=False, **kwargs):
        if self.binded and not force_rebind:
            return
        self._curr_mod = self._get_module(self._default_key,
                                          data_shapes, label_shapes,
                                          for_training)
        self._curr_key = self._default_key
        self.binded = True
        self.for_training = for_training

    def switch_bucket(self, bucket_key, data_shapes,
                      label_shapes=None):
        """Activate the module for a bucket (reference†)."""
        assert self.binded
        mod = self._get_module(bucket_key, data_shapes, label_shapes,
                               self.for_training)
        if not mod.params_initialized and self.params_initialized:
            self._share_params(mod)
        self._curr_mod = mod
        self._curr_key = bucket_key

    def init_params(self, **kwargs):
        assert self.binded
        self._buckets[self._default_key].init_params(**kwargs)
        self.params_initialized = True

    def get_params(self):
        return self._buckets[self._default_key].get_params()

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=None, force_init=False):
        assert self.binded and self.params_initialized
        self._opt_args = (kvstore, optimizer, optimizer_params)
        default = self._buckets[self._default_key]
        default.init_optimizer(kvstore, optimizer, optimizer_params,
                               force_init)
        # ONE updater (and thus one momentum/state set) shared across
        # buckets — weights are shared, so states must be too
        for mod in self._buckets.values():
            if mod is not default:
                self._share_optimizer(mod)
        self.optimizer_initialized = True

    def _share_optimizer(self, mod):
        default = self._buckets[self._default_key]
        mod._optimizer = default._optimizer
        mod._updater = default._updater
        mod.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        key = getattr(data_batch, "bucket_key", self._default_key)
        if key != self._curr_key or key not in self._buckets:
            self.switch_bucket(key, data_batch.provide_data,
                               data_batch.provide_label)
            if self.optimizer_initialized and \
                    not self._curr_mod.optimizer_initialized:
                self._share_optimizer(self._curr_mod)
        self._curr_mod.forward(data_batch, is_train)

    def backward(self, out_grads=None):
        self._curr_mod.backward(out_grads)

    def update(self):
        self._curr_mod.update()
        # weights live in shared arrays; nothing else to sync

    def get_outputs(self, merge_multi_context=True):
        return self._curr_mod.get_outputs()

    def get_input_grads(self):
        return self._curr_mod.get_input_grads()

    def update_metric(self, eval_metric, labels):
        self._curr_mod.update_metric(eval_metric, labels)

    def install_monitor(self, monitor):
        self._monitor = monitor  # later buckets pick it up on creation
        for mod in self._buckets.values():
            mod.install_monitor(monitor)
