"""SequentialModule + PythonModule (reference
``python/mxnet/module/sequential_module.py``† /
``python_module.py``†): chain heterogeneous modules so one module's
outputs feed the next, and wrap plain python compute as a module.
"""
from __future__ import annotations

import logging
from typing import List, Optional

import numpy as np

from ..base import MXNetError
from ..io import DataBatch, DataDesc
from ..ndarray import NDArray, array
from .base_module import BaseModule

__all__ = ["SequentialModule", "PythonModule", "PythonLossModule"]


class SequentialModule(BaseModule):
    """A container chaining modules; outputs of module i become the
    data of module i+1 (reference ``SequentialModule``†)."""

    META_TAKE_LABELS = "take_labels"
    META_AUTO_WIRING = "auto_wiring"

    def __init__(self, logger=logging):
        super().__init__(logger)
        self._modules: List[BaseModule] = []
        self._metas: List[dict] = []
        self._label_shapes = None
        self._data_shapes = None

    def add(self, module: BaseModule, **kwargs) -> "SequentialModule":
        """Append a module.  ``take_labels=True`` marks the module
        that consumes the loader's labels (usually the last one)."""
        self._modules.append(module)
        self._metas.append(kwargs)
        self.binded = False
        return self

    @property
    def data_names(self):
        return self._modules[0].data_names if self._modules else []

    @property
    def output_names(self):
        return self._modules[-1].output_names if self._modules else []

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        return self._modules[-1].output_shapes

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             grad_req="write", **kwargs):
        if self.binded and not force_rebind:
            return
        if not self._modules:
            raise MXNetError("SequentialModule.bind: no modules added")
        self._data_shapes = data_shapes
        self._label_shapes = label_shapes
        cur_shapes = data_shapes
        for i, (mod, meta) in enumerate(zip(self._modules,
                                            self._metas)):
            take_labels = meta.get(self.META_TAKE_LABELS, False) or \
                i == len(self._modules) - 1
            mod.bind(cur_shapes,
                     label_shapes if take_labels else None,
                     for_training=for_training,
                     inputs_need_grad=inputs_need_grad or i > 0,
                     force_rebind=force_rebind, grad_req=grad_req)
            # next module consumes this module's outputs, renamed to
            # its own data names; shapes come from symbol inference
            # (executor outputs don't exist until the first forward)
            if i + 1 == len(self._modules):
                break
            nxt = self._modules[i + 1].data_names
            out_shapes = self._infer_output_shapes(
                mod, cur_shapes,
                label_shapes if take_labels else None)
            cur_shapes = [
                DataDesc(nxt[j] if j < len(nxt) else f"out{j}", s)
                for j, s in enumerate(out_shapes)]
        self.binded = True
        self.for_training = for_training

    @staticmethod
    def _infer_output_shapes(mod, data_shapes, label_shapes):
        sym = getattr(mod, "symbol", None)
        if sym is None:  # e.g. PythonModule mid-chain
            return [tuple(d.shape) for d in mod.output_shapes]
        shapes = {d.name: tuple(d.shape) for d in data_shapes}
        shapes.update({d.name: tuple(d.shape)
                       for d in (label_shapes or [])})
        known = set(sym.list_inputs())
        _, out_shapes, _ = sym.infer_shape(
            **{k: v for k, v in shapes.items() if k in known})
        return [tuple(int(x) for x in s) for s in out_shapes]

    def init_params(self, initializer="uniform", arg_params=None,
                    aux_params=None, allow_missing=False,
                    force_init=False, **kwargs):
        # each child owns only a SUBSET of arg_params, so children run
        # with allow_missing=True; the caller's allow_missing contract
        # is enforced globally below (a typo'd checkpoint key must not
        # silently fresh-initialize)
        for mod in self._modules:
            mod.init_params(initializer=initializer,
                            arg_params=arg_params,
                            aux_params=aux_params,
                            allow_missing=True,
                            force_init=force_init)
        self.params_initialized = True
        if not allow_missing and arg_params is not None:
            arg, aux = self.get_params()
            known = set(arg) | set(aux)
            unknown = [k for k in arg_params if k not in known]
            if unknown:
                raise MXNetError(
                    f"arg_params keys {sorted(unknown)} match no "
                    f"module parameter (allow_missing=False)")
            # every trainable must come from arg_params — a partial
            # checkpoint fails loudly instead of silently
            # fresh-initializing the gaps.  Aux states are only
            # required when aux_params was explicitly provided
            # (aux_params=None means "fresh aux", reference semantics)
            missing = [k for k in arg if k not in arg_params]
            if aux_params is not None:
                missing += [k for k in aux if k not in aux_params]
            if missing:
                raise MXNetError(
                    f"checkpoint is missing parameters "
                    f"{sorted(missing)} (allow_missing=False)")

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=None, force_init=False):
        for mod in self._modules:
            mod.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                               optimizer_params=optimizer_params,
                               force_init=force_init)
        self.optimizer_initialized = True

    def get_params(self):
        arg, aux = {}, {}
        for mod in self._modules:
            a, x = mod.get_params()
            arg.update(a)
            aux.update(x)
        return arg, aux

    def forward(self, data_batch, is_train=None):
        batch = data_batch
        for i, mod in enumerate(self._modules):
            mod.forward(batch, is_train=is_train)
            if i + 1 == len(self._modules):
                break
            outs = mod.get_outputs()
            nxt = self._modules[i + 1]
            batch = DataBatch(
                data=outs, label=data_batch.label,
                pad=getattr(data_batch, "pad", 0),
                provide_data=[
                    DataDesc(n, tuple(o.shape))
                    for n, o in zip(nxt.data_names, outs)],
                provide_label=getattr(data_batch, "provide_label",
                                      None))

    def backward(self, out_grads=None):
        grads = out_grads
        for i in range(len(self._modules) - 1, -1, -1):
            mod = self._modules[i]
            mod.backward(out_grads=grads)
            if i > 0:  # module 0's inputs are the data — no grad
                grads = mod.get_input_grads()

    def update(self):
        for mod in self._modules:
            mod.update()

    def get_outputs(self):
        return self._modules[-1].get_outputs()

    def get_input_grads(self):
        return self._modules[0].get_input_grads()

    def update_metric(self, eval_metric, labels):
        self._modules[-1].update_metric(eval_metric, labels)


class PythonModule(BaseModule):
    """A module whose compute is plain python (reference
    ``PythonModule``†) — parameterless by default; subclass and
    override :meth:`forward`."""

    def __init__(self, data_names, label_names, output_names,
                 logger=logging):
        super().__init__(logger)
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        self._output_names = list(output_names)
        self._outputs: List[NDArray] = []
        self._data_shapes = None
        self._label_shapes = None

    @property
    def data_names(self):
        return self._data_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        return self._compute_output_shapes()

    def _compute_output_shapes(self):
        """Default: one output shaped like the first input."""
        return [DataDesc(self._output_names[0],
                         tuple(self._data_shapes[0].shape))]

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             grad_req="write", **kwargs):
        self._data_shapes = data_shapes
        self._label_shapes = label_shapes
        self.binded = True
        self.for_training = for_training

    def init_params(self, *args, **kwargs):
        self.params_initialized = True

    def init_optimizer(self, *args, **kwargs):
        self.optimizer_initialized = True

    def get_params(self):
        return {}, {}

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError(
            "subclass PythonModule and implement forward")

    def backward(self, out_grads=None):
        pass

    def update(self):
        pass

    def get_outputs(self):
        return self._outputs

    def get_input_grads(self):
        return []

    def update_metric(self, eval_metric, labels):
        eval_metric.update(labels, self.get_outputs())


class PythonLossModule(PythonModule):
    """Loss expressed in python (reference ``PythonLossModule``†):
    forward stores the prediction; ``backward`` produces the gradient
    via ``grad_func(pred, label)``."""

    def __init__(self, name="pyloss", data_names=("data",),
                 label_names=("softmax_label",), grad_func=None,
                 logger=logging):
        super().__init__(data_names, label_names,
                         [name + "_output"], logger)
        self._name = name
        self._grad_func = grad_func
        self._scores = None
        self._labels = None
        self._scores_grad = None

    def forward(self, data_batch, is_train=None):
        self._scores = data_batch.data[0]
        if data_batch.label:
            self._labels = data_batch.label[0]
        self._outputs = [self._scores]

    def backward(self, out_grads=None):
        if self._grad_func is None:
            raise MXNetError("PythonLossModule needs grad_func to "
                             "backpropagate")
        grad = self._grad_func(self._scores, self._labels)
        if not isinstance(grad, NDArray):
            grad = array(np.asarray(grad))
        self._scores_grad = grad

    def get_input_grads(self):
        return [self._scores_grad]
