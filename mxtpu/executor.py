"""Executor — the bind/eval surface of the symbolic API.

Reference: ``python/mxnet/executor.py``† over ``GraphExecutor``
(``src/executor/graph_executor.cc``†).

TPU-native: binding keeps the reference surface (named arg arrays →
``forward``/``backward``/``outputs``) and execution is COMPILED — the
whole symbol interpretation runs under a shape-keyed ``jax.jit`` (the
role of the reference's ``GraphExecutor``: its entire point was the
fast bound path), with ``jax.vjp`` of the same pure interpretation as
the backward graph.  Memory planning, fusion, and scheduling belong to
XLA under jit — the reference's ``PlanMemory``/``AttachOpExecs``
passes have no analogue by design.  Setting a monitor callback (which
needs per-node host values) or ``MXTPU_EXECUTOR_JIT=0`` falls back to
eager per-op interpretation.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .base import MXNetError, _as_list
from . import autograd
from . import knobs
from . import ndarray as nd_mod
from .ndarray.ndarray import NDArray
from .symbol import Symbol, _eval_symbol, _is_aux_name

__all__ = ["Executor"]


class Executor:
    """A symbol bound to argument arrays (reference ``Executor``†)."""

    def __init__(self, symbol: Symbol, ctx=None, args=None, args_grad=None,
                 grad_req="write", aux_states=None):
        self._symbol = symbol
        self._ctx = ctx
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()

        self.arg_dict = self._name_arrays(args, arg_names, "args")
        self.aux_dict = self._name_arrays(aux_states, aux_names,
                                          "aux_states")
        missing = [n for n in arg_names if n not in self.arg_dict]
        if missing:
            raise MXNetError(
                f"bind: unbound argument(s) {missing}; pass arrays for "
                f"every name in list_arguments() = {arg_names}")

        if isinstance(grad_req, str):
            self._grad_req = {n: grad_req for n in arg_names}
        elif isinstance(grad_req, (list, tuple)):
            self._grad_req = dict(zip(arg_names, grad_req))
        else:
            self._grad_req = {n: grad_req.get(n, "null")
                              for n in arg_names}

        if args_grad is None:
            args_grad = {n: nd_mod.zeros(self.arg_dict[n].shape)
                         for n in arg_names
                         if self._grad_req.get(n, "null") != "null"}
        self.grad_dict = self._name_arrays(args_grad, arg_names, "args_grad",
                                           allow_missing=True)

        self._outputs: Optional[List[NDArray]] = None
        self._monitor_callback = None
        self._jit = knobs.get("MXTPU_EXECUTOR_JIT")
        self._jit_cache: Dict[Tuple, Any] = {}
        self._last_call = None  # inputs of the last jitted forward
        self._pending_grads = None
        # observed backward style: "ones" (backward(None) — precompute
        # grads fused with forward), "explicit" (caller supplies
        # cotangents — forward runs outputs only), "none" (caller
        # never calls backward — ditto)
        self._bwd_mode = "ones"

    @staticmethod
    def _name_arrays(arrays, names, what, allow_missing=False):
        if arrays is None:
            return {}
        if isinstance(arrays, dict):
            out = dict(arrays)
        else:
            arrays = _as_list(arrays)
            if len(arrays) != len(names) and not allow_missing:
                raise MXNetError(
                    f"{what}: expected {len(names)} arrays "
                    f"({names}), got {len(arrays)}")
            out = dict(zip(names, arrays))
        return {k: v if isinstance(v, NDArray) else nd_mod.array(v)
                for k, v in out.items() if v is not None}

    # -- reference surface ---------------------------------------------
    @property
    def outputs(self) -> List[NDArray]:
        if self._outputs is None:
            raise MXNetError("run forward() first")
        return self._outputs

    @property
    def arg_arrays(self) -> List[NDArray]:
        return [self.arg_dict[n] for n in self._symbol.list_arguments()]

    @property
    def grad_arrays(self) -> List[Optional[NDArray]]:
        return [self.grad_dict.get(n)
                for n in self._symbol.list_arguments()]

    @property
    def aux_arrays(self) -> List[NDArray]:
        return [self.aux_dict[n]
                for n in self._symbol.list_auxiliary_states()]

    def set_monitor_callback(self, callback, monitor_all=False) -> None:
        self._monitor_callback = callback

    # -- compiled path --------------------------------------------------
    def _pure_eval_fn(self, arg_names, aux_names, training):
        """A pure (jit-traceable) interpretation of the bound symbol:
        (train_vals, other_vals, aux_vals, key_data) -> tuple of raw
        outputs.  RNG ops draw from the traced key stream (the
        hybridize CachedOp mechanism)."""
        import jax

        from .ndarray import random as _rnd
        sym = self._symbol
        rec_names, other_names = arg_names

        def fn(train_vals, other_vals, aux_vals, key_data):
            bindings = {}
            for n, v in zip(rec_names, train_vals):
                bindings[n] = NDArray(v, None, _placed=True)
            for n, v in zip(other_names, other_vals):
                bindings[n] = NDArray(v, None, _placed=True)
            for n, v in zip(aux_names, aux_vals):
                bindings[n] = NDArray(v, None, _placed=True)
            provider = _rnd._TraceKeyProvider(
                jax.random.wrap_key_data(key_data))
            _rnd._push_trace_provider(provider)
            prev_rec = autograd.set_recording(False)
            prev_train = autograd.set_training(training)
            try:
                outs = _eval_symbol(sym, bindings)
            finally:
                autograd.set_training(prev_train)
                autograd.set_recording(prev_rec)
                _rnd._pop_trace_provider()
            return tuple(o.data for o in outs)

        return fn

    def _jit_entry(self, is_train, rec_names):
        import jax
        arg_names = self._symbol.list_arguments()
        aux_names = self._symbol.list_auxiliary_states()
        other_names = [n for n in arg_names if n not in set(rec_names)]
        sig = (is_train, tuple(rec_names),
               tuple((n, self.arg_dict[n].shape,
                      str(self.arg_dict[n].dtype)) for n in arg_names),
               tuple((n, self.aux_dict[n].shape) for n in aux_names))
        entry = self._jit_cache.get(sig)
        if entry is None:
            raw = self._pure_eval_fn((tuple(rec_names),
                                      tuple(other_names)),
                                     tuple(aux_names), is_train)
            fwd = jax.jit(raw)

            def fwd_bwd(train_vals, other_vals, aux_vals, key_data,
                        cotangents):
                primals, vjp_fn = jax.vjp(
                    lambda tv: raw(tv, other_vals, aux_vals, key_data),
                    train_vals)
                grads = vjp_fn(tuple(cotangents))[0]
                return primals, grads

            def fwd_bwd_ones(train_vals, other_vals, aux_vals,
                             key_data):
                # the default-cotangent (ones) step in ONE program:
                # forward(is_train=True)+backward() costs exactly one
                # fwd + one bwd, like the reference executor
                import jax.numpy as jnp
                primals, vjp_fn = jax.vjp(
                    lambda tv: raw(tv, other_vals, aux_vals, key_data),
                    train_vals)
                grads = vjp_fn(tuple(jnp.ones_like(p)
                                     for p in primals))[0]
                return primals, grads

            entry = {"fwd": fwd, "fwd_bwd": jax.jit(fwd_bwd),
                     "fwd_bwd_ones": jax.jit(fwd_bwd_ones),
                     "rec_names": tuple(rec_names),
                     "other_names": tuple(other_names),
                     "aux_names": tuple(aux_names)}
            self._jit_cache[sig] = entry
        return entry

    def forward(self, is_train: bool = False, **kwargs):
        for name, val in kwargs.items():
            val = val if isinstance(val, NDArray) else nd_mod.array(val)
            if name in self.arg_dict:
                self.arg_dict[name] = val
            elif name in self.aux_dict or _is_aux_name(name):
                self.aux_dict[name] = val
            else:
                raise MXNetError(f"unknown argument {name!r}")

        rec_names = [n for n in self.arg_dict
                     if self._grad_req.get(n, "null") != "null"] \
            if is_train else []
        if self._jit and self._monitor_callback is None:
            try:
                return self._forward_jit(is_train, rec_names)
            except MXNetError:
                raise
            except Exception as e:  # unjittable op/graph
                import warnings
                warnings.warn(
                    f"Executor jit path failed "
                    f"({type(e).__name__}: {str(e)[:200]}); falling "
                    f"back to eager interpretation for this executor",
                    stacklevel=2)
                self._jit = False
        return self._forward_eager(is_train, rec_names)

    def _forward_jit(self, is_train, rec_names):
        import jax

        from .ndarray import random as _rnd
        entry = self._jit_entry(is_train, rec_names)
        train_vals = tuple(self.arg_dict[n].data
                           for n in entry["rec_names"])
        other_vals = tuple(self.arg_dict[n].data
                           for n in entry["other_names"])
        aux_vals = tuple(self.aux_dict[n].data
                         for n in entry["aux_names"])
        key_data = jax.random.key_data(_rnd._next_key(None))
        if is_train and entry["rec_names"] and self._bwd_mode == "ones":
            # one program computes outputs AND default-cotangent grads
            # (the common Module loop calls backward(None)).  When the
            # observed usage is explicit cotangents or no backward at
            # all, _bwd_mode switches and forward runs outputs only —
            # otherwise every explicit-cotangent step would pay a
            # wasted ones-backward, and eval-style is_train forwards a
            # whole wasted bwd (r3 advisor, executor.py finding).
            if self._pending_grads is not None:
                # previous forward's precomputed grads were never
                # consumed: caller does not call backward
                self._bwd_mode = "none"
                raw_outs = entry["fwd"](train_vals, other_vals,
                                        aux_vals, key_data)
                self._pending_grads = None
            else:
                raw_outs, grads = entry["fwd_bwd_ones"](
                    train_vals, other_vals, aux_vals, key_data)
                self._pending_grads = grads
        else:
            raw_outs = entry["fwd"](train_vals, other_vals, aux_vals,
                                    key_data)
            self._pending_grads = None
        self._last_call = (entry, train_vals, other_vals, aux_vals,
                           key_data)
        self._recorded = list(entry["rec_names"])
        self._outputs = [NDArray(r, None, _placed=True)
                         for r in raw_outs]
        return self._outputs

    def _forward_eager(self, is_train, rec_names):
        bindings: Dict[str, NDArray] = {}
        bindings.update(self.aux_dict)
        bindings.update(self.arg_dict)
        self._last_call = None
        if is_train:
            for name in rec_names:
                self.arg_dict[name].attach_grad(
                    grad_req=self._grad_req.get(name, "write"))
            self._recorded = rec_names
            with autograd.record():
                outs = _eval_symbol(self._symbol, bindings)
        else:
            outs = _eval_symbol(self._symbol, bindings)

        self._outputs = outs
        if self._monitor_callback is not None:
            for name, out in zip(self._symbol.list_outputs(), outs):
                self._monitor_callback(name, out)
        return outs

    def backward(self, out_grads=None) -> None:
        if self._outputs is None:
            raise MXNetError("forward(is_train=True) before backward()")
        if out_grads is not None:
            out_grads = _as_list(out_grads)
        if self._last_call is not None:
            entry, train_vals, other_vals, aux_vals, key_data = \
                self._last_call
            if out_grads is None and self._pending_grads is not None:
                grads = self._pending_grads  # computed with forward
                self._pending_grads = None
                self._bwd_mode = "ones"
            else:
                if out_grads is None:
                    # caller uses backward(None) but forward ran
                    # outputs-only (mode was explicit/none): recompute
                    # fused and switch back for the next iteration
                    self._bwd_mode = "ones"
                    _, grads = entry["fwd_bwd_ones"](
                        train_vals, other_vals, aux_vals, key_data)
                else:
                    self._bwd_mode = "explicit"
                    cots = tuple(
                        (g.data if isinstance(g, NDArray)
                         else nd_mod.array(g).data).astype(o.data.dtype)
                        for g, o in zip(out_grads, self._outputs))
                    _, grads = entry["fwd_bwd"](train_vals, other_vals,
                                                aux_vals, key_data,
                                                cots)
                self._pending_grads = None
            for name, g in zip(entry["rec_names"], grads):
                self._store_grad(name, NDArray(g, None, _placed=True))
            return
        heads = self._outputs
        autograd.backward(heads, out_grads)
        for name in self._recorded:
            arr = self.arg_dict[name]
            if arr.grad is None:
                continue
            self._store_grad(name, arr.grad)

    def _store_grad(self, name, grad: NDArray) -> None:
        req = self._grad_req.get(name, "write")
        dst = self.grad_dict.get(name)
        if dst is None:
            self.grad_dict[name] = grad
        elif req == "add":
            dst._data = dst._data + grad._data
        else:
            dst._data = grad._data

    def copy_params_from(self, arg_params: Dict[str, NDArray],
                         aux_params: Optional[Dict[str, NDArray]] = None,
                         allow_extra_params: bool = False) -> None:
        for name, arr in arg_params.items():
            if name in self.arg_dict:
                self.arg_dict[name] = arr.copy()
            elif not allow_extra_params:
                raise MXNetError(f"unknown parameter {name!r}")
        for name, arr in (aux_params or {}).items():
            if name in self.aux_dict:
                self.aux_dict[name] = arr.copy()
            elif not allow_extra_params:
                raise MXNetError(f"unknown aux state {name!r}")

    def reshape(self, partial_shaping=False, allow_up_sizing=False,
                **kwargs):
        """Rebind with new shapes — with XLA there is no memory pool to
        re-plan; a fresh Executor (compile-cache-hit per shape) is the
        whole story."""
        new_args = {}
        for n, arr in self.arg_dict.items():
            if n in kwargs:
                new_args[n] = nd_mod.zeros(kwargs[n])
            else:
                new_args[n] = arr
        return Executor(self._symbol, self._ctx, args=new_args,
                        grad_req=self._grad_req,
                        aux_states=dict(self.aux_dict))

    # -- construction helpers ------------------------------------------
    @staticmethod
    def simple_bind(symbol: Symbol, ctx=None, grad_req="write",
                    type_dict=None, **shape_kwargs) -> "Executor":
        """Infer all shapes from the provided input shapes and allocate
        (reference ``simple_bind``†)."""
        arg_shapes, _, aux_shapes = symbol.infer_shape(**shape_kwargs)
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        type_dict = type_dict or {}
        args = {}
        for name, shape in zip(arg_names, arg_shapes):
            dtype = type_dict.get(name, "float32")
            args[name] = nd_mod.zeros(shape, dtype=dtype)
        aux = {name: nd_mod.zeros(shape)
               for name, shape in zip(aux_names, aux_shapes)}
        return Executor(symbol, ctx, args=args, grad_req=grad_req,
                        aux_states=aux)
