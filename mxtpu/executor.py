"""Executor — the bind/eval surface of the symbolic API.

Reference: ``python/mxnet/executor.py``† over ``GraphExecutor``
(``src/executor/graph_executor.cc``†).

TPU-native: binding keeps the reference surface (named arg arrays →
``forward``/``backward``/``outputs``) but execution is interpretation of
the symbol through the eager op namespace, with the autograd tape
providing the backward pass (the reference ran an explicit NNVM grad
graph; here jax vjps recorded per op play that role).  Memory planning,
fusion, and scheduling belong to XLA under jit — the reference's
``PlanMemory``/``AttachOpExecs`` passes have no analogue by design.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .base import MXNetError, _as_list
from . import autograd
from . import ndarray as nd_mod
from .ndarray.ndarray import NDArray
from .symbol import Symbol, _eval_symbol, _is_aux_name

__all__ = ["Executor"]


class Executor:
    """A symbol bound to argument arrays (reference ``Executor``†)."""

    def __init__(self, symbol: Symbol, ctx=None, args=None, args_grad=None,
                 grad_req="write", aux_states=None):
        self._symbol = symbol
        self._ctx = ctx
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()

        self.arg_dict = self._name_arrays(args, arg_names, "args")
        self.aux_dict = self._name_arrays(aux_states, aux_names,
                                          "aux_states")
        missing = [n for n in arg_names if n not in self.arg_dict]
        if missing:
            raise MXNetError(
                f"bind: unbound argument(s) {missing}; pass arrays for "
                f"every name in list_arguments() = {arg_names}")

        if isinstance(grad_req, str):
            self._grad_req = {n: grad_req for n in arg_names}
        elif isinstance(grad_req, (list, tuple)):
            self._grad_req = dict(zip(arg_names, grad_req))
        else:
            self._grad_req = {n: grad_req.get(n, "null")
                              for n in arg_names}

        if args_grad is None:
            args_grad = {n: nd_mod.zeros(self.arg_dict[n].shape)
                         for n in arg_names
                         if self._grad_req.get(n, "null") != "null"}
        self.grad_dict = self._name_arrays(args_grad, arg_names, "args_grad",
                                           allow_missing=True)

        self._outputs: Optional[List[NDArray]] = None
        self._monitor_callback = None

    @staticmethod
    def _name_arrays(arrays, names, what, allow_missing=False):
        if arrays is None:
            return {}
        if isinstance(arrays, dict):
            out = dict(arrays)
        else:
            arrays = _as_list(arrays)
            if len(arrays) != len(names) and not allow_missing:
                raise MXNetError(
                    f"{what}: expected {len(names)} arrays "
                    f"({names}), got {len(arrays)}")
            out = dict(zip(names, arrays))
        return {k: v if isinstance(v, NDArray) else nd_mod.array(v)
                for k, v in out.items() if v is not None}

    # -- reference surface ---------------------------------------------
    @property
    def outputs(self) -> List[NDArray]:
        if self._outputs is None:
            raise MXNetError("run forward() first")
        return self._outputs

    @property
    def arg_arrays(self) -> List[NDArray]:
        return [self.arg_dict[n] for n in self._symbol.list_arguments()]

    @property
    def grad_arrays(self) -> List[Optional[NDArray]]:
        return [self.grad_dict.get(n)
                for n in self._symbol.list_arguments()]

    @property
    def aux_arrays(self) -> List[NDArray]:
        return [self.aux_dict[n]
                for n in self._symbol.list_auxiliary_states()]

    def set_monitor_callback(self, callback, monitor_all=False) -> None:
        self._monitor_callback = callback

    def forward(self, is_train: bool = False, **kwargs):
        for name, val in kwargs.items():
            val = val if isinstance(val, NDArray) else nd_mod.array(val)
            if name in self.arg_dict:
                self.arg_dict[name] = val
            elif name in self.aux_dict or _is_aux_name(name):
                self.aux_dict[name] = val
            else:
                raise MXNetError(f"unknown argument {name!r}")

        bindings: Dict[str, NDArray] = {}
        bindings.update(self.aux_dict)
        bindings.update(self.arg_dict)

        if is_train:
            grads = []
            for name, arr in self.arg_dict.items():
                req = self._grad_req.get(name, "null")
                if req != "null":
                    arr.attach_grad(grad_req=req)
                    grads.append(name)
            self._recorded = grads
            with autograd.record():
                outs = _eval_symbol(self._symbol, bindings)
        else:
            outs = _eval_symbol(self._symbol, bindings)

        self._outputs = outs
        if self._monitor_callback is not None:
            for name, out in zip(self._symbol.list_outputs(), outs):
                self._monitor_callback(name, out)
        return outs

    def backward(self, out_grads=None) -> None:
        if self._outputs is None:
            raise MXNetError("forward(is_train=True) before backward()")
        heads = self._outputs
        if out_grads is not None:
            out_grads = _as_list(out_grads)
        autograd.backward(heads, out_grads)
        for name in self._recorded:
            arr = self.arg_dict[name]
            if arr.grad is None:
                continue
            req = self._grad_req.get(name, "write")
            dst = self.grad_dict.get(name)
            if dst is None:
                self.grad_dict[name] = arr.grad
            elif req == "add":
                dst._data = dst._data + arr.grad._data
            else:
                dst._data = arr.grad._data

    def copy_params_from(self, arg_params: Dict[str, NDArray],
                         aux_params: Optional[Dict[str, NDArray]] = None,
                         allow_extra_params: bool = False) -> None:
        for name, arr in arg_params.items():
            if name in self.arg_dict:
                self.arg_dict[name] = arr.copy()
            elif not allow_extra_params:
                raise MXNetError(f"unknown parameter {name!r}")
        for name, arr in (aux_params or {}).items():
            if name in self.aux_dict:
                self.aux_dict[name] = arr.copy()
            elif not allow_extra_params:
                raise MXNetError(f"unknown aux state {name!r}")

    def reshape(self, partial_shaping=False, allow_up_sizing=False,
                **kwargs):
        """Rebind with new shapes — with XLA there is no memory pool to
        re-plan; a fresh Executor (compile-cache-hit per shape) is the
        whole story."""
        new_args = {}
        for n, arr in self.arg_dict.items():
            if n in kwargs:
                new_args[n] = nd_mod.zeros(kwargs[n])
            else:
                new_args[n] = arr
        return Executor(self._symbol, self._ctx, args=new_args,
                        grad_req=self._grad_req,
                        aux_states=dict(self.aux_dict))

    # -- construction helpers ------------------------------------------
    @staticmethod
    def simple_bind(symbol: Symbol, ctx=None, grad_req="write",
                    type_dict=None, **shape_kwargs) -> "Executor":
        """Infer all shapes from the provided input shapes and allocate
        (reference ``simple_bind``†)."""
        arg_shapes, _, aux_shapes = symbol.infer_shape(**shape_kwargs)
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        type_dict = type_dict or {}
        args = {}
        for name, shape in zip(arg_names, arg_shapes):
            dtype = type_dict.get(name, "float32")
            args[name] = nd_mod.zeros(shape, dtype=dtype)
        aux = {name: nd_mod.zeros(shape)
               for name, shape in zip(aux_names, aux_shapes)}
        return Executor(symbol, ctx, args=args, grad_req=grad_req,
                        aux_states=aux)
