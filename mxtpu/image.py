"""Image utilities (reference ``python/mxnet/image/image.py``†):
decode/resize/crop/normalize helpers over HWC NDArrays + the
python-side ``ImageIter``.

Host-side decode uses cv2 (as upstream); resizes on device go through
``jax.image.resize``.
"""
from __future__ import annotations

import os
import random as pyrandom
from typing import List, Optional, Tuple

import numpy as np

from .base import MXNetError
from .ndarray import NDArray, array

__all__ = ["imdecode", "imread", "imresize", "resize_short",
           "fixed_crop", "random_crop", "center_crop", "color_normalize",
           "HorizontalFlipAug", "CastAug", "ColorNormalizeAug",
           "ResizeAug", "ForceResizeAug", "RandomCropAug",
           "CenterCropAug", "CreateAugmenter", "ImageIter"]


def imdecode(buf, flag=1, to_rgb=True):
    """Decode a jpeg/png byte buffer → HWC NDArray (reference
    ``imdecode``† via OpenCV)."""
    import cv2
    img = cv2.imdecode(np.frombuffer(buf, np.uint8),
                       cv2.IMREAD_COLOR if flag else
                       cv2.IMREAD_GRAYSCALE)
    if img is None:
        raise MXNetError("imdecode failed")
    if flag and to_rgb:
        img = img[:, :, ::-1]
    return array(np.ascontiguousarray(img))


def imread(filename, flag=1, to_rgb=True):
    """Read an image file (reference ``imread``†)."""
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag=flag, to_rgb=to_rgb)


def imresize(src: NDArray, w: int, h: int, interp=1):
    """Resize HWC (reference ``imresize``†)."""
    import jax
    raw = src.data.astype("float32")
    squeeze = False
    if raw.ndim == 2:
        raw = raw[:, :, None]
        squeeze = True
    out = jax.image.resize(raw, (h, w, raw.shape[2]),
                           method="bilinear" if interp else "nearest")
    if src.dtype == np.uint8:
        out = out.round().clip(0, 255).astype("uint8")
    if squeeze:
        out = out[:, :, 0]
    return NDArray(out, None, _placed=True)


def resize_short(src: NDArray, size: int, interp=1):
    """Resize so the shorter edge is ``size`` (reference†)."""
    h, w = src.shape[:2]
    if h > w:
        new_w, new_h = size, int(h * size / w)
    else:
        new_w, new_h = int(w * size / h), size
    return imresize(src, new_w, new_h, interp)


def fixed_crop(src: NDArray, x0, y0, w, h, size=None, interp=1):
    """Crop [y0:y0+h, x0:x0+w] then optionally resize (reference†)."""
    out = src[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        out = imresize(out, size[0], size[1], interp)
    return out


def random_crop(src: NDArray, size: Tuple[int, int], interp=1):
    """Random crop to (w, h); returns (img, (x0, y0, w, h))
    (reference†)."""
    h, w = src.shape[:2]
    new_w, new_h = size
    if w < new_w or h < new_h:
        src = resize_short(src, max(new_w, new_h), interp)
        h, w = src.shape[:2]
    x0 = pyrandom.randint(0, w - new_w)
    y0 = pyrandom.randint(0, h - new_h)
    return fixed_crop(src, x0, y0, new_w, new_h), (x0, y0, new_w, new_h)


def center_crop(src: NDArray, size: Tuple[int, int], interp=1):
    """Center crop to (w, h) (reference†)."""
    h, w = src.shape[:2]
    new_w, new_h = size
    if w < new_w or h < new_h:
        src = resize_short(src, max(new_w, new_h), interp)
        h, w = src.shape[:2]
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    return fixed_crop(src, x0, y0, new_w, new_h), (x0, y0, new_w, new_h)


def color_normalize(src: NDArray, mean, std=None):
    """(src - mean) / std (reference†)."""
    out = src.astype("float32") - array(np.asarray(mean, np.float32))
    if std is not None:
        out = out / array(np.asarray(std, np.float32))
    return out


# -- augmenters (reference ``Augmenter`` family†) -----------------------

class Augmenter:
    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=1):
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=1):
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=1):
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=1):
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        self.p = p

    def __call__(self, src):
        if pyrandom.random() < self.p:
            return src[:, ::-1]
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        self.typ = typ

    def __call__(self, src):
        return src.astype(self.typ)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        self.mean = mean
        self.std = std

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


def CreateAugmenter(data_shape, resize=0, rand_crop=False,
                    rand_mirror=False, mean=None, std=None,
                    inter_method=1, **_ignored):
    """Standard augmenter pipeline builder (reference†)."""
    auglist: List[Augmenter] = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if mean is not None or std is not None:
        if mean is True:
            mean = np.array([123.68, 116.28, 103.53])
        if std is True:
            std = np.array([58.395, 57.12, 57.375])
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter:
    """Python-side image iterator over .rec or .lst inputs
    (reference ``ImageIter``†) — thin veneer over io.ImageRecordIter
    for the rec path."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imgidx=None, shuffle=False, aug_list=None,
                 **kwargs):
        if path_imgrec is None:
            raise MXNetError("ImageIter needs path_imgrec (list-file "
                             "mode: use gluon.data.ImageFolderDataset)")
        from .io import ImageRecordIter
        self._inner = ImageRecordIter(
            path_imgrec=path_imgrec, path_imgidx=path_imgidx,
            data_shape=data_shape, batch_size=batch_size,
            shuffle=shuffle, **kwargs)
        self.provide_data = self._inner.provide_data
        self.provide_label = self._inner.provide_label
        self.batch_size = batch_size
        self.auglist = aug_list if aug_list is not None else []

    def __iter__(self):
        return self

    def reset(self):
        self._inner.reset()

    def next(self):
        batch = self._inner.next()
        if self.auglist:
            # augmenters operate per-sample on HWC; stay on device the
            # whole way (no per-sample host syncs) and restack once
            from .ndarray import stack as _stack
            data = batch.data[0]
            samples = []
            for i in range(data.shape[0]):
                img = data[i].transpose(1, 2, 0)
                for aug in self.auglist:
                    img = aug(img)
                samples.append(img.transpose(2, 0, 1))
            batch.data = [_stack(*samples, axis=0)]
        return batch

    __next__ = next
