"""Image utilities (reference ``python/mxnet/image/image.py``†):
decode/resize/crop/normalize helpers over HWC NDArrays + the
python-side ``ImageIter``.

Host-side decode uses cv2 (as upstream); resizes on device go through
``jax.image.resize``.
"""
from __future__ import annotations

import os
import random as pyrandom
from typing import List, Optional, Tuple

import numpy as np

from .base import MXNetError
from .ndarray import NDArray, array

__all__ = ["imdecode", "imread", "imresize", "resize_short",
           "fixed_crop", "random_crop", "center_crop", "color_normalize",
           "HorizontalFlipAug", "CastAug", "ColorNormalizeAug",
           "ResizeAug", "ForceResizeAug", "RandomCropAug",
           "CenterCropAug", "CreateAugmenter", "ImageIter",
           "ImageDetIter", "DetAugmenter", "DetHorizontalFlipAug",
           "DetRandomCropAug", "CreateDetAugmenter", "pack_det_label"]


def imdecode(buf, flag=1, to_rgb=True):
    """Decode a jpeg/png byte buffer → HWC NDArray (reference
    ``imdecode``† via OpenCV)."""
    import cv2
    img = cv2.imdecode(np.frombuffer(buf, np.uint8),
                       cv2.IMREAD_COLOR if flag else
                       cv2.IMREAD_GRAYSCALE)
    if img is None:
        raise MXNetError("imdecode failed")
    if flag and to_rgb:
        img = img[:, :, ::-1]
    return array(np.ascontiguousarray(img))


def imread(filename, flag=1, to_rgb=True):
    """Read an image file (reference ``imread``†)."""
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag=flag, to_rgb=to_rgb)


def imresize(src: NDArray, w: int, h: int, interp=1):
    """Resize HWC (reference ``imresize``†)."""
    import jax
    raw = src.data.astype("float32")
    squeeze = False
    if raw.ndim == 2:
        raw = raw[:, :, None]
        squeeze = True
    out = jax.image.resize(raw, (h, w, raw.shape[2]),
                           method="bilinear" if interp else "nearest")
    if src.dtype == np.uint8:
        out = out.round().clip(0, 255).astype("uint8")
    if squeeze:
        out = out[:, :, 0]
    return NDArray(out, None, _placed=True)


def resize_short(src: NDArray, size: int, interp=1):
    """Resize so the shorter edge is ``size`` (reference†)."""
    h, w = src.shape[:2]
    if h > w:
        new_w, new_h = size, int(h * size / w)
    else:
        new_w, new_h = int(w * size / h), size
    return imresize(src, new_w, new_h, interp)


def fixed_crop(src: NDArray, x0, y0, w, h, size=None, interp=1):
    """Crop [y0:y0+h, x0:x0+w] then optionally resize (reference†)."""
    out = src[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        out = imresize(out, size[0], size[1], interp)
    return out


def random_crop(src: NDArray, size: Tuple[int, int], interp=1):
    """Random crop to (w, h); returns (img, (x0, y0, w, h))
    (reference†)."""
    h, w = src.shape[:2]
    new_w, new_h = size
    if w < new_w or h < new_h:
        src = resize_short(src, max(new_w, new_h), interp)
        h, w = src.shape[:2]
    x0 = pyrandom.randint(0, w - new_w)
    y0 = pyrandom.randint(0, h - new_h)
    return fixed_crop(src, x0, y0, new_w, new_h), (x0, y0, new_w, new_h)


def center_crop(src: NDArray, size: Tuple[int, int], interp=1):
    """Center crop to (w, h) (reference†)."""
    h, w = src.shape[:2]
    new_w, new_h = size
    if w < new_w or h < new_h:
        src = resize_short(src, max(new_w, new_h), interp)
        h, w = src.shape[:2]
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    return fixed_crop(src, x0, y0, new_w, new_h), (x0, y0, new_w, new_h)


def color_normalize(src: NDArray, mean, std=None):
    """(src - mean) / std (reference†)."""
    out = src.astype("float32") - array(np.asarray(mean, np.float32))
    if std is not None:
        out = out / array(np.asarray(std, np.float32))
    return out


# -- augmenters (reference ``Augmenter`` family†) -----------------------

class Augmenter:
    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=1):
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=1):
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=1):
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=1):
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        self.p = p

    def __call__(self, src):
        if pyrandom.random() < self.p:
            return src[:, ::-1]
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        self.typ = typ

    def __call__(self, src):
        return src.astype(self.typ)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        self.mean = mean
        self.std = std

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


def CreateAugmenter(data_shape, resize=0, rand_crop=False,
                    rand_mirror=False, mean=None, std=None,
                    inter_method=1, **_ignored):
    """Standard augmenter pipeline builder (reference†)."""
    auglist: List[Augmenter] = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if mean is not None or std is not None:
        if mean is True:
            mean = np.array([123.68, 116.28, 103.53])
        if std is True:
            std = np.array([58.395, 57.12, 57.375])
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter:
    """Python-side image iterator over .rec or .lst inputs
    (reference ``ImageIter``†) — thin veneer over io.ImageRecordIter
    for the rec path."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imgidx=None, shuffle=False, aug_list=None,
                 **kwargs):
        if path_imgrec is None:
            raise MXNetError("ImageIter needs path_imgrec (list-file "
                             "mode: use gluon.data.ImageFolderDataset)")
        from .io import ImageRecordIter
        self._inner = ImageRecordIter(
            path_imgrec=path_imgrec, path_imgidx=path_imgidx,
            data_shape=data_shape, batch_size=batch_size,
            shuffle=shuffle, **kwargs)
        self.provide_data = self._inner.provide_data
        self.provide_label = self._inner.provide_label
        self.batch_size = batch_size
        self.auglist = aug_list if aug_list is not None else []

    def __iter__(self):
        return self

    def reset(self):
        self._inner.reset()

    def next(self):
        batch = self._inner.next()
        if self.auglist:
            # augmenters operate per-sample on HWC; stay on device the
            # whole way (no per-sample host syncs) and restack once
            from .ndarray import stack as _stack
            data = batch.data[0]
            samples = []
            for i in range(data.shape[0]):
                img = data[i].transpose(1, 2, 0)
                for aug in self.auglist:
                    img = aug(img)
                samples.append(img.transpose(2, 0, 1))
            batch.data = [_stack(*samples, axis=0)]
        return batch

    __next__ = next


# ======================================================================
# Detection iterator (reference ``python/mxnet/image/detection.py``† +
# ``src/io/iter_image_det_recordio.cc``†): box-aware augmentation over
# det-packed .rec files.
# ======================================================================

class DetAugmenter:
    """Base detection augmenter: ``(img_hwc_np, label_np) -> (img,
    label)`` with label rows ``[cls, x1, y1, x2, y2]`` normalized to
    [0, 1] (reference ``DetAugmenter``†)."""

    def __call__(self, img, label):
        raise NotImplementedError


class DetHorizontalFlipAug(DetAugmenter):
    """Mirror image and boxes with probability p (reference
    ``DetHorizontalFlipAug``†)."""

    def __init__(self, p=0.5, rng=None):
        self.p = p
        self._rng = rng or np.random

    def __call__(self, img, label):
        if self._rng.rand() < self.p:
            img = img[:, ::-1]
            valid = label[:, 0] >= 0
            x1 = label[:, 1].copy()
            label[valid, 1] = 1.0 - label[valid, 3]
            label[valid, 3] = 1.0 - x1[valid]
        return img, label


class DetRandomCropAug(DetAugmenter):
    """IoU-constrained random crop (reference ``DetRandomCropAug``†,
    SSD-style sampling): sample a sub-window whose IoU with at least
    one box exceeds ``min_object_covered``; boxes re-expressed in crop
    coordinates, objects whose center falls outside are dropped
    (marked -1)."""

    def __init__(self, min_object_covered=0.3, aspect_ratio_range=(0.75,
                 1.33), area_range=(0.3, 1.0), max_attempts=25,
                 rng=None):
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self._rng = rng or np.random

    def _try_crop(self, label):
        r = self._rng
        for _ in range(self.max_attempts):
            area = r.uniform(*self.area_range)
            ar = r.uniform(*self.aspect_ratio_range)
            cw = min(np.sqrt(area * ar), 1.0)
            ch = min(np.sqrt(area / ar), 1.0)
            cx = r.uniform(0, 1 - cw)
            cy = r.uniform(0, 1 - ch)
            valid = label[label[:, 0] >= 0]
            if len(valid) == 0:
                return cx, cy, cw, ch
            ix1 = np.maximum(valid[:, 1], cx)
            iy1 = np.maximum(valid[:, 2], cy)
            ix2 = np.minimum(valid[:, 3], cx + cw)
            iy2 = np.minimum(valid[:, 4], cy + ch)
            inter = np.maximum(ix2 - ix1, 0) * np.maximum(iy2 - iy1, 0)
            barea = (valid[:, 3] - valid[:, 1]) * \
                (valid[:, 4] - valid[:, 2])
            cover = inter / np.maximum(barea, 1e-12)
            if cover.max() >= self.min_object_covered:
                return cx, cy, cw, ch
        return None

    def __call__(self, img, label):
        crop = self._try_crop(label)
        if crop is None:
            return img, label
        cx, cy, cw, ch = crop
        h, w = img.shape[:2]
        x0 = int(cx * w)
        y0 = int(cy * h)
        x1 = max(x0 + 1, int((cx + cw) * w))
        y1 = max(y0 + 1, int((cy + ch) * h))
        img = img[y0:y1, x0:x1]
        out = label.copy()
        for i in range(len(out)):
            if out[i, 0] < 0:
                continue
            bx = (out[i, 1] + out[i, 3]) / 2
            by = (out[i, 2] + out[i, 4]) / 2
            if not (cx <= bx <= cx + cw and cy <= by <= cy + ch):
                out[i] = -1.0
                continue
            out[i, 1] = np.clip((out[i, 1] - cx) / cw, 0, 1)
            out[i, 3] = np.clip((out[i, 3] - cx) / cw, 0, 1)
            out[i, 2] = np.clip((out[i, 2] - cy) / ch, 0, 1)
            out[i, 4] = np.clip((out[i, 4] - cy) / ch, 0, 1)
        return img, out


def CreateDetAugmenter(data_shape, rand_crop=0.0, rand_mirror=False,
                       min_object_covered=0.3, aspect_ratio_range=(0.75,
                       1.33), area_range=(0.3, 1.0), max_attempts=25,
                       rng=None):
    """Standard detection augmentation list (reference
    ``CreateDetAugmenter``† subset used by the SSD recipe)."""
    augs: List[DetAugmenter] = []
    if rand_crop > 0:
        augs.append(DetRandomCropAug(min_object_covered,
                                     aspect_ratio_range, area_range,
                                     max_attempts, rng=rng))
    if rand_mirror:
        augs.append(DetHorizontalFlipAug(0.5, rng=rng))
    return augs


class ImageDetIter:
    """Detection-record iterator (reference ``ImageDetIter``†).

    Label wire format (what ``tools/im2rec.py --pack-label`` and
    ``pack_det_label`` write): ``[head_w, obj_w, <extra header...>,
    obj1, obj2, ...]`` with ``obj = [cls, x1, y1, x2, y2]`` normalized.
    Batches pad the object dim with -1 rows to ``max_objs`` so shapes
    stay static (TPU contract)."""

    def __init__(self, path_imgrec, data_shape, batch_size=1,
                 path_imgidx=None, shuffle=False, max_objs=None,
                 rand_crop=0.0, rand_mirror=False, mean_pixels=None,
                 std_pixels=None, scale=1.0, aug_list=None,
                 last_batch_handle="pad", seed=0,
                 preprocess_threads=4, **kwargs):
        from . import recordio as rio
        from .io import DataDesc
        self.data_shape = tuple(data_shape)
        self.batch_size = batch_size
        self.scale = scale
        self.mean = np.asarray(
            mean_pixels if mean_pixels is not None else (0, 0, 0),
            np.float32)
        self.std = np.asarray(
            std_pixels if std_pixels is not None else (1, 1, 1),
            np.float32)
        self._rng = np.random.RandomState(seed)
        # user-supplied augmenters run shared + single-threaded (their
        # rng would race across threads); otherwise augmenters are
        # built per-sample from _aug_args with per-sample seeds —
        # _aug_args is the single switch next() and _decode_one gate on
        self.auglist = aug_list
        self._aug_args = None if aug_list is not None else \
            dict(rand_crop=rand_crop, rand_mirror=rand_mirror)
        self._threads = max(1, int(preprocess_threads))
        self._pool = None
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        if path_imgidx and os.path.exists(path_imgidx):
            self._rec = rio.MXIndexedRecordIO(path_imgidx, path_imgrec,
                                              "r")
            self._keys = list(self._rec.keys)
        else:
            self._rec = rio.MXRecordIO(path_imgrec, "r")
            self._keys = None
            if shuffle:
                raise MXNetError("shuffle requires path_imgidx")
        if max_objs is None:
            max_objs = self._scan_max_objs(path_imgrec)
        self.max_objs = max_objs
        self._DataDesc = DataDesc
        self.reset()

    def _scan_max_objs(self, path):
        from . import recordio as rio
        rec = rio.MXRecordIO(path, "r")
        mx_objs = 1
        while True:
            raw = rec.read()
            if raw is None:
                break
            header, _ = rio.unpack(raw)
            lab = np.asarray(header.label).ravel()
            head_w = int(lab[0])
            obj_w = int(lab[1])
            mx_objs = max(mx_objs, (lab.size - head_w) // obj_w)
        rec.close()
        return mx_objs

    @property
    def provide_data(self):
        return [self._DataDesc(
            "data", (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        return [self._DataDesc(
            "label", (self.batch_size, self.max_objs, 5))]

    def reset(self):
        if self._keys is not None:
            self._order = list(self._keys)
            if self.shuffle:
                self._rng.shuffle(self._order)
            self._pos = 0
        else:
            self._rec.reset()
        self._exhausted = False

    def _read_raw(self):
        if self._keys is not None:
            if self._pos >= len(self._order):
                return None
            raw = self._rec.read_idx(self._order[self._pos])
            self._pos += 1
            return raw
        return self._rec.read()

    def _parse_label(self, lab):
        lab = np.asarray(lab, np.float32).ravel()
        head_w = int(lab[0])
        obj_w = int(lab[1])
        objs = lab[head_w:].reshape(-1, obj_w)[:, :5]
        out = -np.ones((self.max_objs, 5), np.float32)
        n = min(len(objs), self.max_objs)
        out[:n] = objs[:n]
        return out

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None
        rec = getattr(self, "_rec", None)
        if rec is not None and hasattr(rec, "close"):
            rec.close()
            self._rec = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def _decode_one(self, raw, aug_seed=None):
        """``aug_seed``: per-sample augmentation seed drawn serially
        on the consumer (reproducible at any pool size); None = use
        the shared (possibly user-supplied) augmenter list."""
        import cv2

        from . import recordio as rio
        header, img = rio.unpack_img(raw, iscolor=1)
        label = self._parse_label(header.label)
        img = img[:, :, ::-1]  # BGR→RGB
        if aug_seed is None:
            augs = self.auglist or ()
        else:
            augs = CreateDetAugmenter(
                self.data_shape,
                rng=np.random.RandomState(aug_seed),
                **self._aug_args)
        for aug in augs:
            img, label = aug(img, label)
        c, h, w = self.data_shape
        if img.shape[:2] != (h, w):
            img = cv2.resize(img, (w, h))
        img = (img.astype(np.float32) - self.mean) * self.scale / \
            self.std
        return img.transpose(2, 0, 1), label

    def next(self):
        from .io import DataBatch
        if self._exhausted:
            raise StopIteration
        c, h, w = self.data_shape
        data = np.zeros((self.batch_size, c, h, w), np.float32)
        labels = -np.ones((self.batch_size, self.max_objs, 5),
                          np.float32)
        raws = []
        while len(raws) < self.batch_size:
            raw = self._read_raw()
            if raw is None:
                break
            raws.append(raw)
        n = len(raws)
        if n and self._aug_args is not None:
            # per-sample seeds drawn serially: the augmentation stream
            # is identical whatever the decode-pool size
            seeds = self._rng.randint(0, 2 ** 31 - 1, size=n,
                                      dtype=np.int64)
            if self._threads > 1:
                if self._pool is None:
                    from concurrent.futures import ThreadPoolExecutor
                    self._pool = ThreadPoolExecutor(self._threads)
                decoded = list(self._pool.map(self._decode_one, raws,
                                              seeds))
            else:
                decoded = [self._decode_one(r, s)
                           for r, s in zip(raws, seeds)]
        else:
            decoded = [self._decode_one(r) for r in raws]
        for i, (img, label) in enumerate(decoded):
            data[i] = img
            labels[i] = label
        if n == 0:
            self._exhausted = True
            raise StopIteration
        pad = self.batch_size - n
        if pad:
            self._exhausted = True
            if self.last_batch_handle == "discard":
                raise StopIteration
            for i in range(n, self.batch_size):
                data[i] = data[i - n]
                labels[i] = labels[i - n]
        return DataBatch(data=[array(data)], label=[array(labels)],
                         pad=pad, provide_data=self.provide_data,
                         provide_label=self.provide_label)

    def __iter__(self):
        return self

    __next__ = next


def pack_det_label(objects, extra_header=()):
    """Build the det-record label vector from ``[cls, x1, y1, x2, y2]``
    rows (normalized), the layout ``ImageDetIter`` and the reference's
    ``im2rec --pack-label`` expect."""
    objs = np.asarray(objects, np.float32).reshape(-1, 5)
    head = [2 + len(extra_header), 5] + list(extra_header)
    return np.concatenate([np.asarray(head, np.float32),
                           objs.ravel()])
