"""Runtime kernel compilation (reference ``python/mxnet/rtc.py``† —
NVRTC CUDA-from-string).

TPU-native analogue: Pallas-from-Python.  ``PallasKernel`` wraps a
user-written Pallas kernel function into an NDArray-callable — the
same "write a custom kernel without rebuilding the framework" facility
the reference's ``CudaModule`` provides, targeting the MXU/VPU instead
of CUDA cores.  The CUDA-source entry points raise with guidance.
"""
from __future__ import annotations

from typing import Sequence

import jax

from .base import MXNetError
from .ndarray import NDArray

__all__ = ["CudaModule", "PallasKernel"]


class CudaModule:
    """Reference API stub: CUDA source cannot target a TPU."""

    def __init__(self, source, options=(), exports=()):
        raise MXNetError(
            "CudaModule compiles CUDA C — not supported on TPU. Write "
            "the kernel as a Pallas function and wrap it with "
            "mxtpu.rtc.PallasKernel (see mxtpu/kernels/ for worked "
            "examples).")


class PallasKernel:
    """Wrap a Pallas kernel into an NDArray-in/NDArray-out callable.

    kernel_fn: the Pallas body ``(in_ref..., out_ref...) -> None``.
    out_shape: ShapeDtypeStruct (or list) for outputs.
    Extra pallas_call kwargs (grid, in_specs, out_specs, …) pass
    through.  Compiled (and cached) per input shape by jax.jit.
    """

    def __init__(self, kernel_fn, out_shape, **pallas_kwargs):
        from jax.experimental import pallas as pl

        def run(*arrays):
            return pl.pallas_call(kernel_fn, out_shape=out_shape,
                                  **pallas_kwargs)(*arrays)
        self._jitted = jax.jit(run)

    def __call__(self, *inputs):
        raws = [x.data if isinstance(x, NDArray) else x for x in inputs]
        out = self._jitted(*raws)
        if isinstance(out, (tuple, list)):
            return tuple(NDArray(o, None, _placed=True) for o in out)
        return NDArray(out, None, _placed=True)
