"""Operator-breadth tail: init / elemwise / AMP / slice-assign /
storage / linalg / optimizer ops closing the gap against the
reference's inventory (``src/operator/``†, OPS_MANIFEST.md).

Everything here is a pure XLA lowering rule like ``ops_impl.py`` —
the file split is only to keep modules reviewable.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..base import MXNetError
from ..ops.registry import Param, register_op
from .ops_impl import _rescale_clip

# ---------------------------------------------------------------------------
# init ops (tensor/init_op.cc†) — nullary, shape from params
# ---------------------------------------------------------------------------


def _np_dtype(dtype, default="float32"):
    return jnp.dtype(dtype or default)


register_op("_zeros", num_inputs=0, differentiable=False,
            params=[Param("shape", tuple, ()),
                    Param("dtype", str, None)])(
    lambda shape=(), dtype=None: jnp.zeros(shape, _np_dtype(dtype)))

register_op("_ones", num_inputs=0, differentiable=False,
            params=[Param("shape", tuple, ()),
                    Param("dtype", str, None)])(
    lambda shape=(), dtype=None: jnp.ones(shape, _np_dtype(dtype)))

register_op("_full", num_inputs=0, differentiable=False,
            params=[Param("shape", tuple, ()),
                    Param("value", float, 0.0),
                    Param("dtype", str, None)])(
    lambda shape=(), value=0.0, dtype=None: jnp.full(
        shape, value, _np_dtype(dtype)))

# uninitialised memory has no XLA analogue; zeros is the defined choice
register_op("_empty", num_inputs=0, differentiable=False,
            params=[Param("shape", tuple, ()),
                    Param("dtype", str, None)])(
    lambda shape=(), dtype=None: jnp.zeros(shape, _np_dtype(dtype)))


def _arange(start=0.0, stop=None, step=1.0, repeat=1, infer_range=False,
            dtype=None):
    a = jnp.arange(start, stop, step, _np_dtype(dtype))
    if repeat != 1:
        a = jnp.repeat(a, repeat)
    return a


register_op("_arange", num_inputs=0, differentiable=False,
            params=[Param("start", float, 0.0),
                    Param("stop", float, None),
                    Param("step", float, 1.0),
                    Param("repeat", int, 1),
                    Param("infer_range", bool, False),
                    Param("dtype", str, None)])(_arange)

# ---------------------------------------------------------------------------
# elemwise logical tail (elemwise_binary_op_logic.cc†)
# ---------------------------------------------------------------------------

register_op("_logical_and", num_inputs=2, differentiable=False)(
    lambda a, b: jnp.logical_and(a != 0, b != 0).astype(a.dtype))
register_op("_logical_or", num_inputs=2, differentiable=False)(
    lambda a, b: jnp.logical_or(a != 0, b != 0).astype(a.dtype))
register_op("_logical_and_scalar", differentiable=False,
            params=[Param("scalar", float, 0.0)])(
    lambda a, scalar=0.0: jnp.logical_and(a != 0, scalar != 0)
    .astype(a.dtype))
register_op("_logical_or_scalar", differentiable=False,
            params=[Param("scalar", float, 0.0)])(
    lambda a, scalar=0.0: jnp.logical_or(a != 0, scalar != 0)
    .astype(a.dtype))
register_op("_logical_xor_scalar", differentiable=False,
            params=[Param("scalar", float, 0.0)])(
    lambda a, scalar=0.0: jnp.logical_xor(a != 0, scalar != 0)
    .astype(a.dtype))

# ---------------------------------------------------------------------------
# AMP ops (tensor/amp_cast.cc†) — used by automatic mixed precision
# ---------------------------------------------------------------------------

register_op("amp_cast", params=[Param("dtype", str, "float16")])(
    lambda x, dtype="float16": x.astype(jnp.dtype(dtype)))


def _amp_multicast(*arrays, num_outputs=0, cast_narrow=False):
    """Cast the FLOAT inputs to their widest (or narrowest) common
    float type; non-float inputs pass through untouched (reference
    amp_multicast semantics — ints never vote or get cast)."""
    if not arrays:
        raise MXNetError("amp_multicast needs at least one input")
    widths = [(jnp.finfo(a.dtype).bits, i)
              for i, a in enumerate(arrays)
              if jnp.issubdtype(a.dtype, jnp.floating)]
    if not widths:
        return tuple(arrays)
    pick = min(widths)[1] if cast_narrow else max(widths)[1]
    target = arrays[pick].dtype
    return tuple(a.astype(target)
                 if jnp.issubdtype(a.dtype, jnp.floating) else a
                 for a in arrays)


def _amp_multicast_n_outputs(attrs):
    # output count IS the input count; a missing num_outputs attr must
    # fail loudly, not silently declare 1 output for an N-output op
    # (r4 review) — but with a clear message, not a TypeError
    n = int(attrs.get("num_outputs") or 0)
    if n <= 0:
        raise MXNetError("amp_multicast requires num_outputs "
                         "(= number of inputs)")
    return n


register_op("amp_multicast", num_inputs=-1,
            params=[Param("num_outputs", int, 0),
                    Param("cast_narrow", bool, False)],
            num_outputs_fn=_amp_multicast_n_outputs)(_amp_multicast)


def _all_finite(data, init_output=True):
    return jnp.isfinite(data.astype(jnp.float32)).all().reshape(
        (1,)).astype(jnp.float32)


register_op("all_finite", differentiable=False,
            params=[Param("init_output", bool, True)])(_all_finite)


def _multi_all_finite(*arrays, num_arrays=1, init_output=True):
    ok = jnp.array(True)
    for a in arrays:
        ok = jnp.logical_and(ok, jnp.isfinite(
            a.astype(jnp.float32)).all())
    return ok.reshape((1,)).astype(jnp.float32)


register_op("multi_all_finite", num_inputs=-1, differentiable=False,
            params=[Param("num_arrays", int, 1),
                    Param("init_output", bool, True)])(_multi_all_finite)

# ---------------------------------------------------------------------------
# slice-assign family (tensor/matrix_op.cc† _slice_assign /
# _slice_assign_scalar / _crop_assign aliases) — functional: returns the
# updated copy (NDArray __setitem__ rebinds, matching engine semantics)
# ---------------------------------------------------------------------------


def _slices(shape, begin, end, step):
    step = step or ()
    out = []
    for i in range(len(shape)):
        b = begin[i] if i < len(begin) and begin[i] is not None else 0
        e = end[i] if i < len(end) and end[i] is not None else shape[i]
        s = step[i] if i < len(step) and step[i] not in (None, 0) else 1
        out.append(slice(b, e, s))
    return tuple(out)


def _slice_assign(lhs, rhs, begin=(), end=(), step=()):
    return lhs.at[_slices(lhs.shape, begin, end, step)].set(rhs)


register_op("_slice_assign", num_inputs=2,
            params=[Param("begin", tuple, ()),
                    Param("end", tuple, ()),
                    Param("step", tuple, ())],
            aliases=("_crop_assign",))(_slice_assign)


def _slice_assign_scalar(data, scalar=0.0, begin=(), end=(), step=()):
    return data.at[_slices(data.shape, begin, end, step)].set(
        jnp.asarray(scalar, data.dtype))


register_op("_slice_assign_scalar",
            params=[Param("scalar", float, 0.0),
                    Param("begin", tuple, ()),
                    Param("end", tuple, ()),
                    Param("step", tuple, ())],
            aliases=("_crop_assign_scalar",))(_slice_assign_scalar)


def _scatter_set_nd(lhs, rhs, indices, shape=()):
    idx = tuple(indices[i].astype(jnp.int32)
                for i in range(indices.shape[0]))
    return lhs.at[idx].set(rhs)


register_op("_scatter_set_nd", num_inputs=3,
            params=[Param("shape", tuple, ())])(_scatter_set_nd)

# ---------------------------------------------------------------------------
# reduce/pick tail
# ---------------------------------------------------------------------------

register_op("argmax_channel", differentiable=False)(
    lambda x: jnp.argmax(x, axis=1).astype(x.dtype))


def _fill_element_0index(lhs, mhs, rhs):
    """``fill_element_0index``†: out[i, rhs[i]] = mhs[i] (the
    3-operand companion of choose_element_0index/pick)."""
    idx = rhs.astype(jnp.int32)
    rows = jnp.arange(lhs.shape[0])
    return lhs.at[rows, idx].set(mhs.astype(lhs.dtype))


register_op("fill_element_0index", num_inputs=3)(_fill_element_0index)

# ---------------------------------------------------------------------------
# storage ops — dense-backed (SURVEY §7 hard-part 3: the TPU build keeps
# sparse the API, dense the storage; COVERAGE.md documents divergence)
# ---------------------------------------------------------------------------

register_op("cast_storage", params=[Param("stype", str, "default")],
            doc="dense-backed: storage casts are identity at the "
                "buffer level; mxtpu.ndarray.sparse tracks the "
                "compressed-view semantics")(
    lambda x, stype="default": x)


def _sparse_retain(data, indices):
    """Keep only the listed rows of a row_sparse array (zero the rest;
    dense-backed semantics of ``sparse_retain``†)."""
    keep = jnp.zeros((data.shape[0],), jnp.bool_).at[
        indices.astype(jnp.int32)].set(True)
    return jnp.where(keep.reshape((-1,) + (1,) * (data.ndim - 1)),
                     data, 0)


register_op("sparse_retain", num_inputs=2)(_sparse_retain)

# ---------------------------------------------------------------------------
# linalg tail (tensor/la_op.cc†)
# ---------------------------------------------------------------------------


def _f32_precision(dtype):
    """f32 linalg keeps true-f32 MXU passes — the TPU default's bf16
    multiplicands are ~3 decimal digits looser than any linalg user
    (or the reference's CPU oracle) expects."""
    return lax.Precision.HIGHEST \
        if jnp.dtype(dtype) == jnp.float32 else None


def _potri(a):
    """inv(A) from its Cholesky factor L (A = L L^T) — linalg_potri†."""
    eye = jnp.broadcast_to(jnp.eye(a.shape[-1], dtype=a.dtype), a.shape)
    linv = lax.linalg.triangular_solve(a, eye, lower=True,
                                       left_side=True)
    return jnp.matmul(jnp.swapaxes(linv, -1, -2), linv,
                      precision=_f32_precision(a.dtype))


register_op("linalg_potri")(_potri)


def _trmm(a, b, transpose=False, rightside=False, lower=True, alpha=1.0):
    tri = jnp.tril(a) if lower else jnp.triu(a)
    if transpose:
        tri = jnp.swapaxes(tri, -1, -2)
    prec = _f32_precision(a.dtype)
    return alpha * (jnp.matmul(b, tri, precision=prec) if rightside
                    else jnp.matmul(tri, b, precision=prec))


register_op("linalg_trmm", num_inputs=2,
            params=[Param("transpose", bool, False),
                    Param("rightside", bool, False),
                    Param("lower", bool, True),
                    Param("alpha", float, 1.0)])(_trmm)


def _gelqf(a):
    """LQ factorization A = L Q with Q row-orthonormal (linalg_gelqf†),
    via QR of A^T: A^T = Q' R  =>  A = R^T Q'^T."""
    q, r = jnp.linalg.qr(jnp.swapaxes(a, -1, -2))
    return jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2)


register_op("linalg_gelqf", num_outputs=2)(_gelqf)


def _syevd(a):
    w, v = jnp.linalg.eigh(a)
    # reference returns (U, lambda) with rows of U the eigenvectors
    return jnp.swapaxes(v, -1, -2), w


register_op("linalg_syevd", num_outputs=2)(_syevd)


def _slogdet(a):
    sign, logabs = jnp.linalg.slogdet(a)
    return sign, logabs


register_op("linalg_slogdet", num_outputs=2)(_slogdet)

register_op("linalg_makediag", params=[Param("offset", int, 0)])(
    lambda a, offset=0: jnp.vectorize(
        lambda v: jnp.diag(v, k=offset),
        signature="(n)->(m,m)")(a))


def _extracttrian(a, offset=0, lower=True):
    n = a.shape[-1]
    rows, cols = jnp.tril_indices(n, k=offset) if lower else \
        jnp.triu_indices(n, k=offset)
    return a[..., rows, cols]


register_op("linalg_extracttrian",
            params=[Param("offset", int, 0),
                    Param("lower", bool, True)])(_extracttrian)


def _maketrian(a, offset=0, lower=True):
    # infer n from the packed length k = n(n+1)/2 (+/- offset rows)
    k = a.shape[-1]
    n = int((math.isqrt(8 * k + 1) - 1) // 2) + abs(int(offset))
    rows, cols = (np.tril_indices(n, k=offset) if lower
                  else np.triu_indices(n, k=offset))
    out = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
    return out.at[..., rows, cols].set(a)


register_op("linalg_maketrian",
            params=[Param("offset", int, 0),
                    Param("lower", bool, True)])(_maketrian)

# ---------------------------------------------------------------------------
# optimizer tail (optimizer_op.cc†): NAG, multi-precision (fp16 weights
# with fp32 master copies), adagrad, adadelta
# ---------------------------------------------------------------------------


def _nag_mom(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
             rescale_grad=1.0, clip_gradient=-1.0):
    g = _rescale_clip(grad, rescale_grad,
                      clip_gradient if clip_gradient > 0 else None, wd,
                      weight)
    mom_new = momentum * mom + g
    return weight - lr * (g + momentum * mom_new), mom_new


register_op("nag_mom_update", num_inputs=3, num_outputs=2,
            params=[Param("lr", float),
                    Param("momentum", float, 0.0),
                    Param("wd", float, 0.0),
                    Param("rescale_grad", float, 1.0),
                    Param("clip_gradient", float, -1.0)],
            differentiable=False)(_nag_mom)


def _mp_sgd(weight, grad, weight32, lr=0.01, wd=0.0, rescale_grad=1.0,
            clip_gradient=-1.0):
    g = _rescale_clip(grad.astype(jnp.float32), rescale_grad,
                      clip_gradient if clip_gradient > 0 else None, wd,
                      weight32)
    w32 = weight32 - lr * g
    return w32.astype(weight.dtype), w32


register_op("mp_sgd_update", num_inputs=3, num_outputs=2,
            params=[Param("lr", float), Param("wd", float, 0.0),
                    Param("rescale_grad", float, 1.0),
                    Param("clip_gradient", float, -1.0)],
            differentiable=False)(_mp_sgd)


def _mp_sgd_mom(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    g = _rescale_clip(grad.astype(jnp.float32), rescale_grad,
                      clip_gradient if clip_gradient > 0 else None, wd,
                      weight32)
    mom_new = momentum * mom - lr * g
    w32 = weight32 + mom_new
    return w32.astype(weight.dtype), mom_new, w32


register_op("mp_sgd_mom_update", num_inputs=4, num_outputs=3,
            params=[Param("lr", float),
                    Param("momentum", float, 0.0),
                    Param("wd", float, 0.0),
                    Param("rescale_grad", float, 1.0),
                    Param("clip_gradient", float, -1.0)],
            differentiable=False)(_mp_sgd_mom)


def _mp_nag_mom(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    g = _rescale_clip(grad.astype(jnp.float32), rescale_grad,
                      clip_gradient if clip_gradient > 0 else None, wd,
                      weight32)
    mom_new = momentum * mom + g
    w32 = weight32 - lr * (g + momentum * mom_new)
    return w32.astype(weight.dtype), mom_new, w32


register_op("mp_nag_mom_update", num_inputs=4, num_outputs=3,
            params=[Param("lr", float),
                    Param("momentum", float, 0.0),
                    Param("wd", float, 0.0),
                    Param("rescale_grad", float, 1.0),
                    Param("clip_gradient", float, -1.0)],
            differentiable=False)(_mp_nag_mom)


def _multi_mp_sgd(*arrays, lrs=(), wds=(), rescale_grad=1.0,
                  clip_gradient=-1.0, num_weights=0):
    n = len(arrays) // 3
    outs = []
    for i in range(n):
        w, g, w32 = arrays[i * 3], arrays[i * 3 + 1], arrays[i * 3 + 2]
        w16, w32n = _mp_sgd(w, g, w32, lr=lrs[i], wd=wds[i],
                            rescale_grad=rescale_grad,
                            clip_gradient=clip_gradient)
        outs.append(w16)
        outs.append(w32n)
    return tuple(outs)


register_op("multi_mp_sgd_update", num_inputs=-1,
            params=[Param("lrs", tuple, ()), Param("wds", tuple, ()),
                    Param("rescale_grad", float, 1.0),
                    Param("clip_gradient", float, -1.0),
                    Param("num_weights", int, 0)],
            num_outputs_fn=lambda attrs: 2 * int(attrs.get("num_weights") or 1),
            differentiable=False)(_multi_mp_sgd)


def _multi_mp_sgd_mom(*arrays, lrs=(), wds=(), momentum=0.0,
                      rescale_grad=1.0, clip_gradient=-1.0,
                      num_weights=0):
    n = len(arrays) // 4
    outs = []
    for i in range(n):
        w, g, mom, w32 = arrays[i * 4:(i + 1) * 4]
        w16, mom_new, w32n = _mp_sgd_mom(
            w, g, mom, w32, lr=lrs[i], momentum=momentum, wd=wds[i],
            rescale_grad=rescale_grad, clip_gradient=clip_gradient)
        outs += [w16, mom_new, w32n]
    return tuple(outs)


register_op("multi_mp_sgd_mom_update", num_inputs=-1,
            params=[Param("lrs", tuple, ()), Param("wds", tuple, ()),
                    Param("momentum", float, 0.0),
                    Param("rescale_grad", float, 1.0),
                    Param("clip_gradient", float, -1.0),
                    Param("num_weights", int, 0)],
            num_outputs_fn=lambda attrs: 3 * int(attrs.get("num_weights") or 1),
            differentiable=False)(_multi_mp_sgd_mom)


def _adagrad(weight, grad, history, lr=0.01, epsilon=1e-7, wd=0.0,
             rescale_grad=1.0, clip_gradient=-1.0):
    g = _rescale_clip(grad, rescale_grad,
                      clip_gradient if clip_gradient > 0 else None, wd,
                      weight)
    hist_new = history + jnp.square(g)
    return weight - lr * g / (jnp.sqrt(hist_new) + epsilon), hist_new


register_op("adagrad_update", num_inputs=3, num_outputs=2,
            params=[Param("lr", float),
                    Param("epsilon", float, 1e-7),
                    Param("wd", float, 0.0),
                    Param("rescale_grad", float, 1.0),
                    Param("clip_gradient", float, -1.0)],
            differentiable=False, aliases=("_sparse_adagrad_update",))(
    _adagrad)


def _adadelta(weight, grad, acc_g, acc_delta, rho=0.9, epsilon=1e-5,
              wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    g = _rescale_clip(grad, rescale_grad,
                      clip_gradient if clip_gradient > 0 else None, wd,
                      weight)
    acc_g_new = rho * acc_g + (1 - rho) * jnp.square(g)
    delta = jnp.sqrt(acc_delta + epsilon) / \
        jnp.sqrt(acc_g_new + epsilon) * g
    acc_delta_new = rho * acc_delta + (1 - rho) * jnp.square(delta)
    return weight - delta, acc_g_new, acc_delta_new


register_op("adadelta_update", num_inputs=4, num_outputs=3,
            params=[Param("rho", float, 0.9),
                    Param("epsilon", float, 1e-5),
                    Param("wd", float, 0.0),
                    Param("rescale_grad", float, 1.0),
                    Param("clip_gradient", float, -1.0)],
            differentiable=False)(_adadelta)


# ---------------------------------------------------------------------------
# legacy-surface tail: SoftmaxActivation (deprecated op kept for old
# symbols), *_v1 aliases, IdentityAttachKLSparseReg
# ---------------------------------------------------------------------------


def _softmax_activation(data, mode="instance"):
    """Deprecated ``SoftmaxActivation``†: instance mode = softmax over
    the flattened non-batch dims; channel mode = softmax over axis 1
    per spatial position."""
    if mode == "channel":
        return jax.nn.softmax(data, axis=1)
    flat = data.reshape(data.shape[0], -1)
    return jax.nn.softmax(flat, axis=-1).reshape(data.shape)


register_op("SoftmaxActivation",
            params=[Param("mode", str, "instance",
                          enum=("instance", "channel"))],
            aliases=("softmax_activation",))(_softmax_activation)


@jax.custom_vjp
def _id_kl_sparse(data, penalty_grad):
    return data


def _id_kl_fwd(data, penalty_grad):
    return data, penalty_grad


def _id_kl_bwd(penalty_grad, g):
    return g + penalty_grad, jnp.zeros_like(penalty_grad)


_id_kl_sparse.defvjp(_id_kl_fwd, _id_kl_bwd)


def _identity_attach_kl(data, sparseness_target=0.1, penalty=0.001,
                        momentum=0.9):
    """``IdentityAttachKLSparseReg``†: forward identity; backward adds
    the gradient of the KL sparsity penalty between the target rate and
    the mean activation (sigmoid-activation convention).  Functional
    form: the penalty gradient is computed from the CURRENT batch mean
    (the reference's moving average needs mutable aux state)."""
    rho_hat = jnp.clip(jnp.mean(data, axis=0), 1e-6, 1.0 - 1e-6)
    rho = sparseness_target
    dkl = penalty * (-rho / rho_hat + (1.0 - rho) / (1.0 - rho_hat))
    pg = jnp.broadcast_to(dkl / data.shape[0], data.shape)
    return _id_kl_sparse(data, pg.astype(data.dtype))


register_op("IdentityAttachKLSparseReg",
            params=[Param("sparseness_target", float, 0.1),
                    Param("penalty", float, 0.001),
                    Param("momentum", float, 0.9)])(_identity_attach_kl)


# ---------------------------------------------------------------------------
# image ops (src/operator/image/image_random.cc† — the mx.nd.image.*
# namespace backing gluon vision transforms)
# ---------------------------------------------------------------------------


def _image_to_tensor(x):
    """HWC uint8 [0,255] -> CHW float32 [0,1] (image.to_tensor†);
    batched NHWC -> NCHW."""
    xf = x.astype(jnp.float32) / 255.0
    if x.ndim == 3:
        return jnp.transpose(xf, (2, 0, 1))
    return jnp.transpose(xf, (0, 3, 1, 2))


register_op("_image_to_tensor", aliases=("image_to_tensor",))(
    _image_to_tensor)


def _image_normalize(x, mean=(0.0,), std=(1.0,)):
    """Channel-wise (x - mean) / std on CHW/NCHW floats
    (image.normalize†)."""
    m = jnp.asarray(mean, x.dtype).reshape(-1, 1, 1)
    s = jnp.asarray(std, x.dtype).reshape(-1, 1, 1)
    return (x - m) / s


register_op("_image_normalize", aliases=("image_normalize",),
            params=[Param("mean", tuple, (0.0,)),
                    Param("std", tuple, (1.0,))])(_image_normalize)


def _image_flip_lr(x):
    """Flip the width axis of HWC (or NHWC) images
    (image.flip_left_right†)."""
    return x[..., :, ::-1, :]


register_op("_image_flip_left_right",
            aliases=("image_flip_left_right",))(_image_flip_lr)


def _image_flip_tb(x):
    """Flip the height axis (image.flip_top_bottom†)."""
    return x[..., ::-1, :, :]


register_op("_image_flip_top_bottom",
            aliases=("image_flip_top_bottom",))(_image_flip_tb)


def _image_random_flip_lr(x, key):
    flip = jax.random.bernoulli(_img_key(key))
    return jnp.where(flip, x[..., :, ::-1, :], x)


def _img_key(key):
    from .ops_impl import _as_prng_key
    return _as_prng_key(key)


register_op("_image_random_flip_left_right", num_inputs=2,
            aliases=("image_random_flip_left_right",))(
    _image_random_flip_lr)


def _image_random_flip_tb(x, key):
    flip = jax.random.bernoulli(_img_key(key))
    return jnp.where(flip, x[..., ::-1, :, :], x)


register_op("_image_random_flip_top_bottom", num_inputs=2,
            aliases=("image_random_flip_top_bottom",))(
    _image_random_flip_tb)
