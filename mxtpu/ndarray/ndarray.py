"""NDArray — the core array type, async by construction.

Reference: ``src/ndarray/ndarray.cc``† + ``python/mxnet/ndarray/ndarray.py``†.
The reference's NDArray is lazy: every op is pushed to the dependency engine
with read/write vars and Python returns immediately; ``wait_to_read`` /
``asnumpy`` are the sync points where async exceptions re-raise
(``src/engine/threaded_engine.cc``†).

TPU-native: jax's dispatch already gives exactly these semantics — ops
enqueue XLA executables on the device stream and return futures
(jax.Array), with errors surfacing at block_until_ready.  So NDArray is a
thin mutable handle over a jax.Array plus autograd/tape state; there is no
hand-rolled engine to maintain (SURVEY.md §2.1-N5: "mostly subsumed").
NDArray is registered as a jax pytree so values flow through jit/vjp
transparently.
"""
from __future__ import annotations

import os
import struct
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError, env_flags
from ..context import Context, current_context

__all__ = ["NDArray", "array", "zeros", "ones", "full", "empty", "arange",
           "concat", "stack", "save", "load", "waitall", "from_numpy",
           "linspace", "eye"]

_DTYPE_ALIASES = {
    "float32": jnp.float32, "float16": jnp.float16,
    "float64": jnp.float64,  # mxlint: disable=dtype-hygiene (alias table)
    "bfloat16": jnp.bfloat16, "uint8": jnp.uint8, "int8": jnp.int8,
    "int32": jnp.int32, "int64": jnp.int64, "bool": jnp.bool_,
    "uint32": jnp.uint32, "uint64": jnp.uint64, "int16": jnp.int16,
}


def _as_jax_dtype(dtype) -> Any:
    if dtype is None:
        return jnp.dtype(env_flags.default_dtype)
    if isinstance(dtype, str):
        return jnp.dtype(_DTYPE_ALIASES.get(dtype, dtype))
    return jnp.dtype(dtype)


def _is_concrete(x) -> bool:
    return isinstance(x, (jax.Array, np.ndarray)) and not isinstance(
        x, jax.core.Tracer)


class NDArray:
    """Multi-dimensional array on a device context.

    Mutable handle semantics like the reference (``a[:] = b`` and in-place
    arithmetic rebind the underlying buffer); functional under the hood.
    """

    __slots__ = ("_data", "_ctx", "grad", "_grad_req", "_tape",
                 "_deferred_init", "__weakref__")

    def __init__(self, data, ctx: Optional[Context] = None, _placed=False):
        if isinstance(data, NDArray):
            data = data._data
        if ctx is not None and not _placed and _is_concrete(data):
            data = jax.device_put(data, ctx.jax_device)
        elif not isinstance(data, jax.Array) and _is_concrete(data):
            ctx = ctx or current_context()
            data = jax.device_put(jnp.asarray(data), ctx.jax_device)
        self._data = data
        self._ctx = ctx
        self.grad: Optional[NDArray] = None
        self._grad_req: str = "null"
        self._tape = None          # (TapeNode, out_index) set by autograd
        self._deferred_init = None

    # ------------------------------------------------------------------
    # basic properties
    # ------------------------------------------------------------------
    @property
    def data(self):
        """The underlying jax.Array (or tracer during jit tracing)."""
        return self._data

    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return np.dtype(str(self._data.dtype)) if str(self._data.dtype) != \
            "bfloat16" else self._data.dtype

    @property
    def size(self) -> int:
        return int(np.prod(self.shape)) if self.shape else 1

    @property
    def ndim(self) -> int:
        return self._data.ndim

    @property
    def context(self) -> Context:
        if self._ctx is not None:
            return self._ctx
        if _is_concrete(self._data) and isinstance(self._data, jax.Array):
            from ..context import device
            try:
                # prefer THIS process's shard device: global arrays
                # also span remote devices, which have no local Context
                devs = getattr(self._data.sharding,
                               "addressable_devices", None) or \
                    self._data.devices()
                return device(sorted(devs, key=lambda d: d.id)[0])
            except Exception:
                pass
        return current_context()

    ctx = context

    @property
    def stype(self) -> str:
        return "default"

    # ------------------------------------------------------------------
    # sync points (reference: WaitToRead / asnumpy; async errors re-raise
    # here, tested by test_exc_handling.py† in the reference suite)
    # ------------------------------------------------------------------
    def wait_to_read(self) -> None:
        if _is_concrete(self._data) and isinstance(self._data, jax.Array):
            self._data.block_until_ready()

    wait_to_write = wait_to_read

    def asnumpy(self) -> np.ndarray:
        return np.asarray(self._data)

    def asscalar(self):
        if self.size != 1:
            raise MXNetError("the array is not scalar")
        return self.asnumpy().reshape(()).item()

    def item(self):
        return self.asscalar()

    def tolist(self):
        return self.asnumpy().tolist()

    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a

    def __dlpack__(self, **kw):
        return self._data.__dlpack__(**kw)

    # ------------------------------------------------------------------
    # autograd handles (python/mxnet/ndarray/ndarray.py† attach_grad)
    # ------------------------------------------------------------------
    def attach_grad(self, grad_req: str = "write", stype=None) -> None:
        if grad_req not in ("write", "add", "null"):
            raise MXNetError(f"invalid grad_req {grad_req}")
        self._grad_req = grad_req
        self.grad = zeros_like(self) if grad_req != "null" else None
        self._tape = None

    def detach(self) -> "NDArray":
        out = NDArray(self._data, self._ctx, _placed=True)
        return out

    def backward(self, out_grad: Optional["NDArray"] = None,
                 retain_graph: bool = False, train_mode: bool = True) -> None:
        from .. import autograd
        autograd.backward([self], [out_grad] if out_grad is not None
                          else None, retain_graph=retain_graph,
                          train_mode=train_mode)

    # ------------------------------------------------------------------
    # conversion / placement
    # ------------------------------------------------------------------
    def astype(self, dtype, copy: bool = True) -> "NDArray":
        jd = _as_jax_dtype(dtype)
        if not copy and self._data.dtype == jd:
            return self
        from . import _invoke_op
        return _invoke_op("cast", self, dtype=str(jd))

    def copyto(self, other: Union["NDArray", Context]) -> "NDArray":
        if isinstance(other, Context):
            return NDArray(jax.device_put(self._data, other.jax_device),
                           other, _placed=True)
        other._data = jax.device_put(self._data,
                                     other.context.jax_device)
        return other

    def copy(self) -> "NDArray":
        return NDArray(jnp.array(self._data), self._ctx, _placed=True)

    def as_in_context(self, ctx: Context) -> "NDArray":
        if ctx == self.context:
            return self
        return self.copyto(ctx)

    as_in_ctx = as_in_context

    def as_nd_ndarray(self):
        return self

    # ------------------------------------------------------------------
    # mutation (engine write-dep semantics are trivially safe here:
    # rebinding _data after the functional update preserves program order)
    # ------------------------------------------------------------------
    def __setitem__(self, key, value) -> None:
        if isinstance(value, NDArray):
            value = value._data
        if key is None or (isinstance(key, slice) and key == slice(None)):
            self._data = jnp.broadcast_to(
                jnp.asarray(value, dtype=self._data.dtype),
                self.shape) + jnp.zeros_like(self._data)
        else:
            self._data = self._data.at[key].set(
                jnp.asarray(value, dtype=self._data.dtype))

    def __getitem__(self, key):
        from . import _invoke_getitem
        return _invoke_getitem(self, key)

    # ------------------------------------------------------------------
    # operator sugar — routed through the op registry so autograd sees them
    # ------------------------------------------------------------------
    def _binop(self, other, opname, reverse=False):
        from . import _invoke_op
        if isinstance(other, (int, float, bool, np.number)):
            # result_type promotion (python float vs int array must give a
            # float op, e.g. int_array >= 1.5 — not truncate to >= 1)
            other = NDArray(jnp.asarray(
                other, dtype=jnp.result_type(self._data.dtype, other)))
        a, b = (other, self) if reverse else (self, other)
        return _invoke_op(opname, a, b)

    def __add__(self, o): return self._binop(o, "broadcast_add")
    def __radd__(self, o): return self._binop(o, "broadcast_add", True)
    def __sub__(self, o): return self._binop(o, "broadcast_sub")
    def __rsub__(self, o): return self._binop(o, "broadcast_sub", True)
    def __mul__(self, o): return self._binop(o, "broadcast_mul")
    def __rmul__(self, o): return self._binop(o, "broadcast_mul", True)
    def __truediv__(self, o): return self._binop(o, "broadcast_div")
    def __rtruediv__(self, o): return self._binop(o, "broadcast_div", True)
    def __mod__(self, o): return self._binop(o, "broadcast_mod")
    def __rmod__(self, o): return self._binop(o, "broadcast_mod", True)
    def __pow__(self, o): return self._binop(o, "broadcast_power")
    def __rpow__(self, o): return self._binop(o, "broadcast_power", True)
    def __matmul__(self, o): return self._binop(o, "matmul")
    def __neg__(self):
        from . import _invoke_op
        return _invoke_op("negative", self)
    def __abs__(self):
        from . import _invoke_op
        return _invoke_op("abs", self)

    def __eq__(self, o): return self._binop(o, "broadcast_equal")
    def __ne__(self, o): return self._binop(o, "broadcast_not_equal")
    def __lt__(self, o): return self._binop(o, "broadcast_lesser")
    def __le__(self, o): return self._binop(o, "broadcast_lesser_equal")
    def __gt__(self, o): return self._binop(o, "broadcast_greater")
    def __ge__(self, o): return self._binop(o, "broadcast_greater_equal")

    __hash__ = None  # mutable container semantics, like the reference

    def __iadd__(self, o):
        r = self.__add__(o)
        self._data = r._data
        return self

    def __isub__(self, o):
        r = self.__sub__(o)
        self._data = r._data
        return self

    def __imul__(self, o):
        r = self.__mul__(o)
        self._data = r._data
        return self

    def __itruediv__(self, o):
        r = self.__truediv__(o)
        self._data = r._data
        return self

    def __bool__(self):
        if self.size == 1:
            return bool(self.asscalar())
        raise MXNetError("ambiguous truth value of multi-element NDArray")

    def __len__(self) -> int:
        if not self.shape:
            raise MXNetError("len() of 0-d array")
        return self.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __repr__(self) -> str:
        if _is_concrete(self._data):
            return f"\n{self.asnumpy()}\n<NDArray {self.shape} " \
                   f"@{self.context} {self._data.dtype}>"
        return f"<NDArray {self.shape} {self._data.dtype} (traced)>"

    # ------------------------------------------------------------------
    # method mirrors of common ops (populated further in __init__.py)
    # ------------------------------------------------------------------
    def reshape(self, *shape, **kwargs):
        from . import _invoke_op
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return _invoke_op("reshape", self, shape=shape)

    def transpose(self, *axes_pos, axes=None):
        from . import _invoke_op
        if axes_pos and axes is not None:
            raise MXNetError("pass axes positionally or by keyword")
        if axes is None:
            axes = axes_pos
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return _invoke_op("transpose", self,
                          axes=tuple(axes) if axes else None)

    @property
    def T(self):
        return self.transpose()

    def expand_dims(self, axis):
        from . import _invoke_op
        return _invoke_op("expand_dims", self, axis=axis)

    def squeeze(self, axis=None):
        from . import _invoke_op
        return _invoke_op("squeeze", self, axis=axis)

    def flatten(self):
        from . import _invoke_op
        return _invoke_op("flatten", self)

    def sum(self, axis=None, keepdims=False):
        from . import _invoke_op
        return _invoke_op("sum", self, axis=axis, keepdims=keepdims)

    def mean(self, axis=None, keepdims=False):
        from . import _invoke_op
        return _invoke_op("mean", self, axis=axis, keepdims=keepdims)

    def max(self, axis=None, keepdims=False):
        from . import _invoke_op
        return _invoke_op("max", self, axis=axis, keepdims=keepdims)

    def min(self, axis=None, keepdims=False):
        from . import _invoke_op
        return _invoke_op("min", self, axis=axis, keepdims=keepdims)

    def argmax(self, axis=None, keepdims=False):
        from . import _invoke_op
        return _invoke_op("argmax", self, axis=axis, keepdims=keepdims)

    def argmin(self, axis=None, keepdims=False):
        from . import _invoke_op
        return _invoke_op("argmin", self, axis=axis, keepdims=keepdims)

    def clip(self, a_min, a_max):
        from . import _invoke_op
        return _invoke_op("clip", self, a_min=float(a_min),
                          a_max=float(a_max))

    def abs(self):
        return self.__abs__()

    def slice_axis(self, axis, begin, end):
        from . import _invoke_op
        return _invoke_op("slice_axis", self, axis=axis, begin=begin, end=end)

    def tostype(self, stype):
        if stype != "default":
            from .sparse import _cast_storage
            return _cast_storage(self, stype)
        return self


def zeros_like(a: NDArray) -> NDArray:
    return NDArray(jnp.zeros_like(a._data), a._ctx, _placed=True)


# ----------------------------------------------------------------------
# pytree registration: NDArray flows through jit / vjp / shard_map
# ----------------------------------------------------------------------
def _flatten(x: NDArray):
    return (x._data,), None


def _unflatten(aux, children):
    return NDArray(children[0], None, _placed=True)


jax.tree_util.register_pytree_node(NDArray, _flatten, _unflatten)


# ----------------------------------------------------------------------
# creation routines (python/mxnet/ndarray/ndarray.py† equivalents)
# ----------------------------------------------------------------------
def array(source, ctx: Optional[Context] = None, dtype=None) -> NDArray:
    if isinstance(source, NDArray):
        src = source._data
    elif isinstance(source, (np.ndarray, jax.Array)):
        src = source
    else:
        # python scalars / nested lists default to float32 like the
        # reference (mx.nd.array([1,2]) is float32 there)
        src = np.asarray(source)
        # detection-to-DOWNCAST, not f64 math
        if src.dtype == np.float64 or src.dtype == np.int64:  # mxlint: disable=dtype-hygiene
            src = src.astype(env_flags.default_dtype)
    if dtype is not None:
        jd = _as_jax_dtype(dtype)
    else:
        sd = str(src.dtype)
        # 64-bit narrows to 32-bit (jax x64 disabled by default)
        jd = {"float64": jnp.float32, "int64": jnp.int32,
              "uint64": jnp.uint32}.get(sd, src.dtype)
    arr = jnp.asarray(src, dtype=jd)
    ctx = ctx or current_context()
    return NDArray(arr, ctx)


def from_numpy(a: np.ndarray, zero_copy: bool = False) -> NDArray:
    return array(a)


def zeros(shape, ctx=None, dtype=None) -> NDArray:
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return NDArray(jnp.zeros(shape, _as_jax_dtype(dtype)),
                   ctx or current_context())


def ones(shape, ctx=None, dtype=None) -> NDArray:
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return NDArray(jnp.ones(shape, _as_jax_dtype(dtype)),
                   ctx or current_context())


def full(shape, val, ctx=None, dtype=None) -> NDArray:
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return NDArray(jnp.full(shape, val, _as_jax_dtype(dtype)),
                   ctx or current_context())


def empty(shape, ctx=None, dtype=None) -> NDArray:
    return zeros(shape, ctx, dtype)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None,
           dtype=None) -> NDArray:
    a = jnp.arange(start, stop, step, _as_jax_dtype(dtype))
    if repeat > 1:
        a = jnp.repeat(a, repeat)
    return NDArray(a, ctx or current_context())


def linspace(start, stop, num, endpoint=True, ctx=None, dtype=None):
    return NDArray(jnp.linspace(start, stop, num, endpoint=endpoint,
                                dtype=_as_jax_dtype(dtype)),
                   ctx or current_context())


def eye(N, M=None, k=0, ctx=None, dtype=None):
    return NDArray(jnp.eye(N, M, k, _as_jax_dtype(dtype)),
                   ctx or current_context())


def concat(*arrays, dim: int = 1) -> NDArray:
    from . import _invoke_op
    return _invoke_op("concat", *arrays, dim=dim)


def stack(*arrays, axis: int = 0) -> NDArray:
    from . import _invoke_op
    return _invoke_op("stack", *arrays, axis=axis)


def waitall() -> None:
    """Reference ``mx.nd.waitall()``† (Engine::WaitForAll)."""
    for d in jax.live_arrays():
        try:
            d.block_until_ready()
        except Exception:
            raise
    (jax.device_put(0.0) + 0).block_until_ready()


# ----------------------------------------------------------------------
# save / load — named-tensor checkpoint files
# Two on-disk formats:
#   * "legacy" — byte-parity with the reference's dmlc::Stream binary
#     (src/ndarray/ndarray.cc† Save/Load, the .params format), so
#     reference-era checkpoints interchange directly
#     (mxtpu/ndarray/legacy_format.py);
#   * "mxtpu" — MXTPU01 header + numpy .npz payload (the native
#     container; loaders accept plain .npz/.npy too).
# load() auto-detects by magic.  save() format: the ``format=`` arg,
# else MXTPU_SAVE_FORMAT env, else by file extension (.params →
# legacy), else mxtpu.
# ----------------------------------------------------------------------
_SAVE_MAGIC = b"MXTPU01\n"


def _pick_format(fname: str, fmt) -> str:
    from .. import knobs
    fmt = fmt or knobs.get("MXTPU_SAVE_FORMAT") or \
        ("legacy" if fname.endswith(".params") else "mxtpu")
    if fmt not in ("legacy", "mxtpu"):
        raise MXNetError(f"unknown save format {fmt!r}; "
                         f"choices: legacy, mxtpu")
    return fmt


def save(fname: str, data, format=None) -> None:
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, (list, tuple)):
        names, arrays = None, [a.asnumpy() for a in data]
    elif isinstance(data, dict):
        names = list(data.keys())
        arrays = [v.asnumpy() for v in data.values()]
    else:
        raise MXNetError("save expects NDArray, list or dict of NDArray")
    if _pick_format(fname, format) == "legacy":
        from . import legacy_format
        blob = legacy_format.dumps(
            arrays if names is None else dict(zip(names, arrays)))
        with open(fname, "wb") as f:
            f.write(blob)
        return
    import io as _io
    buf = _io.BytesIO()
    if names is None:
        names = [str(i) for i in range(len(arrays))]
    np.savez(buf, **dict(zip(names, arrays)))
    with open(fname, "wb") as f:
        f.write(_SAVE_MAGIC)
        f.write(buf.getvalue())


def loads(blob: bytes):
    """Parse a checkpoint payload from memory — same auto-detection
    as :func:`load` (legacy dmlc magic / MXTPU01 / bare npz)."""
    from . import legacy_format
    if legacy_format.is_legacy(blob[:8]):
        arrays, names = legacy_format.loads(blob)
        if names:
            return {n: array(a) for n, a in zip(names, arrays)}
        return [array(a) for a in arrays]
    import io as _io
    buf = _io.BytesIO(blob)  # copy-on-write wrap — no duplication
    if blob[:len(_SAVE_MAGIC)] == _SAVE_MAGIC:
        buf.seek(len(_SAVE_MAGIC))
    npz = np.load(buf, allow_pickle=False)
    keys = list(npz.keys())
    if all(k.isdigit() for k in keys):
        # list payloads always load as a list, even length-1, matching
        # the reference's MXNDArrayLoad contract
        return [array(npz[k]) for k in sorted(keys, key=int)]
    return {k: array(npz[k]) for k in keys}


def load(fname: str):
    with open(fname, "rb") as f:
        return loads(f.read())
