"""Registered sampling ops (``src/operator/random/*``†).

The reference's samplers are graph ops drawing from per-context RNG
resources; the TPU-native form is counter-based — every op takes an
explicit PRNG ``key`` tensor as its FIRST input (the pattern Dropout
and shuffle already use), so the same rule is pure under jit and usable
from symbols.  The stateful ``mx.nd.random.*`` convenience surface
(``mxtpu/ndarray/random.py``) remains the user-facing API that feeds
keys from the per-context stream.

``_random_*`` draw i.i.d. samples of a given static shape from scalar
distribution params; ``_sample_*`` take per-row param tensors and draw
``shape`` samples per row (reference semantics).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..ops.registry import Param, register_op
from .ops_impl import _as_prng_key


def _dt(dtype):
    return jnp.dtype(dtype or "float32")


def _shape(shape):
    return (shape,) if isinstance(shape, int) else tuple(shape or (1,))


# -- _random_* : scalar params, static shape ---------------------------

def _r(name, fn, params, differentiable=False):
    register_op(name, num_inputs=1, differentiable=differentiable,
                params=[Param("shape", tuple, (1,)),
                        Param("dtype", str, None)] + params)(fn)


_r("_random_uniform",
   lambda key, shape=(1,), dtype=None, low=0.0, high=1.0:
   jax.random.uniform(_as_prng_key(key), _shape(shape), _dt(dtype),
                      low, high),
   [Param("low", float, 0.0), Param("high", float, 1.0)])

_r("_random_normal",
   lambda key, shape=(1,), dtype=None, loc=0.0, scale=1.0:
   loc + scale * jax.random.normal(_as_prng_key(key), _shape(shape),
                                   _dt(dtype)),
   [Param("loc", float, 0.0), Param("scale", float, 1.0)])

_r("_random_gamma",
   lambda key, shape=(1,), dtype=None, alpha=1.0, beta=1.0:
   jax.random.gamma(_as_prng_key(key), alpha, _shape(shape),
                    _dt(dtype)) * beta,
   [Param("alpha", float, 1.0), Param("beta", float, 1.0)])

_r("_random_exponential",
   lambda key, shape=(1,), dtype=None, lam=1.0:
   jax.random.exponential(_as_prng_key(key), _shape(shape),
                          _dt(dtype)) / lam,
   [Param("lam", float, 1.0)])

_r("_random_poisson",
   lambda key, shape=(1,), dtype=None, lam=1.0:
   jax.random.poisson(_as_prng_key(key), lam, _shape(shape)).astype(
       _dt(dtype)),
   [Param("lam", float, 1.0)])


def _neg_binomial(key, shape=(1,), dtype=None, k=1, p=1.0):
    key1, key2 = jax.random.split(_as_prng_key(key))
    lam = jax.random.gamma(key1, k, _shape(shape)) * (1 - p) / p
    return jax.random.poisson(key2, lam, _shape(shape)).astype(
        _dt(dtype))


_r("_random_negative_binomial", _neg_binomial,
   [Param("k", int, 1), Param("p", float, 1.0)])


def _gen_neg_binomial(key, shape=(1,), dtype=None, mu=1.0, alpha=1.0):
    key1, key2 = jax.random.split(_as_prng_key(key))
    r = 1.0 / alpha
    p = r / (r + mu)
    lam = jax.random.gamma(key1, r, _shape(shape)) * (1 - p) / p
    return jax.random.poisson(key2, lam, _shape(shape)).astype(
        _dt(dtype))


_r("_random_generalized_negative_binomial", _gen_neg_binomial,
   [Param("mu", float, 1.0), Param("alpha", float, 1.0)])

_r("_random_randint",
   lambda key, shape=(1,), dtype=None, low=0, high=1:
   jax.random.randint(_as_prng_key(key), _shape(shape), low, high,
                      _dt(dtype or "int32")),
   [Param("low", int, 0), Param("high", int, 1)])


# -- _sample_* : per-row param tensors ---------------------------------
# output shape = params.shape + shape (reference convention)

def _s(name, fn, num_inputs):
    register_op(name, num_inputs=num_inputs, differentiable=False,
                params=[Param("shape", tuple, ()),
                        Param("dtype", str, None)])(fn)


def _draw_shape(param, shape):
    return tuple(param.shape) + _shape(shape) if shape else \
        tuple(param.shape)


_s("_sample_uniform",
   lambda key, low, high, shape=(), dtype=None:
   jax.random.uniform(_as_prng_key(key), _draw_shape(low, shape),
                      _dt(dtype))
   * (high - low).reshape(low.shape + (1,) * len(_shape(shape))
                          if shape else low.shape)
   + low.reshape(low.shape + (1,) * len(_shape(shape))
                 if shape else low.shape), 3)

_s("_sample_normal",
   lambda key, mu, sigma, shape=(), dtype=None:
   mu.reshape(_bshape(mu, shape)) + sigma.reshape(_bshape(sigma, shape))
   * jax.random.normal(_as_prng_key(key), _draw_shape(mu, shape),
                       _dt(dtype)), 3)


def _bshape(param, shape):
    return tuple(param.shape) + (1,) * (len(_shape(shape)) if shape
                                        else 0)


def _sample_gamma(key, alpha, beta, shape=(), dtype=None):
    a = jnp.broadcast_to(alpha.reshape(_bshape(alpha, shape)),
                         _draw_shape(alpha, shape))
    return jax.random.gamma(_as_prng_key(key), a, dtype=_dt(dtype)) \
        * beta.reshape(_bshape(beta, shape))


_s("_sample_gamma", _sample_gamma, 3)

_s("_sample_exponential",
   lambda key, lam, shape=(), dtype=None:
   jax.random.exponential(_as_prng_key(key), _draw_shape(lam, shape),
                          _dt(dtype)) / lam.reshape(_bshape(lam, shape)),
   2)


def _sample_poisson(key, lam, shape=(), dtype=None):
    lam_b = jnp.broadcast_to(lam.reshape(_bshape(lam, shape)),
                             _draw_shape(lam, shape))
    return jax.random.poisson(_as_prng_key(key), lam_b).astype(
        _dt(dtype))


_s("_sample_poisson", _sample_poisson, 2)


def _sample_negative_binomial(key, k, p, shape=(), dtype=None):
    key1, key2 = jax.random.split(_as_prng_key(key))
    kk = jnp.broadcast_to(k.reshape(_bshape(k, shape)),
                          _draw_shape(k, shape)).astype(jnp.float32)
    pp = jnp.broadcast_to(p.reshape(_bshape(p, shape)),
                          _draw_shape(p, shape))
    lam = jax.random.gamma(key1, kk) * (1 - pp) / pp
    return jax.random.poisson(key2, lam).astype(_dt(dtype))


_s("_sample_negative_binomial", _sample_negative_binomial, 3)


def _sample_gen_neg_binomial(key, mu, alpha, shape=(), dtype=None):
    key1, key2 = jax.random.split(_as_prng_key(key))
    mm = jnp.broadcast_to(mu.reshape(_bshape(mu, shape)),
                          _draw_shape(mu, shape))
    aa = jnp.broadcast_to(alpha.reshape(_bshape(alpha, shape)),
                          _draw_shape(alpha, shape))
    r = 1.0 / aa
    p = r / (r + mm)
    lam = jax.random.gamma(key1, r) * (1 - p) / p
    return jax.random.poisson(key2, lam).astype(_dt(dtype))


_s("_sample_generalized_negative_binomial", _sample_gen_neg_binomial, 3)


def _sample_multinomial(key, data, shape=(), get_prob=False,
                        dtype="int32"):
    logits = jnp.log(jnp.maximum(data, 1e-30))
    n = 1
    for s in _shape(shape) if shape else ():
        n *= s
    if data.ndim == 1:
        draw = jax.random.categorical(_as_prng_key(key), logits,
                                      shape=(n,) if shape else ())
    else:
        draw = jax.random.categorical(
            _as_prng_key(key), logits[:, None, :] if shape else logits,
            axis=-1,
            shape=(data.shape[0], n) if shape else (data.shape[0],))
    draw = draw.astype(jnp.dtype(dtype))
    # reference output shape is data.shape[:-1] + shape (a
    # multi-dimensional `shape` is NOT flattened into one axis)
    out_shape = data.shape[:-1] + tuple(_shape(shape) if shape else ())
    if get_prob:
        lsm = jax.nn.log_softmax(logits, axis=-1)
        idx = draw.astype(jnp.int32)
        if data.ndim == 1:
            lp = lsm[idx]
        else:
            lp = jnp.take_along_axis(
                lsm, idx.reshape(data.shape[0], -1), axis=-1
            ).reshape(draw.shape)
        return draw.reshape(out_shape), lp.reshape(out_shape)
    return draw.reshape(out_shape)


register_op("_sample_multinomial", num_inputs=2, differentiable=False,
            params=[Param("shape", tuple, ()),
                    Param("get_prob", bool, False),
                    Param("dtype", str, "int32")],
            num_outputs_fn=lambda attrs:
            2 if attrs.get("get_prob") else 1)(_sample_multinomial)


def _sample_unique_zipfian(key, range_max=0, shape=()):
    """Log-uniform (zipfian) candidate sampler
    (``_sample_unique_zipfian``†).  DIVERGENCE: sampled WITH
    replacement (static shapes — true rejection sampling is
    data-dependent); returns (samples, expected_counts) like the
    reference."""
    sh = _shape(shape)
    u = jax.random.uniform(_as_prng_key(key), sh)
    k = jnp.floor(jnp.exp(u * jnp.log(float(range_max) + 1.0))) - 1.0
    k = jnp.clip(k, 0, range_max - 1).astype(jnp.int64)
    # P(k) = log((k+2)/(k+1)) / log(range_max + 1)
    prob = jnp.log((k + 2.0) / (k + 1.0)) / jnp.log(
        float(range_max) + 1.0)
    n_draws = 1
    for s in sh:
        n_draws *= s
    expected = prob * n_draws
    return k, expected


register_op("_sample_unique_zipfian", num_inputs=1, num_outputs=2,
            differentiable=False,
            params=[Param("range_max", int, 0),
                    Param("shape", tuple, ())])(_sample_unique_zipfian)
