"""Reference-binary ``.params`` serialization (dmlc::Stream layout).

Byte-level parity with the reference's NDArray list files
(``src/ndarray/ndarray.cc``† ``NDArray::Save/Load``, framed by
``MXNDArraySave``† in ``src/c_api/c_api.cc``†), so checkpoints written
by the 2018-era framework load here directly and vice versa:

    uint64  kMXAPINDArrayListMagic = 0x112
    uint64  reserved = 0
    uint64  n_arrays, then per array the NDArray record below
    uint64  n_names,  then per name uint64 length + raw bytes

NDArray record (dense):

    uint32  magic: 0xF993FAC9 (V2 — what the reference era writes) or
            0xF993FACA (V3, written by later 1.x; accepted on read)
    int32   storage type (0 = dense; sparse records are rejected with
            guidance — the TPU port stores row_sparse/csr densely)
    uint32  ndim, then ndim dims as little-endian int64 — TShape
            serializes dim_t (int64) for BOTH V2 and V3; only the
            pre-V1 legacy layout used uint32 dims
    int32   dev_type, int32 dev_id   (context; ignored on load — the
            array lands on the current device)
    int32   type_flag (mshadow order: 0=f32 1=f64 2=f16 3=u8 4=i32
            5=i8 6=i64)
    raw     little-endian data bytes (size * dtype itemsize)

Everything is little-endian, matching dmlc on x86/ARM.
"""
from __future__ import annotations

import math
import struct
import warnings
from typing import Dict, List, Sequence, Tuple, Union

import numpy as np

from ..base import MXNetError

LIST_MAGIC = 0x112
V2_MAGIC = 0xF993FAC9
V3_MAGIC = 0xF993FACA

# mshadow type_flag ↔ numpy (reference mshadow/base.h† TypeFlag)
_TYPE_FLAG_TO_NP = {0: np.float32, 1: np.float64, 2: np.float16,  # mxlint: disable=dtype-hygiene (mshadow table)
                    3: np.uint8, 4: np.int32, 5: np.int8, 6: np.int64}
_NP_TO_TYPE_FLAG = {np.dtype(v): k for k, v in _TYPE_FLAG_TO_NP.items()}


def _write_arr(out: List[bytes], a: np.ndarray) -> None:
    # ascontiguousarray promotes 0-d to 1-d — restore the true shape
    a = np.ascontiguousarray(a).reshape(np.shape(a))
    if a.dtype == np.bool_:
        a = a.astype(np.uint8)
    flag = _NP_TO_TYPE_FLAG.get(np.dtype(a.dtype))
    if flag is None:
        raise MXNetError(
            f"dtype {a.dtype} has no reference type_flag; cast to one "
            f"of {sorted(str(np.dtype(t)) for t in _NP_TO_TYPE_FLAG)}")
    out.append(struct.pack("<I", V2_MAGIC))
    out.append(struct.pack("<i", 0))  # dense storage
    out.append(struct.pack("<I", a.ndim))
    out.append(struct.pack(f"<{a.ndim}q", *a.shape))
    out.append(struct.pack("<ii", 1, 0))  # cpu(0) context
    out.append(struct.pack("<i", flag))
    out.append(a.astype(a.dtype.newbyteorder("<"), copy=False).tobytes())


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def take(self, n: int) -> bytes:
        if n < 0:
            raise MXNetError(
                f"negative read of {n} bytes at {self.pos}; "
                f"corrupt stream?")
        if self.pos + n > len(self.data):
            raise MXNetError(
                f"truncated .params stream at byte {self.pos} "
                f"(wanted {n} more of {len(self.data)})")
        b = self.data[self.pos:self.pos + n]
        self.pos += n
        return b

    def u32(self) -> int:
        return struct.unpack("<I", self.take(4))[0]

    def i32(self) -> int:
        return struct.unpack("<i", self.take(4))[0]

    def u64(self) -> int:
        return struct.unpack("<Q", self.take(8))[0]


def _read_arr(r: _Reader, v2_dims64: bool = True) -> np.ndarray:
    magic = r.u32()
    if magic not in (V2_MAGIC, V3_MAGIC):
        raise MXNetError(
            f"bad NDArray magic 0x{magic:08x} (pre-V2 legacy streams "
            f"are not supported; re-save with a 1.x reference build)")
    stype = r.i32()
    if stype != 0:
        raise MXNetError(
            f"sparse storage type {stype} in .params; the TPU port "
            f"stores sparse densely — convert with tostype('default') "
            f"before saving")
    ndim = r.u32()
    if ndim > 32:
        raise MXNetError(f"implausible ndim {ndim}; corrupt stream?")
    if magic == V2_MAGIC and not v2_dims64:
        # pre-2026-07-30 mxtpu builds wrote V2 dims as uint32 (a bug —
        # the reference's dim_t is int64); this branch re-reads those
        # self-written files when the int64 whole-stream parse failed
        shape = struct.unpack(f"<{ndim}I", r.take(4 * ndim))
    else:
        shape = struct.unpack(f"<{ndim}q", r.take(8 * ndim))
        if any(d < 0 for d in shape):
            raise MXNetError(
                f"negative dim in shape {shape}; corrupt stream?")
    r.i32()  # dev_type — arrays always land on the current device
    r.i32()  # dev_id
    flag = r.i32()
    np_dtype = _TYPE_FLAG_TO_NP.get(flag)
    if np_dtype is None:
        raise MXNetError(f"unknown type_flag {flag} in .params")
    size = math.prod(shape)
    dt = np.dtype(np_dtype).newbyteorder("<")
    nbytes = size * dt.itemsize
    if r.pos + nbytes > len(r.data):
        raise MXNetError(
            f"truncated .params stream at byte {r.pos} "
            f"(wanted {nbytes} more of {len(r.data)})")
    # zero-copy view into the blob (converted only on big-endian hosts)
    arr = np.frombuffer(r.data, dtype=dt, count=size, offset=r.pos)
    r.pos += nbytes
    if arr.dtype != np.dtype(np_dtype):
        arr = arr.astype(np_dtype)
    return arr.reshape(shape)


def dumps(payload: Union[Dict[str, np.ndarray],
                         Sequence[np.ndarray]]) -> bytes:
    """Serialize named (dict) or anonymous (list) arrays to the
    reference binary layout."""
    if isinstance(payload, dict):
        names = list(payload.keys())
        arrays = [payload[n] for n in names]
    else:
        names = []
        arrays = list(payload)
    out: List[bytes] = [struct.pack("<QQ", LIST_MAGIC, 0),
                        struct.pack("<Q", len(arrays))]
    for a in arrays:
        _write_arr(out, np.asarray(a))
    out.append(struct.pack("<Q", len(names)))
    for n in names:
        nb = n.encode("utf-8")
        out.append(struct.pack("<Q", len(nb)))
        out.append(nb)
    return b"".join(out)


def _loads_impl(data: bytes,
                v2_dims64: bool) -> Tuple[List[np.ndarray], List[str]]:
    r = _Reader(data)
    magic = r.u64()
    if magic != LIST_MAGIC:
        raise MXNetError(
            f"not a reference .params stream (list magic "
            f"0x{magic:016x} != 0x{LIST_MAGIC:x})")
    r.u64()  # reserved
    n = r.u64()
    if n > 10 ** 7:
        raise MXNetError(f"implausible array count {n}; corrupt file?")
    arrays = [_read_arr(r, v2_dims64) for _ in range(n)]
    n_names = r.u64()
    if n_names not in (0, n):
        raise MXNetError(
            f"name count {n_names} does not match array count {n}")
    names = []
    for _ in range(n_names):
        ln = r.u64()
        try:
            names.append(r.take(ln).decode("utf-8"))
        except UnicodeDecodeError as e:
            raise MXNetError(f"undecodable name in .params: {e}") \
                from None
    if r.pos != len(data):
        raise MXNetError(
            f"{len(data) - r.pos} trailing bytes after .params "
            f"payload; corrupt stream?")
    return arrays, names


def loads(data: bytes) -> Tuple[List[np.ndarray], List[str]]:
    """Parse a reference binary stream → (arrays, names); names is
    empty for anonymous list saves.

    Tries the correct layout first (V2/V3 dims as int64 — the
    reference's dim_t).  If the WHOLE stream fails to parse that way,
    retries with uint32 V2 dims, the layout mxtpu builds before
    2026-07-30 wrote, and warns.  Whole-stream validation (record
    tails, payload sizes, name section, exact end-of-stream) makes the
    two layouts unambiguous in practice."""
    try:
        return _loads_impl(data, v2_dims64=True)
    except MXNetError as e:
        try:
            out = _loads_impl(data, v2_dims64=False)
        except MXNetError:
            raise e from None
        warnings.warn(
            "loading a .params stream with uint32 V2 dims (written by "
            "a pre-fix mxtpu build); re-save it to get the "
            "reference-compatible int64 layout", stacklevel=2)
        return out


def is_legacy(head: bytes) -> bool:
    """True if the first 8 bytes carry the reference list magic."""
    return len(head) >= 8 and \
        struct.unpack("<Q", head[:8])[0] == LIST_MAGIC
