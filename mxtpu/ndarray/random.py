"""Random sampling ops (``mx.nd.random``).

Reference: ``src/operator/random/``† (samplers over per-context stateful
RNG resources from ``src/resource.cc``†) and ``python/mxnet/random.py``†.

TPU-native: counter-based threefry PRNG.  A process-global key stream per
context preserves the reference's *stateful* seeding API
(``mx.random.seed``) on top of jax's functional keys (SURVEY.md §7 hard
part 5 — statistical parity, not bit parity).
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..base import env_flags
from ..context import Context, current_context
from .ndarray import NDArray, _as_jax_dtype

__all__ = ["seed", "uniform", "normal", "randn", "gamma", "exponential",
           "poisson", "negative_binomial", "generalized_negative_binomial",
           "multinomial", "shuffle", "randint", "bernoulli"]

_LOCK = threading.Lock()
_KEYS: Dict[str, jax.Array] = {}
_DEFAULT_SEED = 0


class _TraceKeyProvider:
    """During a hybridized trace, RNG keys derive from a traced input key
    (fold_in with a per-trace counter) instead of the global stream, so
    each compiled call sees fresh randomness from its key argument."""

    def __init__(self, base_key):
        self.base_key = base_key
        self.counter = 0

    def next(self):
        k = jax.random.fold_in(self.base_key, self.counter)
        self.counter += 1
        return k


_TRACE_PROVIDERS: list = []


def _push_trace_provider(p: _TraceKeyProvider) -> None:
    _TRACE_PROVIDERS.append(p)


def _pop_trace_provider() -> None:
    _TRACE_PROVIDERS.pop()


def _ctx_key(ctx: Optional[Context]) -> str:
    ctx = ctx or current_context()
    return f"{ctx.device_type}:{ctx.device_id}"


def seed(seed_state: int, ctx: str | Context = "all") -> None:
    """``mx.random.seed``† — reseed the global stream (all ctxs or one)."""
    global _DEFAULT_SEED
    with _LOCK:
        if ctx == "all":
            _DEFAULT_SEED = seed_state
            _KEYS.clear()
        else:
            _KEYS[_ctx_key(ctx)] = jax.random.PRNGKey(seed_state)


def _next_key(ctx: Optional[Context] = None) -> jax.Array:
    if _TRACE_PROVIDERS:
        return _TRACE_PROVIDERS[-1].next()
    with _LOCK:
        k = _ctx_key(ctx)
        if k not in _KEYS:
            _KEYS[k] = jax.random.PRNGKey(_DEFAULT_SEED)
        _KEYS[k], sub = jax.random.split(_KEYS[k])
    return sub


def _next_key_nd(ctx: Optional[Context] = None) -> NDArray:
    return NDArray(_next_key(ctx), None, _placed=True)


def _wrap(arr, ctx) -> NDArray:
    return NDArray(arr, ctx or current_context())


def uniform(low=0.0, high=1.0, shape=(1,), dtype=None, ctx=None, out=None):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    a = jax.random.uniform(_next_key(ctx), shape,
                           _as_jax_dtype(dtype), low, high)
    if out is not None:
        out._data = a
        return out
    return _wrap(a, ctx)


def normal(loc=0.0, scale=1.0, shape=(1,), dtype=None, ctx=None, out=None):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    a = loc + scale * jax.random.normal(_next_key(ctx), shape,
                                        _as_jax_dtype(dtype))
    if out is not None:
        out._data = a
        return out
    return _wrap(a, ctx)


def randn(*shape, dtype=None, ctx=None):
    return normal(0.0, 1.0, shape or (1,), dtype, ctx)


def gamma(alpha=1.0, beta=1.0, shape=(1,), dtype=None, ctx=None, out=None):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    a = jax.random.gamma(_next_key(ctx), alpha, shape,
                         _as_jax_dtype(dtype)) * beta
    return _wrap(a, ctx)


def exponential(scale=1.0, shape=(1,), dtype=None, ctx=None, out=None):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    a = jax.random.exponential(_next_key(ctx), shape,
                               _as_jax_dtype(dtype)) * scale
    return _wrap(a, ctx)


def poisson(lam=1.0, shape=(1,), dtype=None, ctx=None, out=None):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    a = jax.random.poisson(_next_key(ctx), lam, shape).astype(
        _as_jax_dtype(dtype))
    return _wrap(a, ctx)


def negative_binomial(k=1, p=1.0, shape=(1,), dtype=None, ctx=None,
                      out=None):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    key1, key2 = jax.random.split(_next_key(ctx))
    lam = jax.random.gamma(key1, k, shape) * (1 - p) / p
    a = jax.random.poisson(key2, lam, shape).astype(_as_jax_dtype(dtype))
    return _wrap(a, ctx)


def generalized_negative_binomial(mu=1.0, alpha=1.0, shape=(1,),
                                  dtype=None, ctx=None, out=None):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    key1, key2 = jax.random.split(_next_key(ctx))
    r = 1.0 / alpha
    p = r / (r + mu)
    lam = jax.random.gamma(key1, r, shape) * (1 - p) / p
    a = jax.random.poisson(key2, lam, shape).astype(_as_jax_dtype(dtype))
    return _wrap(a, ctx)


def multinomial(data, shape=(), get_prob=False, dtype="int32", ctx=None):
    """Sample from categorical distributions given probabilities
    (reference ``sample_multinomial``†)."""
    d = data._data if isinstance(data, NDArray) else jnp.asarray(data)
    n = int(np.prod(shape)) if shape else 1
    logits = jnp.log(jnp.maximum(d, 1e-30))
    if d.ndim == 1:
        draw = jax.random.categorical(_next_key(ctx), logits,
                                      shape=(n,) if shape else ())
    else:
        draw = jax.random.categorical(
            _next_key(ctx), logits[:, None, :] if shape else logits,
            axis=-1, shape=(d.shape[0], n) if shape else (d.shape[0],))
    draw = draw.astype(_as_jax_dtype(dtype))
    if get_prob:
        lp = jnp.take_along_axis(
            jax.nn.log_softmax(logits, axis=-1),
            draw.astype(jnp.int32).reshape(d.shape[0], -1) if d.ndim > 1
            else draw.astype(jnp.int32).reshape(-1)[None, :], axis=-1)
        return _wrap(draw, ctx), _wrap(lp.reshape(draw.shape), ctx)
    return _wrap(draw, ctx)


def shuffle(data, ctx=None):
    d = data._data if isinstance(data, NDArray) else jnp.asarray(data)
    perm = jax.random.permutation(_next_key(ctx), d.shape[0])
    return _wrap(jnp.take(d, perm, axis=0), ctx)


def randint(low, high, shape=(1,), dtype="int32", ctx=None, out=None):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    a = jax.random.randint(_next_key(ctx), shape, low, high,
                           _as_jax_dtype(dtype))
    return _wrap(a, ctx)


def bernoulli(prob=0.5, shape=(1,), dtype=None, ctx=None):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    a = jax.random.bernoulli(_next_key(ctx), prob, shape).astype(
        _as_jax_dtype(dtype))
    return _wrap(a, ctx)
