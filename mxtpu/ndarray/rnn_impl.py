"""Fused RNN operator — LSTM/GRU/vanilla, multi-layer, bidirectional.

Reference: ``src/operator/rnn.cc``† + ``src/operator/nn/cudnn/
cudnn_rnn-inl.h``† — the fused cuDNN RNN op with a single flat parameter
vector, consumed by ``gluon/rnn/rnn_layer.py``†'s ``_forward_kernel``.

TPU-native design: one ``lax.scan`` per layer/direction over time.  The
input-to-hidden projection for ALL timesteps is hoisted out of the scan
as a single large matmul (MXU-friendly: one (T·N, in)×(in, G·H) GEMM
per layer instead of T small ones); only the hidden-to-hidden GEMM and
the elementwise gate math live inside the scan body.  XLA unrolls
nothing — the scan lowers to a While with static shapes.

Flat parameter layout (structurally the cuDNN/MXNet convention —
weights first, then biases):
  for layer in 0..L-1: for direction in 0..D-1:
      W_i2h (G*H, in_l)   then  W_h2h (G*H, H)
  then, in the same (layer, direction) order:
      b_i2h (G*H,)        then  b_h2h (G*H,)
with in_0 = input_size and in_l = D*H for l > 0.  Gate order: LSTM
[i, f, g, o], GRU [r, z, n] (cuDNN order).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..base import MXNetError
from ..ops.registry import Param, register_op

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def rnn_param_size(num_layers: int, input_size: int, state_size: int,
                   bidirectional: bool, mode: str) -> int:
    """Total flat parameter vector length (reference
    ``rnn_param_size``† in rnn-inl.h)."""
    gates = _GATES[mode]
    dirs = 2 if bidirectional else 1
    size = 0
    for layer in range(num_layers):
        in_size = input_size if layer == 0 else state_size * dirs
        size += gates * state_size * (in_size + state_size + 2) * dirs
    return size


def _slice_params(params, num_layers, input_size, state_size,
                  dirs, gates):
    """Static slicing of the flat vector → per-(layer, dir) arrays."""
    H, G = state_size, gates
    weights = []
    off = 0
    for layer in range(num_layers):
        in_size = input_size if layer == 0 else H * dirs
        per_layer = []
        for _ in range(dirs):
            w_i2h = params[off:off + G * H * in_size].reshape(G * H,
                                                             in_size)
            off += G * H * in_size
            w_h2h = params[off:off + G * H * H].reshape(G * H, H)
            off += G * H * H
            per_layer.append([w_i2h, w_h2h, None, None])
        weights.append(per_layer)
    for layer in range(num_layers):
        for d in range(dirs):
            weights[layer][d][2] = params[off:off + G * H]
            off += G * H
            weights[layer][d][3] = params[off:off + G * H]
            off += G * H
    return weights, off


def _scan_dir(x, h0, c0, w_h2h, pre, mode, H, reverse):
    """One direction of one layer. pre: (T, N, G*H) precomputed i2h
    (+ biases as applicable); returns (outputs (T,N,H), h_T, c_T)."""

    if mode == "lstm":
        def body(carry, pre_t):
            h, c = carry
            gates = pre_t + h @ w_h2h.T
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            c2 = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
            h2 = jax.nn.sigmoid(o) * jnp.tanh(c2)
            return (h2, c2), h2
        (h_t, c_t), ys = lax.scan(body, (h0, c0), pre, reverse=reverse)
        return ys, h_t, c_t

    if mode == "gru":
        # pre holds W x + b_i2h for all gates + b_h2h for r,z only; the
        # n-gate recurrent bias b_Rn is loop-invariant and closed over
        # (applied inside the reset product).
        pre_t, b_rn = pre

        def body(h, pre_step):
            hp = h @ w_h2h.T
            pr, pz, pn = jnp.split(pre_step, 3, axis=-1)
            hr, hz, hn = jnp.split(hp, 3, axis=-1)
            r = jax.nn.sigmoid(pr + hr)
            z = jax.nn.sigmoid(pz + hz)
            n = jnp.tanh(pn + r * (hn + b_rn))
            h2 = (1.0 - z) * n + z * h
            return h2, h2
        h_t, ys = lax.scan(body, h0, pre_t, reverse=reverse)
        return ys, h_t, None

    act = jnp.tanh if mode == "rnn_tanh" else jax.nn.relu

    def body(h, pre_t):
        h2 = act(pre_t + h @ w_h2h.T)
        return h2, h2
    h_t, ys = lax.scan(body, h0, pre, reverse=reverse)
    return ys, h_t, None


def _rnn_impl(data, parameters, state, *extra, state_size, num_layers,
              mode="lstm", bidirectional=False, p=0.0,
              state_outputs=False):
    """The fused RNN lowering rule. data: (T, N, I); state: (L*D, N, H);
    lstm also takes state_cell; an optional trailing PRNG key input
    enables inter-layer dropout.  Returns (output, state_n
    [, statecell_n]) — callers that set ``state_outputs=False`` get
    just the output."""
    if mode not in _GATES:
        raise MXNetError(f"unknown RNN mode {mode!r}")
    if mode == "lstm":
        state_cell = extra[0] if extra else None
        key = extra[1] if len(extra) > 1 else None
    else:
        state_cell = None
        key = extra[0] if extra else None
    H = int(state_size)
    L = int(num_layers)
    dirs = 2 if bidirectional else 1
    G = _GATES[mode]
    T, N, I = data.shape

    weights, used = _slice_params(parameters, L, I, H, dirs, G)
    if used != parameters.shape[0]:
        raise MXNetError(
            f"RNN parameter vector has {parameters.shape[0]} elements, "
            f"layout needs {used} (use rnn_param_size)")

    x = data
    h_finals = []
    c_finals = []
    for layer in range(L):
        outs = []
        for d in range(dirs):
            w_i2h, w_h2h, b_i2h, b_h2h = weights[layer][d]
            idx = layer * dirs + d
            h0 = state[idx]
            c0 = state_cell[idx] if state_cell is not None else None
            if mode == "gru":
                b_rn = b_h2h[2 * H:]
                b_rz = jnp.concatenate([b_h2h[:2 * H],
                                        jnp.zeros_like(b_rn)])
                pre = (x @ w_i2h.T + b_i2h + b_rz, b_rn)
            else:
                pre = x @ w_i2h.T + b_i2h + b_h2h
            ys, h_t, c_t = _scan_dir(x, h0, c0, w_h2h, pre, mode, H,
                                     reverse=(d == 1))
            outs.append(ys)
            h_finals.append(h_t)
            if c_t is not None:
                c_finals.append(c_t)
        x = outs[0] if dirs == 1 else jnp.concatenate(outs, axis=-1)
        if p > 0.0 and key is not None and layer < L - 1:
            sub = jax.random.fold_in(key, layer) \
                if jnp.issubdtype(key.dtype, jax.dtypes.prng_key) \
                else jax.random.fold_in(jax.random.wrap_key_data(key),
                                        layer)
            keep = jax.random.bernoulli(sub, 1.0 - p, x.shape)
            x = jnp.where(keep, x / (1.0 - p), 0.0)

    state_n = jnp.stack(h_finals)
    if mode == "lstm":
        cell_n = jnp.stack(c_finals)
        if state_outputs:
            return x, state_n, cell_n
        return x
    if state_outputs:
        return x, state_n
    return x


def _rnn_num_outputs(attrs) -> int:
    so = attrs.get("state_outputs", False)
    if isinstance(so, str):
        so = so not in ("False", "false", "0")
    if not so:
        return 1
    return 3 if attrs.get("mode", "lstm") == "lstm" else 2


register_op(
    "RNN", num_inputs=-1, num_outputs=3,
    params=[Param("state_size", int),
            Param("num_layers", int),
            Param("mode", str, "lstm",
                  enum=("rnn_relu", "rnn_tanh", "lstm", "gru")),
            Param("bidirectional", bool, False),
            Param("p", float, 0.0),
            Param("state_outputs", bool, False)],
    num_outputs_fn=_rnn_num_outputs,
    doc=_rnn_impl.__doc__)(_rnn_impl)


def _kv_cache_write_op(cache, new, step):
    """Bucket-paged KV-cache write for incremental decode
    (mxtpu.serving.generate).  ``cache``: (B, H, L, D) — each batch row
    is one cache *lane* owned by an in-flight request; ``new``:
    (B, H, T, D) freshly projected keys or values; ``step``: (B,)
    per-lane write offsets (each lane advances independently under
    continuous batching).  Lowers to one ``lax.dynamic_update_slice``
    per lane via vmap — the signature contracts/generate_decode.json
    pins.  Values are cast to the cache dtype on write, so a bf16
    cache under mxtpu.amp stays bf16 regardless of compute dtype."""
    idx = jnp.asarray(step).astype(jnp.int32)

    def _one(c, n, s):
        return lax.dynamic_update_slice(c, n.astype(c.dtype), (0, s, 0))
    return jax.vmap(_one)(cache, new, idx)


register_op("kv_cache_write", num_inputs=3, differentiable=False,
            doc=_kv_cache_write_op.__doc__)(_kv_cache_write_op)


def _cached_attention_op(q, k_cache, v_cache, step, sm_scale=-1.0):
    """Decode-step attention over a preallocated KV cache.  ``q``:
    (B, H, T, D) — the T new query tokens of each lane sit at absolute
    positions ``step_b + t``; ``k_cache``/``v_cache``: (B, H, L, D).
    Causal masking against valid lengths (key position l attends iff
    ``l <= step_b + t``), so stale cache contents beyond a lane's
    frontier — including leftovers from a previous occupant of a
    reused lane — are unreachable by construction.  Scores, softmax
    and the probs @ V contraction all accumulate in f32 and only the
    final output is cast back to the query dtype: the zero-hazard
    bf16-decode/f32-accum recipe contracts/prec/generate_decode.json
    pins.  ``sm_scale < 0`` means 1/sqrt(D)."""
    B, H, T, D = q.shape
    L = k_cache.shape[2]
    scale = (1.0 / float(np.sqrt(D))) \
        if (sm_scale is None or sm_scale < 0) else float(sm_scale)
    s = jnp.asarray(step).astype(jnp.int32)
    scores = jnp.einsum("bhtd,bhld->bhtl", q.astype(jnp.float32),
                        k_cache.astype(jnp.float32),
                        preferred_element_type=jnp.float32) * scale
    pos_q = s[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
    pos_k = jnp.arange(L, dtype=jnp.int32)
    mask = pos_k[None, None, :] <= pos_q[:, :, None]
    scores = jnp.where(mask[:, None, :, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhtl,bhld->bhtd", probs,
                     v_cache.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


register_op("cached_attention", num_inputs=4, differentiable=False,
            params=[Param("sm_scale", float, -1.0)],
            doc=_cached_attention_op.__doc__)(_cached_attention_op)


def _flash_attention_op(q, k, v, causal=False, sm_scale=-1.0):
    """Fused attention op (new capability; no reference counterpart —
    SURVEY.md §5.7 mandates it for long-context).  q: (B,H,Tq,D),
    k/v: (B,H,Tk,D); sm_scale < 0 means 1/sqrt(D)."""
    from ..kernels import flash_attention
    scale = None if sm_scale is None or sm_scale < 0 else sm_scale
    return flash_attention(q, k, v, causal=causal, sm_scale=scale)


register_op("flash_attention", num_inputs=3,
            params=[Param("causal", bool, False),
                    Param("sm_scale", float, -1.0)],
            aliases=("contrib_flash_attention",),
            doc=_flash_attention_op.__doc__)(_flash_attention_op)
