"""Sparse NDArray flavours — API parity over dense TPU storage.

Reference: ``python/mxnet/ndarray/sparse.py``† (RowSparseNDArray,
CSRNDArray) over C++ storage types in ``src/ndarray/``†.

TPU has no native sparse storage; per SURVEY.md §7 hard part 3 the API is
kept (indices/data views, ``tostype``, row_sparse gradient aggregation)
while the device representation stays dense — gather/scatter/segment-sum
lower to XLA ops that the compiler handles well.  The compressed fields
are maintained alongside a dense mirror so ``retain``/``indices`` behave
like the reference.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from ..context import Context, current_context
from .ndarray import NDArray, array

__all__ = ["RowSparseNDArray", "CSRNDArray", "row_sparse_array",
           "csr_matrix", "zeros", "cast_storage", "retain", "dot",
           "elemwise_add", "add_n"]


class BaseSparseNDArray(NDArray):
    __slots__ = ()

    def asnumpy(self):
        return np.asarray(self._data)

    def todense(self) -> NDArray:
        return NDArray(self._data, self._ctx, _placed=True)

    def tostype(self, stype):
        if stype == "default":
            return self.todense()
        return _cast_storage(self, stype)


class RowSparseNDArray(BaseSparseNDArray):
    """Rows at ``indices`` hold ``data``; all other rows are zero."""
    __slots__ = ("_indices",)

    def __init__(self, dense_data, indices, ctx=None):
        super().__init__(dense_data, ctx)
        self._indices = jnp.asarray(indices, dtype=jnp.int32) \
            if indices is not None else None

    @property
    def stype(self):
        return "row_sparse"

    @property
    def indices(self) -> NDArray:
        if self._indices is None:
            nz = np.nonzero(np.any(np.asarray(self._data) != 0,
                                   axis=tuple(range(1, self._data.ndim))))[0]
            self._indices = jnp.asarray(nz, dtype=jnp.int32)
        return NDArray(self._indices, self._ctx, _placed=True)

    @property
    def data(self):
        # compressed rows view (reference .data of row_sparse)
        return NDArray(jnp.take(self._data,
                                self.indices._data.astype(jnp.int32),
                                axis=0), self._ctx, _placed=True)

    def retain(self, rsp_indices) -> "RowSparseNDArray":
        idx = rsp_indices._data if isinstance(rsp_indices, NDArray) \
            else jnp.asarray(rsp_indices)
        mask = jnp.zeros((self._data.shape[0],), bool).at[
            idx.astype(jnp.int32)].set(True)
        dense = jnp.where(
            mask.reshape((-1,) + (1,) * (self._data.ndim - 1)),
            self._data, 0)
        return RowSparseNDArray(dense, idx, self._ctx)


class CSRNDArray(BaseSparseNDArray):
    """2-D compressed sparse row array."""
    __slots__ = ("_indptr", "_col_indices")

    def __init__(self, dense_data, indptr=None, indices=None, ctx=None):
        super().__init__(dense_data, ctx)
        self._indptr = None if indptr is None else jnp.asarray(
            indptr, jnp.int32)
        self._col_indices = None if indices is None else jnp.asarray(
            indices, jnp.int32)

    @property
    def stype(self):
        return "csr"

    def _compress(self):
        d = np.asarray(self._data)
        indptr = [0]
        cols = []
        vals = []
        for r in range(d.shape[0]):
            nz = np.nonzero(d[r])[0]
            cols.extend(nz.tolist())
            vals.extend(d[r, nz].tolist())
            indptr.append(len(cols))
        self._indptr = jnp.asarray(indptr, jnp.int32)
        self._col_indices = jnp.asarray(cols, jnp.int32)
        return np.asarray(vals, d.dtype)

    @property
    def indptr(self) -> NDArray:
        if self._indptr is None:
            self._compress()
        return NDArray(self._indptr, self._ctx, _placed=True)

    @property
    def indices(self) -> NDArray:
        if self._col_indices is None:
            self._compress()
        return NDArray(self._col_indices, self._ctx, _placed=True)

    @property
    def data(self):
        vals = self._compress()
        return array(vals)


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    """Create RowSparseNDArray from (data, indices) or a dense source."""
    if isinstance(arg1, (tuple, list)) and len(arg1) == 2 and not \
            np.isscalar(arg1[0]):
        data, indices = arg1
        data = np.asarray(data, dtype=dtype or np.float32)
        indices = np.asarray(indices, dtype=np.int64)
        if shape is None:
            raise MXNetError("row_sparse_array((data, indices)) needs shape")
        dense = np.zeros(shape, dtype=data.dtype)
        dense[indices] = data
        return RowSparseNDArray(jnp.asarray(dense), indices, ctx)
    src = arg1.asnumpy() if isinstance(arg1, NDArray) else np.asarray(
        arg1, dtype=dtype or np.float32)
    return RowSparseNDArray(jnp.asarray(src), None, ctx)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, (tuple, list)) and len(arg1) == 3:
        data, indices, indptr = arg1
        data = np.asarray(data, dtype=dtype or np.float32)
        indices = np.asarray(indices, np.int64)
        indptr = np.asarray(indptr, np.int64)
        if shape is None:
            raise MXNetError("csr_matrix((data,indices,indptr)) needs shape")
        dense = np.zeros(shape, dtype=data.dtype)
        for r in range(shape[0]):
            for j in range(int(indptr[r]), int(indptr[r + 1])):
                dense[r, int(indices[j])] = data[j]
        return CSRNDArray(jnp.asarray(dense), indptr, indices, ctx)
    src = arg1.asnumpy() if isinstance(arg1, NDArray) else np.asarray(
        arg1, dtype=dtype or np.float32)
    return CSRNDArray(jnp.asarray(src), None, None, ctx)


def zeros(stype, shape, ctx=None, dtype=None):
    dense = jnp.zeros(shape, np.dtype(dtype or "float32"))
    if stype == "row_sparse":
        return RowSparseNDArray(dense, np.zeros((0,), np.int64), ctx)
    if stype == "csr":
        return CSRNDArray(dense, None, None, ctx)
    return NDArray(dense, ctx)


def _cast_storage(nd: NDArray, stype: str):
    if stype == "row_sparse":
        return RowSparseNDArray(nd._data, None, nd._ctx)
    if stype == "csr":
        if nd._data.ndim != 2:
            raise MXNetError("csr requires 2-D")
        return CSRNDArray(nd._data, None, None, nd._ctx)
    raise MXNetError(f"unknown stype {stype}")


# ----------------------------------------------------------------------
# sparse operators (reference ``python/mxnet/ndarray/sparse.py``† op
# namespace + ``src/operator/tensor/dot.cc``† storage-type table).
# Compute is dense XLA underneath; the RESULT stype follows the
# reference's inference table so downstream sparse-aware code (lazy
# optimizers, kvstore row_sparse_pull) behaves identically.
# ----------------------------------------------------------------------
def cast_storage(arr: NDArray, stype: str):
    """Reference ``cast_storage``†."""
    if stype == "default":
        return NDArray(arr._data, arr._ctx, _placed=True)
    return _cast_storage(arr, stype)


def retain(data: RowSparseNDArray, indices) -> RowSparseNDArray:
    """Reference ``_sparse_retain``†: keep only the given rows."""
    if not isinstance(data, RowSparseNDArray):
        raise MXNetError("retain expects a RowSparseNDArray")
    return data.retain(indices if isinstance(indices, NDArray)
                       else array(indices))


def dot(lhs: NDArray, rhs: NDArray, transpose_a: bool = False,
        transpose_b: bool = False):
    """Sparse-aware dot (reference storage table: csr·dense → dense;
    csrᵀ·dense → row_sparse; everything else dense)."""
    a = lhs._data
    b = rhs._data
    if transpose_a:
        a = a.T
    if transpose_b:
        b = b.T
    out = jnp.matmul(a, b)
    if isinstance(lhs, CSRNDArray) and transpose_a:
        # output rows = csr columns touched by stored entries
        return RowSparseNDArray(out, None, lhs._ctx)
    return NDArray(out, lhs._ctx, _placed=True)


def _wrap_like(out_data, template):
    if isinstance(template, RowSparseNDArray):
        return RowSparseNDArray(out_data, None, template._ctx)
    if isinstance(template, CSRNDArray):
        return CSRNDArray(out_data, None, None, template._ctx)
    return NDArray(out_data, template._ctx, _placed=True)


def elemwise_add(lhs: NDArray, rhs: NDArray):
    """stype-preserving add: rsp+rsp → rsp, csr+csr → csr, any dense
    operand densifies (the reference's fallback rule)."""
    out = lhs._data + rhs._data
    if type(lhs) is type(rhs) and isinstance(lhs, BaseSparseNDArray):
        return _wrap_like(out, lhs)
    return NDArray(out, lhs._ctx, _placed=True)


def add_n(*arrays):
    out = arrays[0]._data
    for a in arrays[1:]:
        out = out + a._data
    if all(type(a) is type(arrays[0]) and
           isinstance(a, BaseSparseNDArray) for a in arrays):
        return _wrap_like(out, arrays[0])
    return NDArray(out, arrays[0]._ctx, _placed=True)
