"""``mx.nd.contrib`` namespace — control flow + experimental ops.

Reference: ``python/mxnet/ndarray/contrib.py``† (foreach / while_loop /
cond arrived around v1.3, ``src/operator/control_flow.cc``†), plus
contrib ops in ``src/operator/contrib/``†.

TPU-native: control flow maps directly onto ``lax.scan`` / ``lax
.while_loop`` / ``lax.cond`` — compiler-friendly structured control flow
is exactly what the reference was reaching for.  Detection-family ops
(box_nms / multibox) live here too with padded static-shape contracts
(SURVEY.md §7 M7).
"""
from __future__ import annotations

from typing import Any, Callable, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..base import MXNetError
from .ndarray import NDArray


def _unwrap(x):
    return x._data if isinstance(x, NDArray) else x


def _wrap_tree(t):
    return jax.tree_util.tree_map(
        lambda a: NDArray(a, None, _placed=True), t)


def foreach(body: Callable, data, init_states):
    """``mx.nd.contrib.foreach``† — scan body over the leading axis.

    body(data_slice, states) -> (outputs, new_states)
    """
    data_r = jax.tree_util.tree_map(_unwrap, data)
    states_r = jax.tree_util.tree_map(_unwrap, init_states)

    def step(carry, x):
        xs = _wrap_tree(x)
        cs = _wrap_tree(carry)
        out, new_states = body(xs, cs)
        return (jax.tree_util.tree_map(_unwrap, new_states),
                jax.tree_util.tree_map(_unwrap, out))

    final, outs = lax.scan(step, states_r, data_r)
    return _wrap_tree(outs), _wrap_tree(final)


def while_loop(cond: Callable, func: Callable, loop_vars,
               max_iterations: int):
    """``mx.nd.contrib.while_loop``†.  Static max_iterations bound keeps
    shapes XLA-compatible; outputs are padded to max_iterations."""
    vars_r = [_unwrap(v) for v in loop_vars]

    def c(state):
        i, vs = state
        w = [NDArray(v, None, _placed=True) for v in vs]
        keep = cond(*w)
        keep = _unwrap(keep).astype(bool).reshape(())
        return jnp.logical_and(i < max_iterations, keep)

    def b(state):
        i, vs = state
        w = [NDArray(v, None, _placed=True) for v in vs]
        _, new_vars = func(*w)
        return (i + 1, [_unwrap(v) for v in new_vars])

    # note: we drop per-step stacked outputs (rarely used); loop vars
    # carry the result.  Parity gap documented.
    i, out_vars = lax.while_loop(c, b, (jnp.asarray(0), vars_r))
    return ([], [NDArray(v, None, _placed=True) for v in out_vars])


def cond(pred: Callable, then_func: Callable, else_func: Callable):
    """``mx.nd.contrib.cond``†."""
    p = pred() if callable(pred) else pred
    p = _unwrap(p).astype(bool).reshape(())
    t = lambda _: jax.tree_util.tree_map(  # noqa: E731
        _unwrap, then_func())
    f = lambda _: jax.tree_util.tree_map(  # noqa: E731
        _unwrap, else_func())
    out = lax.cond(p, t, f, None)
    return _wrap_tree(out)


# ----------------------------------------------------------------------
# detection ops — padded static-shape NMS family
# ----------------------------------------------------------------------
def box_iou(lhs, rhs, format="corner"):  # noqa: A002
    """Pairwise IoU (reference ``contrib.box_iou``†)."""
    a = _unwrap(lhs)
    b = _unwrap(rhs)
    if format == "center":
        a = jnp.concatenate([a[..., :2] - a[..., 2:] / 2,
                             a[..., :2] + a[..., 2:] / 2], -1)
        b = jnp.concatenate([b[..., :2] - b[..., 2:] / 2,
                             b[..., :2] + b[..., 2:] / 2], -1)
    tl = jnp.maximum(a[..., :, None, :2], b[..., None, :, :2])
    br = jnp.minimum(a[..., :, None, 2:], b[..., None, :, 2:])
    wh = jnp.maximum(br - tl, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.maximum((a[..., 2] - a[..., 0]) *
                         (a[..., 3] - a[..., 1]), 0.0)
    area_b = jnp.maximum((b[..., 2] - b[..., 0]) *
                         (b[..., 3] - b[..., 1]), 0.0)
    union = area_a[..., :, None] + area_b[..., None, :] - inter
    return NDArray(inter / jnp.maximum(union, 1e-12), None, _placed=True)


def _nms_single(scores, boxes, iou_thresh, valid_thresh, topk,
                ids=None):
    """Greedy NMS with static shapes: iterates topk times via fori_loop,
    suppressing overlaps.  ``ids`` (optional per-box class ids) limits
    suppression to same-class pairs (box_nms ``id_index`` semantics
    when ``force_suppress=False``).  Returns keep mask — the
    padded-max-size contract replacing the reference's dynamic-output
    NMS (src/operator/contrib/bounding_box.cc†)."""
    n = scores.shape[0]
    order = jnp.argsort(-scores)
    boxes_s = boxes[order]
    scores_s = scores[order]
    tl = jnp.maximum(boxes_s[:, None, :2], boxes_s[None, :, :2])
    br = jnp.minimum(boxes_s[:, None, 2:], boxes_s[None, :, 2:])
    wh = jnp.maximum(br - tl, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area = jnp.maximum((boxes_s[:, 2] - boxes_s[:, 0]) *
                       (boxes_s[:, 3] - boxes_s[:, 1]), 0.0)
    iou = inter / jnp.maximum(area[:, None] + area[None, :] - inter, 1e-12)
    if ids is not None:
        ids_s = ids[order]
        iou = jnp.where(ids_s[:, None] == ids_s[None, :], iou, 0.0)

    def body(i, keep):
        # suppress j>i overlapping box i if i kept
        sup = (iou[i] > iou_thresh) & (jnp.arange(n) > i) & keep[i]
        return keep & ~sup

    keep0 = scores_s > valid_thresh
    keep = lax.fori_loop(0, n if topk < 0 else min(topk, n), body, keep0)
    inv = jnp.argsort(order)
    return keep[inv], order


def box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
            coord_start=2, score_index=1, id_index=-1, force_suppress=False,
            in_format="corner", out_format="corner"):
    """``contrib.box_nms``† with the padded contract: suppressed entries
    are set to -1 instead of removed (static output shape)."""
    d = _unwrap(data)
    batched = d.ndim == 3
    if not batched:
        d = d[None]

    def one(db):
        scores = db[:, score_index]
        boxes = lax.dynamic_slice_in_dim(db, coord_start, 4, axis=1)
        # id_index restricts suppression to same-class pairs unless
        # force_suppress (reference box_nms semantics)
        ids = db[:, id_index] if id_index >= 0 and not force_suppress \
            else None
        keep, order = _nms_single(scores, boxes, overlap_thresh,
                                  valid_thresh, topk, ids=ids)
        out = jnp.where(keep[:, None], db, -jnp.ones_like(db))
        return out

    out = jax.vmap(one)(d)
    if not batched:
        out = out[0]
    return NDArray(out, None, _placed=True)


def boolean_mask(data, index, axis=0):
    """``contrib.boolean_mask``† — dynamic output in the reference; here
    the padded contract: masked-out rows are zeroed and compacted to the
    front, output keeps the input's static length."""
    d = _unwrap(data)
    m = _unwrap(index).astype(bool)
    idx = jnp.argsort(~m)  # true rows first, stable
    compacted = jnp.take(d, idx, axis=axis)
    mask_sorted = jnp.sort(~m) == False  # noqa: E712
    shape = [1] * d.ndim
    shape[axis] = d.shape[axis]
    return NDArray(
        compacted * mask_sorted.reshape(shape).astype(d.dtype),
        None, _placed=True)


def getnnz(data, axis=None):
    d = _unwrap(data)
    return NDArray(jnp.asarray(
        jnp.sum(d != 0) if axis is None else jnp.sum(d != 0, axis=axis)
    ).astype(jnp.int64), None, _placed=True)


def count_sketch(data, h, s, out_dim):
    """``contrib.count_sketch``† — compact bilinear pooling primitive."""
    d = _unwrap(data)
    hh = _unwrap(h).astype(jnp.int32)
    ss = _unwrap(s)
    out = jnp.zeros(d.shape[:-1] + (out_dim,), d.dtype)
    out = out.at[..., hh].add(d * ss)
    return NDArray(out, None, _placed=True)


def fft(data, compute_size=128):
    d = _unwrap(data)
    f = jnp.fft.fft(d, axis=-1)
    out = jnp.stack([f.real, f.imag], axis=-1).reshape(
        d.shape[:-1] + (2 * d.shape[-1],))
    return NDArray(out.astype(d.dtype), None, _placed=True)


def ifft(data, compute_size=128):
    d = _unwrap(data)
    c = d.reshape(d.shape[:-1] + (d.shape[-1] // 2, 2))
    comp = c[..., 0] + 1j * c[..., 1]
    out = jnp.fft.ifft(comp, axis=-1).real * comp.shape[-1]
    return NDArray(out.astype(d.dtype), None, _placed=True)


def quadratic(data, a=0.0, b=0.0, c=0.0):
    """The reference's tutorial op (``src/operator/contrib/quadratic_op``†)."""
    d = _unwrap(data)
    return NDArray(a * d * d + b * d + c, None, _placed=True)
