"""``mx.nd.contrib`` namespace — control flow + experimental ops.

Reference: ``python/mxnet/ndarray/contrib.py``† (foreach / while_loop /
cond arrived around v1.3, ``src/operator/control_flow.cc``†), plus
contrib ops in ``src/operator/contrib/``†.

TPU-native: control flow maps directly onto ``lax.scan`` / ``lax
.while_loop`` / ``lax.cond`` — compiler-friendly structured control flow
is exactly what the reference was reaching for.  Detection-family ops
(box_nms / multibox) live here too with padded static-shape contracts
(SURVEY.md §7 M7).
"""
from __future__ import annotations

from typing import Any, Callable, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..base import MXNetError
from ..ops.registry import Param, register_op
from .ndarray import NDArray


def _unwrap(x):
    return x._data if isinstance(x, NDArray) else x


def _wrap_tree(t):
    return jax.tree_util.tree_map(
        lambda a: NDArray(a, None, _placed=True), t)


def foreach(body: Callable, data, init_states):
    """``mx.nd.contrib.foreach``† — scan body over the leading axis.

    body(data_slice, states) -> (outputs, new_states)
    """
    data_r = jax.tree_util.tree_map(_unwrap, data)
    states_r = jax.tree_util.tree_map(_unwrap, init_states)

    def step(carry, x):
        xs = _wrap_tree(x)
        cs = _wrap_tree(carry)
        out, new_states = body(xs, cs)
        return (jax.tree_util.tree_map(_unwrap, new_states),
                jax.tree_util.tree_map(_unwrap, out))

    final, outs = lax.scan(step, states_r, data_r)
    return _wrap_tree(outs), _wrap_tree(final)


def while_loop(cond: Callable, func: Callable, loop_vars,
               max_iterations: int):
    """``mx.nd.contrib.while_loop``†.  Static max_iterations bound keeps
    shapes XLA-compatible; outputs are padded to max_iterations."""
    vars_r = [_unwrap(v) for v in loop_vars]

    def c(state):
        i, vs = state
        w = [NDArray(v, None, _placed=True) for v in vs]
        keep = cond(*w)
        keep = _unwrap(keep).astype(bool).reshape(())
        return jnp.logical_and(i < max_iterations, keep)

    def b(state):
        i, vs = state
        w = [NDArray(v, None, _placed=True) for v in vs]
        _, new_vars = func(*w)
        return (i + 1, [_unwrap(v) for v in new_vars])

    # note: we drop per-step stacked outputs (rarely used); loop vars
    # carry the result.  Parity gap documented.
    i, out_vars = lax.while_loop(c, b, (jnp.asarray(0), vars_r))
    return ([], [NDArray(v, None, _placed=True) for v in out_vars])


def cond(pred: Callable, then_func: Callable, else_func: Callable):
    """``mx.nd.contrib.cond``†."""
    p = pred() if callable(pred) else pred
    p = _unwrap(p).astype(bool).reshape(())
    t = lambda _: jax.tree_util.tree_map(  # noqa: E731
        _unwrap, then_func())
    f = lambda _: jax.tree_util.tree_map(  # noqa: E731
        _unwrap, else_func())
    out = lax.cond(p, t, f, None)
    return _wrap_tree(out)


# ----------------------------------------------------------------------
# detection ops — padded static-shape NMS family
# ----------------------------------------------------------------------
def _box_iou_raw(a, b, format="corner"):  # noqa: A002
    """Pairwise IoU (reference ``contrib.box_iou``†)."""
    if format == "center":
        a = jnp.concatenate([a[..., :2] - a[..., 2:] / 2,
                             a[..., :2] + a[..., 2:] / 2], -1)
        b = jnp.concatenate([b[..., :2] - b[..., 2:] / 2,
                             b[..., :2] + b[..., 2:] / 2], -1)
    tl = jnp.maximum(a[..., :, None, :2], b[..., None, :, :2])
    br = jnp.minimum(a[..., :, None, 2:], b[..., None, :, 2:])
    wh = jnp.maximum(br - tl, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.maximum((a[..., 2] - a[..., 0]) *
                         (a[..., 3] - a[..., 1]), 0.0)
    area_b = jnp.maximum((b[..., 2] - b[..., 0]) *
                         (b[..., 3] - b[..., 1]), 0.0)
    union = area_a[..., :, None] + area_b[..., None, :] - inter
    return inter / jnp.maximum(union, 1e-12)


register_op("_contrib_box_iou", num_inputs=2,
            params=[Param("format", str, "corner",
                          enum=("corner", "center"))])(_box_iou_raw)


def box_iou(lhs, rhs, format="corner"):  # noqa: A002
    """Pairwise IoU (reference ``contrib.box_iou``†)."""
    return NDArray(_box_iou_raw(_unwrap(lhs), _unwrap(rhs),
                                format=format), None, _placed=True)


def _nms_single(scores, boxes, iou_thresh, valid_thresh, topk,
                ids=None):
    """Greedy NMS with static shapes: iterates topk times via fori_loop,
    suppressing overlaps.  ``ids`` (optional per-box class ids) limits
    suppression to same-class pairs (box_nms ``id_index`` semantics
    when ``force_suppress=False``).  Returns keep mask — the
    padded-max-size contract replacing the reference's dynamic-output
    NMS (src/operator/contrib/bounding_box.cc†)."""
    n = scores.shape[0]
    order = jnp.argsort(-scores)
    boxes_s = boxes[order]
    scores_s = scores[order]
    tl = jnp.maximum(boxes_s[:, None, :2], boxes_s[None, :, :2])
    br = jnp.minimum(boxes_s[:, None, 2:], boxes_s[None, :, 2:])
    wh = jnp.maximum(br - tl, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area = jnp.maximum((boxes_s[:, 2] - boxes_s[:, 0]) *
                       (boxes_s[:, 3] - boxes_s[:, 1]), 0.0)
    iou = inter / jnp.maximum(area[:, None] + area[None, :] - inter, 1e-12)
    if ids is not None:
        ids_s = ids[order]
        iou = jnp.where(ids_s[:, None] == ids_s[None, :], iou, 0.0)

    def body(i, keep):
        # suppress j>i overlapping box i if i kept
        sup = (iou[i] > iou_thresh) & (jnp.arange(n) > i) & keep[i]
        return keep & ~sup

    keep0 = scores_s > valid_thresh
    keep = lax.fori_loop(0, n if topk < 0 else min(topk, n), body, keep0)
    inv = jnp.argsort(order)
    return keep[inv], order


def _box_nms_raw(d, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
                 coord_start=2, score_index=1, id_index=-1,
                 force_suppress=False, in_format="corner",
                 out_format="corner"):
    """``contrib.box_nms``† with the padded contract: suppressed entries
    are set to -1 instead of removed (static output shape)."""
    batched = d.ndim == 3
    if not batched:
        d = d[None]

    def one(db):
        scores = db[:, score_index]
        boxes = lax.dynamic_slice_in_dim(db, coord_start, 4, axis=1)
        # id_index restricts suppression to same-class pairs unless
        # force_suppress (reference box_nms semantics)
        ids = db[:, id_index] if id_index >= 0 and not force_suppress \
            else None
        keep, order = _nms_single(scores, boxes, overlap_thresh,
                                  valid_thresh, topk, ids=ids)
        out = jnp.where(keep[:, None], db, -jnp.ones_like(db))
        return out

    out = jax.vmap(one)(d)
    if not batched:
        out = out[0]
    return out


register_op("_contrib_box_nms",
            params=[Param("overlap_thresh", float, 0.5),
                    Param("valid_thresh", float, 0.0),
                    Param("topk", int, -1),
                    Param("coord_start", int, 2),
                    Param("score_index", int, 1),
                    Param("id_index", int, -1),
                    Param("force_suppress", bool, False),
                    Param("in_format", str, "corner"),
                    Param("out_format", str, "corner")],
            aliases=("box_nms",), differentiable=False)(_box_nms_raw)


def box_nms(data, **kwargs):
    return NDArray(_box_nms_raw(_unwrap(data), **kwargs), None,
                   _placed=True)


def _boolean_mask_raw(d, m, axis=0):
    m = m.astype(bool)
    idx = jnp.argsort(~m)  # true rows first, stable
    compacted = jnp.take(d, idx, axis=axis)
    mask_sorted = jnp.sort(~m) == False  # noqa: E712
    shape = [1] * d.ndim
    shape[axis] = d.shape[axis]
    return compacted * mask_sorted.reshape(shape).astype(d.dtype)


register_op("_contrib_boolean_mask", num_inputs=2,
            params=[Param("axis", int, 0)])(_boolean_mask_raw)


def boolean_mask(data, index, axis=0):
    """``contrib.boolean_mask``† — dynamic output in the reference; here
    the padded contract: masked-out rows are zeroed and compacted to the
    front, output keeps the input's static length."""
    return NDArray(_boolean_mask_raw(_unwrap(data), _unwrap(index),
                                     axis=axis), None, _placed=True)


def _getnnz_raw(d, axis=None):
    return jnp.asarray(
        jnp.sum(d != 0) if axis is None else jnp.sum(d != 0, axis=axis)
    ).astype(jnp.int64)


register_op("_contrib_getnnz", params=[Param("axis", int, None)],
            differentiable=False)(_getnnz_raw)


def getnnz(data, axis=None):
    return NDArray(_getnnz_raw(_unwrap(data), axis=axis), None,
                   _placed=True)


def _count_sketch_raw(d, hh, ss, out_dim=0):
    """``contrib.count_sketch``† — compact bilinear pooling primitive.
    Input order (data, h, s) matches the reference op signature."""
    hh = hh.astype(jnp.int32)
    out = jnp.zeros(d.shape[:-1] + (int(out_dim),), d.dtype)
    return out.at[..., hh].add(d * ss)


register_op("_contrib_count_sketch", num_inputs=3,
            params=[Param("out_dim", int, 0)],
            aliases=("_contrib_CountSketch",))(_count_sketch_raw)


def count_sketch(data, h, s, out_dim):
    return NDArray(_count_sketch_raw(_unwrap(data), _unwrap(h),
                                     _unwrap(s), out_dim=out_dim),
                   None, _placed=True)


def _fft_raw(d, compute_size=128):
    f = jnp.fft.fft(d, axis=-1)
    return jnp.stack([f.real, f.imag], axis=-1).reshape(
        d.shape[:-1] + (2 * d.shape[-1],)).astype(d.dtype)


register_op("_contrib_fft",
            params=[Param("compute_size", int, 128)])(_fft_raw)


def fft(data, compute_size=128):
    return NDArray(_fft_raw(_unwrap(data), compute_size=compute_size),
                   None, _placed=True)


def _ifft_raw(d, compute_size=128):
    """Real-matmul IDFT: N*ifft(x)_n = sum_k a_k cos(2pi kn/N)
    - b_k sin(2pi kn/N) for x = a + bi.  Complex arithmetic is
    unimplemented on some experimental TPU backends (axon) — and one
    unimplemented op poisons the whole client — while a (N, N)
    cos/sin matmul rides the MXU; the op's contract (contrib.ifft†,
    compute_size~128) keeps N small."""
    c = d.reshape(d.shape[:-1] + (d.shape[-1] // 2, 2))
    a = c[..., 0]
    b = c[..., 1]
    n = a.shape[-1]
    k = np.arange(n)
    ang = 2.0 * np.pi * np.outer(k, k) / n
    cos_t = jnp.asarray(np.cos(ang), jnp.float32)
    sin_t = jnp.asarray(np.sin(ang), jnp.float32)
    prec = lax.Precision.HIGHEST \
        if jnp.dtype(d.dtype) == jnp.float32 else None
    out = jnp.matmul(a, cos_t, precision=prec) - \
        jnp.matmul(b, sin_t, precision=prec)
    return out.astype(d.dtype)


register_op("_contrib_ifft",
            params=[Param("compute_size", int, 128)])(_ifft_raw)


def ifft(data, compute_size=128):
    return NDArray(_ifft_raw(_unwrap(data), compute_size=compute_size),
                   None, _placed=True)


def _quadratic_raw(d, a=0.0, b=0.0, c=0.0):
    """The reference's tutorial op (``src/operator/contrib/quadratic_op``†)."""
    return a * d * d + b * d + c


register_op("_contrib_quadratic",
            params=[Param("a", float, 0.0), Param("b", float, 0.0),
                    Param("c", float, 0.0)])(_quadratic_raw)


def quadratic(data, a=0.0, b=0.0, c=0.0):
    return NDArray(_quadratic_raw(_unwrap(data), a=a, b=b, c=c), None,
                   _placed=True)


def _bipartite_matching_raw(data, is_ascend=False, threshold=0.0,
                            topk=-1):
    """``contrib.bipartite_matching``†: greedy bipartite matching over a
    (R, C) score matrix.  Returns (row_match, col_match) with -1 for
    unmatched; static shapes via a fori_loop of min(R, C) greedy picks.
    """
    batched = data.ndim == 3
    d = data if batched else data[None]

    def one(s):
        R, C = s.shape
        worst = jnp.inf if is_ascend else -jnp.inf

        def body(_, state):
            s_cur, rm, cm = state
            flat = jnp.argmin(s_cur) if is_ascend else jnp.argmax(s_cur)
            r, c = flat // C, flat % C
            v = s_cur[r, c]
            ok = (v < threshold) if is_ascend else (v > threshold)
            rm = jnp.where(ok, rm.at[r].set(c.astype(rm.dtype)), rm)
            cm = jnp.where(ok, cm.at[c].set(r.astype(cm.dtype)), cm)
            s_cur = jnp.where(ok, s_cur.at[r, :].set(worst)
                              .at[:, c].set(worst), s_cur)
            return s_cur, rm, cm

        n = min(R, C) if topk < 0 else min(topk, R, C)
        init = (s.astype(jnp.float32),
                -jnp.ones((R,), jnp.float32),
                -jnp.ones((C,), jnp.float32))
        _, rm, cm = lax.fori_loop(0, n, body, init)
        return rm, cm

    rm, cm = jax.vmap(one)(d)
    if not batched:
        rm, cm = rm[0], cm[0]
    return rm, cm


register_op("_contrib_bipartite_matching", num_outputs=2,
            params=[Param("is_ascend", bool, False),
                    Param("threshold", float, 0.0),
                    Param("topk", int, -1)],
            differentiable=False)(_bipartite_matching_raw)


def bipartite_matching(data, **kwargs):
    rm, cm = _bipartite_matching_raw(_unwrap(data), **kwargs)
    return (NDArray(rm, None, _placed=True),
            NDArray(cm, None, _placed=True))
