"""Spatial transform operators: GridGenerator / BilinearSampler /
SpatialTransformer / Crop / Correlation / UpSampling companions.

Reference: ``src/operator/bilinear_sampler.cc``†,
``grid_generator.cc``†, ``spatial_transformer.cc``†, ``crop.cc``†,
``src/operator/correlation.cc``† (FlowNet layer).

TPU-native notes: sampling is expressed as gather-free bilinear
interpolation over clipped integer corners (differentiable through
jax AD); Correlation enumerates the static displacement grid with
rolled shifts — no dynamic shapes anywhere.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..base import MXNetError
from ..ops.registry import Param, register_op


# ----------------------------------------------------------------------
# GridGenerator
# ----------------------------------------------------------------------
def _affine_grid(theta, H, W):
    """theta (N, 6) → normalized sampling grid (N, 2, H, W) in
    [-1, 1] (x, y) — the reference's affine convention."""
    xs = jnp.linspace(-1.0, 1.0, W)
    ys = jnp.linspace(-1.0, 1.0, H)
    gx, gy = jnp.meshgrid(xs, ys)               # (H, W)
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=0).reshape(3, -1)  # (3, HW)

    def one(th):
        m = th.reshape(2, 3)
        out = m @ base                          # (2, HW)
        return out.reshape(2, H, W)

    return jax.vmap(one)(theta)


def _grid_generator(data, transform_type="affine", target_shape=(0, 0)):
    if transform_type == "affine":
        H, W = int(target_shape[0]), int(target_shape[1])
        if H <= 0 or W <= 0:
            raise MXNetError(
                "GridGenerator(affine) needs target_shape=(H, W)")
        return _affine_grid(data, H, W)
    if transform_type == "warp":
        # data: flow field (N, 2, H, W) in pixels; grid = identity+flow
        N, _, H, W = data.shape
        xs = jnp.arange(W, dtype=jnp.float32)
        ys = jnp.arange(H, dtype=jnp.float32)
        gx, gy = jnp.meshgrid(xs, ys)
        px = gx[None] + data[:, 0]
        py = gy[None] + data[:, 1]
        # normalize to [-1, 1]
        nx = 2.0 * px / jnp.maximum(W - 1, 1) - 1.0
        ny = 2.0 * py / jnp.maximum(H - 1, 1) - 1.0
        return jnp.stack([nx, ny], axis=1)
    raise MXNetError(f"GridGenerator transform_type {transform_type!r} "
                     f"unsupported")


register_op("GridGenerator", num_inputs=1,
            params=[Param("transform_type", str, "affine",
                          enum=("affine", "warp")),
                    Param("target_shape", tuple, (0, 0))])(
    _grid_generator)


# ----------------------------------------------------------------------
# BilinearSampler
# ----------------------------------------------------------------------
def _bilinear_sample(data, grid):
    """data (N, C, H, W); grid (N, 2, Ho, Wo) normalized [-1, 1]
    (x, y).  Zero padding outside the input (reference
    ``BilinearSampler``†)."""
    N, C, H, W = data.shape
    x = (grid[:, 0] + 1.0) * (W - 1) / 2.0      # (N, Ho, Wo)
    y = (grid[:, 1] + 1.0) * (H - 1) / 2.0
    x0 = jnp.floor(x)
    y0 = jnp.floor(y)
    wx = x - x0
    wy = y - y0

    def corner(xi, yi):
        inb = (xi >= 0) & (xi <= W - 1) & (yi >= 0) & (yi <= H - 1)
        xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
        yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)

        def per_image(img, yc1, xc1, inb1):
            # img (C, H, W); index maps (Ho, Wo)
            vals = img[:, yc1, xc1]             # (C, Ho, Wo)
            return jnp.where(inb1[None], vals, 0.0)

        return jax.vmap(per_image)(data, yc, xc, inb)

    v00 = corner(x0, y0)
    v01 = corner(x0 + 1, y0)
    v10 = corner(x0, y0 + 1)
    v11 = corner(x0 + 1, y0 + 1)
    wx = wx[:, None]
    wy = wy[:, None]
    return (v00 * (1 - wx) * (1 - wy) + v01 * wx * (1 - wy) +
            v10 * (1 - wx) * wy + v11 * wx * wy).astype(data.dtype)


register_op("BilinearSampler", num_inputs=2)(_bilinear_sample)


def _spatial_transformer(data, loc, target_shape=(0, 0),
                         transform_type="affine",
                         sampler_type="bilinear"):
    if transform_type != "affine" or sampler_type != "bilinear":
        raise MXNetError("SpatialTransformer supports affine+bilinear")
    H, W = int(target_shape[0]), int(target_shape[1])
    if H <= 0 or W <= 0:
        H, W = data.shape[2], data.shape[3]
    grid = _affine_grid(loc, H, W)
    return _bilinear_sample(data, grid)


register_op("SpatialTransformer", num_inputs=2,
            params=[Param("target_shape", tuple, (0, 0)),
                    Param("transform_type", str, "affine"),
                    Param("sampler_type", str, "bilinear")])(
    _spatial_transformer)


# ----------------------------------------------------------------------
# Crop
# ----------------------------------------------------------------------
def _crop(*inputs, offset=(0, 0), h_w=(0, 0), center_crop=False,
          num_args=1):
    """Reference ``Crop``†: crop inputs[0] spatially to h_w (or to
    inputs[1]'s spatial dims when two inputs are given)."""
    data = inputs[0]
    if len(inputs) > 1:
        th, tw = inputs[1].shape[2], inputs[1].shape[3]
    else:
        th, tw = int(h_w[0]), int(h_w[1])
        if th <= 0 or tw <= 0:
            raise MXNetError("Crop needs h_w or a second reference "
                             "input")
    H, W = data.shape[2], data.shape[3]
    if center_crop:
        oy, ox = (H - th) // 2, (W - tw) // 2
    else:
        oy, ox = int(offset[0]), int(offset[1])
    if oy + th > H or ox + tw > W:
        raise MXNetError(f"Crop window ({oy}+{th}, {ox}+{tw}) exceeds "
                         f"input ({H}, {W})")
    return data[:, :, oy:oy + th, ox:ox + tw]


register_op("Crop", num_inputs=-1,
            params=[Param("offset", tuple, (0, 0)),
                    Param("h_w", tuple, (0, 0)),
                    Param("center_crop", bool, False),
                    Param("num_args", int, 1)])(_crop)


# ----------------------------------------------------------------------
# Correlation (FlowNet)
# ----------------------------------------------------------------------
def _correlation(data1, data2, kernel_size=1, max_displacement=1,
                 stride1=1, stride2=1, pad_size=0,
                 is_multiply=True):
    """Patch correlation between two feature maps (reference
    ``Correlation``†).  Output channel d enumerates the
    (2·max_disp/stride2+1)² displacement grid."""
    if kernel_size != 1:
        raise MXNetError("Correlation: only kernel_size=1 is "
                         "supported (the FlowNet configuration)")
    if pad_size:
        pad = ((0, 0), (0, 0), (pad_size, pad_size),
               (pad_size, pad_size))
        data1 = jnp.pad(data1, pad)
        data2 = jnp.pad(data2, pad)
    N, C, H, W = data1.shape
    d = int(max_displacement)
    s2 = int(stride2)
    offsets = range(-d, d + 1, s2)
    outs = []
    for dy in offsets:
        for dx in offsets:
            shifted = jnp.roll(data2, (-dy, -dx), axis=(2, 3))
            # zero out wrapped regions
            ys = jnp.arange(H)
            xs = jnp.arange(W)
            vy = (ys + dy >= 0) & (ys + dy < H)
            vx = (xs + dx >= 0) & (xs + dx < W)
            mask = (vy[:, None] & vx[None, :]).astype(data1.dtype)
            if is_multiply:
                corr = jnp.mean(data1 * shifted, axis=1)
            else:
                corr = jnp.mean(jnp.abs(data1 - shifted), axis=1)
            outs.append(corr * mask[None])
    out = jnp.stack(outs, axis=1)  # (N, D², H, W)
    if stride1 > 1:
        out = out[:, :, ::stride1, ::stride1]
    return out


register_op("Correlation", num_inputs=2,
            params=[Param("kernel_size", int, 1),
                    Param("max_displacement", int, 1),
                    Param("stride1", int, 1),
                    Param("stride2", int, 1),
                    Param("pad_size", int, 0),
                    Param("is_multiply", bool, True)])(_correlation)
