"""Operator library: lowering rules for the framework op inventory.

TPU-native re-design of ``src/operator/``† (~500 ops: ``tensor/`` elemwise/
broadcast/reduce/matrix/indexing/ordering families, ``nn/`` convolution/
pooling/norm/activation/dropout, optimizer ops, random samplers).  Instead
of per-device FCompute kernels, every op is ONE pure jax lowering rule that
XLA fuses and schedules for the MXU; gradients come from jax AD rather than
hand-written FGradient entries (SURVEY.md §2.1-N8).

Naming parity: op names follow the reference's public API
(``broadcast_add``, ``FullyConnected``, ``Pooling``…) so reference-era user
code keeps working.  Parameter names match the reference's
``dmlc::Parameter`` fields (kernel/stride/pad/num_filter/num_hidden…).
"""
from __future__ import annotations

import functools
import math
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .. import amp as _amp
from ..base import MXNetError
from ..ops.registry import Param, register_op

# ======================================================================
# helpers
# ======================================================================

def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def _tuple(v, n):
    if v is None:
        return (1,) * n
    if isinstance(v, int):
        return (v,) * n
    t = tuple(int(x) for x in v)
    if len(t) == 1:
        return t * n
    return t


# ======================================================================
# unary elementwise family (src/operator/tensor/elemwise_unary_op*.cc†,
# scalar functors in src/operator/mshadow_op.h†)
# ======================================================================
_UNARY = {
    "abs": jnp.abs, "sign": jnp.sign, "negative": jnp.negative,
    "reciprocal": jnp.reciprocal, "square": jnp.square, "sqrt": jnp.sqrt,
    "rsqrt": lambda x: lax.rsqrt(x), "cbrt": jnp.cbrt,
    "rcbrt": lambda x: 1.0 / jnp.cbrt(x),
    "exp": jnp.exp, "log": jnp.log, "log2": jnp.log2, "log10": jnp.log10,
    "log1p": jnp.log1p, "expm1": jnp.expm1,
    "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan,
    "arcsin": jnp.arcsin, "arccos": jnp.arccos, "arctan": jnp.arctan,
    "sinh": jnp.sinh, "cosh": jnp.cosh, "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh, "arccosh": jnp.arccosh, "arctanh": jnp.arctanh,
    "floor": jnp.floor, "ceil": jnp.ceil, "round": jnp.round,
    "rint": jnp.rint, "trunc": jnp.trunc, "fix": jnp.trunc,
    "sigmoid": jax.nn.sigmoid, "softsign": jax.nn.soft_sign,
    "relu": jax.nn.relu, "erf": jax.scipy.special.erf,
    "erfinv": jax.scipy.special.erfinv,
    "gamma": lambda x: jnp.exp(jax.scipy.special.gammaln(x)),
    "gammaln": jax.scipy.special.gammaln,
    "logical_not": lambda x: jnp.logical_not(x).astype(x.dtype)
    if jnp.issubdtype(x.dtype, jnp.floating) else jnp.logical_not(x),
    "degrees": jnp.degrees, "radians": jnp.radians,
    "identity": lambda x: x,
}
_UNARY_NONDIFF = {"sign", "floor", "ceil", "round", "rint", "trunc", "fix",
                  "logical_not"}

for _name, _fn in _UNARY.items():
    register_op(_name, differentiable=_name not in _UNARY_NONDIFF,
                doc=f"elementwise {_name}")(
        (lambda f: lambda x: f(x))(_fn))

register_op("_copy", aliases=("copy",))(lambda x: x)
register_op("BlockGrad", aliases=("stop_gradient",))(
    lambda x: lax.stop_gradient(x))
# (MakeLoss with its defined-gradient semantics is registered with the
# legacy output ops below; alias "make_loss")


# ======================================================================
# binary broadcast family (src/operator/tensor/elemwise_binary_broadcast_op*†)
# ======================================================================
def _cmp(fn):
    return lambda a, b: fn(a, b).astype(
        a.dtype if jnp.issubdtype(a.dtype, jnp.floating) else jnp.float32)


_BINARY = {
    "broadcast_add": (jnp.add, True, ("elemwise_add", "_plus")),
    "broadcast_sub": (jnp.subtract, True, ("elemwise_sub", "_minus")),
    "broadcast_mul": (jnp.multiply, True, ("elemwise_mul", "_mul")),
    "broadcast_div": (jnp.divide, True, ("elemwise_div", "_div",
                                         "_scatter_elemwise_div")),
    "broadcast_mod": (jnp.mod, True, ("_mod",)),
    "broadcast_power": (jnp.power, True, ("_power", "pow")),
    "broadcast_maximum": (jnp.maximum, True, ("maximum", "_maximum")),
    "broadcast_minimum": (jnp.minimum, True, ("minimum", "_minimum")),
    "broadcast_hypot": (jnp.hypot, True, ("_hypot",)),
    "arctan2": (jnp.arctan2, True, ("_arctan2",)),
    "broadcast_equal": (_cmp(jnp.equal), False, ("_equal",)),
    "broadcast_not_equal": (_cmp(jnp.not_equal), False, ("_not_equal",)),
    "broadcast_greater": (_cmp(jnp.greater), False, ("_greater",)),
    "broadcast_greater_equal": (_cmp(jnp.greater_equal), False,
                                ("_greater_equal",)),
    "broadcast_lesser": (_cmp(jnp.less), False, ("_lesser",)),
    "broadcast_lesser_equal": (_cmp(jnp.less_equal), False,
                               ("_lesser_equal",)),
    "broadcast_logical_and": (_cmp(jnp.logical_and), False, ()),
    "broadcast_logical_or": (_cmp(jnp.logical_or), False, ()),
    "broadcast_logical_xor": (_cmp(jnp.logical_xor), False, ()),
}

for _name, (_fn, _diff, _aliases) in _BINARY.items():
    register_op(_name, num_inputs=2, differentiable=_diff,
                aliases=_aliases)((lambda f: lambda a, b: f(a, b))(_fn))

# ======================================================================
# scalar family (src/operator/tensor/elemwise_binary_scalar_op*†) —
# tensor∘scalar with the scalar a typed op param, so Symbol graphs can
# serialize scalar arithmetic the way the reference does
# ======================================================================
_SCALAR_OPS = {
    "_plus_scalar": (lambda x, s: x + s, True,
                     ("_PlusScalar", "_scatter_plus_scalar")),
    "_minus_scalar": (lambda x, s: x - s, True,
                      ("_MinusScalar", "_scatter_minus_scalar")),
    "_rminus_scalar": (lambda x, s: s - x, True, ("_RMinusScalar",)),
    "_mul_scalar": (lambda x, s: x * s, True, ("_MulScalar",)),
    "_div_scalar": (lambda x, s: x / s, True, ("_DivScalar",)),
    "_rdiv_scalar": (lambda x, s: s / x, True, ("_RDivScalar",)),
    "_mod_scalar": (lambda x, s: jnp.mod(x, s), True, ()),
    "_rmod_scalar": (lambda x, s: jnp.mod(s, x), True, ()),
    "_power_scalar": (lambda x, s: jnp.power(x, s), True,
                      ("_PowerScalar",)),
    "_rpower_scalar": (lambda x, s: jnp.power(s, x), True,
                       ("_RPowerScalar",)),
    "_maximum_scalar": (lambda x, s: jnp.maximum(x, s), True,
                        ("_MaximumScalar",)),
    "_minimum_scalar": (lambda x, s: jnp.minimum(x, s), True,
                        ("_MinimumScalar",)),
    "_hypot_scalar": (lambda x, s: jnp.hypot(x, s), True, ()),
    "_equal_scalar": (lambda x, s: (x == s).astype(x.dtype), False, ()),
    "_not_equal_scalar": (lambda x, s: (x != s).astype(x.dtype), False, ()),
    "_greater_scalar": (lambda x, s: (x > s).astype(x.dtype), False, ()),
    "_greater_equal_scalar": (lambda x, s: (x >= s).astype(x.dtype),
                              False, ()),
    "_lesser_scalar": (lambda x, s: (x < s).astype(x.dtype), False, ()),
    "_lesser_equal_scalar": (lambda x, s: (x <= s).astype(x.dtype),
                             False, ()),
}

for _name, (_fn, _diff, _aliases) in _SCALAR_OPS.items():
    register_op(_name, params=[Param("scalar", float, 0.0)],
                differentiable=_diff, aliases=_aliases)(
        (lambda f: lambda x, scalar=0.0: f(x, scalar))(_fn))


register_op("smooth_l1", params=[Param("scalar", float, 1.0)])(
    lambda x, scalar=1.0: jnp.where(
        jnp.abs(x) < 1.0 / (scalar ** 2),
        0.5 * (scalar * x) ** 2,
        jnp.abs(x) - 0.5 / (scalar ** 2)))


# ======================================================================
# reductions (src/operator/tensor/broadcast_reduce_op*†)
# ======================================================================
def _reduce(fn, name, diff=True, int_out=False):
    def rule(x, axis=None, keepdims=False, exclude=False):
        ax = _norm_axis(axis)
        if exclude and ax is not None:
            axt = (ax,) if isinstance(ax, int) else ax
            ax = tuple(i for i in range(x.ndim) if i not in axt)
        return fn(x, axis=ax, keepdims=bool(keepdims))
    register_op(name, params=[
        Param("axis", tuple, None), Param("keepdims", bool, False),
        Param("exclude", bool, False)], differentiable=diff)(rule)


for _n, _f in [("sum", jnp.sum), ("mean", jnp.mean), ("prod", jnp.prod),
               ("max", jnp.max), ("min", jnp.min),
               ("nansum", jnp.nansum), ("nanprod", jnp.nanprod)]:
    _reduce(_f, _n)

register_op("sum_axis", params=[
    Param("axis", tuple, None), Param("keepdims", bool, False)],
    aliases=())(lambda x, axis=None, keepdims=False:
                jnp.sum(x, axis=_norm_axis(axis), keepdims=keepdims))

for _n, _f in [("argmax", jnp.argmax), ("argmin", jnp.argmin)]:
    register_op(_n, params=[Param("axis", tuple, None),
                            Param("keepdims", bool, False)],
                differentiable=False)(
        (lambda f: lambda x, axis=None, keepdims=False: f(
            x, axis=None if axis is None else int(axis[0])
            if isinstance(axis, tuple) else int(axis),
            keepdims=keepdims).astype(jnp.float32))(_f))

register_op("norm", params=[Param("ord", int, 2),
                            Param("axis", tuple, None),
                            Param("keepdims", bool, False)])(
    lambda x, ord=2, axis=None, keepdims=False:
    jnp.linalg.norm(x.reshape(-1) if axis is None and not keepdims else x,
                    ord=ord, axis=_norm_axis(axis), keepdims=keepdims)
    if axis is not None or keepdims else
    jnp.linalg.norm(x.reshape(-1), ord=ord).reshape((1,)))

register_op("L2Normalization", params=[Param("eps", float, 1e-10),
                                       Param("mode", str, "instance")])(
    lambda x, eps=1e-10, mode="instance":
    x / jnp.sqrt(jnp.sum(jnp.square(x),
                         axis=tuple(range(1, x.ndim)) if mode == "instance"
                         else (1,), keepdims=True) + eps))


# ======================================================================
# shape / layout ops (src/operator/tensor/matrix_op*†)
# ======================================================================
def _reshape(x, shape=None):
    # supports the reference's special codes 0 (keep) and -1 (infer)
    shape = tuple(shape)
    out = []
    for i, s in enumerate(shape):
        if s == 0:
            out.append(x.shape[i])
        else:
            out.append(int(s))
    return jnp.reshape(x, tuple(out))


register_op("reshape", params=[Param("shape", tuple, None)],
            aliases=("Reshape",))(_reshape)
register_op("reshape_like", num_inputs=2)(
    lambda x, y: jnp.reshape(x, y.shape))
register_op("transpose", params=[Param("axes", tuple, None)])(
    lambda x, axes=None: jnp.transpose(x, axes))
register_op("expand_dims", params=[Param("axis", int, 0)])(
    lambda x, axis=0: jnp.expand_dims(x, axis))
register_op("squeeze", params=[Param("axis", tuple, None)])(
    lambda x, axis=None: jnp.squeeze(x, _norm_axis(axis)))
register_op("flatten", aliases=("Flatten",))(
    lambda x: jnp.reshape(x, (x.shape[0], -1)))
register_op("broadcast_to", params=[Param("shape", tuple, None)])(
    lambda x, shape=None: jnp.broadcast_to(
        x, tuple(int(x.shape[i]) if s == 0 else int(s)
                 for i, s in enumerate(shape))))
register_op("broadcast_like", num_inputs=2)(
    lambda x, y: jnp.broadcast_to(x, y.shape))
register_op("broadcast_axis", params=[Param("axis", tuple, ()),
                                      Param("size", tuple, ())])(
    lambda x, axis=(), size=():
    jnp.broadcast_to(x, tuple(
        int(dict(zip(axis, size)).get(i, x.shape[i]))
        for i in range(x.ndim))))
register_op("tile", params=[Param("reps", tuple, None)])(
    lambda x, reps=None: jnp.tile(x, reps))
register_op("repeat", params=[Param("repeats", int, 1),
                              Param("axis", tuple, None)])(
    lambda x, repeats=1, axis=None: jnp.repeat(
        x, repeats, axis=None if axis is None else int(axis[0])
        if isinstance(axis, tuple) else axis))
register_op("flip", params=[Param("axis", tuple, None)],
            aliases=("reverse",))(
    lambda x, axis=None: jnp.flip(x, _norm_axis(axis)))
register_op("swapaxes", params=[Param("dim1", int, 0),
                                Param("dim2", int, 0)],
            aliases=("SwapAxis",))(
    lambda x, dim1=0, dim2=0: jnp.swapaxes(x, dim1, dim2))
register_op("diag", params=[Param("k", int, 0)])(
    lambda x, k=0: jnp.diag(x, k) if x.ndim <= 2 else
    jnp.diagonal(x, k, -2, -1))
register_op("depth_to_space", params=[Param("block_size", int, None)])(
    lambda x, block_size=None: _depth_to_space(x, block_size))
register_op("space_to_depth", params=[Param("block_size", int, None)])(
    lambda x, block_size=None: _space_to_depth(x, block_size))


def _depth_to_space(x, b):
    n, c, h, w = x.shape
    x = x.reshape(n, b, b, c // (b * b), h, w)
    x = x.transpose(0, 3, 4, 1, 5, 2)
    return x.reshape(n, c // (b * b), h * b, w * b)


def _space_to_depth(x, b):
    n, c, h, w = x.shape
    x = x.reshape(n, c, h // b, b, w // b, b)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return x.reshape(n, c * b * b, h // b, w // b)


def _slice(x, begin=None, end=None, step=None):
    nd = x.ndim
    begin = tuple(begin) + (None,) * (nd - len(begin))
    end = tuple(end) + (None,) * (nd - len(end))
    step = (tuple(step) if step else ()) + (None,) * (
        nd - (len(step) if step else 0))
    idx = tuple(slice(b, e, s) for b, e, s in zip(begin, end, step))
    return x[idx]


register_op("slice", params=[Param("begin", tuple, ()),
                             Param("end", tuple, ()),
                             Param("step", tuple, None)])(_slice)
register_op("slice_axis", params=[Param("axis", int, 0),
                                  Param("begin", int, 0),
                                  Param("end", tuple, None)])(
    lambda x, axis=0, begin=0, end=None:
    lax.slice_in_dim(x, begin,
                     x.shape[axis] if end is None or
                     (isinstance(end, tuple) and end[0] is None)
                     else (int(end[0]) if isinstance(end, tuple) else int(end)),
                     axis=axis))
register_op("slice_like", num_inputs=2,
            params=[Param("axes", tuple, ())])(
    lambda x, y, axes=(): x[tuple(
        slice(0, y.shape[i]) if (not axes or i in axes or
                                 (i - x.ndim) in axes) else slice(None)
        for i in range(x.ndim))])


def _split(x, num_outputs=1, axis=1, squeeze_axis=False):
    parts = jnp.split(x, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts) if num_outputs > 1 else parts[0]


register_op("split", params=[Param("num_outputs", int, 1),
                             Param("axis", int, 1),
                             Param("squeeze_axis", bool, False)],
            num_outputs=-1, aliases=("SliceChannel",))(_split)

register_op("concat", num_inputs=-1, params=[Param("dim", int, 1)],
            aliases=("Concat",))(
    lambda *xs, dim=1: jnp.concatenate(xs, axis=dim))
register_op("stack", num_inputs=-1, params=[Param("axis", int, 0)])(
    lambda *xs, axis=0: jnp.stack(xs, axis=axis))
register_op("add_n", num_inputs=-1, aliases=("ElementWiseSum",))(
    lambda *xs: functools.reduce(jnp.add, xs))

register_op("clip", params=[Param("a_min", float, None),
                            Param("a_max", float, None)])(
    lambda x, a_min=None, a_max=None: jnp.clip(x, a_min, a_max))
register_op("cast", params=[Param("dtype", str, "float32")],
            aliases=("Cast",))(
    lambda x, dtype="float32": x.astype(
        jnp.bfloat16 if dtype == "bfloat16" else np.dtype(dtype)))
register_op("zeros_like")(lambda x: jnp.zeros_like(x))
register_op("ones_like")(lambda x: jnp.ones_like(x))
register_op("shape_array", differentiable=False)(
    lambda x: jnp.asarray(x.shape, dtype=jnp.int64)
    if jax.config.jax_enable_x64 else jnp.asarray(x.shape, jnp.int32))
register_op("size_array", differentiable=False)(
    lambda x: jnp.asarray([math.prod(x.shape)], jnp.int32))


def _pad(x, mode="constant", pad_width=None, constant_value=0.0):
    pw = tuple(pad_width)
    pairs = tuple((int(pw[2 * i]), int(pw[2 * i + 1]))
                  for i in range(len(pw) // 2))
    jmode = {"constant": "constant", "edge": "edge",
             "reflect": "reflect"}[mode]
    if jmode == "constant":
        return jnp.pad(x, pairs, mode="constant",
                       constant_values=constant_value)
    return jnp.pad(x, pairs, mode=jmode)


register_op("pad", params=[Param("mode", str, "constant",
                                 enum=("constant", "edge", "reflect")),
                           Param("pad_width", tuple, ()),
                           Param("constant_value", float, 0.0)],
            aliases=("Pad",))(_pad)


# ======================================================================
# indexing ops (src/operator/tensor/indexing_op*†)
# ======================================================================
def _take(a, indices, axis=0, mode="clip"):
    idx = indices.astype(jnp.int32)
    return jnp.take(a, idx, axis=axis,
                    mode="clip" if mode == "clip" else "wrap")


register_op("take", num_inputs=2,
            params=[Param("axis", int, 0),
                    Param("mode", str, "clip",
                          enum=("clip", "wrap", "raise"))])(_take)

register_op("Embedding", num_inputs=2,
            params=[Param("input_dim", int, 0),
                    Param("output_dim", int, 0),
                    Param("dtype", str, "float32"),
                    Param("sparse_grad", bool, False)],
            aliases=("embedding",))(
    lambda data, weight, input_dim=0, output_dim=0, dtype="float32",
    sparse_grad=False: jnp.take(weight, data.astype(jnp.int32), axis=0))

register_op("one_hot", params=[Param("depth", int, None),
                               Param("on_value", float, 1.0),
                               Param("off_value", float, 0.0),
                               Param("dtype", str, "float32")],
            differentiable=False)(
    lambda x, depth=None, on_value=1.0, off_value=0.0, dtype="float32":
    jax.nn.one_hot(x.astype(jnp.int32), depth, dtype=np.dtype(dtype))
    * (on_value - off_value) + off_value)

register_op("gather_nd", num_inputs=2)(
    lambda data, indices: data[tuple(indices.astype(jnp.int32))])


def _scatter_nd(data, indices, shape=None):
    out = jnp.zeros(tuple(shape), dtype=data.dtype)
    return out.at[tuple(indices.astype(jnp.int32))].add(data)


register_op("scatter_nd", num_inputs=2,
            params=[Param("shape", tuple, None)])(_scatter_nd)

def _pick(data, index, axis=(-1,), keepdims=False, mode="clip"):
    ax = int(axis[0]) if isinstance(axis, tuple) else int(axis)
    idx = jnp.clip(index.astype(jnp.int32), 0, data.shape[ax] - 1)
    out = jnp.take_along_axis(data, jnp.expand_dims(idx, ax), axis=ax)
    return out if keepdims else jnp.squeeze(out, axis=ax)


register_op("pick", num_inputs=2,
            params=[Param("axis", tuple, (-1,)),
                    Param("keepdims", bool, False),
                    Param("mode", str, "clip")])(_pick)

register_op("where", num_inputs=3)(
    lambda cond, x, y: jnp.where(cond.astype(bool), x, y))

register_op("SequenceMask", num_inputs=-1,
            params=[Param("use_sequence_length", bool, False),
                    Param("value", float, 0.0),
                    Param("axis", int, 0)])(
    lambda data, *seq, use_sequence_length=False, value=0.0, axis=0:
    _sequence_mask(data, seq[0], value, axis)
    if use_sequence_length and seq else data)


def _sequence_mask(data, seq_len, value, axis):
    # data: (T, N, ...) if axis=0 else (N, T, ...)
    T = data.shape[axis]
    pos = jnp.arange(T)
    if axis == 0:
        mask = pos[:, None] < seq_len.astype(jnp.int32)[None, :]
        mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    else:
        mask = pos[None, :] < seq_len.astype(jnp.int32)[:, None]
        mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    return jnp.where(mask, data, value)


def _sequence_last(data, seq_len, axis):
    if seq_len is None:
        return jnp.take(data, data.shape[axis] - 1, axis=axis)
    idx = (seq_len.astype(jnp.int32) - 1)
    if axis == 0:
        idx = idx.reshape((1, -1) + (1,) * (data.ndim - 2))
    else:
        idx = idx.reshape((-1, 1) + (1,) * (data.ndim - 2))
    return jnp.take_along_axis(data, idx, axis=axis).squeeze(axis)


register_op("SequenceLast", num_inputs=-1,
            params=[Param("use_sequence_length", bool, False),
                    Param("axis", int, 0)])(
    lambda data, *seq, use_sequence_length=False, axis=0:
    _sequence_last(data, seq[0] if (use_sequence_length and seq) else None,
                   axis))

register_op("SequenceReverse", num_inputs=-1,
            params=[Param("use_sequence_length", bool, False),
                    Param("axis", int, 0)])(
    lambda data, *seq, use_sequence_length=False, axis=0:
    _seq_reverse(data, seq[0], axis)
    if use_sequence_length and seq else jnp.flip(data, axis))


def _seq_reverse(data, seq_len, axis):
    # reverse the first seq_len[n] entries along `axis` per batch row;
    # batch axis is the other of {0, 1}
    T = data.shape[axis]
    batch_axis = 1 - axis
    pos = jnp.arange(T)
    sl = seq_len.astype(jnp.int32)
    if axis == 0:
        src = jnp.where(pos[:, None] < sl[None, :],
                        sl[None, :] - 1 - pos[:, None], pos[:, None])
    else:
        src = jnp.where(pos[None, :] < sl[:, None],
                        sl[:, None] - 1 - pos[None, :], pos[None, :])
    src = src.reshape(src.shape + (1,) * (data.ndim - 2))
    return jnp.take_along_axis(data, src, axis=axis)


# ======================================================================
# ordering (src/operator/tensor/ordering_op*†)
# ======================================================================
def _sort(x, axis=(-1,), is_ascend=True):
    ax = int(axis[0]) if isinstance(axis, tuple) else int(axis)
    s = jnp.sort(x, axis=ax)
    # flip instead of negate: negation is wrong for unsigned/bool dtypes
    return s if is_ascend else jnp.flip(s, axis=ax)


register_op("sort", params=[Param("axis", tuple, (-1,)),
                            Param("is_ascend", bool, True)])(_sort)


def _argsort(x, axis=(-1,), is_ascend=True, dtype="float32"):
    ax = int(axis[0]) if isinstance(axis, tuple) else int(axis)
    idx = jnp.argsort(x, axis=ax)
    if not is_ascend:
        idx = jnp.flip(idx, axis=ax)
    return idx.astype(np.dtype(dtype))


register_op("argsort", params=[Param("axis", tuple, (-1,)),
                               Param("is_ascend", bool, True),
                               Param("dtype", str, "float32")],
            differentiable=False)(_argsort)


def _topk(x, axis=(-1,), k=1, ret_typ="indices", is_ascend=False,
          dtype="float32"):
    ax = int(axis[0]) if isinstance(axis, tuple) else int(axis)
    xm = jnp.moveaxis(x, ax, -1)
    vals, idx = lax.top_k(-xm if is_ascend else xm, k)
    if is_ascend:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, ax)
    idx = jnp.moveaxis(idx, -1, ax).astype(np.dtype(dtype))
    if ret_typ == "value":
        return vals
    if ret_typ == "indices":
        return idx
    if ret_typ == "both":
        return vals, idx
    raise MXNetError(f"topk ret_typ {ret_typ} unsupported")


register_op("topk", params=[Param("axis", tuple, (-1,)),
                            Param("k", int, 1),
                            Param("ret_typ", str, "indices"),
                            Param("is_ascend", bool, False),
                            Param("dtype", str, "float32")],
            num_outputs=-1, differentiable=False)(_topk)


# ======================================================================
# linalg (src/operator/tensor/dot.cc†, la_op.cc†)
# ======================================================================
def _dot(a, b, transpose_a=False, transpose_b=False):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
    pref = _amp.matmul_preferred(a, b)
    if a.ndim == 1 and b.ndim == 1:
        if pref is not None:  # bf16 fwd+bwd GEMMs, f32 accumulation
            return _amp.dot_general(a, b, (((0,), (0,)), ((), ())))
        return jnp.dot(a, b, preferred_element_type=pref)
    # reference dot: contract last axis of a with first axis of b
    if pref is not None:
        return _amp.dot_general(a, b,
                                (((a.ndim - 1,), (0,)), ((), ())))
    return jnp.tensordot(a, b, axes=([a.ndim - 1], [0]),
                         preferred_element_type=pref)


register_op("dot", num_inputs=2,
            params=[Param("transpose_a", bool, False),
                    Param("transpose_b", bool, False)])(_dot)

def _matmul2(a, b):
    pref = _amp.matmul_preferred(a, b)
    if pref is not None and a.ndim >= 2 and b.ndim >= 2:
        # bf16 fwd+bwd GEMMs, f32 accumulation (amp's custom VJP)
        return _amp.matmul(a, b)
    return jnp.matmul(a, b, preferred_element_type=pref)


register_op("batch_dot", num_inputs=2,
            params=[Param("transpose_a", bool, False),
                    Param("transpose_b", bool, False)])(
    lambda a, b, transpose_a=False, transpose_b=False:
    _matmul2(jnp.swapaxes(a, -1, -2) if transpose_a else a,
             jnp.swapaxes(b, -1, -2) if transpose_b else b))

register_op("matmul", num_inputs=2)(_matmul2)

register_op("linalg_gemm2", num_inputs=2,
            params=[Param("transpose_a", bool, False),
                    Param("transpose_b", bool, False),
                    Param("alpha", float, 1.0)])(
    lambda a, b, transpose_a=False, transpose_b=False, alpha=1.0:
    alpha * _matmul2(jnp.swapaxes(a, -1, -2) if transpose_a else a,
                     jnp.swapaxes(b, -1, -2) if transpose_b else b))
register_op("linalg_gemm", num_inputs=3,
            params=[Param("transpose_a", bool, False),
                    Param("transpose_b", bool, False),
                    Param("alpha", float, 1.0),
                    Param("beta", float, 1.0)])(
    lambda a, b, c, transpose_a=False, transpose_b=False, alpha=1.0,
    beta=1.0: alpha * _matmul2(
        jnp.swapaxes(a, -1, -2) if transpose_a else a,
        jnp.swapaxes(b, -1, -2) if transpose_b else b) + beta * c)
register_op("linalg_potrf")(lambda a: jnp.linalg.cholesky(a))
register_op("linalg_trsm", num_inputs=2,
            params=[Param("transpose", bool, False),
                    Param("rightside", bool, False),
                    Param("lower", bool, True),
                    Param("alpha", float, 1.0)])(
    lambda a, b, transpose=False, rightside=False, lower=True, alpha=1.0:
    alpha * jax.scipy.linalg.solve_triangular(
        jnp.swapaxes(a, -1, -2) if transpose else a, b,
        lower=lower != transpose, trans=0)
    if not rightside else
    alpha * jnp.swapaxes(jax.scipy.linalg.solve_triangular(
        jnp.swapaxes(a if transpose else jnp.swapaxes(a, -1, -2), -1, -2),
        jnp.swapaxes(b, -1, -2), lower=not (lower != transpose)), -1, -2))
register_op("linalg_syrk", params=[Param("transpose", bool, False),
                                   Param("alpha", float, 1.0)])(
    lambda a, transpose=False, alpha=1.0:
    alpha * (jnp.matmul(jnp.swapaxes(a, -1, -2), a) if transpose
             else jnp.matmul(a, jnp.swapaxes(a, -1, -2))))
register_op("linalg_sumlogdiag")(
    lambda a: jnp.sum(jnp.log(jnp.diagonal(a, axis1=-2, axis2=-1)),
                      axis=-1))
register_op("linalg_extractdiag", params=[Param("offset", int, 0)])(
    lambda a, offset=0: jnp.diagonal(a, offset, -2, -1))
register_op("linalg_inverse")(lambda a: jnp.linalg.inv(a))
register_op("linalg_det")(lambda a: jnp.linalg.det(a))
register_op("khatri_rao", num_inputs=-1)(
    lambda *xs: functools.reduce(
        lambda a, b: jnp.einsum("ir,jr->ijr", a, b).reshape(-1, a.shape[1]),
        xs))


# ======================================================================
# neural-net ops (src/operator/nn/†)
# ======================================================================
register_op("FullyConnected", num_inputs=-1,
            params=[Param("num_hidden", int, 0),
                    Param("no_bias", bool, False),
                    Param("flatten", bool, True)],
            aliases=("fully_connected",))(
    lambda data, weight, *maybe_bias, num_hidden=0, no_bias=False,
    flatten=True: _fully_connected(data, weight,
                                   maybe_bias[0] if maybe_bias else None,
                                   no_bias, flatten))


def _fully_connected(x, w, b, no_bias, flatten):
    if flatten and x.ndim > 2:
        x = x.reshape(x.shape[0], -1)
    pref = _amp.matmul_preferred(x, w)
    if pref is not None:  # bf16 fwd+bwd GEMMs, f32 accumulation
        y = _amp.dot_general(x, w,
                             (((x.ndim - 1,), (1,)), ((), ())))
    else:
        y = jnp.matmul(x, w.T, preferred_element_type=pref)
    if b is not None and not no_bias:
        y = y + b
    return y


_CONV_DN = {  # layout string -> (lhs, rhs, out) dimension numbers
    # weight follows the reference's convention: kernel dims take the
    # data layout's spatial order, so channels-last layouts store
    # weights O<spatial>I (e.g. NHWC -> OHWI), matching
    # src/operator/nn/convolution.cc† kernel layouts
    "NCHW": ("NCHW", "OIHW", "NCHW"),
    "NHWC": ("NHWC", "OHWI", "NHWC"),
    "NCW": ("NCH", "OIH", "NCH"),
    "NWC": ("NHC", "OHI", "NHC"),
    "NCDHW": ("NCDHW", "OIDHW", "NCDHW"),
    "NDHWC": ("NDHWC", "ODHWI", "NDHWC"),
}


def _convolution(x, w, b=None, kernel=(), stride=None, dilate=None,
                 pad=None, num_filter=0, num_group=1, no_bias=False,
                 layout=None):
    nd = len(kernel)
    layout = layout or {1: "NCW", 2: "NCHW", 3: "NCDHW"}[nd]
    dn = _CONV_DN[layout]
    stride = _tuple(stride, nd)
    dilate = _tuple(dilate, nd)
    pad = _tuple(pad, nd) if pad is not None else (0,) * nd
    if _amp.matmul_preferred(x, w) is not None:
        # bf16 operands under autocast: lax's builtin conv transpose
        # rule rejects the f32-cotangent/bf16-operand pair, so the
        # f32-accumulating conv carries its own VJP in mxtpu.amp
        out = _amp.conv_general(
            x, w, stride, tuple((p, p) for p in pad), dilate, dn,
            num_group)
    else:
        out = lax.conv_general_dilated(
            x, w, window_strides=stride,
            padding=[(p, p) for p in pad],
            rhs_dilation=dilate,
            dimension_numbers=dn,
            feature_group_count=num_group)
    if b is not None and not no_bias:
        if layout.endswith("C"):
            out = out + b
        else:
            out = out + b.reshape((1, -1) + (1,) * nd)
    return out


register_op("Convolution", num_inputs=-1,
            params=[Param("kernel", tuple, ()),
                    Param("stride", tuple, None),
                    Param("dilate", tuple, None),
                    Param("pad", tuple, None),
                    Param("num_filter", int, 0),
                    Param("num_group", int, 1),
                    Param("no_bias", bool, False),
                    Param("layout", str, None)],
            aliases=("convolution", "Convolution_v1"))(
    lambda data, weight, *b, **kw: _convolution(
        data, weight, b[0] if b else None, **kw))


def _deconvolution(x, w, b=None, kernel=(), stride=None, dilate=None,
                   pad=None, adj=None, num_filter=0, num_group=1,
                   no_bias=False, layout=None):
    nd = len(kernel)
    layout = layout or {1: "NCW", 2: "NCHW", 3: "NCDHW"}[nd]
    dn = _CONV_DN[layout]
    stride = _tuple(stride, nd)
    pad = _tuple(pad, nd) if pad is not None else (0,) * nd
    # MXNet deconv weight layout (in, out, kH, kW) == the forward-conv
    # OIHW kernel of the conv this op transposes, which is exactly what
    # lax.conv_transpose(transpose_kernel=True) expects.  Explicit padding
    # pairs apply to the stride-dilated input, so the reference's `pad`
    # (forward-conv padding) maps to k-1-p per side, giving
    # out = (in-1)*stride + kernel - 2*pad like the reference.
    dil = _tuple(dilate, nd)
    tpad = [(dil[i] * (int(kernel[i]) - 1) - pad[i],) * 2
            for i in range(nd)]
    out = lax.conv_transpose(
        x, w, strides=stride, padding=tpad, rhs_dilation=dil,
        dimension_numbers=dn, transpose_kernel=True)
    if b is not None and not no_bias:
        out = out + (b.reshape((1, -1) + (1,) * nd)
                     if layout.startswith("NC") else b)
    return out


register_op("Deconvolution", num_inputs=-1,
            params=[Param("kernel", tuple, ()),
                    Param("stride", tuple, None),
                    Param("dilate", tuple, None),
                    Param("pad", tuple, None),
                    Param("adj", tuple, None),
                    Param("num_filter", int, 0),
                    Param("num_group", int, 1),
                    Param("no_bias", bool, False),
                    Param("layout", str, None)])(
    lambda data, weight, *b, **kw: _deconvolution(
        data, weight, b[0] if b else None, **kw))


def _pooling(x, kernel=(), pool_type="max", global_pool=False, stride=None,
             pad=None, count_include_pad=True, layout=None):
    nd = len(kernel) if kernel else x.ndim - 2
    layout = layout or {1: "NCW", 2: "NCHW", 3: "NCDHW"}[nd]
    channels_last = layout.endswith("C")
    sp_axes = tuple(range(1, 1 + nd)) if channels_last \
        else tuple(range(2, 2 + nd))
    if global_pool:
        if pool_type == "max":
            return jnp.max(x, axis=sp_axes, keepdims=True)
        return jnp.mean(x, axis=sp_axes, keepdims=True)
    stride = _tuple(stride, nd)
    pad = _tuple(pad, nd) if pad is not None else (0,) * nd
    window = [1] * x.ndim
    strides = [1] * x.ndim
    padding = [(0, 0)] * x.ndim
    for i, ax in enumerate(sp_axes):
        window[ax] = int(kernel[i])
        strides[ax] = int(stride[i])
        padding[ax] = (int(pad[i]), int(pad[i]))
    if pool_type == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else \
            jnp.iinfo(x.dtype).min
        return lax.reduce_window(x, init, lax.max, window, strides, padding)
    if pool_type in ("avg", "sum"):
        s = lax.reduce_window(x, 0.0, lax.add, window, strides, padding)
        if pool_type == "sum":
            return s
        if count_include_pad:
            return s / math.prod(int(k) for k in kernel)
        ones = jnp.ones_like(x)
        cnt = lax.reduce_window(ones, 0.0, lax.add, window, strides,
                                padding)
        return s / cnt
    if pool_type == "lp":
        p2 = lax.reduce_window(jnp.square(x), 0.0, lax.add, window,
                               strides, padding)
        return jnp.sqrt(p2)
    raise MXNetError(f"pool_type {pool_type} unsupported")


register_op("Pooling",
            params=[Param("kernel", tuple, ()),
                    Param("pool_type", str, "max",
                          enum=("max", "avg", "sum", "lp")),
                    Param("global_pool", bool, False),
                    Param("stride", tuple, None),
                    Param("pad", tuple, None),
                    Param("count_include_pad", bool, True),
                    Param("layout", str, None)],
            aliases=("pooling", "Pooling_v1"))(_pooling)


def _activation(x, act_type="relu"):
    return {
        "relu": jax.nn.relu, "sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh,
        "softrelu": jax.nn.softplus, "softsign": jax.nn.soft_sign,
    }[act_type](x)


register_op("Activation", params=[
    Param("act_type", str, "relu",
          enum=("relu", "sigmoid", "tanh", "softrelu", "softsign"))],
    aliases=("activation",))(_activation)


def _leaky_relu(x, *extra, act_type="leaky", slope=0.25, lower_bound=0.125,
                upper_bound=0.334):
    if act_type == "leaky":
        return jnp.where(x > 0, x, slope * x)
    if act_type == "prelu":
        gamma = extra[0]
        if gamma.ndim == 1 and x.ndim > 1:
            gamma = gamma.reshape((1, -1) + (1,) * (x.ndim - 2))
        return jnp.where(x > 0, x, gamma * x)
    if act_type == "elu":
        return jnp.where(x > 0, x, slope * (jnp.exp(x) - 1.0))
    if act_type == "selu":
        alpha, scale = 1.6732632423543772, 1.0507009873554805
        return scale * jnp.where(x > 0, x, alpha * (jnp.exp(x) - 1.0))
    if act_type == "gelu":
        return jax.nn.gelu(x, approximate=True)
    if act_type == "rrelu":
        mid = (lower_bound + upper_bound) / 2.0
        return jnp.where(x > 0, x, mid * x)
    raise MXNetError(f"LeakyReLU act_type {act_type} unsupported")


register_op("LeakyReLU", num_inputs=-1,
            params=[Param("act_type", str, "leaky",
                          enum=("leaky", "prelu", "elu", "selu", "gelu",
                                "rrelu")),
                    Param("slope", float, 0.25),
                    Param("lower_bound", float, 0.125),
                    Param("upper_bound", float, 0.334)])(_leaky_relu)


def _softmax(x, axis=-1, temperature=None, length=None):
    if temperature is not None and temperature != 1.0:
        x = x / temperature
    return jax.nn.softmax(x, axis=axis)


register_op("softmax", params=[Param("axis", int, -1),
                               Param("temperature", tuple, None)])(
    lambda x, axis=-1, temperature=None: _softmax(
        x, axis, None if temperature in (None, ()) else float(
            temperature[0] if isinstance(temperature, tuple)
            else temperature)))
register_op("log_softmax", params=[Param("axis", int, -1)])(
    lambda x, axis=-1: jax.nn.log_softmax(x, axis=axis))
register_op("softmin", params=[Param("axis", int, -1)])(
    lambda x, axis=-1: jax.nn.softmax(-x, axis=axis))

register_op("softmax_cross_entropy", num_inputs=2)(
    lambda data, label: -jnp.sum(
        jax.nn.log_softmax(data, axis=-1) *
        jax.nn.one_hot(label.astype(jnp.int32), data.shape[-1])))

@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def _softmax_output_core(data, label, grad_scale, ignore_label,
                         use_ignore, normalization):
    return jax.nn.softmax(data, axis=-1)


def _so_fwd(data, label, grad_scale, ignore_label, use_ignore,
            normalization):
    out = jax.nn.softmax(data, axis=-1)
    return out, (out, label)


def _so_bwd(grad_scale, ignore_label, use_ignore, normalization, res,
            g):
    # Reference semantics (src/operator/softmax_output-inl.h†): the op
    # IS the cross-entropy loss head — backward emits
    # grad_scale * (softmax - onehot(label)) and ignores incoming
    # cotangents (the reference's Backward does the same).
    out, label = res
    onehot = jax.nn.one_hot(label.astype(jnp.int32), out.shape[-1],
                            dtype=out.dtype)
    grad = (out - onehot) * grad_scale
    valid = None
    if use_ignore:
        keep = (label != ignore_label)
        grad = grad * keep[..., None].astype(grad.dtype)
        valid = jnp.maximum(jnp.sum(keep), 1)
    if normalization == "valid":
        n = valid if valid is not None else \
            jnp.asarray(label.size, grad.dtype)
        grad = grad / n
    elif normalization == "batch":
        grad = grad / label.shape[0]
    # integer labels need a float0 tangent per jax's custom_vjp contract
    if jnp.issubdtype(label.dtype, jnp.floating):
        label_ct = jnp.zeros_like(label)
    else:
        label_ct = np.zeros(label.shape, dtype=jax.dtypes.float0)
    return grad, label_ct


_softmax_output_core.defvjp(_so_fwd, _so_bwd)


register_op("SoftmaxOutput", num_inputs=2,
            params=[Param("grad_scale", float, 1.0),
                    Param("ignore_label", float, -1.0),
                    Param("use_ignore", bool, False),
                    Param("multi_output", bool, False),
                    Param("preserve_shape", bool, False),
                    Param("normalization", str, "null")],
            aliases=("Softmax",))(
    lambda data, label, grad_scale=1.0, ignore_label=-1.0,
    use_ignore=False, multi_output=False, preserve_shape=False,
    normalization="null": _softmax_output_core(
        data, label, grad_scale, ignore_label, use_ignore,
        normalization) if not multi_output else _raise(
        MXNetError("SoftmaxOutput multi_output=True (softmax over axis "
                   "1) is not implemented yet — reshape to (N*d, C) "
                   "and use the default mode")))


def _raise(err):
    raise err


def _layer_norm(x, gamma, beta, axis=-1, eps=1e-5):
    if axis in (-1, x.ndim - 1):
        # hot path: fused Pallas kernel on TPU, lax composite elsewhere
        from ..kernels import layer_norm as _fused_ln
        return _fused_ln(x, gamma, beta, eps=eps)
    mean = jnp.mean(x, axis=axis, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=axis, keepdims=True)
    inv = lax.rsqrt(var + eps)
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    return (x - mean) * inv * gamma.reshape(shape) + beta.reshape(shape)


register_op("LayerNorm", num_inputs=3,
            params=[Param("axis", int, -1), Param("eps", float, 1e-5)])(
    _layer_norm)


def _instance_norm(x, gamma, beta, eps=1e-3):
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=axes, keepdims=True)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    return (x - mean) * lax.rsqrt(var + eps) * gamma.reshape(shape) + \
        beta.reshape(shape)


register_op("InstanceNorm", num_inputs=3,
            params=[Param("eps", float, 1e-3)])(_instance_norm)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _bn_train_core(x, gamma, beta, axis, eps):
    out, mean, var, _ = _bn_train_fwd_impl(x, gamma, beta, axis, eps)
    return out, mean, var


def _bn_train_fwd_impl(x, gamma, beta, axis, eps):
    axes = tuple(i for i in range(x.ndim) if i != axis)
    shape = tuple(-1 if i == axis else 1 for i in range(x.ndim))
    n = 1
    for i in axes:
        n *= x.shape[i]
    # statistics in f32 (AMP discipline: bf16 mantissas lose small
    # variance contributions) via E[x^2]-E[x]^2 — ONE fused read of x.
    # The big tensor itself streams in its own dtype: out = x*scale +
    # shift with per-channel f32 scalars, so the pass is bf16-in/
    # bf16-out instead of materialising an f32 copy (2x bandwidth).
    s1 = jnp.sum(x.astype(jnp.float32), axis=axes)
    s2 = jnp.sum(jnp.square(x.astype(jnp.float32)), axis=axes)
    mean = s1 / n
    var = jnp.maximum(s2 / n - jnp.square(mean), 0.0)
    rstd = lax.rsqrt(var + eps)
    g32 = gamma.astype(jnp.float32)
    scale = (g32 * rstd).reshape(shape)
    shift = (beta.astype(jnp.float32) - mean * g32 * rstd).reshape(shape)
    out = (x.astype(jnp.float32) * scale + shift).astype(x.dtype)
    return out, mean, var, rstd


def _bn_core_fwd(x, gamma, beta, axis, eps):
    out, mean, var, rstd = _bn_train_fwd_impl(x, gamma, beta, axis, eps)
    return (out, mean, var), (x, gamma, mean, rstd)


def _bn_core_bwd(axis, eps, res, dys):
    # batch mean/var are the aux-state channel (running-stat EMA);
    # like the reference's FMutateInputs aux states they are not a
    # differentiable output — their cotangents are ignored
    dy = dys[0]
    # Analytic batch-norm backward (2 passes over the big tensors):
    #   dbeta  = sum(dy);  dgamma = sum(dy * xhat)
    #   dx = g*rstd * (dy - dbeta/N - xhat * dgamma/N)
    # vs autodiff of the mean/var graph, which saves f32 residuals of
    # activation size and re-reads them — measured 47 ms of the 121 ms
    # ResNet-50 b256 step before this kernel (BASELINE.md r4).
    x, gamma, mean, rstd = res
    nd_ = x.ndim
    axes = tuple(i for i in range(nd_) if i != axis)
    shape = tuple(-1 if i == axis else 1 for i in range(nd_))
    n = 1
    for i in axes:
        n *= x.shape[i]
    dy32 = dy.astype(jnp.float32)
    xhat = (x.astype(jnp.float32) - mean.reshape(shape)) * \
        rstd.reshape(shape)
    dbeta = jnp.sum(dy32, axis=axes)
    dgamma = jnp.sum(dy32 * xhat, axis=axes)
    g32 = gamma.astype(jnp.float32)
    dx = (g32 * rstd).reshape(shape) * (
        dy32 - (dbeta / n).reshape(shape)
        - xhat * (dgamma / n).reshape(shape))
    return (dx.astype(x.dtype), dgamma.astype(gamma.dtype),
            dbeta.astype(gamma.dtype))


_bn_train_core.defvjp(_bn_core_fwd, _bn_core_bwd)


def _batch_norm(x, gamma, beta, moving_mean, moving_var, eps=1e-5,
                momentum=0.9, fix_gamma=True, use_global_stats=False,
                output_mean_var=False, axis=1):
    """Normalise over all axes but `axis`.  Returns (out, batch_mean,
    batch_var) — the gluon layer owns the running-stat update (the
    reference mutates aux states inside the op via FMutateInputs;
    functionally we return them instead).

    The training path runs through a custom-VJP core with the analytic
    2-pass backward; batch stats are returned via stop_gradient (the
    running-stat EMA is not a differentiable consumer, matching the
    reference's aux-state semantics)."""
    axis = axis % x.ndim
    axes = tuple(i for i in range(x.ndim) if i != axis)
    shape = tuple(-1 if i == axis else 1 for i in range(x.ndim))
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    if use_global_stats:
        mean = moving_mean.astype(jnp.float32)
        var = moving_var.astype(jnp.float32)
        scale = (g.astype(jnp.float32) * lax.rsqrt(var + eps))
        out = (x.astype(jnp.float32) - mean.reshape(shape)) * \
            scale.reshape(shape) + \
            beta.astype(jnp.float32).reshape(shape)
        return out.astype(x.dtype), mean, var
    if axis == 1 and x.ndim >= 3:
        # one-HBM-pass Pallas kernel when the channel-block fits VMEM
        # (falls back to _bn_train_core internally)
        from ..kernels.batch_norm import fused_bn_act
        return fused_bn_act(x, g, beta, eps=eps, act="none")
    out, mean, var = _bn_train_core(x, g, beta, axis, eps)
    return out, mean, var


register_op("BatchNorm", num_inputs=5, num_outputs=3,
            params=[Param("eps", float, 1e-5),
                    Param("momentum", float, 0.9),
                    Param("fix_gamma", bool, True),
                    Param("use_global_stats", bool, False),
                    Param("output_mean_var", bool, False),
                    Param("axis", int, 1)],
            aliases=("batch_norm", "BatchNorm_v1"))(_batch_norm)


def _batch_norm_fused_act(x, gamma, beta, moving_mean, moving_var,
                          residual=None, eps=1e-5, momentum=0.9,
                          fix_gamma=True, use_global_stats=False,
                          axis=1):
    """BatchNorm with a fused ReLU (and optional residual-add)
    epilogue — the reference's fused ``BatchNormAddRelu`` cuDNN/CUDA
    tier (``src/operator/nn/batch_norm.cu``†, SURVEY §2.1-N8), rebuilt
    as the channel-blocked Pallas kernel
    (``mxtpu/kernels/batch_norm.py``).  Training mode runs stats +
    normalize + add + relu in ONE HBM read of x (vs the composite's
    two), and the backward recomputes the relu mask in-VMEM instead of
    materializing it."""
    axis = axis % x.ndim
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    if use_global_stats:
        shape = tuple(-1 if i == axis else 1 for i in range(x.ndim))
        mean = moving_mean.astype(jnp.float32)
        var = moving_var.astype(jnp.float32)
        scale = g.astype(jnp.float32) * lax.rsqrt(var + eps)
        out = (x.astype(jnp.float32) - mean.reshape(shape)) * \
            scale.reshape(shape) + \
            beta.astype(jnp.float32).reshape(shape)
        if residual is not None:
            out = out + residual.astype(jnp.float32)
        out = jnp.maximum(out, 0.0)
        return out.astype(x.dtype), mean, var
    if axis == 1 and x.ndim >= 3:
        from ..kernels.batch_norm import fused_bn_act
        return fused_bn_act(x, g, beta, eps=eps, act="relu",
                            residual=residual)
    out, mean, var = _bn_train_core(x, g, beta, axis, eps)
    if residual is not None:
        out = out + residual
    out = jnp.maximum(out, jnp.zeros((), out.dtype))
    return out, mean, var


_BN_ACT_PARAMS = [Param("eps", float, 1e-5),
                  Param("momentum", float, 0.9),
                  Param("fix_gamma", bool, True),
                  Param("use_global_stats", bool, False),
                  Param("axis", int, 1)]

register_op("BatchNormRelu", num_inputs=5, num_outputs=3,
            params=_BN_ACT_PARAMS)(
    lambda data, gamma, beta, moving_mean, moving_var, **kw:
    _batch_norm_fused_act(data, gamma, beta, moving_mean, moving_var,
                          None, **kw))

# input order: (data, addend, gamma, beta, moving_mean, moving_var) —
# the addend is the bottleneck's shortcut branch
register_op("BatchNormAddRelu", num_inputs=6, num_outputs=3,
            params=_BN_ACT_PARAMS)(
    lambda data, addend, gamma, beta, moving_mean, moving_var, **kw:
    _batch_norm_fused_act(data, gamma, beta, moving_mean, moving_var,
                          addend, **kw))


def _as_prng_key(key):
    """Accept either a typed PRNG key (trace-time fold_in keys) or raw
    uint32[2] key data (the eager global stream) — never a constant."""
    if jnp.issubdtype(key.dtype, jax.dtypes.prng_key):
        return key
    return jax.random.wrap_key_data(
        key.reshape((2,)).astype(jnp.uint32))


def _dropout(x, key, p=0.5, mode="training", axes=()):
    if mode != "training" or p <= 0.0:
        return x
    shape = list(x.shape)
    for ax in axes:
        shape[ax] = 1
    keep = 1.0 - p
    mask = jax.random.bernoulli(_as_prng_key(key), keep, tuple(shape))
    return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


register_op("Dropout", num_inputs=2,
            params=[Param("p", float, 0.5),
                    Param("mode", str, "training"),
                    Param("axes", tuple, ())],
            aliases=("dropout",))(_dropout)


def _fused_residual_ln(h, bias, res, gamma, beta, key, p=0.1, eps=1e-5,
                       mode="training"):
    from ..kernels.layer_norm import fused_residual_layer_norm
    key_data = jax.random.key_data(_as_prng_key(key))
    return fused_residual_layer_norm(
        h, bias, res, gamma, beta, key_data, p=p, eps=eps,
        training=(mode == "training"))


# the transformer post-LN epilogue — y = LN(res + dropout(h + bias)) —
# as one op so the Pallas kernel sees it whole (kernels/layer_norm.py)
register_op("FusedResidualLayerNorm", num_inputs=6,
            params=[Param("p", float, 0.1), Param("eps", float, 1e-5),
                    Param("mode", str, "training")])(
    _fused_residual_ln)


def _lrn(x, nsize=5, alpha=1e-4, beta=0.75, knorm=2.0):
    sq = jnp.square(x)
    half = nsize // 2
    pad = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    acc = jnp.zeros_like(x)
    for i in range(nsize):
        acc = acc + lax.dynamic_slice_in_dim(pad, i, x.shape[1], axis=1)
    return x / jnp.power(knorm + alpha * acc, beta)


register_op("LRN", params=[Param("nsize", int, 5),
                           Param("alpha", float, 1e-4),
                           Param("beta", float, 0.75),
                           Param("knorm", float, 2.0)])(_lrn)


def _upsampling(x, scale=2, sample_type="nearest", num_filter=0):
    n, c, h, w = x.shape
    if sample_type == "nearest":
        return jnp.repeat(jnp.repeat(x, scale, axis=2), scale, axis=3)
    return jax.image.resize(x, (n, c, h * scale, w * scale), "bilinear")


register_op("UpSampling", num_inputs=-1,
            params=[Param("scale", int, 2),
                    Param("sample_type", str, "nearest",
                          enum=("nearest", "bilinear")),
                    Param("num_filter", int, 0)])(
    lambda x, *rest, **kw: _upsampling(x, **kw))


# ======================================================================
# optimizer ops (src/operator/optimizer_op.cc† — "optimizers are ops")
# Functional: return updated tensors instead of mutating; the Optimizer
# layer rebinds.  All are fused into the compiled train step under jit.
# ======================================================================
def _rescale_clip(grad, rescale_grad, clip_gradient, wd=0.0, weight=None):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    # wd may be a traced scalar (the compiled train step passes it as an
    # argument so schedule changes don't recompile) — no bool() on it.
    if weight is not None and not (isinstance(wd, (int, float)) and wd == 0):
        g = g + wd * weight
    return g


register_op("sgd_update", num_inputs=2,
            params=[Param("lr", float), Param("wd", float, 0.0),
                    Param("rescale_grad", float, 1.0),
                    Param("clip_gradient", float, -1.0)],
            differentiable=False)(
    lambda weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
    clip_gradient=-1.0: weight - lr * _rescale_clip(
        grad, rescale_grad, clip_gradient if clip_gradient > 0 else None,
        wd, weight))


def _sgd_mom(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
             rescale_grad=1.0, clip_gradient=-1.0):
    g = _rescale_clip(grad, rescale_grad,
                      clip_gradient if clip_gradient > 0 else None, wd,
                      weight)
    mom_new = momentum * mom - lr * g
    return weight + mom_new, mom_new


register_op("sgd_mom_update", num_inputs=3, num_outputs=2,
            params=[Param("lr", float),
                    Param("momentum", float, 0.0),
                    Param("wd", float, 0.0),
                    Param("rescale_grad", float, 1.0),
                    Param("clip_gradient", float, -1.0)],
            differentiable=False)(_sgd_mom)


def _adam(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
          epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    g = _rescale_clip(grad, rescale_grad,
                      clip_gradient if clip_gradient > 0 else None, wd,
                      weight)
    mean_new = beta1 * mean + (1 - beta1) * g
    var_new = beta2 * var + (1 - beta2) * jnp.square(g)
    w_new = weight - lr * mean_new / (jnp.sqrt(var_new) + epsilon)
    return w_new, mean_new, var_new


register_op("adam_update", num_inputs=4, num_outputs=3,
            params=[Param("lr", float),
                    Param("beta1", float, 0.9),
                    Param("beta2", float, 0.999),
                    Param("epsilon", float, 1e-8),
                    Param("wd", float, 0.0),
                    Param("rescale_grad", float, 1.0),
                    Param("clip_gradient", float, -1.0)],
            differentiable=False)(_adam)


def _rmsprop(weight, grad, n, lr=0.001, gamma1=0.9, epsilon=1e-8,
             wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
             clip_weights=-1.0):
    g = _rescale_clip(grad, rescale_grad,
                      clip_gradient if clip_gradient > 0 else None, wd,
                      weight)
    n_new = gamma1 * n + (1 - gamma1) * jnp.square(g)
    w_new = weight - lr * g / jnp.sqrt(n_new + epsilon)
    if clip_weights > 0:
        w_new = jnp.clip(w_new, -clip_weights, clip_weights)
    return w_new, n_new


register_op("rmsprop_update", num_inputs=3, num_outputs=2,
            params=[Param("lr", float),
                    Param("gamma1", float, 0.9),
                    Param("epsilon", float, 1e-8),
                    Param("wd", float, 0.0),
                    Param("rescale_grad", float, 1.0),
                    Param("clip_gradient", float, -1.0),
                    Param("clip_weights", float, -1.0)],
            differentiable=False)(_rmsprop)


def _lamb(weight, grad, mean, var, t, lr=0.001, beta1=0.9, beta2=0.999,
          epsilon=1e-6, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
          bias_correction=True, stacked=False):
    """LAMB (You et al. 2020): Adam moments + per-tensor trust ratio.
    ``t`` is the step count as a traced input (scalar, or (n,) when
    ``stacked``) so schedules never recompile; ``stacked=True`` treats
    axis 0 as a bundle of independent parameters and computes the trust
    ratio per slice (the batched optimizer path)."""
    g = _rescale_clip(grad, rescale_grad,
                      clip_gradient if clip_gradient > 0 else None)
    m_new = beta1 * mean + (1 - beta1) * g
    v_new = beta2 * var + (1 - beta2) * jnp.square(g)
    mhat, vhat = m_new, v_new
    if bias_correction:
        tf = t.astype(jnp.float32) if hasattr(t, "astype") \
            else jnp.float32(t)
        if stacked and getattr(tf, "ndim", 0) == 1:
            tf = tf.reshape((-1,) + (1,) * (weight.ndim - 1))
        mhat = m_new / (1.0 - beta1 ** tf)
        vhat = v_new / (1.0 - beta2 ** tf)
    r = mhat / (jnp.sqrt(vhat) + epsilon)
    # wd may be traced (train-step schedule arg) — no bool() on it
    r = r + wd * weight
    axes = tuple(range(1, weight.ndim)) if stacked else None
    wnorm = jnp.sqrt(jnp.sum(jnp.square(weight), axis=axes,
                             keepdims=stacked))
    rnorm = jnp.sqrt(jnp.sum(jnp.square(r), axis=axes,
                             keepdims=stacked))
    trust = jnp.where((wnorm > 0) & (rnorm > 0), wnorm / rnorm, 1.0)
    return weight - lr * trust * r, m_new, v_new


register_op("lamb_update", num_inputs=5, num_outputs=3,
            params=[Param("lr", float),
                    Param("beta1", float, 0.9),
                    Param("beta2", float, 0.999),
                    Param("epsilon", float, 1e-6),
                    Param("wd", float, 0.0),
                    Param("rescale_grad", float, 1.0),
                    Param("clip_gradient", float, -1.0),
                    Param("bias_correction", bool, True),
                    Param("stacked", bool, False)],
            differentiable=False)(_lamb)


def _rmspropalex(weight, grad, n, g_state, delta, lr=0.001, gamma1=0.95,
                 gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                 clip_gradient=-1.0):
    g = _rescale_clip(grad, rescale_grad,
                      clip_gradient if clip_gradient > 0 else None, wd,
                      weight)
    n_new = gamma1 * n + (1 - gamma1) * jnp.square(g)
    g_new = gamma1 * g_state + (1 - gamma1) * g
    delta_new = gamma2 * delta - lr * g / jnp.sqrt(
        n_new - jnp.square(g_new) + epsilon)
    return weight + delta_new, n_new, g_new, delta_new


register_op("rmspropalex_update", num_inputs=5, num_outputs=4,
            params=[Param("lr", float),
                    Param("gamma1", float, 0.95),
                    Param("gamma2", float, 0.9),
                    Param("epsilon", float, 1e-8),
                    Param("wd", float, 0.0),
                    Param("rescale_grad", float, 1.0),
                    Param("clip_gradient", float, -1.0)],
            differentiable=False)(_rmspropalex)


def _ftrl(weight, grad, z, n, lr=0.1, lamda1=0.01, beta=1.0, wd=0.0,
          rescale_grad=1.0, clip_gradient=-1.0):
    g = _rescale_clip(grad, rescale_grad,
                      clip_gradient if clip_gradient > 0 else None)
    n_new = n + jnp.square(g)
    sigma = (jnp.sqrt(n_new) - jnp.sqrt(n)) / lr
    z_new = z + g - sigma * weight
    w_new = jnp.where(
        jnp.abs(z_new) <= lamda1, 0.0,
        -(z_new - jnp.sign(z_new) * lamda1) /
        ((beta + jnp.sqrt(n_new)) / lr + wd))
    return w_new, z_new, n_new


register_op("ftrl_update", num_inputs=4, num_outputs=3,
            params=[Param("lr", float),
                    Param("lamda1", float, 0.01),
                    Param("beta", float, 1.0),
                    Param("wd", float, 0.0),
                    Param("rescale_grad", float, 1.0),
                    Param("clip_gradient", float, -1.0)],
            differentiable=False)(_ftrl)

register_op("signsgd_update", num_inputs=2,
            params=[Param("lr", float), Param("wd", float, 0.0),
                    Param("rescale_grad", float, 1.0),
                    Param("clip_gradient", float, -1.0)],
            differentiable=False)(
    lambda weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
    clip_gradient=-1.0: weight - lr * (jnp.sign(_rescale_clip(
        grad, rescale_grad, clip_gradient if clip_gradient > 0 else None))
        + wd * weight))


def _signum(weight, grad, mom, lr=0.01, momentum=0.9, wd=0.0,
            rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0):
    g = _rescale_clip(grad, rescale_grad,
                      clip_gradient if clip_gradient > 0 else None)
    mom_new = momentum * mom - (1 - momentum) * g
    w_new = (1 - lr * wd_lh) * weight + lr * jnp.sign(mom_new) * (-1.0) \
        * (-1.0) - lr * wd * weight
    w_new = weight + lr * jnp.sign(mom_new) - lr * wd * weight \
        if wd_lh == 0.0 else w_new
    return w_new, mom_new


register_op("signum_update", num_inputs=3, num_outputs=2,
            params=[Param("lr", float),
                    Param("momentum", float, 0.9),
                    Param("wd", float, 0.0),
                    Param("rescale_grad", float, 1.0),
                    Param("clip_gradient", float, -1.0),
                    Param("wd_lh", float, 0.0)],
            differentiable=False)(_signum)


# ----------------------------------------------------------------------
# legacy output ops (reference ``src/operator/regression_output*.cc``†,
# ``make_loss.cc``†, ``svm_output.cc``†): forward is (mostly) identity;
# the op DEFINES its gradient via custom_vjp, matching the reference's
# hand-written backward
# ----------------------------------------------------------------------
def _make_output_op(fwd_fn, bwd_fn):
    @functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
    def core(data, label, grad_scale):
        return fwd_fn(data, label)

    def fwd(data, label, grad_scale):
        return fwd_fn(data, label), (data, label)

    def bwd(grad_scale, res, g):
        data, label = res
        return bwd_fn(data, label, grad_scale, g), jnp.zeros_like(label)

    core.defvjp(fwd, bwd)
    return core


def _per_sample_outputs(d):
    # reference regression_output-inl.h†: scale = grad_scale /
    # (label.Size() / batch) — outputs PER SAMPLE, not batch size
    return max(int(np.prod(d.shape[1:])), 1) if d.ndim > 1 else 1


_linreg_core = _make_output_op(
    lambda d, l: d,
    lambda d, l, s, g: (d - l.reshape(d.shape)) * s /
    _per_sample_outputs(d) * jnp.ones_like(g))
_maereg_core = _make_output_op(
    lambda d, l: d,
    lambda d, l, s, g: jnp.sign(d - l.reshape(d.shape)) * s /
    _per_sample_outputs(d) * jnp.ones_like(g))
_logreg_core = _make_output_op(
    lambda d, l: jax.nn.sigmoid(d),
    lambda d, l, s, g: (jax.nn.sigmoid(d) - l.reshape(d.shape)) * s /
    _per_sample_outputs(d) * jnp.ones_like(g))

register_op("LinearRegressionOutput", num_inputs=2,
            params=[Param("grad_scale", float, 1.0)])(
    lambda data, label, grad_scale=1.0:
    _linreg_core(data, label, grad_scale))
register_op("MAERegressionOutput", num_inputs=2,
            params=[Param("grad_scale", float, 1.0)])(
    lambda data, label, grad_scale=1.0:
    _maereg_core(data, label, grad_scale))
register_op("LogisticRegressionOutput", num_inputs=2,
            params=[Param("grad_scale", float, 1.0)])(
    lambda data, label, grad_scale=1.0:
    _logreg_core(data, label, grad_scale))


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _make_loss_core(data, grad_scale, normalization, valid_thresh):
    return data


def _ml_fwd(data, grad_scale, normalization, valid_thresh):
    return data, data


def _ml_bwd(grad_scale, normalization, valid_thresh, data, g):
    scale = jnp.asarray(grad_scale, g.dtype)
    if normalization == "batch":
        scale = scale / data.shape[0]
    elif normalization == "valid":
        # reference: divide by the count of elements above
        # valid_thresh (make_loss.cc†)
        n_valid = jnp.sum(data > valid_thresh).astype(g.dtype)
        scale = scale / jnp.maximum(n_valid, 1.0)
    # the reference ignores the incoming gradient: MakeLoss IS a loss
    return (jnp.broadcast_to(scale, data.shape).astype(g.dtype),)


_make_loss_core.defvjp(_ml_fwd, _ml_bwd)

register_op("MakeLoss", num_inputs=1,
            params=[Param("grad_scale", float, 1.0),
                    Param("valid_thresh", float, 0.0),
                    Param("normalization", str, "null",
                          enum=("null", "batch", "valid"))],
            aliases=("make_loss",))(
    lambda data, grad_scale=1.0, valid_thresh=0.0,
    normalization="null": _make_loss_core(data, grad_scale,
                                          normalization,
                                          valid_thresh))


def _svm_core_builder():
    @functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
    def core(data, label, margin, reg_coef, use_linear):
        return data

    def fwd(data, label, margin, reg_coef, use_linear):
        return data, (data, label)

    def bwd(margin, reg_coef, use_linear, res, g):
        data, label = res
        C = data.shape[1]
        onehot = jax.nn.one_hot(label.astype(jnp.int32), C,
                                dtype=data.dtype)
        # hinge: grad = -y for margin violators (y in {-1, +1})
        y = 2.0 * onehot - 1.0
        viol = (y * data) < margin
        grad = jnp.where(viol, -y, 0.0) * reg_coef
        if not use_linear:   # squared hinge
            grad = grad * jnp.maximum(margin - y * data, 0.0) * 2.0
        return grad * jnp.ones_like(g), jnp.zeros_like(label)

    core.defvjp(fwd, bwd)
    return core


_svm_core = _svm_core_builder()

register_op("SVMOutput", num_inputs=2,
            params=[Param("margin", float, 1.0),
                    Param("regularization_coefficient", float, 1.0),
                    Param("use_linear", bool, False)])(
    lambda data, label, margin=1.0, regularization_coefficient=1.0,
    use_linear=False: _svm_core(data, label, margin,
                                regularization_coefficient,
                                use_linear))


# ----------------------------------------------------------------------
# normalization / statistics additions
# ----------------------------------------------------------------------
def _group_norm(data, gamma, beta, num_groups=1, eps=1e-5):
    """(N, C, ...) grouped normalization (reference ``GroupNorm``†)."""
    N, C = data.shape[0], data.shape[1]
    if C % num_groups:
        raise MXNetError(f"GroupNorm: {C} channels not divisible by "
                         f"{num_groups} groups")
    x = data.reshape((N, num_groups, -1))
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    x = (x - mean) * lax.rsqrt(var + eps)
    x = x.reshape(data.shape)
    shape = [1] * data.ndim
    shape[1] = C
    return x * gamma.reshape(shape) + beta.reshape(shape)


register_op("GroupNorm", num_inputs=3,
            params=[Param("num_groups", int, 1),
                    Param("eps", float, 1e-5)])(_group_norm)


def _moments(data, axes=None, keepdims=False):
    ax = tuple(axes) if axes is not None else None
    mean_k = jnp.mean(data, axis=ax, keepdims=True)
    var = jnp.mean(jnp.square(data - mean_k), axis=ax,
                   keepdims=keepdims)
    mean = mean_k if keepdims else jnp.squeeze(
        mean_k, axis=ax if ax is not None
        else tuple(range(data.ndim)))
    return mean, var


register_op("moments", num_outputs=2,
            params=[Param("axes", tuple, None),
                    Param("keepdims", bool, False)])(_moments)


# ----------------------------------------------------------------------
# elementwise / indexing additions
# ----------------------------------------------------------------------
register_op("digamma")(lambda x: jax.scipy.special.digamma(x))
register_op("logical_xor", num_inputs=2, aliases=("_logical_xor",))(
    lambda a, b: ((a != 0) ^ (b != 0)).astype(a.dtype))
register_op("hard_sigmoid",
            params=[Param("alpha", float, 0.2),
                    Param("beta", float, 0.5)])(
    lambda x, alpha=0.2, beta=0.5: jnp.clip(alpha * x + beta, 0.0,
                                            1.0))
register_op("log_sigmoid")(lambda x: jax.nn.log_sigmoid(x))
register_op("mish")(lambda x: x * jnp.tanh(jax.nn.softplus(x)))
register_op("_eye", num_inputs=0,
            params=[Param("N", int, 0), Param("M", int, 0),
                    Param("k", int, 0),
                    Param("dtype", str, "float32")])(
    lambda N=0, M=0, k=0, dtype="float32":
    jnp.eye(N, M if M > 0 else None, k=k, dtype=dtype))
register_op("_linspace", num_inputs=0,
            params=[Param("start", float, 0.0),
                    Param("stop", float, 1.0),
                    Param("num", int, 50),
                    Param("endpoint", bool, True),
                    Param("dtype", str, "float32")])(
    lambda start=0.0, stop=1.0, num=50, endpoint=True,
    dtype="float32": jnp.linspace(start, stop, num,
                                  endpoint=endpoint, dtype=dtype))


def _histogram(data, bin_cnt=10, range=None):
    # keep lo/hi traced (no float()) so shape inference and jitted
    # use work
    lo, hi = (range if range is not None
              else (jnp.min(data), jnp.max(data)))
    counts, edges = jnp.histogram(data, bins=int(bin_cnt),
                                  range=(lo, hi))
    return counts, edges.astype(jnp.float32)


register_op("histogram", num_outputs=2,
            params=[Param("bin_cnt", int, 10),
                    Param("range", tuple, None)],
            aliases=("_histogram",), differentiable=False)(_histogram)

register_op("batch_take", num_inputs=2)(
    lambda a, indices: jnp.take_along_axis(
        a, indices.astype(jnp.int32)[:, None], axis=1)[:, 0])
register_op("unravel_index", aliases=("_unravel_index",),
            params=[Param("shape", tuple, None)],
            differentiable=False)(
    lambda indices, shape=None: jnp.stack(
        jnp.unravel_index(indices.astype(jnp.int32), shape)).astype(
        indices.dtype))
register_op("ravel_multi_index", aliases=("_ravel_multi_index",),
            params=[Param("shape", tuple, None)],
            differentiable=False)(
    lambda indices, shape=None: jnp.ravel_multi_index(
        tuple(indices.astype(jnp.int32)), shape,
        mode="clip").astype(indices.dtype))


def _shuffle(data, key):
    return jax.random.permutation(_as_prng_key(key), data, axis=0)


register_op("shuffle", num_inputs=2, aliases=("_shuffle",),
            differentiable=False)(_shuffle)


def _split_v2(data, indices=(), axis=0, squeeze_axis=False,
              sections=0):
    if sections:
        parts = jnp.split(data, sections, axis=axis)
    else:
        parts = jnp.split(data, list(indices), axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


register_op("split_v2", aliases=("_split_v2",),
            params=[Param("indices", tuple, ()),
                    Param("axis", int, 0),
                    Param("squeeze_axis", bool, False),
                    Param("sections", int, 0)],
            num_outputs_fn=lambda p:
                int(p["sections"]) if p.get("sections")
                else len(tuple(p.get("indices", ()))) + 1)(_split_v2)


# ----------------------------------------------------------------------
# fused multi-weight optimizer updates (reference
# ``src/operator/optimizer_op.cc``† multi_sgd family — one kernel
# updating every weight, the AMP/horovod fast path)
# ----------------------------------------------------------------------
def _multi_sgd_update(*arrays, lrs=(), wds=(), rescale_grad=1.0,
                      clip_gradient=-1.0, num_weights=1):
    n = int(num_weights)
    if len(arrays) != 2 * n:
        raise MXNetError(f"multi_sgd_update expects {2 * n} inputs "
                         f"(weight, grad)×{n}, got {len(arrays)}")
    outs = []
    for i in range(n):
        w, g = arrays[2 * i], arrays[2 * i + 1]
        g = g * rescale_grad
        if clip_gradient > 0:
            g = jnp.clip(g, -clip_gradient, clip_gradient)
        outs.append(w - lrs[i] * (g + wds[i] * w))
    return tuple(outs) if n > 1 else outs[0]


register_op("multi_sgd_update", num_inputs=-1,
            params=[Param("lrs", tuple, ()),
                    Param("wds", tuple, ()),
                    Param("rescale_grad", float, 1.0),
                    Param("clip_gradient", float, -1.0),
                    Param("num_weights", int, 1)],
            num_outputs_fn=lambda p: int(p.get("num_weights", 1)),
            differentiable=False)(_multi_sgd_update)


def _multi_sgd_mom_update(*arrays, lrs=(), wds=(), momentum=0.0,
                          rescale_grad=1.0, clip_gradient=-1.0,
                          num_weights=1):
    n = int(num_weights)
    if len(arrays) != 3 * n:
        raise MXNetError(f"multi_sgd_mom_update expects {3 * n} inputs "
                         f"(weight, grad, mom)×{n}, got {len(arrays)}")
    outs = []
    moms = []
    for i in range(n):
        w, g, m = arrays[3 * i], arrays[3 * i + 1], arrays[3 * i + 2]
        g = g * rescale_grad
        if clip_gradient > 0:
            g = jnp.clip(g, -clip_gradient, clip_gradient)
        m2 = momentum * m - lrs[i] * (g + wds[i] * w)
        outs.append(w + m2)
        moms.append(m2)
    return tuple(outs + moms) if n > 1 else (outs[0], moms[0])


register_op("multi_sgd_mom_update", num_inputs=-1,
            params=[Param("lrs", tuple, ()),
                    Param("wds", tuple, ()),
                    Param("momentum", float, 0.0),
                    Param("rescale_grad", float, 1.0),
                    Param("clip_gradient", float, -1.0),
                    Param("num_weights", int, 1)],
            num_outputs_fn=lambda p: 2 * int(p.get("num_weights",
                                                    1)),
            differentiable=False)(_multi_sgd_mom_update)
