"""NN-tier operator tail: im2col/col2im, deformable convolution,
(PS)ROI pooling variants, ROIAlign, adaptive pooling, bilinear resize,
SyncBatchNorm, index_copy, and the INT8 quantized execution tier
(reference ``src/operator/contrib/*``† and
``src/operator/quantization/*``† rebuilt as XLA lowering rules).

TPU notes: everything is static-shaped and vectorised — per-ROI/per-tap
work is ``vmap`` over gathers and masked reductions (no data-dependent
loops), and int8 conv/fc accumulate in int32 on the MXU via
``preferred_element_type``.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..base import MXNetError
from ..ops.registry import Param, register_op
from .ops_impl import _tuple

# ---------------------------------------------------------------------------
# im2col / col2im (src/operator/nn/im2col.h† exposed as ops in 1.5;
# also the building block our deformable conv reuses)
# ---------------------------------------------------------------------------


def _im2col(data, kernel=(), stride=None, dilate=None, pad=None):
    """(N, C, H, W) -> (N, C*kh*kw, Ho*Wo) patch matrix."""
    kh, kw = int(kernel[0]), int(kernel[1])
    sh, sw = _tuple(stride, 2)
    dh, dw = _tuple(dilate, 2)
    ph, pw = _tuple(pad, 2) if pad is not None else (0, 0)
    N, C, H, W = data.shape
    x = jnp.pad(data, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
    Ho = (H + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    Wo = (W + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    cols = []
    for i in range(kh):
        for j in range(kw):
            patch = lax.slice(
                x, (0, 0, i * dh, j * dw),
                (N, C, i * dh + (Ho - 1) * sh + 1,
                 j * dw + (Wo - 1) * sw + 1),
                (1, 1, sh, sw))
            cols.append(patch)
    out = jnp.stack(cols, axis=2)        # (N, C, kh*kw, Ho, Wo)
    return out.reshape(N, C * kh * kw, Ho * Wo)


register_op("im2col",
            params=[Param("kernel", tuple, ()),
                    Param("stride", tuple, None),
                    Param("dilate", tuple, None),
                    Param("pad", tuple, None)])(_im2col)


def _col2im(col, output_size=(), kernel=(), stride=None, dilate=None,
            pad=None):
    """Scatter-add the inverse of im2col (gradient-style fold)."""
    kh, kw = int(kernel[0]), int(kernel[1])
    sh, sw = _tuple(stride, 2)
    dh, dw = _tuple(dilate, 2)
    ph, pw = _tuple(pad, 2) if pad is not None else (0, 0)
    H, W = int(output_size[0]), int(output_size[1])
    N = col.shape[0]
    C = col.shape[1] // (kh * kw)
    Ho = (H + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    Wo = (W + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    cols = col.reshape(N, C, kh * kw, Ho, Wo)
    out = jnp.zeros((N, C, H + 2 * ph, W + 2 * pw), col.dtype)
    k = 0
    for i in range(kh):
        for j in range(kw):
            ys = i * dh + sh * jnp.arange(Ho)
            xs = j * dw + sw * jnp.arange(Wo)
            out = out.at[:, :, ys[:, None], xs[None, :]].add(
                cols[:, :, k])
            k += 1
    return out[:, :, ph:ph + H, pw:pw + W]


register_op("col2im",
            params=[Param("output_size", tuple, ()),
                    Param("kernel", tuple, ()),
                    Param("stride", tuple, None),
                    Param("dilate", tuple, None),
                    Param("pad", tuple, None)])(_col2im)

# ---------------------------------------------------------------------------
# bilinear helpers
# ---------------------------------------------------------------------------


def _bilinear_gather(img, y, x):
    """img (C, H, W); y/x arbitrary same-shaped coords; zero outside.
    Returns (C,) + y.shape."""
    H, W = img.shape[-2], img.shape[-1]
    y0 = jnp.floor(y)
    x0 = jnp.floor(x)
    wy1 = y - y0
    wx1 = x - x0
    out = 0.0
    for dy, wy in ((0, 1.0 - wy1), (1, wy1)):
        for dx, wx in ((0, 1.0 - wx1), (1, wx1)):
            yy = y0 + dy
            xx = x0 + dx
            inb = (yy >= 0) & (yy <= H - 1) & (xx >= 0) & (xx <= W - 1)
            yc = jnp.clip(yy, 0, H - 1).astype(jnp.int32)
            xc = jnp.clip(xx, 0, W - 1).astype(jnp.int32)
            val = img[..., yc, xc]          # (C,) + coord shape
            out = out + val * (wy * wx * inb)
    return out


# ---------------------------------------------------------------------------
# Deformable convolution (contrib/deformable_convolution.cc†,
# Dai et al. 2017)
# ---------------------------------------------------------------------------


def _deformable_convolution(data, offset, weight, bias=None, kernel=(),
                            stride=None, dilate=None, pad=None,
                            num_filter=0, num_group=1,
                            num_deformable_group=1, no_bias=False):
    """data (N,C,H,W); offset (N, 2*G*kh*kw, Ho, Wo) with per-tap
    (dy, dx) pairs for each of G deformable groups; weight
    (O, C/num_group, kh, kw).  Bilinear sampling at deformed tap
    positions, then the conv contraction runs as one einsum on the MXU.
    """
    kh, kw = int(kernel[0]), int(kernel[1])
    sh, sw = _tuple(stride, 2)
    dh, dw = _tuple(dilate, 2)
    ph, pw = _tuple(pad, 2) if pad is not None else (0, 0)
    N, C, H, W = data.shape
    G = num_deformable_group
    K = kh * kw
    Ho = (H + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    Wo = (W + 2 * pw - dw * (kw - 1) - 1) // sw + 1

    base_y = (sh * jnp.arange(Ho) - ph)[:, None]        # (Ho, 1)
    base_x = (sw * jnp.arange(Wo) - pw)[None, :]        # (1, Wo)
    off = offset.reshape(N, G, K, 2, Ho, Wo)

    cg = C // G

    def per_image(img, off_i):           # img (C,H,W), off_i (G,K,2,...)
        taps = []
        for k in range(K):
            i, j = divmod(k, kw)
            tap_g = []
            for g in range(G):
                y = base_y + i * dh + off_i[g, k, 0]    # (Ho, Wo)
                x = base_x + j * dw + off_i[g, k, 1]
                tap_g.append(_bilinear_gather(
                    img[g * cg:(g + 1) * cg], y, x))    # (cg, Ho, Wo)
            taps.append(jnp.concatenate(tap_g, axis=0))  # (C, Ho, Wo)
        return jnp.stack(taps, axis=1)   # (C, K, Ho, Wo)

    cols = jax.vmap(per_image)(data, off)               # (N, C, K, Ho, Wo)
    O = weight.shape[0]
    w = weight.reshape(num_group, O // num_group, C // num_group, K)
    colsg = cols.reshape(N, num_group, C // num_group, K, Ho, Wo)
    out = jnp.einsum("ngckhw,gock->ngohw", colsg, w,
                     preferred_element_type=cols.dtype)
    out = out.reshape(N, O, Ho, Wo)
    if bias is not None and not no_bias:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


register_op("_contrib_DeformableConvolution", num_inputs=-1,
            params=[Param("kernel", tuple, ()),
                    Param("stride", tuple, None),
                    Param("dilate", tuple, None),
                    Param("pad", tuple, None),
                    Param("num_filter", int, 0),
                    Param("num_group", int, 1),
                    Param("num_deformable_group", int, 1),
                    Param("no_bias", bool, False)],
            aliases=("DeformableConvolution",))(
    lambda data, offset, weight, *b, **kw: _deformable_convolution(
        data, offset, weight, b[0] if b else None, **kw))

# ---------------------------------------------------------------------------
# PSROIPooling + DeformablePSROIPooling (contrib†, R-FCN heads)
# ---------------------------------------------------------------------------


def _psroi_core(data, rois, spatial_scale, output_dim, pooled_size,
                group_size, trans=None, trans_std=0.0, part_size=0):
    P = int(pooled_size)
    gs = int(group_size) or P
    N, C, H, W = data.shape
    ys = jnp.arange(H, dtype=jnp.float32)
    xs = jnp.arange(W, dtype=jnp.float32)

    def one_roi(roi, tr):
        bidx = roi[0].astype(jnp.int32)
        # reference rounds roi corners then scales
        x1 = jnp.round(roi[1]) * spatial_scale - 0.5
        y1 = jnp.round(roi[2]) * spatial_scale - 0.5
        x2 = (jnp.round(roi[3]) + 1.0) * spatial_scale - 0.5
        y2 = (jnp.round(roi[4]) + 1.0) * spatial_scale - 0.5
        rh = jnp.maximum(y2 - y1, 0.1)
        rw = jnp.maximum(x2 - x1, 0.1)
        bin_h = rh / P
        bin_w = rw / P
        img = data[bidx]

        def one_bin(d, i, j):
            # deformable shift for this bin, scaled by roi size
            if tr is not None:
                dy = tr[0, i * P + j] * trans_std * rh
                dx = tr[1, i * P + j] * trans_std * rw
            else:
                dy = 0.0
                dx = 0.0
            hstart = y1 + i * bin_h + dy
            hend = hstart + bin_h
            wstart = x1 + j * bin_w + dx
            wend = wstart + bin_w
            mask = ((ys[:, None] >= hstart) & (ys[:, None] < hend) &
                    (xs[None, :] >= wstart) & (xs[None, :] < wend))
            gi = jnp.clip(jnp.floor_divide(i * gs, P), 0, gs - 1)
            gj = jnp.clip(jnp.floor_divide(j * gs, P), 0, gs - 1)
            ch = (d * gs + gi) * gs + gj
            cnt = jnp.maximum(mask.sum(), 1)
            return jnp.where(mask, img[ch], 0.0).sum() / cnt

        dd, ii, jj = jnp.meshgrid(jnp.arange(output_dim),
                                  jnp.arange(P), jnp.arange(P),
                                  indexing="ij")
        vals = jax.vmap(one_bin)(dd.ravel(), ii.ravel(), jj.ravel())
        return vals.reshape(output_dim, P, P)

    if trans is None:
        return jax.vmap(lambda r: one_roi(r, None))(rois)
    return jax.vmap(one_roi)(rois, trans)


def _psroipooling(data, rois, spatial_scale=1.0, output_dim=0,
                  pooled_size=0, group_size=0):
    return _psroi_core(data, rois, spatial_scale, int(output_dim),
                       pooled_size, group_size or pooled_size)


register_op("_contrib_PSROIPooling", num_inputs=2,
            params=[Param("spatial_scale", float, 1.0),
                    Param("output_dim", int, 0),
                    Param("pooled_size", int, 0),
                    Param("group_size", int, 0)],
            aliases=("PSROIPooling",))(_psroipooling)


def _deformable_psroipooling(data, rois, trans=None, spatial_scale=1.0,
                             output_dim=0, pooled_size=0, group_size=0,
                             part_size=0, sample_per_part=1,
                             trans_std=0.0, no_trans=False):
    if no_trans or trans is None:
        return _psroi_core(data, rois, spatial_scale, int(output_dim),
                           pooled_size, group_size or pooled_size)
    P = int(pooled_size)
    R = rois.shape[0]
    tr = trans.reshape(R, 2, -1)
    return _psroi_core(data, rois, spatial_scale, int(output_dim),
                       pooled_size, group_size or pooled_size,
                       trans=tr, trans_std=trans_std)


register_op("_contrib_DeformablePSROIPooling", num_inputs=-1,
            params=[Param("spatial_scale", float, 1.0),
                    Param("output_dim", int, 0),
                    Param("pooled_size", int, 0),
                    Param("group_size", int, 0),
                    Param("part_size", int, 0),
                    Param("sample_per_part", int, 1),
                    Param("trans_std", float, 0.0),
                    Param("no_trans", bool, False)],
            aliases=("DeformablePSROIPooling",))(
    lambda data, rois, *t, **kw: _deformable_psroipooling(
        data, rois, t[0] if t else None, **kw))

# ---------------------------------------------------------------------------
# ROIAlign (contrib/roi_align.cc†, Mask R-CNN)
# ---------------------------------------------------------------------------


def _roi_align(data, rois, pooled_size=(7, 7), spatial_scale=1.0,
               sample_ratio=2, position_sensitive=False):
    """DIVERGENCE vs reference (contrib/roi_align.cc†): the reference's
    sample_ratio<=0 means ADAPTIVE sampling (ceil(roi_size/pooled) grid
    points per bin, data-dependent) — impossible under XLA static
    shapes, so it is approximated with a fixed 2x2 grid per bin (the
    value detection configs hard-code anyway).  position_sensitive
    (R-FCN-style channel splitting) is not implemented and raises
    rather than silently ignoring the flag (r3 advisor)."""
    if position_sensitive:
        raise MXNetError(
            "ROIAlign position_sensitive=True is not implemented; use "
            "_contrib_PSROIPooling for position-sensitive pooling")
    ph, pw = int(pooled_size[0]), int(pooled_size[1])
    N, C, H, W = data.shape
    s = int(sample_ratio) if int(sample_ratio) > 0 else 2

    def one_roi(roi):
        bidx = roi[0].astype(jnp.int32)
        x1 = roi[1] * spatial_scale
        y1 = roi[2] * spatial_scale
        x2 = roi[3] * spatial_scale
        y2 = roi[4] * spatial_scale
        rh = jnp.maximum(y2 - y1, 1.0)
        rw = jnp.maximum(x2 - x1, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        # s*s sample points per bin, bilinear, averaged
        iy = (jnp.arange(ph)[:, None] +
              (jnp.arange(s)[None, :] + 0.5) / s).reshape(-1)  # (ph*s,)
        ix = (jnp.arange(pw)[:, None] +
              (jnp.arange(s)[None, :] + 0.5) / s).reshape(-1)
        yy = y1 + iy * bin_h                  # (ph*s,)
        xx = x1 + ix * bin_w                  # (pw*s,)
        grid_y = jnp.broadcast_to(yy[:, None], (ph * s, pw * s))
        grid_x = jnp.broadcast_to(xx[None, :], (ph * s, pw * s))
        vals = _bilinear_gather(data[bidx], grid_y, grid_x)
        vals = vals.reshape(C, ph, s, pw, s)
        return vals.mean(axis=(2, 4))

    return jax.vmap(one_roi)(rois)


register_op("_contrib_ROIAlign", num_inputs=2,
            params=[Param("pooled_size", tuple, ()),
                    Param("spatial_scale", float, 1.0),
                    Param("sample_ratio", int, 2),
                    Param("position_sensitive", bool, False)],
            aliases=("ROIAlign",))(_roi_align)

# ---------------------------------------------------------------------------
# AdaptiveAvgPooling2D + BilinearResize2D (contrib†)
# ---------------------------------------------------------------------------


def _adaptive_avg_pool(data, output_size=()):
    if not output_size:
        oh = ow = 1
    elif len(output_size) == 1:
        oh = ow = int(output_size[0])
    else:
        oh, ow = int(output_size[0]), int(output_size[1])
    N, C, H, W = data.shape

    def axis_weights(inp, out):
        # uniform averaging over [floor(i*inp/out), ceil((i+1)*inp/out))
        i = np.arange(out)
        starts = np.floor(i * inp / out).astype(int)
        ends = np.ceil((i + 1) * inp / out).astype(int)
        w = np.zeros((out, inp), np.float32)
        for r in range(out):
            w[r, starts[r]:ends[r]] = 1.0 / (ends[r] - starts[r])
        return jnp.asarray(w)

    wh = axis_weights(H, oh)                 # (oh, H)
    ww = axis_weights(W, ow)                 # (ow, W)
    # two small matmuls — MXU-friendly, no gather; exact averaging
    # wants true-f32 accumulation, not the TPU default's bf16 inputs
    prec = lax.Precision.HIGHEST \
        if jnp.dtype(data.dtype) == jnp.float32 else None
    return jnp.einsum("oh,nchw,pw->ncop", wh, data, ww,
                      precision=prec)


register_op("_contrib_AdaptiveAvgPooling2D",
            params=[Param("output_size", tuple, ())],
            aliases=("AdaptiveAvgPooling2D",))(_adaptive_avg_pool)


def _bilinear_resize(data, height=0, width=0, scale_height=None,
                     scale_width=None):
    N, C, H, W = data.shape
    oh = int(height) if height else int(round(H * scale_height))
    ow = int(width) if width else int(round(W * scale_width))
    # align_corners=True (the reference's convention)
    ys = jnp.linspace(0.0, H - 1.0, oh)
    xs = jnp.linspace(0.0, W - 1.0, ow)
    grid_y = jnp.broadcast_to(ys[:, None], (oh, ow))
    grid_x = jnp.broadcast_to(xs[None, :], (oh, ow))
    return jax.vmap(lambda img: _bilinear_gather(img, grid_y, grid_x))(
        data)


register_op("_contrib_BilinearResize2D",
            params=[Param("height", int, 0),
                    Param("width", int, 0),
                    Param("scale_height", float, None),
                    Param("scale_width", float, None)],
            aliases=("BilinearResize2D",))(_bilinear_resize)

# ---------------------------------------------------------------------------
# SyncBatchNorm (contrib/sync_batch_norm.cc†) — cross-device statistics.
# TPU-native: inside pjit/shard_map the mean/var reduce with
# lax.pmean over the data-parallel axis; outside (axis_name=None /
# unbound) it degrades to plain BatchNorm, which matches the
# reference's single-device behavior.
# ---------------------------------------------------------------------------


def _sync_batch_norm(x, gamma, beta, moving_mean, moving_var, eps=1e-3,
                     momentum=0.9, fix_gamma=True,
                     use_global_stats=False, output_mean_var=False,
                     ndev=1, key="", axis_name=""):
    ax = 1
    axes = tuple(i for i in range(x.ndim) if i != ax)
    x32 = x.astype(jnp.float32)
    if use_global_stats:
        mean = moving_mean.astype(jnp.float32)
        var = moving_var.astype(jnp.float32)
    else:
        mean = jnp.mean(x32, axis=axes)
        msq = jnp.mean(jnp.square(x32), axis=axes)
        if axis_name:
            mean = lax.pmean(mean, axis_name)
            msq = lax.pmean(msq, axis_name)
        var = msq - jnp.square(mean)
    shape = tuple(-1 if i == ax else 1 for i in range(x.ndim))
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    out = (x32 - mean.reshape(shape)) * lax.rsqrt(
        var.reshape(shape) + eps) * g.astype(jnp.float32).reshape(shape) \
        + beta.astype(jnp.float32).reshape(shape)
    return out.astype(x.dtype), mean, var


register_op("_contrib_SyncBatchNorm", num_inputs=5, num_outputs=3,
            params=[Param("eps", float, 1e-3),
                    Param("momentum", float, 0.9),
                    Param("fix_gamma", bool, True),
                    Param("use_global_stats", bool, False),
                    Param("output_mean_var", bool, False),
                    Param("ndev", int, 1),
                    Param("key", str, ""),
                    Param("axis_name", str, "")],
            aliases=("SyncBatchNorm",))(_sync_batch_norm)

# ---------------------------------------------------------------------------
# index_copy (contrib†)
# ---------------------------------------------------------------------------


def _index_copy(old, idx, new):
    return old.at[idx.astype(jnp.int32)].set(new.astype(old.dtype))


register_op("_contrib_index_copy", num_inputs=3)(_index_copy)

# ---------------------------------------------------------------------------
# INT8 quantized execution tier (src/operator/quantization/*†).
# Convention matches quantize/dequantize in detection_impl.py: int8 is
# symmetric [-127, 127] over [min, max]; int32 accumulators carry the
# product of input scales.  TPU: s8 x s8 -> s32 runs on the MXU via
# preferred_element_type.
# ---------------------------------------------------------------------------


def _qrange(dtype):
    if dtype == jnp.uint8:
        return 0.0, 255.0
    if dtype == jnp.int8:
        return -127.0, 127.0
    return -2147483647.0, 2147483647.0  # int32


def _scale_of(lo, hi, dtype):
    qmin, qmax = _qrange(dtype)
    return (qmax - qmin) / jnp.maximum(hi - lo, 1e-12)


def _requantize(data, min_range, max_range, min_calib_range=None,
                max_calib_range=None, out_type="int8"):
    """int32 -> int8/uint8 given the int32's float range
    (requantize†).  uint8 output uses the shifted range [0, hi]
    (zero-point 0, the post-ReLU convention of the uint8 tier)."""
    lo = min_range.reshape(())
    hi = max_range.reshape(())
    f = (data.astype(jnp.float32) /
         _scale_of(lo, hi, jnp.int32))       # back to float
    if min_calib_range is not None:
        lo = jnp.asarray(min_calib_range, jnp.float32)
        hi = jnp.asarray(max_calib_range, jnp.float32)
        if out_type == "uint8":
            lo = jnp.maximum(lo, 0.0)
    elif out_type == "uint8":
        lo = jnp.asarray(0.0, jnp.float32)
        hi = jnp.maximum(f.max(), 1e-12)
    else:
        amax = jnp.maximum(jnp.abs(f).max(), 1e-12)
        lo, hi = -amax, amax
    if out_type == "uint8":
        scale = _scale_of(lo, hi, jnp.uint8)
        q = jnp.clip(jnp.round(f * scale), 0, 255).astype(jnp.uint8)
    else:
        scale = _scale_of(lo, hi, jnp.int8)
        q = jnp.clip(jnp.round(f * scale), -127, 127).astype(jnp.int8)
    return q, jnp.asarray(lo, jnp.float32), jnp.asarray(hi, jnp.float32)


register_op("_contrib_requantize", num_inputs=3, num_outputs=3,
            params=[Param("min_calib_range", float, None),
                    Param("max_calib_range", float, None),
                    Param("out_type", str, "int8",
                          enum=("int8", "uint8"))],
            aliases=("requantize",), differentiable=False)(_requantize)


def _q_out_range(min_d, max_d, min_w, max_w, in_dtype, w_dtype):
    """float value of one int32 accumulator unit = 1/(sd*sw); the int32
    range bound below mirrors the reference's
    GetQuantizedElemwiseOutputRange logic."""
    sd = _scale_of(min_d.reshape(()), max_d.reshape(()), in_dtype)
    sw = _scale_of(min_w.reshape(()), max_w.reshape(()), w_dtype)
    unit = 1.0 / (sd * sw)
    bound = 2147483647.0 * unit
    return unit, -bound, bound


def _quantized_conv(data, weight, *rest, kernel=(), stride=None,
                    dilate=None, pad=None, num_filter=0, num_group=1,
                    no_bias=True, layout=None):
    """int8 conv with int32 accumulation (quantized_conv†).  Inputs:
    data(int8/uint8), weight(int8), [bias(int8)], then min/max scalars
    for each tensor in the same order.  Returns (int32, min, max)."""
    n_tensors = 2 if no_bias else 3
    if len(rest) != (0 if no_bias else 1) + 2 * n_tensors:
        raise MXNetError(
            f"quantized_conv expects {n_tensors} tensors + "
            f"{2 * n_tensors} ranges")
    if no_bias:
        bias = None
        mins_maxes = rest
    else:
        bias = rest[0]
        mins_maxes = rest[1:]
    min_d, max_d, min_w, max_w = mins_maxes[:4]
    nd = len(kernel)
    stride_t = _tuple(stride, nd)
    dilate_t = _tuple(dilate, nd)
    pad_t = _tuple(pad, nd) if pad is not None else (0,) * nd
    from .ops_impl import _CONV_DN
    layout = layout or {1: "NCW", 2: "NCHW", 3: "NCDHW"}[nd]
    if data.dtype == jnp.uint8:
        # uint8 activations use the shifted-range-with-zero-point-0
        # convention (min_data == 0, the post-ReLU default — the
        # reference's MKLDNN u8s8s32 tier ditto), so the accumulator
        # stays scale-only.  conv_general_dilated requires matching
        # operand dtypes; int16 holds u8 and s8 exactly.  A blind
        # .astype(int8) would wrap 128..255 negative (r3 advisor).
        lhs = data.astype(jnp.int16)
        rhs = weight.astype(jnp.int16)
    elif data.dtype == jnp.int8:
        lhs = data
        rhs = weight.astype(jnp.int8)
    else:
        raise MXNetError(
            f"quantized_conv expects int8/uint8 data, got {data.dtype}")
    out = lax.conv_general_dilated(
        lhs, rhs,
        window_strides=stride_t, padding=[(p, p) for p in pad_t],
        rhs_dilation=dilate_t,
        dimension_numbers=_CONV_DN[layout],
        feature_group_count=num_group,
        preferred_element_type=jnp.int32)
    unit, lo, hi = _q_out_range(min_d, max_d, min_w, max_w,
                                data.dtype, jnp.int8)
    if bias is not None:
        min_b, max_b = mins_maxes[4:6]
        sb = _scale_of(min_b.reshape(()), max_b.reshape(()), jnp.int8)
        # rescale int8 bias into int32 accumulator units
        b32 = jnp.round(bias.astype(jnp.float32) / sb / unit)
        out = out + b32.astype(jnp.int32).reshape(1, -1, *([1] * nd))
    return out, jnp.asarray(lo, jnp.float32), jnp.asarray(hi, jnp.float32)


register_op("_contrib_quantized_conv", num_inputs=-1, num_outputs=3,
            params=[Param("kernel", tuple, ()),
                    Param("stride", tuple, None),
                    Param("dilate", tuple, None),
                    Param("pad", tuple, None),
                    Param("num_filter", int, 0),
                    Param("num_group", int, 1),
                    Param("no_bias", bool, True),
                    Param("layout", str, None)],
            aliases=("quantized_conv",),
            differentiable=False)(_quantized_conv)


def _quantized_fully_connected(data, weight, *rest, num_hidden=0,
                               no_bias=True, flatten=True):
    if no_bias:
        bias = None
        mins_maxes = rest
    else:
        bias = rest[0]
        mins_maxes = rest[1:]
    min_d, max_d, min_w, max_w = mins_maxes[:4]
    if data.dtype not in (jnp.int8, jnp.uint8):
        raise MXNetError(
            f"quantized_fully_connected expects int8/uint8 data, got "
            f"{data.dtype}")
    x = data.reshape(data.shape[0], -1) if flatten else data
    # dot_general takes mixed u8 x s8 operands directly (uint8 keeps
    # the zero-point-0 convention — see _quantized_conv); casting
    # uint8 through int8 would wrap 128..255 negative (r3 advisor)
    out = lax.dot_general(
        x, weight.astype(jnp.int8),
        (((x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)
    unit, lo, hi = _q_out_range(min_d, max_d, min_w, max_w,
                                data.dtype, jnp.int8)
    if bias is not None:
        min_b, max_b = mins_maxes[4:6]
        sb = _scale_of(min_b.reshape(()), max_b.reshape(()), jnp.int8)
        b32 = jnp.round(bias.astype(jnp.float32) / sb / unit)
        out = out + b32.astype(jnp.int32)
    return out, jnp.asarray(lo, jnp.float32), jnp.asarray(hi, jnp.float32)


register_op("_contrib_quantized_fully_connected", num_inputs=-1,
            num_outputs=3,
            params=[Param("num_hidden", int, 0),
                    Param("no_bias", bool, True),
                    Param("flatten", bool, True)],
            aliases=("quantized_fully_connected",),
            differentiable=False)(_quantized_fully_connected)


def _quantized_pooling(data, min_data, max_data, kernel=(),
                       pool_type="max", global_pool=False, stride=None,
                       pad=None):
    from .ops_impl import _pooling
    # max/avg pooling commute with the affine quantization map, so the
    # int8 domain result equals quantize(pool(dequantize)) with the
    # SAME range — no requantization step needed
    out = _pooling(data.astype(jnp.float32), kernel=kernel,
                   pool_type=pool_type, global_pool=global_pool,
                   stride=stride, pad=pad)
    out = jnp.round(out).astype(data.dtype) if pool_type == "avg" \
        else out.astype(data.dtype)
    return out, min_data.reshape(()), max_data.reshape(())


register_op("_contrib_quantized_pooling", num_inputs=3, num_outputs=3,
            params=[Param("kernel", tuple, ()),
                    Param("pool_type", str, "max"),
                    Param("global_pool", bool, False),
                    Param("stride", tuple, None),
                    Param("pad", tuple, None)],
            aliases=("quantized_pooling",),
            differentiable=False)(_quantized_pooling)


def _quantized_flatten(data, min_data, max_data):
    return (data.reshape(data.shape[0], -1), min_data.reshape(()),
            max_data.reshape(()))


register_op("_contrib_quantized_flatten", num_inputs=3, num_outputs=3,
            aliases=("quantized_flatten",),
            differentiable=False)(_quantized_flatten)


def _quantized_act(data, min_data, max_data, act_type="relu"):
    if act_type != "relu":
        raise MXNetError("quantized_act supports relu only (the "
                         "reference's quantized_activation ditto)")
    # symmetric int8: float 0 is int 0
    out = jnp.maximum(data, 0).astype(data.dtype)
    return out, min_data.reshape(()), max_data.reshape(())


register_op("_contrib_quantized_act", num_inputs=3, num_outputs=3,
            params=[Param("act_type", str, "relu")],
            aliases=("quantized_act",),
            differentiable=False)(_quantized_act)


def _quantized_concat(*args, num_args=0, dim=1):
    n = (len(args)) // 3
    datas = args[:n]
    mins = [m.reshape(()) for m in args[n::2]]
    maxs = [m.reshape(()) for m in args[n + 1::2]]
    out_min = jnp.stack(mins).min()
    out_max = jnp.stack(maxs).max()
    scale_out = _scale_of(out_min, out_max, jnp.int8)
    parts = []
    for d, lo, hi in zip(datas, mins, maxs):
        s = _scale_of(lo, hi, jnp.int8)
        parts.append(jnp.clip(jnp.round(
            d.astype(jnp.float32) * (scale_out / s)), -127, 127)
            .astype(jnp.int8))
    return jnp.concatenate(parts, axis=dim), out_min, out_max


register_op("_contrib_quantized_concat", num_inputs=-1, num_outputs=3,
            params=[Param("num_args", int, 0), Param("dim", int, 1)],
            aliases=("quantized_concat",),
            differentiable=False)(_quantized_concat)


# ---------------------------------------------------------------------------
# Switch-MoE feed-forward (new capability; parallel/moe.py is the
# functional core — expert parallelism engages when the expert-axis
# parameters are sharded P("ep") via param_spec_fn, GSPMD propagates)
# ---------------------------------------------------------------------------


def _contrib_moe_ffn(data, gate_w, w1, b1, w2, b2,
                     capacity_factor=1.25, activation="relu"):
    from ..parallel.moe import moe_ffn  # lazy: avoids an import cycle
    act = {"relu": jax.nn.relu, "gelu": jax.nn.gelu,
           "tanh": jnp.tanh}.get(activation)
    if act is None:
        raise MXNetError(f"MoEFFN activation {activation!r} not in "
                         f"relu/gelu/tanh")
    y, aux = moe_ffn(data, gate_w, w1, b1, w2, b2,
                     capacity_factor=float(capacity_factor),
                     activation=act)
    return y, aux


register_op("_contrib_MoEFFN", num_inputs=6, num_outputs=2,
            params=[Param("capacity_factor", float, 1.25),
                    Param("activation", str, "relu",
                          enum=("relu", "gelu", "tanh"))],
            aliases=("MoEFFN",))(_contrib_moe_ffn)
