"""Detection, CTC, and quantization operators.

Reference: ``src/operator/contrib/multibox_*.cc``† (SSD ops),
``src/operator/roi_pooling.cc``†, ``src/operator/contrib/ctc_loss.cc``†,
``src/operator/quantization/``†.

TPU-native notes: everything keeps STATIC shapes (SURVEY §7 hard-part
2) — NMS-style ops mark suppressed entries -1 instead of shrinking;
ROIPooling evaluates each output bin as a masked max over the feature
map (vectorized, no dynamic slices); CTC is a ``lax.scan`` over time in
log space, differentiable by jax AD (no hand-written backward).
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..base import MXNetError
from ..ops.registry import Param, register_op

_NEG = -1e30


# ----------------------------------------------------------------------
# ROIPooling
# ----------------------------------------------------------------------

def _roi_pooling(data, rois, pooled_size=(7, 7), spatial_scale=1.0):
    """data (N,C,H,W); rois (R,5) = [batch_idx, x1, y1, x2, y2] in image
    coords; output (R, C, ph, pw) (reference ``ROIPooling``†)."""
    ph, pw = pooled_size
    N, C, H, W = data.shape

    ys = jnp.arange(H, dtype=jnp.float32)
    xs = jnp.arange(W, dtype=jnp.float32)

    def one_roi(roi):
        bidx = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * spatial_scale)
        y1 = jnp.round(roi[2] * spatial_scale)
        x2 = jnp.round(roi[3] * spatial_scale)
        y2 = jnp.round(roi[4] * spatial_scale)
        rh = jnp.maximum(y2 - y1 + 1.0, 1.0)
        rw = jnp.maximum(x2 - x1 + 1.0, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        img = data[bidx]  # (C,H,W)

        def one_bin(i, j):
            hstart = jnp.floor(y1 + i * bin_h)
            hend = jnp.ceil(y1 + (i + 1) * bin_h)
            wstart = jnp.floor(x1 + j * bin_w)
            wend = jnp.ceil(x1 + (j + 1) * bin_w)
            mask = ((ys[:, None] >= hstart) & (ys[:, None] < hend) &
                    (xs[None, :] >= wstart) & (xs[None, :] < wend))
            masked = jnp.where(mask[None], img, _NEG)
            val = jnp.max(masked, axis=(1, 2))
            return jnp.where(jnp.any(mask), val, 0.0)

        ii, jj = jnp.meshgrid(jnp.arange(ph), jnp.arange(pw),
                              indexing="ij")
        bins = jax.vmap(jax.vmap(one_bin))(ii, jj)  # (ph, pw, C)
        return jnp.transpose(bins, (2, 0, 1))

    return jax.vmap(one_roi)(rois.astype(jnp.float32))


register_op("ROIPooling", num_inputs=2,
            params=[Param("pooled_size", tuple, (7, 7)),
                    Param("spatial_scale", float, 1.0)])(_roi_pooling)


# ----------------------------------------------------------------------
# MultiBox (SSD) family
# ----------------------------------------------------------------------

def _multibox_prior(data, sizes=(1.0,), ratios=(1.0,), steps=(-1.0, -1.0),
                    offsets=(0.5, 0.5), clip=False):
    """Anchor generation (reference ``MultiBoxPrior``†): (1, H*W*(S+R-1),
    4) corner boxes, normalized coords."""
    H, W = data.shape[2], data.shape[3]
    sizes = tuple(sizes)
    ratios = tuple(ratios)
    step_y = steps[0] if steps[0] > 0 else 1.0 / H
    step_x = steps[1] if steps[1] > 0 else 1.0 / W
    cy = (jnp.arange(H, dtype=jnp.float32) + offsets[0]) * step_y
    cx = (jnp.arange(W, dtype=jnp.float32) + offsets[1]) * step_x
    # anchor (w,h) list: all sizes at ratios[0], then sizes[0] at the
    # other ratios — the reference's S+R-1 convention
    r0 = float(np.sqrt(ratios[0]))
    whs = [(s * r0, s / r0) for s in sizes]
    whs += [(sizes[0] * float(np.sqrt(r)), sizes[0] / float(np.sqrt(r)))
            for r in ratios[1:]]
    wh = jnp.asarray(whs, jnp.float32)  # (K, 2): (w, h)
    cyx = jnp.stack(jnp.meshgrid(cy, cx, indexing="ij"),
                    axis=-1).reshape(-1, 2)  # (H*W, 2) = (cy, cx)
    cyx = jnp.repeat(cyx, wh.shape[0], axis=0)
    whr = jnp.tile(wh, (H * W, 1))
    boxes = jnp.stack([cyx[:, 1] - whr[:, 0] / 2,
                       cyx[:, 0] - whr[:, 1] / 2,
                       cyx[:, 1] + whr[:, 0] / 2,
                       cyx[:, 0] + whr[:, 1] / 2], axis=1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return boxes[None]


register_op("MultiBoxPrior", num_inputs=1,
            params=[Param("sizes", tuple, (1.0,)),
                    Param("ratios", tuple, (1.0,)),
                    Param("steps", tuple, (-1.0, -1.0)),
                    Param("offsets", tuple, (0.5, 0.5)),
                    Param("clip", bool, False)],
            differentiable=False)(_multibox_prior)


def _iou_corner(a, b):
    """a (A,4), b (B,4) corner boxes → (A,B) IoU."""
    tl = jnp.maximum(a[:, None, :2], b[None, :, :2])
    br = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(br - tl, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.maximum((a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1]), 0.0)
    area_b = jnp.maximum((b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]), 0.0)
    return inter / jnp.maximum(area_a[:, None] + area_b[None] - inter,
                               1e-12)


def _encode(anchors, gt, variances):
    """Corner anchors + matched gt corners → regression targets."""
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2
    gw = jnp.maximum(gt[:, 2] - gt[:, 0], 1e-12)
    gh = jnp.maximum(gt[:, 3] - gt[:, 1], 1e-12)
    gcx = (gt[:, 0] + gt[:, 2]) / 2
    gcy = (gt[:, 1] + gt[:, 3]) / 2
    tx = (gcx - acx) / jnp.maximum(aw, 1e-12) / variances[0]
    ty = (gcy - acy) / jnp.maximum(ah, 1e-12) / variances[1]
    tw = jnp.log(gw / jnp.maximum(aw, 1e-12)) / variances[2]
    th = jnp.log(gh / jnp.maximum(ah, 1e-12)) / variances[3]
    return jnp.stack([tx, ty, tw, th], axis=1)


def _multibox_target(anchors, labels, cls_preds, overlap_threshold=0.5,
                     ignore_label=-1.0, negative_mining_ratio=-1.0,
                     variances=(0.1, 0.1, 0.2, 0.2)):
    """Anchor↔gt matching + target encoding (reference
    ``MultiBoxTarget``†).  labels (N, O, 5) rows [cls, x1, y1, x2, y2],
    cls = -1 padding.  Returns (box_target (N, A*4), box_mask (N, A*4),
    cls_target (N, A)); cls_target 0 = background, gt class + 1
    otherwise."""
    anc = anchors[0]
    A = anc.shape[0]
    variances = jnp.asarray(variances, jnp.float32)

    def one(lab, cls_pred):
        valid = lab[:, 0] >= 0
        gt_boxes = lab[:, 1:5]
        iou = _iou_corner(anc, gt_boxes)  # (A, O)
        iou = jnp.where(valid[None, :], iou, -1.0)
        best_gt = jnp.argmax(iou, axis=1)           # per-anchor
        best_iou = jnp.max(iou, axis=1)
        pos = best_iou > overlap_threshold
        # force-match: each VALID gt claims its best anchor; padding
        # rows scatter to the out-of-range index A (mode='drop') so
        # they can never clobber a real match at a duplicate index
        best_anchor = jnp.argmax(iou, axis=0)       # (O,)
        scatter_idx = jnp.where(valid, best_anchor, A)
        forced = jnp.zeros(A, bool).at[scatter_idx].set(
            True, mode="drop")
        forced_gt = jnp.zeros(A, jnp.int32).at[scatter_idx].set(
            jnp.arange(lab.shape[0], dtype=jnp.int32), mode="drop")
        gt_idx = jnp.where(forced, forced_gt, best_gt)
        pos = pos | forced
        matched = gt_boxes[gt_idx]
        target = _encode(anc, matched, variances)
        target = jnp.where(pos[:, None], target, 0.0)
        mask = jnp.where(pos[:, None],
                         jnp.ones_like(target), 0.0)
        cls = jnp.where(pos, lab[gt_idx, 0] + 1.0, 0.0)
        if negative_mining_ratio > 0:
            # hard-negative mining (reference semantics): rank negative
            # anchors by their max foreground confidence, keep the
            # hardest ratio*num_pos as background targets, mark the
            # rest ignore_label so the loss skips them
            fg_conf = jnp.max(cls_pred[1:], axis=0)  # (A,)
            neg = ~pos
            num_pos = jnp.sum(pos)
            max_neg = (negative_mining_ratio *
                       num_pos.astype(jnp.float32)).astype(jnp.int32)
            score = jnp.where(neg, fg_conf, -jnp.inf)
            order = jnp.argsort(-score)
            rank = jnp.argsort(order)
            keep_neg = neg & (rank < max_neg)
            cls = jnp.where(pos, cls,
                            jnp.where(keep_neg, 0.0, ignore_label))
        return target.reshape(-1), mask.reshape(-1), cls

    bt, bm, ct = jax.vmap(one)(labels, cls_preds)
    return bt, bm, ct


register_op("MultiBoxTarget", num_inputs=3, num_outputs=3,
            params=[Param("overlap_threshold", float, 0.5),
                    Param("ignore_label", float, -1.0),
                    Param("negative_mining_ratio", float, -1.0),
                    Param("variances", tuple, (0.1, 0.1, 0.2, 0.2))],
            differentiable=False)(_multibox_target)


def _decode(anchors, loc, variances):
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2
    cx = loc[:, 0] * variances[0] * aw + acx
    cy = loc[:, 1] * variances[1] * ah + acy
    w = jnp.exp(loc[:, 2] * variances[2]) * aw
    h = jnp.exp(loc[:, 3] * variances[3]) * ah
    return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                     axis=1)


def _multibox_detection(cls_prob, loc_pred, anchors, clip=True,
                        threshold=0.01, nms_threshold=0.5,
                        force_suppress=False, nms_topk=-1,
                        variances=(0.1, 0.1, 0.2, 0.2)):
    """Decode + class-select + NMS (reference ``MultiBoxDetection``†).
    cls_prob (N, C, A) incl. background class 0; output (N, A, 6) rows
    [cls_id, score, x1, y1, x2, y2], suppressed rows -1."""
    anc = anchors[0]
    variances = jnp.asarray(variances, jnp.float32)

    def one(probs, loc):
        boxes = _decode(anc, loc.reshape(-1, 4), variances)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        fg = probs[1:]                      # (C-1, A)
        cls_id = jnp.argmax(fg, axis=0).astype(jnp.float32)
        score = jnp.max(fg, axis=0)
        keep_score = score > threshold
        # NMS over kept boxes (class-aware unless force_suppress)
        order = jnp.argsort(-score)
        bs = boxes[order]
        ss = jnp.where(keep_score[order], score[order], 0.0)
        cs = cls_id[order]
        A = bs.shape[0]
        # class-aware suppression = mask cross-class pairs out of the
        # IoU matrix (unless force_suppress)
        same_cls = (cs[:, None] == cs[None, :]) | force_suppress
        iou = jnp.where(same_cls, _iou_corner(bs, bs), 0.0)
        keep0 = ss > 0.0
        if nms_topk > 0:
            # reference: only the top-k scored boxes enter NMS at all
            keep0 = keep0 & (jnp.arange(A) < nms_topk)
        keep = _greedy_nms_keep(
            iou, keep0, nms_threshold,
            A if nms_topk < 0 else min(nms_topk, A))
        out = jnp.concatenate([cs[:, None], ss[:, None], bs], axis=1)
        return jnp.where(keep[:, None], out, -jnp.ones_like(out))

    return jax.vmap(one)(cls_prob, loc_pred)


register_op("MultiBoxDetection", num_inputs=3,
            params=[Param("clip", bool, True),
                    Param("threshold", float, 0.01),
                    Param("nms_threshold", float, 0.5),
                    Param("force_suppress", bool, False),
                    Param("nms_topk", int, -1),
                    Param("variances", tuple, (0.1, 0.1, 0.2, 0.2))],
            differentiable=False)(_multibox_detection)


# ----------------------------------------------------------------------
# CTC loss
# ----------------------------------------------------------------------

def _ctc_loss(data, label, *lengths, use_data_lengths=False,
              use_label_lengths=False, blank_label="first"):
    """CTC negative log likelihood (reference ``ctc_loss``†).
    data (T, N, C) pre-softmax activations; label (N, L) with -1 (or 0
    for blank_label='last' semantics) padding.  Optional trailing
    inputs: data_lengths (N,) then label_lengths (N,) gated by the
    use_* flags.  Blank index 0 for 'first' (labels are 1-based),
    C-1 for 'last' (labels 0-based).  Returns (N,) losses.
    Differentiable through the scan.
    """
    T, N, C = data.shape
    L = label.shape[1]
    data_lengths = None
    label_lengths = None
    rest = list(lengths)
    if use_data_lengths:
        if not rest:
            raise MXNetError("use_data_lengths=True needs a "
                             "data_lengths input")
        data_lengths = rest.pop(0).astype(jnp.int32)
    if use_label_lengths:
        if not rest:
            raise MXNetError("use_label_lengths=True needs a "
                             "label_lengths input")
        label_lengths = rest.pop(0).astype(jnp.int32)
    logp = jax.nn.log_softmax(data, axis=-1)
    blank = 0 if blank_label == "first" else C - 1
    lab = label.astype(jnp.int32)
    if blank_label == "first":
        # labels come 1-based; padding <= 0
        valid = lab > 0
        lab_idx = jnp.where(valid, lab, 1)
    else:
        valid = lab >= 0
        lab_idx = jnp.where(valid, lab, 0)
    if label_lengths is not None:
        valid = jnp.arange(L)[None, :] < label_lengths[:, None]
        lab_idx = jnp.where(valid, lab_idx,
                            1 if blank_label == "first" else 0)
    label_len = jnp.sum(valid.astype(jnp.int32), axis=1)  # (N,)

    # extended sequence: blank, l1, blank, l2, ..., blank (2L+1)
    S = 2 * L + 1
    ext = jnp.full((N, S), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(lab_idx)
    ext_valid_len = 2 * label_len + 1

    # alpha recursion in log space
    idx_s = jnp.arange(S)
    same_as_prev2 = jnp.concatenate(
        [jnp.zeros((N, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1)
    allow_skip = (idx_s[None, :] % 2 == 1) & ~same_as_prev2

    def emit(t):
        # (N, S) log prob of emitting ext symbol at time t
        return jnp.take_along_axis(logp[t], ext, axis=1)

    alpha0 = jnp.full((N, S), _NEG)
    alpha0 = alpha0.at[:, 0].set(emit(0)[:, 0])
    alpha0 = alpha0.at[:, 1].set(
        jnp.where(label_len > 0, emit(0)[:, 1], _NEG))

    def step(alpha, t):
        prev1 = jnp.concatenate(
            [jnp.full((N, 1), _NEG), alpha[:, :-1]], axis=1)
        prev2 = jnp.concatenate(
            [jnp.full((N, 2), _NEG), alpha[:, :-2]], axis=1)
        prev2 = jnp.where(allow_skip, prev2, _NEG)
        stacked = jnp.stack([alpha, prev1, prev2])
        m = jnp.max(stacked, axis=0)
        tot = m + jnp.log(jnp.sum(jnp.exp(stacked - m), axis=0) + 1e-30)
        alpha_new = tot + emit(t)
        if data_lengths is not None:
            # past a sequence's length the alphas freeze, so the final
            # read sees the values at t = len-1
            active = (t < data_lengths)[:, None]
            alpha_new = jnp.where(active, alpha_new, alpha)
        return alpha_new, None

    alpha, _ = lax.scan(step, alpha0, jnp.arange(1, T))
    # final: last blank or last label (identical cells when the label
    # is empty — count once, not twice)
    last = ext_valid_len - 1
    a_last = jnp.take_along_axis(alpha, last[:, None], axis=1)[:, 0]
    a_prev = jnp.take_along_axis(
        alpha, jnp.maximum(last - 1, 0)[:, None], axis=1)[:, 0]
    m = jnp.maximum(a_last, a_prev)
    both = m + jnp.log(jnp.exp(a_last - m) + jnp.exp(a_prev - m) +
                       1e-30)
    ll = jnp.where(last == 0, a_last, both)
    return -ll


register_op("ctc_loss", num_inputs=-1,
            params=[Param("use_data_lengths", bool, False),
                    Param("use_label_lengths", bool, False),
                    Param("blank_label", str, "first",
                          enum=("first", "last"))],
            aliases=("CTCLoss",))(_ctc_loss)


# ----------------------------------------------------------------------
# quantization family
# ----------------------------------------------------------------------

def _quantize(data, min_range, max_range, out_type="uint8"):
    """Affine quantization (reference ``quantize``†).  Returns
    (quantized, min_range, max_range)."""
    if out_type == "uint8":
        qmin, qmax, dt = 0.0, 255.0, jnp.uint8
    elif out_type == "int8":
        qmin, qmax, dt = -127.0, 127.0, jnp.int8
    else:
        raise MXNetError(f"unsupported out_type {out_type}")
    lo = min_range.reshape(())
    hi = max_range.reshape(())
    scale = (qmax - qmin) / jnp.maximum(hi - lo, 1e-12)
    q = jnp.clip(jnp.round((data - lo) * scale + qmin), qmin, qmax)
    return q.astype(dt), lo, hi


register_op("quantize", num_inputs=3, num_outputs=3,
            params=[Param("out_type", str, "uint8",
                          enum=("uint8", "int8"))],
            differentiable=False)(_quantize)


def _dequantize(data, min_range, max_range, out_type="float32"):
    lo = min_range.reshape(())
    hi = max_range.reshape(())
    if data.dtype == jnp.uint8:
        qmin, qmax = 0.0, 255.0
    elif data.dtype == jnp.int32:
        # int32 accumulators from the quantized conv/fc tier
        qmin, qmax = -2147483647.0, 2147483647.0
    else:
        qmin, qmax = -127.0, 127.0
    scale = jnp.maximum(hi - lo, 1e-12) / (qmax - qmin)
    return (data.astype(jnp.float32) - qmin) * scale + lo


register_op("dequantize", num_inputs=3,
            params=[Param("out_type", str, "float32")],
            differentiable=False)(_dequantize)


def _quantize_v2(data, min_calib_range=None, max_calib_range=None,
                 out_type="int8"):
    """Calibrated quantization (reference ``_contrib_quantize_v2``†):
    ranges from calibration params or data min/max."""
    lo = jnp.asarray(min_calib_range if min_calib_range is not None
                     else jnp.min(data), jnp.float32)
    hi = jnp.asarray(max_calib_range if max_calib_range is not None
                     else jnp.max(data), jnp.float32)
    return _quantize(data, lo, hi, out_type=out_type)


register_op("quantize_v2", num_inputs=1, num_outputs=3,
            params=[Param("min_calib_range", float, None),
                    Param("max_calib_range", float, None),
                    Param("out_type", str, "int8",
                          enum=("uint8", "int8"))],
            aliases=("_contrib_quantize_v2",),
            differentiable=False)(_quantize_v2)


# ----------------------------------------------------------------------
# RPN Proposal (reference ``src/operator/contrib/proposal.cc``†)
# ----------------------------------------------------------------------

def _base_anchors(stride, scales, ratios):
    """Anchors centered on one stride cell (reference
    ``GenerateAnchors``†: ratio enumeration preserves area, then
    scales)."""
    base = float(stride)
    cx = cy = (base - 1.0) / 2.0
    out = []
    area = base * base
    for r in ratios:
        w = np.round(np.sqrt(area / r))
        h = np.round(w * r)
        for s in scales:
            ws, hs = w * s, h * s
            out.append([cx - (ws - 1) / 2, cy - (hs - 1) / 2,
                        cx + (ws - 1) / 2, cy + (hs - 1) / 2])
    return np.asarray(out, np.float32)


def _anchor_grid(height, width, feature_stride, scales, ratios):
    """All anchors for a height×width feature map in pixel coords,
    position-major anchor-minor — THE ordering contract shared by the
    Proposal op and models.rcnn.rpn_anchors."""
    base = _base_anchors(feature_stride, scales, ratios)
    sx = np.arange(width, dtype=np.float32) * feature_stride
    sy = np.arange(height, dtype=np.float32) * feature_stride
    shift = np.stack([np.tile(sx, height), np.repeat(sy, width),
                      np.tile(sx, height), np.repeat(sy, width)],
                     axis=1)
    return (shift[:, None, :] + base[None]).reshape(-1, 4)


def _pixel_iou(boxes):
    """Pairwise IoU under the reference's +1-pixel convention
    (proposal.cc†: widths are x2-x1+1)."""
    tl = jnp.maximum(boxes[:, None, :2], boxes[None, :, :2])
    br = jnp.minimum(boxes[:, None, 2:], boxes[None, :, 2:])
    wh = jnp.maximum(br - tl + 1.0, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area = (boxes[:, 2] - boxes[:, 0] + 1.0) * \
        (boxes[:, 3] - boxes[:, 1] + 1.0)
    return inter / jnp.maximum(area[:, None] + area[None] - inter,
                               1e-12)


def _greedy_nms_keep(iou, keep0, threshold, n_iter):
    """The one greedy-suppression loop (score-descending rows): row i,
    if alive, kills every later row whose (possibly masked) IoU
    exceeds the threshold."""
    n = iou.shape[0]

    def body(i, keep):
        sup = (iou[i] > threshold) & (jnp.arange(n) > i) & keep[i]
        return keep & ~sup

    return lax.fori_loop(0, n_iter, body, keep0)


def _proposal(cls_prob, bbox_pred, im_info, scales=(4.0, 8.0, 16.0,
                                                    32.0),
              ratios=(0.5, 1.0, 2.0), feature_stride=16,
              rpn_pre_nms_top_n=6000, rpn_post_nms_top_n=300,
              threshold=0.7, rpn_min_size=16, output_score=False):
    """RPN proposals: decode anchor deltas, clip, min-size filter,
    top-k, NMS (reference ``_contrib_Proposal``†).  cls_prob
    (N, 2A, H, W) — background scores first; bbox_pred (N, 4A, H, W);
    im_info (N, 3) rows [height, width, scale].  Returns rois
    (N*post_nms, 5) rows [batch_idx, x1, y1, x2, y2] (+ scores
    (N*post_nms, 1) when output_score); short batches pad with
    zero-boxes."""
    N, twoA, H, W = cls_prob.shape
    A = twoA // 2
    if A != len(scales) * len(ratios):
        raise MXNetError(
            f"Proposal: cls_prob carries {A} anchors/position but "
            f"scales×ratios = {len(scales)}×{len(ratios)} = "
            f"{len(scales) * len(ratios)}")
    anchors = jnp.asarray(_anchor_grid(H, W, feature_stride, scales,
                                       ratios))
    M = anchors.shape[0]
    pre_n = min(int(rpn_pre_nms_top_n), M) \
        if rpn_pre_nms_top_n > 0 else M
    post_n = int(rpn_post_nms_top_n)

    def one(scores_hw, deltas_hw, info):
        # (2A,H,W) → fg (H,W,A) → (M,), position-major anchor-minor
        fg = jnp.transpose(scores_hw[A:], (1, 2, 0)).reshape(-1)
        d = jnp.transpose(
            deltas_hw.reshape(A, 4, H, W), (2, 3, 0, 1)).reshape(-1, 4)
        aw = anchors[:, 2] - anchors[:, 0] + 1.0
        ah = anchors[:, 3] - anchors[:, 1] + 1.0
        acx = anchors[:, 0] + (aw - 1.0) / 2
        acy = anchors[:, 1] + (ah - 1.0) / 2
        cx = d[:, 0] * aw + acx
        cy = d[:, 1] * ah + acy
        w = jnp.exp(jnp.clip(d[:, 2], -10.0, 10.0)) * aw
        h = jnp.exp(jnp.clip(d[:, 3], -10.0, 10.0)) * ah
        boxes = jnp.stack([cx - (w - 1) / 2, cy - (h - 1) / 2,
                           cx + (w - 1) / 2, cy + (h - 1) / 2], axis=1)
        # clip to image, drop boxes below min size (at image scale)
        ih, iw, scl = info[0], info[1], info[2]
        boxes = jnp.stack([
            jnp.clip(boxes[:, 0], 0.0, iw - 1.0),
            jnp.clip(boxes[:, 1], 0.0, ih - 1.0),
            jnp.clip(boxes[:, 2], 0.0, iw - 1.0),
            jnp.clip(boxes[:, 3], 0.0, ih - 1.0)], axis=1)
        min_sz = rpn_min_size * scl
        keep_sz = ((boxes[:, 2] - boxes[:, 0] + 1.0) >= min_sz) & \
            ((boxes[:, 3] - boxes[:, 1] + 1.0) >= min_sz)
        score = jnp.where(keep_sz, fg, -jnp.inf)
        order = jnp.argsort(-score)[:pre_n]
        bs = boxes[order]
        ss = score[order]
        keep = _greedy_nms_keep(_pixel_iou(bs), ss > -jnp.inf,
                                threshold, pre_n)
        # compact kept rows into the first post_n slots
        rank = jnp.cumsum(keep) - 1
        tgt = jnp.where(keep & (rank < post_n), rank, post_n)
        out_b = jnp.zeros((post_n + 1, 4), jnp.float32) \
            .at[tgt].set(bs, mode="drop")[:post_n]
        out_s = jnp.zeros((post_n + 1,), jnp.float32) \
            .at[tgt].set(jnp.where(keep, ss, 0.0),
                         mode="drop")[:post_n]
        return out_b, out_s

    boxes, scores = jax.vmap(one)(cls_prob, bbox_pred, im_info)
    batch_idx = jnp.repeat(jnp.arange(N, dtype=jnp.float32), post_n)
    rois = jnp.concatenate(
        [batch_idx[:, None], boxes.reshape(-1, 4)], axis=1)
    if output_score:
        return rois, scores.reshape(-1, 1)
    return rois


register_op("Proposal", num_inputs=3,
            params=[Param("scales", tuple, (4.0, 8.0, 16.0, 32.0)),
                    Param("ratios", tuple, (0.5, 1.0, 2.0)),
                    Param("feature_stride", int, 16),
                    Param("rpn_pre_nms_top_n", int, 6000),
                    Param("rpn_post_nms_top_n", int, 300),
                    Param("threshold", float, 0.7),
                    Param("rpn_min_size", int, 16),
                    Param("output_score", bool, False)],
            aliases=("_contrib_Proposal", "_contrib_MultiProposal"),
            num_outputs_fn=lambda params:
                2 if params.get("output_score") else 1,
            differentiable=False)(_proposal)
