"""``mx.nd.linalg`` namespace (reference ``python/mxnet/ndarray/linalg.py``†
over ``src/operator/tensor/la_op.cc``†)."""
from __future__ import annotations

from . import _invoke_op


def gemm(a, b, c, transpose_a=False, transpose_b=False, alpha=1.0,
         beta=1.0):
    return _invoke_op("linalg_gemm", a, b, c, transpose_a=transpose_a,
                      transpose_b=transpose_b, alpha=alpha, beta=beta)


def gemm2(a, b, transpose_a=False, transpose_b=False, alpha=1.0):
    return _invoke_op("linalg_gemm2", a, b, transpose_a=transpose_a,
                      transpose_b=transpose_b, alpha=alpha)


def potrf(a):
    return _invoke_op("linalg_potrf", a)


def trsm(a, b, transpose=False, rightside=False, lower=True, alpha=1.0):
    return _invoke_op("linalg_trsm", a, b, transpose=transpose,
                      rightside=rightside, lower=lower, alpha=alpha)


def syrk(a, transpose=False, alpha=1.0):
    return _invoke_op("linalg_syrk", a, transpose=transpose, alpha=alpha)


def sumlogdiag(a):
    return _invoke_op("linalg_sumlogdiag", a)


def extractdiag(a, offset=0):
    return _invoke_op("linalg_extractdiag", a, offset=offset)


def inverse(a):
    return _invoke_op("linalg_inverse", a)


def det(a):
    return _invoke_op("linalg_det", a)
