"""``mxtpu.nd`` — the eager NDArray op namespace.

Reference: ``python/mxnet/ndarray/``† where op wrappers are *generated*
from the C registry at import time.  Here the same generation happens from
the Python op registry: every registered op becomes a module-level function
taking/returning NDArray, routed through the autograd tape when recording.
"""
from __future__ import annotations

import functools
import sys
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from .. import amp as _amp
from .. import quant as _quant
from ..base import MXNetError
from ..ops.registry import OP_REGISTRY, get_op, list_ops
from . import ops_impl  # noqa: F401  (populates the registry)
from . import rnn_impl  # noqa: F401  (fused RNN op)
from . import detection_impl  # noqa: F401  (SSD/ROI/CTC/quantize ops)
from . import spatial_impl  # noqa: F401  (grid/sampler/crop/corr ops)
from . import ops_extra  # noqa: F401  (init/amp/linalg/optimizer tail)
from . import nn_extra  # noqa: F401  (deformable/psroi/quantized tier)
from . import random_ops  # noqa: F401  (_random_*/_sample_* ops)
from .ndarray import (NDArray, array, zeros, ones, full, empty, arange,
                      concat, stack, save, load, waitall, from_numpy,
                      linspace, eye, zeros_like as _zeros_like_fn)

__all__ = ["NDArray", "array", "zeros", "ones", "full", "empty", "arange",
           "concat", "stack", "save", "load", "waitall", "from_numpy",
           "linspace", "eye", "random", "sparse", "linalg", "contrib"]


_prof = None    # lazily bound profiler module (circular import at load)
_engine = None  # lazily bound engine module


def _invoke_op(name: str, *inputs, **kwargs):
    """Eager dispatch — the role of ``MXImperativeInvokeEx``
    (``src/c_api/c_api_ndarray.cc``† → ``Imperative::Invoke``†).
    jax's dispatch cache plays the part of the engine's async push."""
    global _prof, _engine
    if _prof is None:
        from .. import profiler as _prof_mod
        from .. import engine as _engine_mod
        _prof = _prof_mod
        _engine = _engine_mod
    if _prof._ACTIVE or _engine._SYNC:
        t0 = _prof._now_us()
        out = _invoke_op_inner(name, *inputs, **kwargs)
        if _engine._SYNC:
            # NaiveEngine debug mode: serialize every dispatch so async
            # failures surface at the faulting op (SURVEY §5.2)
            jax.block_until_ready(tuple(
                o._data for o in (out if isinstance(out, tuple)
                                  else (out,))))
        if _prof._ACTIVE:
            _prof.record_op(name, t0, _prof._now_us() - t0)
        return out
    return _invoke_op_inner(name, *inputs, **kwargs)


def _invoke_op_inner(name: str, *inputs, **kwargs):
    op = get_op(name)
    arrays = []
    ctx = None
    for x in inputs:
        if isinstance(x, NDArray):
            arrays.append(x._data)
            if ctx is None:
                ctx = x._ctx
        else:
            arrays.append(jnp.asarray(x))
    resolved = op.resolve_params(kwargs)

    # policy-driven INT8 quantization (mxtpu.quant): inside a
    # calibration scope candidate contractions are observed, inside a
    # quantize scope the ones with a recorded scale become int8 GEMMs
    # with i32 accumulation.  Checked BEFORE amp so a quantized op is
    # never double-rewritten; both off paths cost one global read.
    q_fn = _quant.wrap_op(name, op, arrays, resolved) \
        if _quant._ACTIVE else None
    # policy-driven autocast (mxtpu.amp): inside an autocast scope,
    # allow-listed contractions get their f32 inputs cast to bf16
    # *inside* the dispatched function so both jax AD and the eager
    # tape differentiate through the casts.  Off path: one global read.
    amp_fn = q_fn if q_fn is not None else (
        _amp.wrap_op(name, op, arrays, resolved)
        if _amp._ACTIVE else None)

    from .. import autograd
    if (autograd.is_recording() and op.differentiable
            and any(autograd._needs_grad(x) for x in inputs)):
        fn = amp_fn or (lambda *arrs: op.fn(*arrs, **resolved))  # noqa: E731
        out, node = autograd.record_op(name, fn, inputs, arrays)
        if isinstance(out, tuple):
            wrapped = tuple(NDArray(o, ctx, _placed=True) for o in out)
            for i, w in enumerate(wrapped):
                autograd.attach_output(w, node, i)
            return wrapped
        w = NDArray(out, ctx, _placed=True)
        autograd.attach_output(w, node, 0)
        return w

    out = amp_fn(*arrays) if amp_fn is not None \
        else op.fn(*arrays, **resolved)
    if isinstance(out, tuple):
        return tuple(NDArray(o, ctx, _placed=True) for o in out)
    return NDArray(out, ctx, _placed=True)


def _invoke_getitem(nd: NDArray, key):
    """Basic + advanced indexing, differentiable w.r.t. the data."""
    def norm(k):
        if isinstance(k, NDArray):
            return k._data if k._data.dtype != jnp.float32 \
                else k._data.astype(jnp.int32)
        if isinstance(k, tuple):
            return tuple(norm(e) for e in k)
        return k
    jkey = norm(key)

    from .. import autograd
    if autograd.is_recording() and autograd._needs_grad(nd):
        fn = lambda d: d[jkey]  # noqa: E731
        out, node = autograd.record_op("getitem", fn, (nd,), (nd._data,))
        w = NDArray(out, nd._ctx, _placed=True)
        autograd.attach_output(w, node, 0)
        return w
    return NDArray(nd._data[jkey], nd._ctx, _placed=True)


# ----------------------------------------------------------------------
# generate the namespace from the registry
# ----------------------------------------------------------------------
_THIS_MODULE = sys.modules[__name__]


def _make_op_fn(opname: str):
    op = get_op(opname)

    def fn(*args, out=None, **kwargs):
        res = _invoke_op(opname, *args, **kwargs)
        if out is not None:
            out._data = res._data if isinstance(res, NDArray) else res[0]._data
            return out
        return res
    fn.__name__ = opname
    fn.__qualname__ = opname
    fn.__doc__ = op.doc
    return fn


_seen = set()
for _op in list(OP_REGISTRY._entries.values()):
    for _n in (_op.name,) + _op.aliases:
        if _n not in _seen:
            _seen.add(_n)
            setattr(_THIS_MODULE, _n, _make_op_fn(_n))

# Dropout convenience: auto key + mode from autograd training state
_raw_dropout = getattr(_THIS_MODULE, "Dropout")


def Dropout(data, p=0.5, mode=None, axes=()):  # noqa: N802
    """Reference nn.Dropout op†; key drawn from the global RNG stream.
    mode defaults to 'training' under autograd.record(train_mode=True)."""
    from .. import autograd
    from . import random as _rnd
    if mode is None:
        mode = "training" if autograd.is_training() else "always_off"
    if mode == "always_off" or p <= 0.0:
        return data if isinstance(data, NDArray) else array(data)
    key = _rnd._next_key_nd()
    return _raw_dropout(data, key, p=p, mode="training", axes=axes)


setattr(_THIS_MODULE, "Dropout", Dropout)
setattr(_THIS_MODULE, "dropout", Dropout)

# FusedResidualLayerNorm convenience: auto key + mode, like Dropout
_raw_frln = getattr(_THIS_MODULE, "FusedResidualLayerNorm")


def FusedResidualLayerNorm(data, bias, residual, gamma, beta, p=0.1,  # noqa: N802
                           eps=1e-5, mode=None):
    """LN(residual + dropout(data + bias)) — the fused transformer
    epilogue; key drawn from the global RNG stream in training mode."""
    from .. import autograd
    from . import random as _rnd
    if mode is None:
        mode = "training" if autograd.is_training() else "always_off"
    training = mode == "training" and p > 0.0
    key = _rnd._next_key_nd() if training else zeros((2,), dtype="uint32")
    return _raw_frln(data, bias, residual, gamma, beta, key, p=p,
                     eps=eps, mode="training" if training else "always_off")


setattr(_THIS_MODULE, "FusedResidualLayerNorm", FusedResidualLayerNorm)

# shuffle convenience: auto key (reference mx.nd.shuffle draws from
# the global RNG)
_raw_shuffle = getattr(_THIS_MODULE, "shuffle")


def shuffle(data):  # noqa: N802
    from . import random as _rnd
    return _raw_shuffle(data, _rnd._next_key_nd())


setattr(_THIS_MODULE, "shuffle", shuffle)
setattr(_THIS_MODULE, "_shuffle", shuffle)

zeros_like = getattr(_THIS_MODULE, "zeros_like")
ones_like = getattr(_THIS_MODULE, "ones_like")

from . import random    # noqa: E402
from . import sparse    # noqa: E402
from . import linalg    # noqa: E402
from . import contrib   # noqa: E402
