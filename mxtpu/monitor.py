"""Tensor-statistics monitor (reference ``python/mxnet/monitor.py``†).

Attaches a stat function to executor outputs / Gluon block outputs for
debugging.  Sync note: pulling stats forces device sync each batch —
debug tool, not a training-loop resident.
"""
from __future__ import annotations

import logging
import re
from typing import Callable, List, Optional, Tuple

from .ndarray import NDArray

__all__ = ["Monitor"]


class Monitor:
    """Collect per-tensor statistics every ``interval`` batches
    (reference ``Monitor``†)."""

    def __init__(self, interval, stat_func=None, pattern=".*",
                 sort=False):
        if stat_func is None:
            def stat_func(x):
                return x.abs().mean() if hasattr(x, "abs") else abs(x).mean()
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue: List[Tuple[int, str, NDArray]] = []
        self.step = 0
        self.exes = []
        self.re_prog = re.compile(pattern)
        self.sort = sort

    def install(self, exe) -> None:
        """Hook an Executor (reference ``install``†)."""
        exe.set_monitor_callback(self._stat_helper)
        self.exes.append(exe)

    def _stat_helper(self, name, arr) -> None:
        if not self.activated or not self.re_prog.match(name):
            return
        self.queue.append((self.step, name, self.stat_func(arr)))

    def tic(self) -> None:
        """Start collecting for this batch (reference ``tic``†)."""
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True

    def toc(self) -> List[Tuple[int, str, str]]:
        """Stop collecting, return stats (reference ``toc``†)."""
        if not self.activated:
            self.step += 1
            return []
        self.activated = False
        res = []
        queue = self.queue
        if self.sort:
            queue = sorted(queue, key=lambda x: x[1])
        for n, k, v in queue:
            res.append((n, k, str(v.asnumpy().ravel()
                                  if isinstance(v, NDArray) else v)))
        self.queue = []
        self.step += 1
        return res

    def toc_print(self) -> None:
        for n, k, v in self.toc():
            logging.info("Batch: %7d %30s %s", n, k, v)
