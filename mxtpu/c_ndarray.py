"""Python side of the training-tier C ABI (VERDICT r3 item 8;
reference ``src/c_api/c_api_ndarray.cc``† / ``c_api.cc``†).

``core/c_api_ndarray.cc`` embeds CPython and calls these helpers; the
boundary stays numpy-free on the C side — tensors cross as PyBytes,
shapes as tuples, op params as string key/value pairs (exactly the
reference ABI's convention, where attrs are strings).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .base import MXNetError
from .ndarray.ndarray import NDArray
from .symbol import _coerce_attr

# the reference's type codes (mshadow/base.h†)
_DTYPE_CODE = {"float32": 0, "float64": 1, "float16": 2, "uint8": 3,
               "int32": 4, "int8": 5, "int64": 6, "bool": 7,
               "bfloat16": 12}
_CODE_DTYPE = {v: k for k, v in _DTYPE_CODE.items()}


def create(shape: Sequence[int], dtype_code: int = 0) -> NDArray:
    """Zero-initialised array (MXNDArrayCreate semantics; XLA has no
    uninitialised alloc, so delay_alloc degrades to zeros)."""
    import jax.numpy as jnp
    dt = _CODE_DTYPE.get(int(dtype_code))
    if dt is None:
        raise MXNetError(f"unknown dtype code {dtype_code}")
    return NDArray(jnp.zeros(tuple(int(s) for s in shape),
                             jnp.dtype(dt)), None, _placed=True)


def from_bytes(shape: Sequence[int], dtype_code: int,
               blob: bytes) -> NDArray:
    dt = _CODE_DTYPE[int(dtype_code)]
    arr = np.frombuffer(blob, dtype=np.dtype(dt)).reshape(
        tuple(int(s) for s in shape)).copy()
    import jax.numpy as jnp
    return NDArray(jnp.asarray(arr), None, _placed=True)


def to_bytes(h: NDArray) -> bytes:
    return np.ascontiguousarray(h.asnumpy()).tobytes()


def shape_of(h: NDArray) -> Tuple[int, ...]:
    return tuple(int(s) for s in h.shape)


def dtype_code_of(h: NDArray) -> int:
    name = str(np.dtype(h.dtype).name) if h.dtype != "bfloat16" \
        else "bfloat16"
    code = _DTYPE_CODE.get(name)
    if code is None:
        raise MXNetError(f"dtype {name} has no reference type code")
    return code


def invoke(op_name: str, inputs: Sequence[NDArray],
           param_keys: Sequence[str],
           param_vals: Sequence[str]) -> List[NDArray]:
    """MXImperativeInvoke: run a registry op on NDArray inputs with
    string-typed params (coerced exactly like symbol JSON attrs)."""
    from .ops.registry import get_op
    op = get_op(op_name)
    kwargs = {k: _coerce_attr(v)
              for k, v in zip(param_keys, param_vals)}
    out = op(*[h.data for h in inputs], **kwargs)
    leaves = out if isinstance(out, (tuple, list)) else [out]
    return [NDArray(l, None, _placed=True) for l in leaves]


def save(fname: str, handles: Sequence[NDArray],
         keys: Optional[Sequence[str]] = None) -> None:
    from .ndarray import ndarray as nd_mod
    if keys:
        nd_mod.save(fname, dict(zip(keys, handles)))
    else:
        nd_mod.save(fname, list(handles))


def load(fname: str) -> Tuple[List[NDArray], List[str]]:
    from .ndarray import ndarray as nd_mod
    data = nd_mod.load(fname)
    if isinstance(data, dict):
        names = list(data.keys())
        return [data[n] for n in names], names
    return list(data), []
