"""Pallas TPU kernels — the cuDNN-fusion tier of the reference
(``src/operator/nn/cudnn/``†), rebuilt as hand-written TPU kernels for
the ops XLA's automatic fusion doesn't nail (SURVEY.md §7 M6).

Dispatch policy: kernels engage on the TPU backend (or when
``MXTPU_PALLAS=interpret`` forces interpreter mode for CPU testing);
every kernel has a pure-lax reference implementation used as fallback
and as the parity oracle in tests.
"""
from __future__ import annotations

import jax

from .. import knobs

__all__ = ["layer_norm", "flash_attention", "pallas_enabled",
           "precision_metadata", "layout_metadata"]


def layout_metadata():
    """``{kernel_name: LAYOUT}`` for every Pallas kernel — the
    declared operand-layout contract (which physical layouts each
    custom call binds without relayout copies, and the knob that
    picks a variant).  The layout half of the AMP/MFU work: transpose
    brackets around custom calls are invisible to cost_analysis, so
    the contract is stated where dispatch lives and audited by
    test/hlocheck instead of rediscovered per regression."""
    import importlib
    return {
        name: dict(importlib.import_module(
            f"{__name__}.{name}").LAYOUT)
        for name in ("flash_attention", "layer_norm", "batch_norm")
    }


def precision_metadata():
    """``{kernel_name: PRECISION}`` for every Pallas kernel that
    declares its accumulation discipline — evidence for mxprec's
    ``contracts/amp_policy.json`` ``custom_calls`` section (custom
    calls are opaque to the HLO dtype-flow scan)."""
    # the kernel entry points shadow their module names in this
    # namespace (``flash_attention`` is the function), so resolve the
    # modules explicitly
    import importlib
    return {
        name: dict(importlib.import_module(
            f"{__name__}.{name}").PRECISION)
        for name in ("flash_attention", "layer_norm", "batch_norm")
    }


def pallas_enabled() -> bool:
    """True when the Pallas path should be used."""
    flag = knobs.get("MXTPU_PALLAS")
    if flag in ("0", "off", "false"):
        return False
    if flag == "interpret":
        return True
    return jax.default_backend() == "tpu"


def interpret_mode() -> bool:
    return knobs.get("MXTPU_PALLAS") == "interpret" or \
        jax.default_backend() != "tpu"


from .layer_norm import layer_norm, layer_norm_reference  # noqa: E402
from .flash_attention import (flash_attention,  # noqa: E402
                              attention_reference)
