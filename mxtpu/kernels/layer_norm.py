"""Fused LayerNorm (forward + backward) Pallas kernels.

Replaces the reference's cuDNN/hand-CUDA LayerNorm
(``src/operator/nn/layer_norm.cc``†) on TPU.  Fusion wins: one HBM
read of x per pass instead of XLA's potentially split mean/var/normalize
pipeline, with mean/rstd residuals saved for a one-read backward.

Layout: rows = all leading dims flattened, normalization over the last
axis.  Row blocks of 128 keep the VPU lanes full; the feature axis is
kept whole in VMEM (fine up to ~tens of thousands of features).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .. import knobs

# Declared numerics contract for ``contracts/amp_policy.json`` (see
# flash_attention.PRECISION).
PRECISION = {
    "accum_dtype": "f32",
    "safe_input_dtypes": ["bf16", "f32"],
    "note": "x is staged to f32 before mean/var; rstd and the "
            "normalize epilogue stay f32; mean/rstd residuals saved "
            "in f32 for the backward",
}

# Operand-layout contract (see batch_norm.LAYOUT): already minor-most
# on the reduced axis, so no relayout brackets arise — the row-major
# (rows, features) view IS the layout the producing matmuls emit.
LAYOUT = {
    "native": {
        "view": "(rows, features) row blocks, features on lanes",
        "binds": "row-major — matches the (…, D) activations the "
                 "surrounding matmuls produce; no transpose brackets",
    },
    "dispatch": "always; feature axis stages whole in VMEM",
}


def layer_norm_reference(x, gamma, beta, eps=1e-5):
    """Pure-lax composite — the fallback path and parity oracle."""
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    inv = lax.rsqrt(var + eps)
    return (x - mean) * inv * gamma + beta


# ----------------------------------------------------------------------
# forward
# ----------------------------------------------------------------------

def _ln_fwd_kernel(x_ref, g_ref, b_ref, y_ref, mean_ref, rstd_ref, *,
                   eps):
    x = x_ref[:].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    rstd = lax.rsqrt(var + eps)
    y = xc * rstd * g_ref[:].astype(jnp.float32) + \
        b_ref[:].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)
    mean_ref[:] = mean
    rstd_ref[:] = rstd


def _ln_bwd_kernel(x_ref, g_ref, mean_ref, rstd_ref, dy_ref, dx_ref,
                   dg_ref, db_ref):
    x = x_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    dy = dy_ref[:].astype(jnp.float32)
    mean = mean_ref[:]
    rstd = rstd_ref[:]
    xhat = (x - mean) * rstd
    dyg = dy * g
    c1 = jnp.mean(dyg, axis=-1, keepdims=True)
    c2 = jnp.mean(dyg * xhat, axis=-1, keepdims=True)
    dx = rstd * (dyg - c1 - xhat * c2)
    dx_ref[:] = dx.astype(dx_ref.dtype)
    # per-row-block partial reductions; each block writes an 8-row tile
    # (TPU min sublane tile) with the partial in row 0 — summed outside
    dg_ref[:] = jnp.pad(jnp.sum(dy * xhat, axis=0, keepdims=True),
                        ((0, 7), (0, 0)))
    db_ref[:] = jnp.pad(jnp.sum(dy, axis=0, keepdims=True),
                        ((0, 7), (0, 0)))


def _row_block(n_rows: int, n_cols: int, budget: int = 4 << 20):
    """Largest row block that divides n_rows and keeps the x-block
    within a VMEM-friendly budget; None → use the lax fallback."""
    for blk in (256, 128, 64, 32, 16, 8):
        if n_rows % blk == 0 and blk * n_cols * 4 <= budget:
            return blk
    return None


def _pallas_ln_fwd(x2, gamma, beta, eps, interpret):
    R, C = x2.shape
    BR = _row_block(R, C)
    grid = (R // BR,)
    y, mean, rstd = pl.pallas_call(
        functools.partial(_ln_fwd_kernel, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((BR, C), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, C), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, C), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((BR, C), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((BR, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((BR, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((R, C), x2.dtype),
            jax.ShapeDtypeStruct((R, 1), jnp.float32),
            jax.ShapeDtypeStruct((R, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x2, gamma.reshape(1, C), beta.reshape(1, C))
    return y, mean, rstd


def _pallas_ln_bwd(x2, gamma, mean, rstd, dy2, interpret):
    R, C = x2.shape
    BR = _row_block(R, C)
    grid = (R // BR,)
    dx, dg_part, db_part = pl.pallas_call(
        _ln_bwd_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((BR, C), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, C), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((BR, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((BR, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((BR, C), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((BR, C), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((8, C), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((8, C), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((R, C), x2.dtype),
            jax.ShapeDtypeStruct((R // BR * 8, C), jnp.float32),
            jax.ShapeDtypeStruct((R // BR * 8, C), jnp.float32),
        ],
        interpret=interpret,
    )(x2, gamma.reshape(1, C), mean, rstd, dy2)
    return dx, dg_part.sum(0), db_part.sum(0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _layer_norm_pallas(x2, gamma, beta, eps):
    from . import interpret_mode
    y, _, _ = _pallas_ln_fwd(x2, gamma, beta, eps, interpret_mode())
    return y


def _ln_fwd_rule(x2, gamma, beta, eps):
    from . import interpret_mode
    y, mean, rstd = _pallas_ln_fwd(x2, gamma, beta, eps,
                                   interpret_mode())
    return y, (x2, gamma, mean, rstd)


def _ln_bwd_rule(eps, res, dy):
    from . import interpret_mode
    x2, gamma, mean, rstd = res
    dx, dg, db = _pallas_ln_bwd(x2, gamma, mean, rstd, dy,
                                interpret_mode())
    return dx, dg.astype(gamma.dtype), db.astype(gamma.dtype)


_layer_norm_pallas.defvjp(_ln_fwd_rule, _ln_bwd_rule)


def layer_norm(x, gamma, beta, eps=1e-5):
    """Fused LayerNorm over the last axis.  Pallas on TPU (or interpret
    mode), lax composite elsewhere."""
    from . import pallas_enabled
    C = x.shape[-1]
    n_rows = 1
    for d in x.shape[:-1]:
        n_rows *= d
    if not pallas_enabled() or _row_block(n_rows, C) is None:
        return layer_norm_reference(x, gamma, beta, eps)
    lead = x.shape[:-1]
    x2 = x.reshape(-1, C)
    y = _layer_norm_pallas(x2, gamma.reshape(-1), beta.reshape(-1),
                           float(eps))
    return y.reshape(*lead, C)


# ======================================================================
# fused residual epilogue: y = LN(res + dropout(h + bias))
#
# The transformer post-LN epilogue (proj-bias add, dropout, residual
# add, LayerNorm) is 4 elementwise/reduction ops between two GEMMs.
# Unfused, XLA streams:  fwd  read h,res / write u  +  read u / write y
# (5 (R,C) HBM transfers, plus u resident until the backward);  fused:
# read h,res / write y (3 transfers, no u activation at all).  The bwd
# recomputes the dropout mask and u from h/res in VMEM (4 reads, 2
# writes vs 6 unfused).  Traffic analysis + in-context measurements in
# BASELINE.md "BERT cost split" (fused-BN evidentiary standard).
#
# The mask comes from a hand-rolled threefry2x32 over the global linear
# element index — pure uint32 jnp arithmetic, so the SAME function runs
# inside the Pallas kernel (interpret or compiled: `pltpu.prng_*` has
# no CPU interpret lowering in this jax) and inside the lax composite
# below, making fused-vs-composite parity exact, not statistical.
# ======================================================================

_THREEFRY_PARITY = np.uint32(0x1BD11BDA)
_ROTATIONS = ((13, 15, 26, 6), (17, 29, 16, 24))


def _threefry2x32(k0, k1, x0, x1):
    """Standard 20-round threefry2x32 in pure uint32 jnp ops."""
    ks = (k0, k1, _THREEFRY_PARITY ^ k0 ^ k1)
    x0 = x0 + ks[0]
    x1 = x1 + ks[1]
    for grp in range(5):
        for rot in _ROTATIONS[grp % 2]:
            x0 = x0 + x1
            x1 = (x1 << rot) | (x1 >> (32 - rot))
            x1 = x1 ^ x0
        x0 = x0 + ks[(grp + 1) % 3]
        x1 = x1 + ks[(grp + 2) % 3] + np.uint32(grp + 1)
    return x0, x1


def _mask_bits(k0, k1, row0, n_rows, n_cols):
    """uint32 bits for rows [row0, row0+n_rows) of an (R, C) dropout
    mask; counter = global linear element index, so any row block of
    the same logical tensor draws identical bits."""
    r = lax.broadcasted_iota(jnp.uint32, (n_rows, n_cols), 0)
    c = lax.broadcasted_iota(jnp.uint32, (n_rows, n_cols), 1)
    ctr = (row0 + r) * jnp.uint32(n_cols) + c
    bits, _ = _threefry2x32(k0, k1, ctr, jnp.zeros_like(ctr))
    return bits


def _keep_thresh(keep: float) -> int:
    # P(bits < thresh) == keep for bits ~ U[0, 2^32)
    return min((1 << 32) - 1, int(round(keep * (1 << 32))))


def fused_residual_ln_reference(h, bias, res, gamma, beta, key_data,
                                p=0.1, eps=1e-5, training=True):
    """Lax composite of the epilogue using the SAME threefry mask as
    the Pallas kernel — the non-TPU fallback and exact parity oracle."""
    C = h.shape[-1]
    hb = h.astype(jnp.float32) + bias.astype(jnp.float32).reshape(-1)
    if training and p > 0.0:
        keep = 1.0 - p
        n = 1
        for d in h.shape:
            n *= d
        k0 = key_data.reshape(-1)[0].astype(jnp.uint32)
        k1 = key_data.reshape(-1)[1].astype(jnp.uint32)
        if n < (1 << 32):
            bits = _mask_bits(k0, k1, jnp.uint32(0),
                              n // C, C).reshape(h.shape)
            mask = bits < jnp.uint32(_keep_thresh(keep))
        else:  # counter would wrap; no Pallas path here either
            key = jax.random.wrap_key_data(jnp.stack([k0, k1]))
            mask = jax.random.bernoulli(key, keep, h.shape)
        hb = jnp.where(mask, hb * (1.0 / keep), 0.0)
    u = res.astype(jnp.float32) + hb
    y = layer_norm_reference(u, gamma.astype(jnp.float32),
                             beta.astype(jnp.float32), eps)
    return y.astype(h.dtype)


def _frln_fwd_kernel(seed_ref, h_ref, bias_ref, res_ref, g_ref, b_ref,
                     y_ref, mean_ref, rstd_ref, *, eps, keep, thresh,
                     block_rows):
    hb = h_ref[:].astype(jnp.float32) + bias_ref[:].astype(jnp.float32)
    if keep < 1.0:
        row0 = (pl.program_id(0) * block_rows).astype(jnp.uint32)
        bits = _mask_bits(seed_ref[0], seed_ref[1], row0, *hb.shape)
        hb = jnp.where(bits < jnp.uint32(thresh),
                       hb * (1.0 / keep), 0.0)
    u = res_ref[:].astype(jnp.float32) + hb
    mean = jnp.mean(u, axis=-1, keepdims=True)
    uc = u - mean
    var = jnp.mean(uc * uc, axis=-1, keepdims=True)
    rstd = lax.rsqrt(var + eps)
    y = uc * rstd * g_ref[:].astype(jnp.float32) + \
        b_ref[:].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)
    mean_ref[:] = mean
    rstd_ref[:] = rstd


def _frln_bwd_kernel(seed_ref, h_ref, bias_ref, res_ref, g_ref,
                     mean_ref, rstd_ref, dy_ref,
                     dh_ref, dres_ref, dg_ref, db_ref, dbias_ref, *,
                     keep, thresh, block_rows):
    # recompute the mask and u = res + dropout(h + bias) in VMEM — no
    # saved activation between the GEMM and the LN
    hb = h_ref[:].astype(jnp.float32) + bias_ref[:].astype(jnp.float32)
    if keep < 1.0:
        row0 = (pl.program_id(0) * block_rows).astype(jnp.uint32)
        mask = _mask_bits(seed_ref[0], seed_ref[1], row0,
                          *hb.shape) < jnp.uint32(thresh)
        hb = jnp.where(mask, hb * (1.0 / keep), 0.0)
    u = res_ref[:].astype(jnp.float32) + hb
    mean = mean_ref[:]
    rstd = rstd_ref[:]
    xhat = (u - mean) * rstd
    dy = dy_ref[:].astype(jnp.float32)
    dyg = dy * g_ref[:].astype(jnp.float32)
    c1 = jnp.mean(dyg, axis=-1, keepdims=True)
    c2 = jnp.mean(dyg * xhat, axis=-1, keepdims=True)
    du = rstd * (dyg - c1 - xhat * c2)
    if keep < 1.0:
        dh = jnp.where(mask, du * (1.0 / keep), 0.0)
    else:
        dh = du
    dh_ref[:] = dh.astype(dh_ref.dtype)
    dres_ref[:] = du.astype(dres_ref.dtype)
    # 8-row padded partial-reduction tiles, summed outside (same
    # convention as _ln_bwd_kernel)
    dg_ref[:] = jnp.pad(jnp.sum(dy * xhat, axis=0, keepdims=True),
                        ((0, 7), (0, 0)))
    db_ref[:] = jnp.pad(jnp.sum(dy, axis=0, keepdims=True),
                        ((0, 7), (0, 0)))
    dbias_ref[:] = jnp.pad(jnp.sum(dh, axis=0, keepdims=True),
                           ((0, 7), (0, 0)))


def _pallas_frln_fwd(h2, bias, res2, gamma, beta, seed, keep, eps,
                     interpret):
    R, C = h2.shape
    BR = _row_block(R, C, budget=1 << 20)
    grid = (R // BR,)
    row = lambda i: (i, 0)
    vrow = lambda bs: pl.BlockSpec(bs, row, memory_space=pltpu.VMEM)
    one = lambda: pl.BlockSpec((1, C), lambda i: (0, 0),
                               memory_space=pltpu.VMEM)
    y, mean, rstd = pl.pallas_call(
        functools.partial(_frln_fwd_kernel, eps=eps, keep=keep,
                          thresh=_keep_thresh(keep), block_rows=BR),
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            vrow((BR, C)), one(), vrow((BR, C)), one(), one(),
        ],
        out_specs=[vrow((BR, C)), vrow((BR, 1)), vrow((BR, 1))],
        out_shape=[
            jax.ShapeDtypeStruct((R, C), h2.dtype),
            jax.ShapeDtypeStruct((R, 1), jnp.float32),
            jax.ShapeDtypeStruct((R, 1), jnp.float32),
        ],
        interpret=interpret,
    )(seed, h2, bias.reshape(1, C), res2, gamma.reshape(1, C),
      beta.reshape(1, C))
    return y, mean, rstd


def _pallas_frln_bwd(h2, bias, res2, gamma, seed, mean, rstd, dy2,
                     keep, interpret):
    R, C = h2.shape
    BR = _row_block(R, C, budget=1 << 20)
    grid = (R // BR,)
    row = lambda i: (i, 0)
    vrow = lambda bs: pl.BlockSpec(bs, row, memory_space=pltpu.VMEM)
    one = lambda: pl.BlockSpec((1, C), lambda i: (0, 0),
                               memory_space=pltpu.VMEM)
    part = jax.ShapeDtypeStruct((R // BR * 8, C), jnp.float32)
    dh, dres, dg_p, db_p, dbias_p = pl.pallas_call(
        functools.partial(_frln_bwd_kernel, keep=keep,
                          thresh=_keep_thresh(keep), block_rows=BR),
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            vrow((BR, C)), one(), vrow((BR, C)), one(),
            vrow((BR, 1)), vrow((BR, 1)), vrow((BR, C)),
        ],
        out_specs=[vrow((BR, C)), vrow((BR, C)),
                   vrow((8, C)), vrow((8, C)), vrow((8, C))],
        out_shape=[
            jax.ShapeDtypeStruct((R, C), h2.dtype),
            jax.ShapeDtypeStruct((R, C), h2.dtype),
            part, part, part,
        ],
        interpret=interpret,
    )(seed, h2, bias.reshape(1, C), res2, gamma.reshape(1, C),
      mean, rstd, dy2)
    return dh, dres, dg_p.sum(0), db_p.sum(0), dbias_p.sum(0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7))
def _fused_residual_ln_pallas(h2, bias, res2, gamma, beta, seed, keep,
                              eps):
    from . import interpret_mode
    y, _, _ = _pallas_frln_fwd(h2, bias, res2, gamma, beta, seed, keep,
                               eps, interpret_mode())
    return y


def _frln_fwd_rule(h2, bias, res2, gamma, beta, seed, keep, eps):
    from . import interpret_mode
    y, mean, rstd = _pallas_frln_fwd(h2, bias, res2, gamma, beta, seed,
                                     keep, eps, interpret_mode())
    return y, (h2, bias, res2, gamma, seed, mean, rstd)


def _frln_bwd_rule(keep, eps, saved, dy):
    from . import interpret_mode
    h2, bias, res2, gamma, seed, mean, rstd = saved
    dh, dres, dg, db, dbias = _pallas_frln_bwd(
        h2, bias, res2, gamma, seed, mean, rstd, dy, keep,
        interpret_mode())
    return (dh, dbias.astype(bias.dtype), dres,
            dg.astype(gamma.dtype), db.astype(gamma.dtype),
            np.zeros(seed.shape, dtype=jax.dtypes.float0))


_fused_residual_ln_pallas.defvjp(_frln_fwd_rule, _frln_bwd_rule)


def epilogue_enabled() -> bool:
    """Kill switch for the Pallas epilogue (MXTPU_FUSED_LN_EPILOGUE=0
    falls back to the lax composite with identical mask numerics)."""
    return knobs.get("MXTPU_FUSED_LN_EPILOGUE")


def fused_residual_layer_norm(h, bias, res, gamma, beta, key_data,
                              p=0.1, eps=1e-5, training=True):
    """y = LayerNorm(res + dropout(h + bias)) over the last axis.

    ``key_data`` is raw uint32[2] threefry key words (from
    ``jax.random.key_data``).  Pallas on TPU/interpret, lax composite
    elsewhere — both draw the identical mask."""
    from . import pallas_enabled
    C = h.shape[-1]
    n_rows = 1
    for d in h.shape[:-1]:
        n_rows *= d
    keep = 1.0 if (not training or p <= 0.0) else float(1.0 - p)
    if (not pallas_enabled() or not epilogue_enabled()
            or _row_block(n_rows, C, budget=1 << 20) is None
            or n_rows * C >= (1 << 32)):
        return fused_residual_ln_reference(
            h, bias, res, gamma, beta, key_data, p=p, eps=eps,
            training=training)
    lead = h.shape[:-1]
    seed = key_data.reshape((2,)).astype(jnp.uint32)
    y = _fused_residual_ln_pallas(
        h.reshape(-1, C), bias.reshape(-1), res.reshape(-1, C),
        gamma.reshape(-1), beta.reshape(-1), seed, keep, float(eps))
    return y.reshape(*lead, C)
