"""Fused LayerNorm (forward + backward) Pallas kernels.

Replaces the reference's cuDNN/hand-CUDA LayerNorm
(``src/operator/nn/layer_norm.cc``†) on TPU.  Fusion wins: one HBM
read of x per pass instead of XLA's potentially split mean/var/normalize
pipeline, with mean/rstd residuals saved for a one-read backward.

Layout: rows = all leading dims flattened, normalization over the last
axis.  Row blocks of 128 keep the VPU lanes full; the feature axis is
kept whole in VMEM (fine up to ~tens of thousands of features).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def layer_norm_reference(x, gamma, beta, eps=1e-5):
    """Pure-lax composite — the fallback path and parity oracle."""
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    inv = lax.rsqrt(var + eps)
    return (x - mean) * inv * gamma + beta


# ----------------------------------------------------------------------
# forward
# ----------------------------------------------------------------------

def _ln_fwd_kernel(x_ref, g_ref, b_ref, y_ref, mean_ref, rstd_ref, *,
                   eps):
    x = x_ref[:].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    rstd = lax.rsqrt(var + eps)
    y = xc * rstd * g_ref[:].astype(jnp.float32) + \
        b_ref[:].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)
    mean_ref[:] = mean
    rstd_ref[:] = rstd


def _ln_bwd_kernel(x_ref, g_ref, mean_ref, rstd_ref, dy_ref, dx_ref,
                   dg_ref, db_ref):
    x = x_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    dy = dy_ref[:].astype(jnp.float32)
    mean = mean_ref[:]
    rstd = rstd_ref[:]
    xhat = (x - mean) * rstd
    dyg = dy * g
    c1 = jnp.mean(dyg, axis=-1, keepdims=True)
    c2 = jnp.mean(dyg * xhat, axis=-1, keepdims=True)
    dx = rstd * (dyg - c1 - xhat * c2)
    dx_ref[:] = dx.astype(dx_ref.dtype)
    # per-row-block partial reductions; each block writes an 8-row tile
    # (TPU min sublane tile) with the partial in row 0 — summed outside
    dg_ref[:] = jnp.pad(jnp.sum(dy * xhat, axis=0, keepdims=True),
                        ((0, 7), (0, 0)))
    db_ref[:] = jnp.pad(jnp.sum(dy, axis=0, keepdims=True),
                        ((0, 7), (0, 0)))


def _row_block(n_rows: int, n_cols: int):
    """Largest row block that divides n_rows and keeps the x-block
    within a VMEM-friendly budget; None → use the lax fallback."""
    for blk in (256, 128, 64, 32, 16, 8):
        if n_rows % blk == 0 and blk * n_cols * 4 <= (4 << 20):
            return blk
    return None


def _pallas_ln_fwd(x2, gamma, beta, eps, interpret):
    R, C = x2.shape
    BR = _row_block(R, C)
    grid = (R // BR,)
    y, mean, rstd = pl.pallas_call(
        functools.partial(_ln_fwd_kernel, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((BR, C), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, C), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, C), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((BR, C), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((BR, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((BR, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((R, C), x2.dtype),
            jax.ShapeDtypeStruct((R, 1), jnp.float32),
            jax.ShapeDtypeStruct((R, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x2, gamma.reshape(1, C), beta.reshape(1, C))
    return y, mean, rstd


def _pallas_ln_bwd(x2, gamma, mean, rstd, dy2, interpret):
    R, C = x2.shape
    BR = _row_block(R, C)
    grid = (R // BR,)
    dx, dg_part, db_part = pl.pallas_call(
        _ln_bwd_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((BR, C), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, C), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((BR, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((BR, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((BR, C), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((BR, C), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((8, C), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((8, C), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((R, C), x2.dtype),
            jax.ShapeDtypeStruct((R // BR * 8, C), jnp.float32),
            jax.ShapeDtypeStruct((R // BR * 8, C), jnp.float32),
        ],
        interpret=interpret,
    )(x2, gamma.reshape(1, C), mean, rstd, dy2)
    return dx, dg_part.sum(0), db_part.sum(0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _layer_norm_pallas(x2, gamma, beta, eps):
    from . import interpret_mode
    y, _, _ = _pallas_ln_fwd(x2, gamma, beta, eps, interpret_mode())
    return y


def _ln_fwd_rule(x2, gamma, beta, eps):
    from . import interpret_mode
    y, mean, rstd = _pallas_ln_fwd(x2, gamma, beta, eps,
                                   interpret_mode())
    return y, (x2, gamma, mean, rstd)


def _ln_bwd_rule(eps, res, dy):
    from . import interpret_mode
    x2, gamma, mean, rstd = res
    dx, dg, db = _pallas_ln_bwd(x2, gamma, mean, rstd, dy,
                                interpret_mode())
    return dx, dg.astype(gamma.dtype), db.astype(gamma.dtype)


_layer_norm_pallas.defvjp(_ln_fwd_rule, _ln_bwd_rule)


def layer_norm(x, gamma, beta, eps=1e-5):
    """Fused LayerNorm over the last axis.  Pallas on TPU (or interpret
    mode), lax composite elsewhere."""
    from . import pallas_enabled
    C = x.shape[-1]
    n_rows = 1
    for d in x.shape[:-1]:
        n_rows *= d
    if not pallas_enabled() or _row_block(n_rows, C) is None:
        return layer_norm_reference(x, gamma, beta, eps)
    lead = x.shape[:-1]
    x2 = x.reshape(-1, C)
    y = _layer_norm_pallas(x2, gamma.reshape(-1), beta.reshape(-1),
                           float(eps))
    return y.reshape(*lead, C)
