"""Fused BatchNorm(+Add)+ReLU Pallas kernels — training mode.

The reference ships fused BN kernels at its cuDNN tier
(``src/operator/nn/cudnn/cudnn_batch_norm.cc``† and the fused
``BatchNormAddRelu``/NHWC BN in ``src/operator/nn/batch_norm.cu``†).
On TPU the XLA-composite BatchNorm is already at its *fusion-level*
minimum HBM traffic — fwd ``2R+1W``, bwd ``4R+1W`` of activation-sized
tensors — because the stats/sums reductions are barriers XLA cannot
fuse across.  These kernels beat that minimum by exploiting the one
structural fact XLA's fuser can't: BN statistics are **per channel**,
so a whole channel-block (all ``N*H*W`` elements of ``cb`` channels)
can be staged in VMEM once and both phases (stats then normalize, or
sums then dx) run on the staged copy:

    fwd:  1R + 1W   (stats + scale/shift + optional add + relu)
    bwd:  2R + 1W   (dbeta/dgamma sums + drelu mask + dx, one read
                     each of x and dy)

The ReLU (and the bottleneck's residual add) ride along for free —
the drelu mask is recomputed in-kernel from the staged x and the
per-channel scale/shift, so no mask tensor is ever materialized.

Feasibility is shape-gated: a channel-block of ``cb`` channels costs
``N * cb * pad128(S) * itemsize`` bytes of VMEM per buffer and Mosaic
double-buffers every grid operand, so large-spatial layers (ResNet's
112x112 stem) fall back to the analytic-VJP composite
(``ops_impl._bn_train_core``) which keeps the XLA-minimum traffic.

MEASURED OUTCOME (r5, tools/probe_bn_fusion.py + BASELINE.md "Fused-BN
verdict"): standalone, the kernel beats the composite (e.g. fwd 1.46
vs 1.65 ms/layer at s4_7 b256 bf16).  In a real conv network it LOSES
— XLA lays conv activations out channels-minor (``{1,0,3,2}``: lanes =
C, sublanes = N) while a pallas custom call pins its operands
row-major, so every call is bracketed by full-tensor transpose copies
that cost more than the fused pass saves; and re-expressing the kernel
in the native channels-minor layout is VMEM-infeasible for the stages
holding ~90% of the BN bytes (the reduction extent N*H*W times the
128-lane minimum block is 51-205 MB).  The Pallas path is therefore
**opt-in** (``MXTPU_FUSED_BN=1``); the default composite keeps the
XLA-minimum traffic with the add/relu epilogue fused by XLA.

Layout contract: channel axis 1 (``(N, C, *spatial)``) — the bench /
model-zoo NCHW convention.  Other axes use the composite fallback.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .. import knobs

# Declared numerics contract for ``contracts/amp_policy.json`` (see
# flash_attention.PRECISION).
PRECISION = {
    "accum_dtype": "f32",
    "safe_input_dtypes": ["bf16", "f32"],
    "note": "the staged channel-block is cast to f32 before the "
            "per-channel stats/sums reductions; scale/shift and the "
            "add+relu epilogue compute in f32",
}

# Declared operand-layout contract, aggregated by
# ``mxtpu.kernels.layout_metadata()`` — what each variant pins about
# the physical layout of the tensors the custom call binds, so the
# layout cost (transpose brackets, r5's measured loss) is stated where
# the dispatch decision lives instead of rediscovered per audit.
LAYOUT = {
    "channels_major": {
        "view": "(N, C, S) blocks, C on sublanes",
        "binds": "row-major NCHW operands; conv nets whose "
                 "activations XLA stores channels-minor ({1,0,3,2}) "
                 "pay full-tensor transpose brackets per call",
    },
    "channels_minor": {
        "view": "(N*S, C) blocks, C on lanes",
        "binds": "the native channels-minor conv activation layout — "
                 "the (N,C,S)->(N*S,C) relayout resolves to the "
                 "copy XLA already performs (or a no-op when the "
                 "producer is channels-minor), removing the "
                 "per-call transpose brackets",
    },
    "dispatch": "MXTPU_BN_LAYOUT: auto prefers channels-minor when "
                "one (rows, C) stage fits MXTPU_BN_VMEM_CAP_MB, else "
                "channels-major, else composite; cm/major force",
}


# ----------------------------------------------------------------------
# composite oracle (plain jnp, jax-autodiff) — parity target for tests
# ----------------------------------------------------------------------

def bn_act_reference(x, gamma, beta, eps=1e-5, act="none",
                     residual=None):
    """Pure-jnp BN(+add)+act with batch stats; returns (y, mean, var)."""
    axes = tuple(i for i in range(x.ndim) if i != 1)
    shape = tuple(-1 if i == 1 else 1 for i in range(x.ndim))
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=axes)
    var = jnp.mean(jnp.square(x32), axis=axes) - jnp.square(mean)
    var = jnp.maximum(var, 0.0)
    rstd = lax.rsqrt(var + eps)
    scale = gamma.astype(jnp.float32) * rstd
    shift = beta.astype(jnp.float32) - mean * scale
    y = x32 * scale.reshape(shape) + shift.reshape(shape)
    if residual is not None:
        y = y + residual.astype(jnp.float32)
    if act == "relu":
        y = jnp.maximum(y, 0.0)
    return y.astype(x.dtype), mean, var


# ----------------------------------------------------------------------
# kernels
# ----------------------------------------------------------------------

def _fwd_kernel(*refs, n, eps, act, add):
    # Vectorized over the whole (N, cb, S) block, with channels kept on
    # SUBLANES throughout: reductions go lanes-first (axis 2, keepdims)
    # then over the untiled leading axis, so per-channel values live as
    # (cb, 1) and broadcast back with a lane-splat — never forming the
    # 1-D lane vector whose lane->sublane relayout Mosaic rejects.
    # (A per-sample fori_loop formulation compiles too but is ~2x
    # slower: 256 tiny 2-D iterations are loop-bound, not VPU-bound —
    # tools/probe_bn_fusion.py history.)
    if add:
        x_ref, r_ref, g_ref, b_ref, y_ref, mean_ref, var_ref = refs
    else:
        x_ref, g_ref, b_ref, y_ref, mean_ref, var_ref = refs
    x = x_ref[:].astype(jnp.float32)                     # (N, cb, S)
    s1 = jnp.sum(jnp.sum(x, axis=2, keepdims=True), axis=0)
    s2 = jnp.sum(jnp.sum(x * x, axis=2, keepdims=True), axis=0)
    mean = s1 / n                                        # (cb, 1)
    var = jnp.maximum(s2 / n - mean * mean, 0.0)
    rstd = lax.rsqrt(var + eps)
    g = g_ref[:].astype(jnp.float32)                     # (cb, 1)
    scale = g * rstd
    shift = b_ref[:].astype(jnp.float32) - mean * scale
    y = x * scale[None, :, :] + shift[None, :, :]
    if add:
        y = y + r_ref[:].astype(jnp.float32)
    if act == "relu":
        y = jnp.maximum(y, 0.0)
    y_ref[:] = y.astype(y_ref.dtype)
    mean_ref[:] = mean
    var_ref[:] = var


def _bwd_kernel(*refs, n, act, add):
    if add:
        (x_ref, r_ref, dy_ref, g_ref, b_ref, mean_ref, rstd_ref,
         dx_ref, dr_ref, dg_ref, db_ref) = refs
    else:
        (x_ref, dy_ref, g_ref, b_ref, mean_ref, rstd_ref,
         dx_ref, dg_ref, db_ref) = refs
    mean = mean_ref[:]                                   # (cb, 1)
    rstd = rstd_ref[:]
    g = g_ref[:].astype(jnp.float32)
    b = b_ref[:].astype(jnp.float32)
    x = x_ref[:].astype(jnp.float32)                     # (N, cb, S)
    dy = dy_ref[:].astype(jnp.float32)
    xhat = (x - mean[None, :, :]) * rstd[None, :, :]
    if act == "relu":
        # recompute the pre-activation sign from the staged x — no
        # mask tensor is ever written to HBM
        a = xhat * g[None, :, :] + b[None, :, :]
        if add:
            a = a + r_ref[:].astype(jnp.float32)
        dy = jnp.where(a > 0, dy, 0.0)
    if add:
        dr_ref[:] = dy.astype(dr_ref.dtype)
    dbeta = jnp.sum(jnp.sum(dy, axis=2, keepdims=True), axis=0)
    dgamma = jnp.sum(jnp.sum(dy * xhat, axis=2, keepdims=True), axis=0)
    grs = g * rstd
    dx = grs[None, :, :] * (dy - (dbeta / n)[None, :, :]
                            - xhat * (dgamma / n)[None, :, :])
    dx_ref[:] = dx.astype(dx_ref.dtype)
    dg_ref[:] = dgamma
    db_ref[:] = dbeta


def _fwd_kernel_cm(*refs, n, eps, act, add):
    # Channels-MINOR twin: the block is (R, cbl) with channels on
    # LANES — the layout conv activations already have — and the
    # per-channel stats reduce over the row (sublane) axis, landing
    # as (1, cbl) lane vectors that broadcast back row-wise with no
    # relayout at all.
    if add:
        x_ref, r_ref, g_ref, b_ref, y_ref, mean_ref, var_ref = refs
    else:
        x_ref, g_ref, b_ref, y_ref, mean_ref, var_ref = refs
    x = x_ref[:].astype(jnp.float32)                     # (R, cbl)
    s1 = jnp.sum(x, axis=0, keepdims=True)               # (1, cbl)
    s2 = jnp.sum(x * x, axis=0, keepdims=True)
    mean = s1 / n
    var = jnp.maximum(s2 / n - mean * mean, 0.0)
    rstd = lax.rsqrt(var + eps)
    g = g_ref[:].astype(jnp.float32)                     # (1, cbl)
    scale = g * rstd
    shift = b_ref[:].astype(jnp.float32) - mean * scale
    y = x * scale + shift
    if add:
        y = y + r_ref[:].astype(jnp.float32)
    if act == "relu":
        y = jnp.maximum(y, 0.0)
    y_ref[:] = y.astype(y_ref.dtype)
    mean_ref[:] = mean
    var_ref[:] = var


def _bwd_kernel_cm(*refs, n, act, add):
    if add:
        (x_ref, r_ref, dy_ref, g_ref, b_ref, mean_ref, rstd_ref,
         dx_ref, dr_ref, dg_ref, db_ref) = refs
    else:
        (x_ref, dy_ref, g_ref, b_ref, mean_ref, rstd_ref,
         dx_ref, dg_ref, db_ref) = refs
    mean = mean_ref[:]                                   # (1, cbl)
    rstd = rstd_ref[:]
    g = g_ref[:].astype(jnp.float32)
    b = b_ref[:].astype(jnp.float32)
    x = x_ref[:].astype(jnp.float32)                     # (R, cbl)
    dy = dy_ref[:].astype(jnp.float32)
    xhat = (x - mean) * rstd
    if act == "relu":
        a = xhat * g + b
        if add:
            a = a + r_ref[:].astype(jnp.float32)
        dy = jnp.where(a > 0, dy, 0.0)
    if add:
        dr_ref[:] = dy.astype(dr_ref.dtype)
    dbeta = jnp.sum(dy, axis=0, keepdims=True)
    dgamma = jnp.sum(dy * xhat, axis=0, keepdims=True)
    grs = g * rstd
    dx = grs * (dy - dbeta / n - xhat * (dgamma / n))
    dx_ref[:] = dx.astype(dx_ref.dtype)
    dg_ref[:] = dgamma
    db_ref[:] = dbeta


# ----------------------------------------------------------------------
# block selection / feasibility
# ----------------------------------------------------------------------

def _vmem_cap():
    return knobs.get("MXTPU_BN_VMEM_CAP_MB") << 20


def _pick_cb(N, C, S, itemsize, mult):
    """Largest channel-block that divides C, respects the sublane tile,
    and keeps the kernel's scoped-VMEM footprint under the cap.

    ``mult`` is the measured scoped-VMEM multiplier in units of one
    (N, cb, pad128(S)) block at the native dtype: double-buffered I/O
    blocks plus the f32 temporaries Mosaic materializes.  Measured on
    the real chip (fwd kernel, bf16, s4_7 cb=256: 124.73M scoped for a
    16.8M block ~ 7.5x); 14 for the backward (x, dy, dx I/O + f32
    temps), 20 for the residual-add backward.  None -> composite
    fallback."""
    sub = 16 if itemsize == 2 else 8
    spad = -(-S // 128) * 128
    per_ch = N * spad * itemsize
    best = None
    cb = sub
    while cb <= C:
        if C % cb == 0 and mult * cb * per_ch <= _vmem_cap():
            best = cb
        cb += sub
    return best


def _pick_cbl(R, C, itemsize, mult):
    """Channels-minor lane-block: the largest channel count (lane
    extent) dividing C whose (R, cbl) stage — rows padded to the
    sublane tile, lanes to 128 — keeps ``mult`` staged copies under
    the VMEM cap.  The reduction extent R = N*S stages WHOLE, which is
    what makes the large-spatial stages infeasible in this layout
    (the r5 measurement) and why dispatch is per-layer."""
    sub = 16 if itemsize == 2 else 8
    rpad = -(-R // sub) * sub
    best = None
    cands = sorted({c for c in list(range(128, C + 1, 128)) + [C]
                    if C % c == 0})
    for cbl in cands:
        lpad = -(-cbl // 128) * 128
        if mult * rpad * lpad * itemsize <= _vmem_cap():
            best = cbl
    return best


# ----------------------------------------------------------------------
# pallas_call wrappers (operate on (N, C, S) views)
# ----------------------------------------------------------------------

def _blk3(N, cb, S):
    return pl.BlockSpec((N, cb, S), lambda i: (0, i, 0),
                        memory_space=pltpu.VMEM)


def _blkc(cb):
    return pl.BlockSpec((cb, 1), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)


def _compiler_params(interpret):
    if interpret:
        return None
    # the default scoped-VMEM limit for TPU custom calls is 16 MiB;
    # the channel-block staging strategy deliberately uses most of
    # physical VMEM (measured OOM text: "Scoped allocation ... limit
    # 16.00M" — see tools/probe_bn_fusion.py)
    return pltpu.CompilerParams(vmem_limit_bytes=_vmem_cap())


def _fwd_call(x3, gamma, beta, resid3, eps, act, cb, interpret):
    N, C, S = x3.shape
    n = float(N * S)
    grid = (C // cb,)
    ins = [x3] + ([resid3] if resid3 is not None else []) + \
        [gamma.reshape(C, 1), beta.reshape(C, 1)]
    in_specs = [_blk3(N, cb, S)] + \
        ([_blk3(N, cb, S)] if resid3 is not None else []) + \
        [_blkc(cb), _blkc(cb)]
    y, mean, var = pl.pallas_call(
        functools.partial(_fwd_kernel, n=n, eps=eps, act=act,
                          add=resid3 is not None),
        grid=grid,
        in_specs=in_specs,
        out_specs=[_blk3(N, cb, S), _blkc(cb), _blkc(cb)],
        out_shape=[
            jax.ShapeDtypeStruct((N, C, S), x3.dtype),
            jax.ShapeDtypeStruct((C, 1), jnp.float32),
            jax.ShapeDtypeStruct((C, 1), jnp.float32),
        ],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(*ins)
    return y, mean.reshape(C), var.reshape(C)


def _bwd_call(x3, resid3, dy3, gamma, beta, mean, rstd, act, cb,
              interpret):
    N, C, S = x3.shape
    n = float(N * S)
    grid = (C // cb,)
    add = resid3 is not None
    ins = [x3] + ([resid3] if add else []) + \
        [dy3, gamma.reshape(C, 1), beta.reshape(C, 1),
         mean.reshape(C, 1), rstd.reshape(C, 1)]
    in_specs = [_blk3(N, cb, S)] + ([_blk3(N, cb, S)] if add else []) + \
        [_blk3(N, cb, S), _blkc(cb), _blkc(cb), _blkc(cb), _blkc(cb)]
    out_specs = [_blk3(N, cb, S)] + ([_blk3(N, cb, S)] if add else []) + \
        [_blkc(cb), _blkc(cb)]
    out_shape = [jax.ShapeDtypeStruct((N, C, S), x3.dtype)] + \
        ([jax.ShapeDtypeStruct((N, C, S), dy3.dtype)] if add else []) + \
        [jax.ShapeDtypeStruct((C, 1), jnp.float32),
         jax.ShapeDtypeStruct((C, 1), jnp.float32)]
    outs = pl.pallas_call(
        functools.partial(_bwd_kernel, n=n, act=act, add=add),
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(*ins)
    if add:
        dx, dr, dg, db = outs
    else:
        dx, dg, db = outs
        dr = None
    return dx, dr, dg.reshape(C), db.reshape(C)


# ----------------------------------------------------------------------
# pallas_call wrappers — channels-minor ((N*S, C) views)
# ----------------------------------------------------------------------

def _blk2(R, cbl):
    return pl.BlockSpec((R, cbl), lambda i: (0, i),
                        memory_space=pltpu.VMEM)


def _blkc_cm(cbl):
    return pl.BlockSpec((1, cbl), lambda i: (0, i),
                        memory_space=pltpu.VMEM)


def _fwd_call_cm(x2, gamma, beta, resid2, eps, act, cbl, interpret):
    R, C = x2.shape
    n = float(R)
    grid = (C // cbl,)
    add = resid2 is not None
    ins = [x2] + ([resid2] if add else []) + \
        [gamma.reshape(1, C), beta.reshape(1, C)]
    in_specs = [_blk2(R, cbl)] + ([_blk2(R, cbl)] if add else []) + \
        [_blkc_cm(cbl), _blkc_cm(cbl)]
    y, mean, var = pl.pallas_call(
        functools.partial(_fwd_kernel_cm, n=n, eps=eps, act=act,
                          add=add),
        grid=grid,
        in_specs=in_specs,
        out_specs=[_blk2(R, cbl), _blkc_cm(cbl), _blkc_cm(cbl)],
        out_shape=[
            jax.ShapeDtypeStruct((R, C), x2.dtype),
            jax.ShapeDtypeStruct((1, C), jnp.float32),
            jax.ShapeDtypeStruct((1, C), jnp.float32),
        ],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(*ins)
    return y, mean.reshape(C), var.reshape(C)


def _bwd_call_cm(x2, resid2, dy2, gamma, beta, mean, rstd, act, cbl,
                 interpret):
    R, C = x2.shape
    n = float(R)
    grid = (C // cbl,)
    add = resid2 is not None
    ins = [x2] + ([resid2] if add else []) + \
        [dy2, gamma.reshape(1, C), beta.reshape(1, C),
         mean.reshape(1, C), rstd.reshape(1, C)]
    in_specs = [_blk2(R, cbl)] + ([_blk2(R, cbl)] if add else []) + \
        [_blk2(R, cbl), _blkc_cm(cbl), _blkc_cm(cbl), _blkc_cm(cbl),
         _blkc_cm(cbl)]
    out_specs = [_blk2(R, cbl)] + ([_blk2(R, cbl)] if add else []) + \
        [_blkc_cm(cbl), _blkc_cm(cbl)]
    out_shape = [jax.ShapeDtypeStruct((R, C), x2.dtype)] + \
        ([jax.ShapeDtypeStruct((R, C), dy2.dtype)] if add else []) + \
        [jax.ShapeDtypeStruct((1, C), jnp.float32),
         jax.ShapeDtypeStruct((1, C), jnp.float32)]
    outs = pl.pallas_call(
        functools.partial(_bwd_kernel_cm, n=n, act=act, add=add),
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(*ins)
    if add:
        dx, dr, dg, db = outs
    else:
        dx, dg, db = outs
        dr = None
    return dx, dr, dg.reshape(C), db.reshape(C)


# ----------------------------------------------------------------------
# custom-VJP wrappers
# ----------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _fused_bn(x3, gamma, beta, eps, act, cb):
    from . import interpret_mode
    return _fwd_call(x3, gamma, beta, None, eps, act, cb,
                     interpret_mode())


def _fused_bn_fwd(x3, gamma, beta, eps, act, cb):
    from . import interpret_mode
    y, mean, var = _fwd_call(x3, gamma, beta, None, eps, act, cb,
                             interpret_mode())
    return (y, mean, var), (x3, gamma, beta, mean, var)


def _fused_bn_bwd(eps, act, cb, res, dys):
    from . import interpret_mode
    x3, gamma, beta, mean, var = res
    rstd = lax.rsqrt(var + eps)
    dx, _, dg, db = _bwd_call(x3, None, dys[0], gamma, beta, mean,
                              rstd, act, cb, interpret_mode())
    return dx, dg.astype(gamma.dtype), db.astype(beta.dtype)


_fused_bn.defvjp(_fused_bn_fwd, _fused_bn_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _fused_bn_add(x3, resid3, gamma, beta, eps, act, cb):
    from . import interpret_mode
    return _fwd_call(x3, gamma, beta, resid3, eps, act, cb,
                     interpret_mode())


def _fused_bn_add_fwd(x3, resid3, gamma, beta, eps, act, cb):
    from . import interpret_mode
    y, mean, var = _fwd_call(x3, gamma, beta, resid3, eps, act, cb,
                             interpret_mode())
    return (y, mean, var), (x3, resid3, gamma, beta, mean, var)


def _fused_bn_add_bwd(eps, act, cb, res, dys):
    from . import interpret_mode
    x3, resid3, gamma, beta, mean, var = res
    rstd = lax.rsqrt(var + eps)
    dx, dr, dg, db = _bwd_call(x3, resid3, dys[0], gamma, beta, mean,
                               rstd, act, cb, interpret_mode())
    return dx, dr, dg.astype(gamma.dtype), db.astype(beta.dtype)


_fused_bn_add.defvjp(_fused_bn_add_fwd, _fused_bn_add_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _fused_bn_cm(x2, gamma, beta, eps, act, cbl):
    from . import interpret_mode
    return _fwd_call_cm(x2, gamma, beta, None, eps, act, cbl,
                        interpret_mode())


def _fused_bn_cm_fwd(x2, gamma, beta, eps, act, cbl):
    from . import interpret_mode
    y, mean, var = _fwd_call_cm(x2, gamma, beta, None, eps, act, cbl,
                                interpret_mode())
    return (y, mean, var), (x2, gamma, beta, mean, var)


def _fused_bn_cm_bwd(eps, act, cbl, res, dys):
    from . import interpret_mode
    x2, gamma, beta, mean, var = res
    rstd = lax.rsqrt(var + eps)
    dx, _, dg, db = _bwd_call_cm(x2, None, dys[0], gamma, beta, mean,
                                 rstd, act, cbl, interpret_mode())
    return dx, dg.astype(gamma.dtype), db.astype(beta.dtype)


_fused_bn_cm.defvjp(_fused_bn_cm_fwd, _fused_bn_cm_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _fused_bn_add_cm(x2, resid2, gamma, beta, eps, act, cbl):
    from . import interpret_mode
    return _fwd_call_cm(x2, gamma, beta, resid2, eps, act, cbl,
                        interpret_mode())


def _fused_bn_add_cm_fwd(x2, resid2, gamma, beta, eps, act, cbl):
    from . import interpret_mode
    y, mean, var = _fwd_call_cm(x2, gamma, beta, resid2, eps, act, cbl,
                                interpret_mode())
    return (y, mean, var), (x2, resid2, gamma, beta, mean, var)


def _fused_bn_add_cm_bwd(eps, act, cbl, res, dys):
    from . import interpret_mode
    x2, resid2, gamma, beta, mean, var = res
    rstd = lax.rsqrt(var + eps)
    dx, dr, dg, db = _bwd_call_cm(x2, resid2, dys[0], gamma, beta,
                                  mean, rstd, act, cbl,
                                  interpret_mode())
    return dx, dr, dg.astype(gamma.dtype), db.astype(beta.dtype)


_fused_bn_add_cm.defvjp(_fused_bn_add_cm_fwd, _fused_bn_add_cm_bwd)


# ----------------------------------------------------------------------
# public entry
# ----------------------------------------------------------------------

def fused_bn_act(x, gamma, beta, eps=1e-5, act="none", residual=None):
    """Training-mode BN over channel axis 1, with optional fused
    residual add and ReLU.  Returns ``(y, batch_mean, batch_var)``
    (mean/var are the aux-state channel — not differentiable outputs).

    Dispatches to the one-pass Pallas kernels when the channel-block
    fits VMEM (see module docstring); composite otherwise.  The
    composite fallback still uses the analytic-VJP BN core, so the
    gradient math is identical on every path.
    """
    from . import pallas_enabled
    eps = float(eps)
    # OPT-IN (MXTPU_FUSED_BN=1): the kernel wins per-op (probe table 1
    # in BASELINE.md) but XLA stores conv activations channels-minor
    # ({1,0,3,2}) while pallas custom calls force row-major operands,
    # so in a real conv network every call is bracketed by transpose
    # copies that cost more than the fusion saves (probe table 2).
    feasible = (
        pallas_enabled() and x.ndim >= 3
        and (residual is None or residual.shape == x.shape)
        and knobs.get("MXTPU_FUSED_BN")
    )
    if feasible:
        N, C = x.shape[0], x.shape[1]
        S = 1
        for d in x.shape[2:]:
            S *= d
        # bwd is the high-water mark for scoped VMEM (see _pick_cb)
        mult = 20 if residual is not None else 14
        layout = knobs.get("MXTPU_BN_LAYOUT").strip().lower()
        if layout in ("auto", "cm"):
            # channels-minor first (the AMP layout fix): C rides the
            # lanes like the conv activations feeding it, so the
            # custom call binds without the transpose brackets that
            # made the channels-major kernel a net loss in conv nets
            # (module docstring, r5).  Infeasible (large-spatial
            # stage) -> channels-major under "auto", composite when
            # forced "cm".
            cbl = _pick_cbl(N * S, C, x.dtype.itemsize, mult)
            if cbl is not None:
                x2 = x.reshape(N, C, S).swapaxes(1, 2).reshape(N * S, C)
                r2 = residual.reshape(N, C, S).swapaxes(1, 2) \
                    .reshape(N * S, C) if residual is not None else None
                if r2 is None:
                    y, mean, var = _fused_bn_cm(x2, gamma, beta, eps,
                                                act, cbl)
                else:
                    y, mean, var = _fused_bn_add_cm(x2, r2, gamma,
                                                    beta, eps, act,
                                                    cbl)
                y = y.reshape(N, S, C).swapaxes(1, 2).reshape(x.shape)
                return y, mean, var
        if layout in ("auto", "major"):
            cb = _pick_cb(N, C, S, x.dtype.itemsize, mult)
            if cb is not None:
                x3 = x.reshape(N, C, S)
                r3 = residual.reshape(N, C, S) \
                    if residual is not None else None
                if r3 is None:
                    y, mean, var = _fused_bn(x3, gamma, beta, eps, act,
                                             cb)
                else:
                    y, mean, var = _fused_bn_add(x3, r3, gamma, beta,
                                                 eps, act, cb)
                return y.reshape(x.shape), mean, var
    # composite fallback: analytic-VJP core + jnp epilogue
    from ..ndarray.ops_impl import _bn_train_core
    y, mean, var = _bn_train_core(x, gamma, beta, 1, eps)
    if residual is not None:
        y = y + residual
    if act == "relu":
        y = jnp.maximum(y, jnp.zeros((), y.dtype))
    return y, mean, var
