"""Flash attention (blockwise, online softmax) Pallas kernel.

The reference has no fused attention (2018-era; attention lived in
example code) — this is a *new-capability* kernel mandated by the north
star (SURVEY.md §5.7): O(T) memory attention for long-context training,
the building block for the BERT/Transformer configs.

Design: grid (batch·heads, q_blocks, kv_blocks) with the kv axis
innermost; VMEM scratch carries the running max ``m``, normalizer ``l``
and accumulator across kv blocks (the TPU grid is sequential, so
scratch persists).  Softmax runs in f32 regardless of input dtype; the
q·kᵀ and p·v matmuls hit the MXU with
``preferred_element_type=float32``.  Causal blocks strictly above the
diagonal are skipped via ``pl.when``.

Backward: recompute-based (jax AD through the lax reference) — exact
but O(T·S) memory per head; a blockwise backward kernel is the
follow-up.  Forward-only inference (the common serving path) stays
O(T·D).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def attention_reference(q, k, v, causal=False, sm_scale=None):
    """Pure-lax attention — fallback path and parity oracle.
    q: (B, H, Tq, D); k, v: (B, H, Tk, D)."""
    D = q.shape[-1]
    scale = sm_scale if sm_scale is not None else 1.0 / (D ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        Tq, Tk = s.shape[-2], s.shape[-1]
        row = jnp.arange(Tq)[:, None] + (Tk - Tq)
        col = jnp.arange(Tk)[None, :]
        s = jnp.where(col <= row, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _block(n: int, prefer: int) -> int:
    for blk in (prefer, 256, 128, 64, 32, 16, 8):
        if blk <= prefer and n % blk == 0:
            return blk
    return n


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
               sm_scale, causal, bq, bk, nk, delta):
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal: skip blocks strictly above the diagonal (every column in
    # the block is in the future of every row); delta = Tk - Tq aligns
    # the diagonal when kv is longer than q (cached decoding)
    run = True
    if causal:
        first_row = i * bq + delta
        first_col = j * bk
        run = first_col <= first_row + bq - 1

    @pl.when(run)
    def _step():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            row = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + \
                i * bq + delta
            col = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + \
                j * bk
            s = jnp.where(col <= row, s, _NEG_INF)
        m_prev = m_scr[:]
        l_prev = l_scr[:]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = m_new
        l_scr[:] = l_new

    @pl.when(j == nk - 1)
    def _finalize():
        l = l_scr[:]
        safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / safe).astype(o_ref.dtype)


def _flash_forward(q3, k3, v3, causal, sm_scale, interpret):
    BH, Tq, D = q3.shape
    Tk = k3.shape[1]
    bq = _block(Tq, 128)
    bk = _block(Tk, 128)
    nq, nk = Tq // bq, Tk // bk
    kernel = functools.partial(_fa_kernel, sm_scale=sm_scale,
                               causal=causal, bq=bq, bk=bk, nk=nk,
                               delta=Tk - Tq)
    return pl.pallas_call(
        kernel,
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((BH, Tq, D), q3.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_attention_pallas(q, k, v, causal, sm_scale):
    from . import interpret_mode
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    o = _flash_forward(q.reshape(B * H, Tq, D),
                       k.reshape(B * H, Tk, D),
                       v.reshape(B * H, Tk, D), causal, sm_scale,
                       interpret_mode())
    return o.reshape(B, H, Tq, D)


def _fa_fwd(q, k, v, causal, sm_scale):
    return _flash_attention_pallas(q, k, v, causal, sm_scale), (q, k, v)


def _fa_bwd(causal, sm_scale, res, do):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: attention_reference(q_, k_, v_, causal,
                                               sm_scale), q, k, v)
    return vjp(do)


_flash_attention_pallas.defvjp(_fa_fwd, _fa_bwd)


def flash_attention(q, k, v, causal=False, sm_scale=None):
    """Fused attention.  q: (B, H, Tq, D); k, v: (B, H, Tk, D).
    Pallas on TPU, lax reference elsewhere or for awkward shapes."""
    from . import pallas_enabled
    D = q.shape[-1]
    scale = float(sm_scale) if sm_scale is not None else 1.0 / (D ** 0.5)
    Tq, Tk = q.shape[2], k.shape[2]
    if not pallas_enabled() or D > 512 or Tq % 8 or Tk % 8:
        return attention_reference(q, k, v, causal, scale)
    return _flash_attention_pallas(q, k, v, bool(causal), scale)
