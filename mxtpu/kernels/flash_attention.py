"""Flash attention (blockwise, online softmax) Pallas kernel.

The reference has no fused attention (2018-era; attention lived in
example code) — this is a *new-capability* kernel mandated by the north
star (SURVEY.md §5.7): O(T) memory attention for long-context training,
the building block for the BERT/Transformer configs.

Design: grid (batch·heads, q_blocks, kv_blocks) with the kv axis
innermost; VMEM scratch carries the running max ``m``, normalizer ``l``
and accumulator across kv blocks (the TPU grid is sequential, so
scratch persists).  Softmax runs in f32 regardless of input dtype; the
q·kᵀ and p·v matmuls hit the MXU with
``preferred_element_type=float32``.  Causal blocks strictly above the
diagonal are skipped via ``pl.when``.

Backward: blockwise Pallas kernels (flash-attention-2 style).  The
forward additionally emits the per-row logsumexp; the backward
recomputes each (q_block, kv_block) score tile from q/k and the saved
lse — p = exp(s − lse) is exactly the forward's normalized softmax —
and accumulates dq (kv-innermost grid) and dk/dv (q-innermost grid) in
VMEM scratch.  Memory stays O(T·D) per head; the O(T²) attention
matrix is never materialised in either direction.

Backward dispatch (``MXTPU_FLASH_BWD``): ``auto`` (default) picks AD
through the fused lax reference below T=1024 — measured faster on
v5e while everything is floor-bound — and the blockwise kernels from
T=1024 up (1.4×/2.2×/3.8× vs the fallback at T=1024/2048/4096 with
512-blocks, r4 honest harness; and the only option when O(T²) would
blow HBM); ``pallas``/``ref`` force a path.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30

# Declared numerics contract, aggregated by
# ``mxtpu.kernels.precision_metadata`` into
# ``contracts/amp_policy.json`` — custom calls are opaque to the HLO
# dtype-flow scan, so the kernel states its accumulation discipline
# here and the parity tests hold it to that.
PRECISION = {
    "accum_dtype": "f32",
    "safe_input_dtypes": ["bf16", "f32"],
    "note": "online softmax (m/l/acc scratch) in f32; q.kT and p.v "
            "matmuls use preferred_element_type=float32; single "
            "downcast to the input dtype on output",
}

# Operand-layout contract (see batch_norm.LAYOUT): head_dim minor is
# the layout the QKV projection matmuls emit, so the custom call
# binds transpose-free on every operand.
LAYOUT = {
    "native": {
        "view": "(seq_block, head_dim) tiles per (batch*heads) "
                "program, head_dim on lanes",
        "binds": "row-major (B, H, T, D) — the projection matmul "
                 "output layout; k is transposed in-kernel on the "
                 "MXU, never relaid out in HBM",
    },
    "dispatch": "MXTPU_FLASH_BWD picks the backward path; forward "
                "always blockwise on TPU",
}


def attention_reference(q, k, v, causal=False, sm_scale=None):
    """Pure-lax attention — fallback path and parity oracle.
    q: (B, H, Tq, D); k, v: (B, H, Tk, D).

    f32 inputs run the MXU at HIGHEST precision (the same discipline as
    the Pallas kernel's _precision_for): on TPU the jax default feeds
    bf16 multiplicands, which would make the oracle ~3 decimal digits
    loose and the production f32 fallback silently half-precision."""
    D = q.shape[-1]
    scale = sm_scale if sm_scale is not None else 1.0 / (D ** 0.5)
    prec = _precision_for(q.dtype)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32,
                   precision=prec) * scale
    if causal:
        Tq, Tk = s.shape[-2], s.shape[-1]
        row = jnp.arange(Tq)[:, None] + (Tk - Tq)
        col = jnp.arange(Tk)[None, :]
        s = jnp.where(col <= row, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    if causal and s.shape[-2] > s.shape[-1]:
        # rows with NO visible key (Tq > Tk) output 0, not the uniform
        # attention a softmax over all-sentinel scores degrades to —
        # matches the Pallas kernel's fully-masked-row convention
        Tq, Tk = s.shape[-2], s.shape[-1]
        visible = (jnp.arange(Tq) + (Tk - Tq)) >= 0
        p = p * visible[:, None].astype(p.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v,
                      precision=_precision_for(v.dtype))


def _block(n: int, prefer: int) -> int:
    for blk in (prefer, 256, 128, 64, 32, 16, 8):
        if blk <= prefer and n % blk == 0:
            return blk
    return n


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr,
               acc_scr, *, sm_scale, causal, bq, bk, nk, delta,
               valid_kv, precision):
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal: skip blocks strictly above the diagonal (every column in
    # the block is in the future of every row); delta = Tk - Tq aligns
    # the diagonal when kv is longer than q (cached decoding)
    run = True
    if causal:
        first_row = i * bq + delta
        first_col = j * bk
        run = first_col <= first_row + bq - 1

    @pl.when(run)
    def _step():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=precision) * sm_scale
        if causal:
            row = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + \
                i * bq + delta
            col = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + \
                j * bk
            s = jnp.where(col <= row, s, _NEG_INF)
        if valid_kv is not None:
            # static pad-mask bound: key columns >= valid_kv are
            # zero-padding, not data — sentinel them out before the
            # online softmax so they carry exactly zero weight
            col = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + \
                j * bk
            s = jnp.where(col < valid_kv, s, _NEG_INF)
        m_prev = m_scr[:]
        l_prev = l_scr[:]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision)
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = m_new
        l_scr[:] = l_new

    @pl.when(j == nk - 1)
    def _finalize():
        # fully-masked rows (causal with Tq > Tk): every score is the
        # _NEG_INF sentinel, so m never rises above its init — l==0
        # canNOT detect this (p=exp(0)=1 per masked column makes l=Tk)
        # and lse=m+log(l) would absorb log(l) into -1e30, inflating
        # the backward's p=exp(s-lse) to 1 instead of 0.  Such rows
        # output 0 with lse=+BIG: fwd and bwd are then consistent
        # (zero output, zero grads) — see attention_reference, which
        # applies the same convention.
        masked = m_scr[:] == _NEG_INF
        l = l_scr[:]
        safe = jnp.where(l == 0.0, 1.0, l)
        o = acc_scr[:] / safe
        o_ref[0] = jnp.where(masked, 0.0, o).astype(o_ref.dtype)
        lse_ref[0] = jnp.where(masked, -_NEG_INF,
                               m_scr[:] + jnp.log(safe))


def _precision_for(dtype):
    """f32 inputs get true-f32 MXU passes (Pallas' default is bf16
    multiplicands — 0.5% relative error at T=4k); bf16 inputs keep the
    fast single-pass path."""
    return jax.lax.Precision.HIGHEST \
        if jnp.dtype(dtype) == jnp.float32 else None


def _flash_forward(q3, k3, v3, causal, sm_scale, interpret,
                   valid_kv=None, delta=None):
    BH, Tq, D = q3.shape
    Tk = k3.shape[1]
    # 512-blocks: r4 measurement — 128-blocks made the grid 16x finer
    # and each MXU dot tiny; 512 took T=2048 fwd+bwd from 16.1 to
    # 5.1 ms (fallback: 10.9).  VMEM: s-tile 512^2 f32 = 1 MB.
    bq = _block(Tq, 512)
    bk = _block(Tk, 512)
    nq, nk = Tq // bq, Tk // bk
    kernel = functools.partial(_fa_kernel, sm_scale=sm_scale,
                               causal=causal, bq=bq, bk=bk, nk=nk,
                               delta=Tk - Tq if delta is None else delta,
                               valid_kv=valid_kv,
                               precision=_precision_for(q3.dtype))
    return pl.pallas_call(
        kernel,
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            # (BH, Tq, 1) with (1, bq, 1) blocks: TPU lowering needs
            # the trailing two block dims ∈ {multiple-of-(8,128),
            # equal-to-array}; a 2D (1, bq) row block violates that
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Tq, D), q3.dtype),
            jax.ShapeDtypeStruct((BH, Tq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3)


# ----------------------------------------------------------------------
# blockwise backward (flash-attention-2): dq with kv innermost,
# dk/dv with q innermost; p recomputed from q,k and the saved lse
# ----------------------------------------------------------------------
def _recompute_p(q_ref, k_ref, lse_ref, sm_scale, causal, bq, bk,
                 i, j, delta, valid_kv, precision):
    q = q_ref[0].astype(jnp.float32)
    k = k_ref[0].astype(jnp.float32)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=precision) * sm_scale
    if causal:
        row = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + \
            i * bq + delta
        col = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + j * bk
        s = jnp.where(col <= row, s, _NEG_INF)
    if valid_kv is not None:
        col = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + j * bk
        s = jnp.where(col < valid_kv, s, _NEG_INF)
    return jnp.exp(s - lse_ref[0])  # lse block is (bq, 1) — broadcasts


def _fa_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dt_ref, dq_ref,
                  dq_scr, *, sm_scale, causal, bq, bk, nk, delta,
                  valid_kv, precision):
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    run = True
    if causal:
        run = j * bk <= i * bq + delta + bq - 1

    @pl.when(run)
    def _step():
        p = _recompute_p(q_ref, k_ref, lse_ref, sm_scale, causal,
                         bq, bk, i, j, delta, valid_kv, precision)
        do = do_ref[0].astype(jnp.float32)
        dp = jax.lax.dot_general(
            do, v_ref[0].astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision)
        ds = p * (dp - dt_ref[0]) * sm_scale
        dq_scr[:] += jax.lax.dot_general(
            ds, k_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision)

    @pl.when(j == nk - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _fa_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dt_ref,
                   dk_ref, dv_ref, dk_scr, dv_scr, *, sm_scale, causal,
                   bq, bk, nq, delta, valid_kv, precision):
    j = pl.program_id(1)  # kv block (outer)
    i = pl.program_id(2)  # q block (inner)

    @pl.when(i == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run = True
    if causal:
        run = j * bk <= i * bq + delta + bq - 1

    @pl.when(run)
    def _step():
        p = _recompute_p(q_ref, k_ref, lse_ref, sm_scale, causal,
                         bq, bk, i, j, delta, valid_kv, precision)
        do = do_ref[0].astype(jnp.float32)
        dv_scr[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision)
        dp = jax.lax.dot_general(
            do, v_ref[0].astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision)
        ds = p * (dp - dt_ref[0]) * sm_scale
        dk_scr[:] += jax.lax.dot_general(
            ds, q_ref[0].astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision)

    @pl.when(i == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_backward(q3, k3, v3, do3, lse, delta_rows, causal, sm_scale,
                    interpret, valid_kv=None, delta=None):
    BH, Tq, D = q3.shape
    Tk = k3.shape[1]
    bq = _block(Tq, 512)
    bk = _block(Tk, 512)
    nq, nk = Tq // bq, Tk // bk
    d = Tk - Tq if delta is None else delta

    q_spec_i = pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0),
                            memory_space=pltpu.VMEM)
    kv_spec_j = pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0),
                             memory_space=pltpu.VMEM)
    row_spec_i = pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0),
                              memory_space=pltpu.VMEM)
    dq = pl.pallas_call(
        functools.partial(_fa_dq_kernel, sm_scale=sm_scale,
                          causal=causal, bq=bq, bk=bk, nk=nk, delta=d,
                          valid_kv=valid_kv,
                          precision=_precision_for(q3.dtype)),
        grid=(BH, nq, nk),
        in_specs=[q_spec_i, kv_spec_j, kv_spec_j, q_spec_i, row_spec_i,
                  row_spec_i],
        out_specs=q_spec_i,
        out_shape=jax.ShapeDtypeStruct((BH, Tq, D), q3.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta_rows)

    # q innermost now: index maps take (b, j, i)
    q_spec_t = pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, 0),
                            memory_space=pltpu.VMEM)
    kv_spec_t = pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0),
                             memory_space=pltpu.VMEM)
    row_spec_t = pl.BlockSpec((1, bq, 1), lambda b, j, i: (b, i, 0),
                              memory_space=pltpu.VMEM)
    dk, dv = pl.pallas_call(
        functools.partial(_fa_dkv_kernel, sm_scale=sm_scale,
                          causal=causal, bq=bq, bk=bk, nq=nq, delta=d,
                          valid_kv=valid_kv,
                          precision=_precision_for(q3.dtype)),
        grid=(BH, nk, nq),
        in_specs=[q_spec_t, kv_spec_t, kv_spec_t, q_spec_t, row_spec_t,
                  row_spec_t],
        out_specs=[kv_spec_t, kv_spec_t],
        out_shape=[jax.ShapeDtypeStruct((BH, Tk, D), k3.dtype),
                   jax.ShapeDtypeStruct((BH, Tk, D), v3.dtype)],
        scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                        pltpu.VMEM((bk, D), jnp.float32)],
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta_rows)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention_pallas(q, k, v, causal, sm_scale, valid_kv=None,
                            delta=None):
    from . import interpret_mode
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    o, _ = _flash_forward(q.reshape(B * H, Tq, D),
                          k.reshape(B * H, Tk, D),
                          v.reshape(B * H, Tk, D), causal, sm_scale,
                          interpret_mode(), valid_kv, delta)
    return o.reshape(B, H, Tq, D)


def _fa_fwd(q, k, v, causal, sm_scale, valid_kv=None, delta=None):
    from . import interpret_mode
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    o, lse = _flash_forward(q.reshape(B * H, Tq, D),
                            k.reshape(B * H, Tk, D),
                            v.reshape(B * H, Tk, D), causal, sm_scale,
                            interpret_mode(), valid_kv, delta)
    return o.reshape(B, H, Tq, D), (q, k, v, o.reshape(B, H, Tq, D),
                                    lse)


def _fa_bwd(causal, sm_scale, valid_kv, delta, res, do):
    q, k, v, o, lse = res
    from .. import knobs
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    mode = knobs.get("MXTPU_FLASH_BWD")
    if mode not in ("auto", "pallas", "ref"):
        raise ValueError(
            f"MXTPU_FLASH_BWD={mode!r} not recognised; "
            f"choices: auto, pallas, ref")
    # Measured on v5e (r4, honest chained harness with 512-blocks):
    # ref-bwd wins at T=512 (2.6 vs 3.5 ms), blockwise wins from
    # T=1024 (2.9 vs 4.0 ms; 2.2x at 2048, 3.8x at 4096) — and is the
    # only option when the score matrix would blow HBM.  (The r3
    # threshold of 4096 came from the retracted per-dispatch harness.)
    # Padded runs (valid_kv/delta set) always take the blockwise
    # kernels: attention_reference knows neither the pad-mask bound
    # nor a diagonal offset different from its own Tk - Tq.
    use_pallas = (mode == "pallas" or valid_kv is not None
                  or delta is not None
                  or (mode == "auto" and (max(Tq, Tk) >= 1024
                      or B * H * Tq * Tk * 4 > 2 ** 31)))
    if not use_pallas:
        _, vjp = jax.vjp(
            lambda q_, k_, v_: attention_reference(q_, k_, v_, causal,
                                                   sm_scale), q, k, v)
        return vjp(do)
    from . import interpret_mode
    # delta_i = rowsum(do ⊙ o) — the softmax-jacobian diagonal term
    delta_rows = jnp.sum(do.astype(jnp.float32) *
                         o.astype(jnp.float32), axis=-1)
    dq, dk, dv = _flash_backward(
        q.reshape(B * H, Tq, D), k.reshape(B * H, Tk, D),
        v.reshape(B * H, Tk, D), do.reshape(B * H, Tq, D),
        lse, delta_rows.reshape(B * H, Tq, 1), causal, sm_scale,
        interpret_mode(), valid_kv, delta)
    return (dq.reshape(B, H, Tq, D), dk.reshape(B, H, Tk, D),
            dv.reshape(B, H, Tk, D))


_flash_attention_pallas.defvjp(_fa_fwd, _fa_bwd)


_warned_fallback = set()


def _padded_flash(q, k, v, causal, scale):
    """Run the Pallas kernel on T-padded inputs, exactly.

    Sequence lengths are zero-padded up to the 8-multiple the TPU
    lowering needs, then the padded rows are sliced off the output.
    Padded KEY columns are masked *inside* the kernels: the static
    ``valid_kv`` bound turns their scores into the ``_NEG_INF``
    sentinel before the online softmax, so they carry exactly zero
    weight forward and contribute exactly zero dk/dv backward.  The
    causal diagonal keeps the ORIGINAL ``delta = Tk - Tq`` (passed
    statically), so cross-length causal attention — including
    Tq % 8 != Tk % 8, which the earlier plain-pad construction could
    not align — pads exactly too.  Padded QUERY rows compute values
    that are sliced off here; their cotangents are zero (jnp.pad's
    VJP), so no gradient leaks either direction.
    """
    Tq = q.shape[2]
    Tk = k.shape[2]
    pq = (-Tq) % 8
    pk = (-Tk) % 8
    padq = [(0, 0), (0, 0), (0, pq), (0, 0)]
    padk = [(0, 0), (0, 0), (0, pk), (0, 0)]
    out = _flash_attention_pallas(
        jnp.pad(q, padq), jnp.pad(k, padk), jnp.pad(v, padk),
        causal, scale, Tk if pk else None, Tk - Tq)
    return out[:, :, :Tq]


def flash_attention(q, k, v, causal=False, sm_scale=None):
    """Fused attention.  q: (B, H, Tq, D); k, v: (B, H, Tk, D).
    Pallas on TPU, lax reference elsewhere.
    Sequence lengths that are not multiples of 8 are padded to the
    block multiple and the pad keys masked statically inside the
    kernels (exactly — see ``_padded_flash``), so EVERY model-layer
    sequence length — odd T, causal, cross-length decoding — keeps the
    fused kernel's memory bound; the only remaining fallback is
    head_dim > 512.
    """
    import warnings

    from . import pallas_enabled
    D = q.shape[-1]
    scale = float(sm_scale) if sm_scale is not None else 1.0 / (D ** 0.5)
    Tq, Tk = q.shape[2], k.shape[2]
    if not pallas_enabled():
        # CPU / interpret-off: the reference path IS the intended path
        return attention_reference(q, k, v, causal, scale)
    if D > 512:
        # warn once per full (q, k) shape tuple: the O(T^2)-memory
        # fallback silently losing the flash memory guarantee is
        # exactly the failure mode a user needs to hear about — once
        # per distinct call shape, not once per step of a long epoch
        sig = ("head_dim", tuple(q.shape), tuple(k.shape))
        if sig not in _warned_fallback:
            _warned_fallback.add(sig)
            warnings.warn(
                f"flash_attention falling back to the O(T^2) reference "
                f"path (head_dim {D} > 512 kernel bound) for "
                f"q{tuple(q.shape)} k{tuple(k.shape)}", stacklevel=2)
        return attention_reference(q, k, v, causal, scale)
    if Tq % 8 or Tk % 8:
        return _padded_flash(q, k, v, bool(causal), scale)
    return _flash_attention_pallas(q, k, v, bool(causal), scale,
                                   None, None)
